"""Process pool: spawned worker processes, shm-ring or ZMQ star topology.

Parity: /root/reference/petastorm/workers_pool/process_pool.py —
main PUSH -> workers (ventilate), main PUB -> workers (control),
workers -> main (results) (:52-74); spawn not fork (:15-17);
startup handshake (:208-214); orphaned-worker suicide via a main-pid monitor
thread (:324-331); slow-joiner-safe shutdown rebroadcasting FINISHED (:287-304);
pluggable payload serializers; ``diagnostics`` (:306-314).

TPU-first departure: the high-bandwidth worker->main results path defaults to
the first-party C++ shared-memory SPSC ring (native/shm_ring.cpp) — one memcpy
in, one out, no socket syscalls — with the reference-style ZMQ PULL as the
fallback (``transport='zmq'``). Ventilation and control stay on ZMQ (ipc://
endpoints in a private temp dir): they are low-bandwidth and need fan-out/
fan-in semantics the ring does not provide.

Supervision (``docs/robustness.md``): the pool is its workers' supervisor.
Every ventilated item gets a pool-assigned *dispatch id*; workers claim the
item they are processing via a heartbeat message piggybacked on the results
transport, and the consumer-side idle loop polls ``Process.exitcode`` — so a
dead worker is detected in O(heartbeat interval), not O(results timeout). On
death the supervisor respawns the worker (fresh ring for the shm transport)
and requeues exactly the items the dead worker owned; requeued items get a
NEW dispatch id, so any straggler message from the old attempt is recognized
as stale and dropped — each item completes exactly once no matter how many
times it was retried. Items that keep killing or erroring workers are
governed by the uniform ``on_error``/``max_item_retries`` policy
(``workers/supervision.py``): quarantined and skipped, or surfaced as
:class:`PoisonItemError`. When respawn itself keeps failing the pool sheds
the broken slot with a loud warning and only fails at zero live workers.

Note: workers are spawned, so (as with any ``multiprocessing`` spawn user)
scripts creating a ProcessPool at module level must guard the pool-creating code
with ``if __name__ == '__main__':`` — the child re-imports ``__main__``.
"""

from __future__ import annotations

import logging
import multiprocessing
import os
import pickle
import shutil
import sys
import tempfile
import threading
import time
import uuid

import zmq

from petastorm_tpu import faults, observability as obs
from petastorm_tpu.observability import blackbox
from petastorm_tpu.errors import (EmptyResultError, PoisonItemError,
                                  TimeoutWaitingForResultError, WorkerPoolDepletedError)
from petastorm_tpu.native.lifetime import (RingBorrowLedger,
                                           registry as lifetime_registry)
# every wire constant (message kinds, ring framing, dispatch ids) comes from
# the canonical protocol module — lint rule PT801 rejects local redefinitions.
# MSG_HEARTBEAT is the supervision piggyback (claim + liveness beacons);
# MSG_METRICS the telemetry piggyback — both ride the ordered results channel
# so a claim always precedes its item's completion and the final metrics
# snapshot lands before the pool looks drained.
from petastorm_tpu.workers.protocol import (CONTROL_FINISHED, MSG_BLOB, MSG_DATA,
                                            MSG_DONE, MSG_ERROR, MSG_HEARTBEAT,
                                            MSG_METRICS, MSG_STARTED, DispatchIds,
                                            ring_header, ring_unpack)
from petastorm_tpu.workers.supervision import (ErrorPolicy, attach_remote_context,
                                               format_exception_tb, quarantine_record)

logger = logging.getLogger(__name__)

_WORKER_STARTUP_TIMEOUT_S = 30
_DEFAULT_RESULTS_HWM = 50
_DEFAULT_RING_BYTES = 64 << 20
#: default worker heartbeat period; death detection latency is one supervise
#: tick (<= 100ms) for exitcode-visible deaths, one interval for wedge age
_DEFAULT_HEARTBEAT_S = 0.5
#: wait after a death before requeueing its orphaned items: in-transit
#: messages from the dead worker (zmq delivery, ring leftovers) land first,
#: so an item that actually completed is never re-run
_REQUEUE_GRACE_S = 0.25
#: consecutive startup deaths (never claimed an item) before a worker slot is
#: declared broken and shed
_MAX_RESPAWN_FAILURES = 3
#: payloads at least this large ride the per-message /dev/shm blob sidechannel
#: (when the serializer supports single-copy serialize_into): the worker writes
#: the message straight into an mmapped tmpfs file and only the file name
#: crosses the ring/zmq transport — 1 data copy end-to-end instead of 3
#: (serialize join + ring in + ring out). Small payloads keep the low-latency
#: in-band path.
_DEFAULT_BLOB_THRESHOLD = 1 << 20
#: per-POOL bound on UNCONSUMED blob bytes (workers share the run's blob dir,
#: and blobs are unlinked on read, so the dir size is the live backlog) — the
#: byte-backpressure analog of the ring's capacity: workers whose consumer
#: lags block instead of parking unbounded row groups in tmpfs. A single
#: over-budget blob is still allowed through (mirroring the ring's
#: one-payload-must-fit invariant).
_BLOB_BUDGET_BYTES = 256 << 20


#: minimum age before a blob dir with a dead/unparseable owner pid may be
#: reaped — protects a just-created dir whose owner the pid probe cannot see
#: (e.g. a different PID namespace sharing /dev/shm)
_BLOB_SWEEP_GRACE_S = 600


def _sweep_stale_blob_dirs(shm_root):
    """Reap ``pstpu_blobs_<pid>_*`` dirs whose owning process is gone AND whose
    mtime is older than a grace period: blobs from a hard-killed run persist in
    tmpfs forever (no kernel reclaim), and enough of them would silently
    self-disable the sidechannel for every later pool via the headroom check.
    Dirs without a parseable pid are treated as dead-owner (nothing alive can
    own them across a restart) but still get the mtime grace. Best-effort: any
    per-entry error skips that entry, never pool startup."""
    try:
        entries = list(os.scandir(shm_root))
    except OSError:
        return
    now = time.time()
    for entry in entries:
        if not entry.name.startswith('pstpu_blobs_'):
            continue
        try:
            owner_alive = False
            parts = entry.name.split('_')
            # <= 10 digits: anything longer overflows a C pid_t (os.kill would
            # raise OverflowError) and is treated as no-parseable-owner instead
            if (len(parts) >= 3 and parts[2].isascii() and parts[2].isdigit()
                    and len(parts[2]) <= 10):
                pid = int(parts[2])
                if pid == os.getpid():
                    continue
                try:
                    os.kill(pid, 0)  # signal 0: existence probe only
                    owner_alive = True
                except ProcessLookupError:
                    owner_alive = False
                except PermissionError:
                    owner_alive = True  # exists, owned by someone else
            if not owner_alive and now - entry.stat().st_mtime >= _BLOB_SWEEP_GRACE_S:
                shutil.rmtree(entry.path, ignore_errors=True)
        except (OSError, OverflowError, ValueError):
            # e.g. os.kill OverflowError on an absurd digit string: skip the
            # entry, never pool startup
            continue


def _read_blob(path):
    """Map a blob file copy-on-write and unlink it, returning
    ``(memoryview, slot)``: the view's consumers (numpy views) keep the
    mapping — and thus the pages — alive; the name disappears immediately, so
    nothing leaks even if deserialization fails. ACCESS_COPY gives WRITABLE
    views without an upfront copy — the uniform process-pool contract (the
    shm ring's per-message bytearray is writable too, and the zmq fallback
    copies to match): writability must not depend on which channel a payload
    happened to ride.

    :borrows: the returned view borrows the mapping; the caller adopts the
        deserialized arrays into ``slot`` and seals it, so the map is closed
        (and counted in ``lifetime_live_borrows`` while alive) exactly when
        the batch dies."""
    import mmap
    with open(path, 'rb') as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
    os.unlink(path)

    def _close():
        try:
            mm.close()
        except BufferError:
            pass  # a straggler export closes it when the GC drops the chain

    slot = lifetime_registry().open_slot(on_release=_close, label='pool-blob')
    return memoryview(mm), slot  # noqa: PT500 - registered with the lifetime registry


class ProcessPool(object):
    def __init__(self, workers_count, results_queue_size=_DEFAULT_RESULTS_HWM, serializer=None,
                 results_timeout_s=None, transport=None, ring_bytes=_DEFAULT_RING_BYTES,
                 blob_threshold_bytes=_DEFAULT_BLOB_THRESHOLD,
                 on_error='raise', max_item_retries=None,
                 supervision=True, heartbeat_interval_s=_DEFAULT_HEARTBEAT_S,
                 protocol_monitor=None, zero_copy=False):
        """``results_timeout_s``: raise if no worker message arrives within this
        many seconds (None = block indefinitely, matching ThreadPool).
        ``transport``: 'shm' (first-party C++ shared-memory rings) | 'zmq' |
        None = shm when the native library is available, else zmq.
        ``ring_bytes``: per-worker ring capacity for the shm transport; one
        serialized row-group payload must fit.
        ``blob_threshold_bytes``: payloads >= this ride the single-copy
        /dev/shm blob sidechannel when the serializer supports
        ``serialize_into`` (0 disables).
        ``on_error``/``max_item_retries``: the uniform item-failure policy
        ('raise' | 'skip' | 'retry'; see ``workers/supervision.py``).
        ``supervision``: heartbeat + exitcode monitoring with respawn/requeue;
        disabling it restores the legacy behavior where a dead worker strands
        its items until ``results_timeout_s``.
        ``heartbeat_interval_s``: worker liveness beacon period.
        ``protocol_monitor``: opt-in runtime conformance checking of the
        supervision protocol (``docs/protocol.md``) — a
        :class:`~petastorm_tpu.analysis.protocol.monitor.ProtocolMonitor`
        instance, truthy for a fresh one, or None to honor the
        ``PSTPU_PROTOCOL_MONITOR`` env var; any observed event sequence the
        protocol spec rejects raises
        :class:`~petastorm_tpu.errors.ProtocolViolation`.
        ``zero_copy``: deliver MSG_DATA batches as views straight into the
        shm ring's slot instead of a per-message copy; every view is
        lifetime-tracked through ``native/lifetime.py`` (the slot's ring
        bytes are only reused once the batch's arrays die — docs/native.md,
        "Zero-copy views and slot lifetimes"). shm transport only; the zmq
        fallback already hands out owned buffers."""
        self._workers_count = workers_count
        self._results_hwm = results_queue_size
        from petastorm_tpu.serializers import PickleSerializer
        self._serializer = serializer or PickleSerializer()
        self._results_timeout_s = results_timeout_s
        if transport is None:
            from petastorm_tpu.native import shm_ring
            transport = 'shm' if shm_ring.is_available() else 'zmq'
        if transport not in ('shm', 'zmq'):
            raise ValueError("transport must be 'shm', 'zmq' or None, got {!r}".format(transport))
        self._transport = transport
        self._ring_bytes = ring_bytes
        self._blob_threshold = blob_threshold_bytes
        # zero-copy consumer views: only meaningful over the shm transport
        self._zero_copy = bool(zero_copy) and transport == 'shm'
        self._ring_ledgers = {}  # id(ring) -> RingBorrowLedger (consumer side)
        self._policy = (on_error if isinstance(on_error, ErrorPolicy)
                        else ErrorPolicy(on_error, **({} if max_item_retries is None
                                                      else {'max_item_retries': max_item_retries})))
        self._supervision = bool(supervision)
        self._heartbeat_interval_s = heartbeat_interval_s
        self._blob_dir = None
        self._rings = []            # per-slot ring (or None); index == worker_id
        self._retired_rings = []    # dead workers' rings, polled until drained
        self._context = None
        self._processes = []        # per-slot Process (None = slot shed)
        self._ventilator = None
        self._ventilated_items = 0
        self._completed_items = 0
        self._stopped = False
        self._ipc_dir = None
        # The C++ ring is strictly single-consumer; this lock serializes the
        # get_results() poll loop against the join() drain so two threads never
        # race pstpu_ring_read on the same ring.
        self._ring_lock = threading.Lock()
        # consumer-side idle-wait escalation (consumer thread only)
        from petastorm_tpu.native.shm_ring import IdleWait
        self._idle_wait = IdleWait()
        # item ownership/accounting state — _state_lock guards everything the
        # ventilator thread (ventilate) and the consumer thread (get_results/
        # supervise) both touch; callbacks into the ventilator always run with
        # it RELEASED (single lock, no ordering cycles)
        self._state_lock = threading.Lock()
        self._dispatch_ids = DispatchIds()
        self._inflight = {}         # dispatch id -> item record dict
        self._orphans = {}          # dispatch id -> monotonic death time
        self._quarantined = []
        self._items_requeued = 0
        self._worker_restarts = 0
        # zmq PUSH sockets are not thread-safe: the ventilator thread and the
        # consumer-side requeue both send on _ventilator_send
        self._vent_lock = threading.Lock()
        # supervision bookkeeping (consumer thread only)
        self._worker_state = {}     # worker_id -> liveness/ownership view
        self._heartbeats_received = 0  # overhead accounting (tests assert the bound)
        self._dying = {}            # worker_id -> {'proc', 'ring', 'at'} awaiting drain
        self._retiring = set()      # worker_ids deliberately retired: shed, not respawned
        self._respawn_failures = {}
        self._deaths_seen = False
        self._idle_sweep_since = None
        self._last_supervise = 0.0
        self._spawn_info = None
        self._run_id = uuid.uuid4().hex[:12]
        # checkpoint plumbing (see thread_pool.py): data messages resolve to
        # the ventilator-assigned item seq through the in-flight records
        self.last_result_seq = None
        self.done_callback = None
        # trace linkage: virtual-root TraceContext of the last payload,
        # resolved from the in-flight record (no trace bytes on the ring)
        self.last_result_trace = None
        # pid -> latest cumulative metrics snapshot from that worker process
        # (consumer thread only; merged by Reader.diagnostics)
        self._telemetry_by_pid = {}
        # opt-in protocol conformance monitor (docs/protocol.md); the analysis
        # import stays lazy so the default path never loads the linter stack.
        # Monitor events are emitted under _state_lock where they must order
        # with the accounting they describe (dispatch/requeue/complete), so
        # the only lock nesting is _state_lock -> monitor lock, never reverse.
        self.protocol_monitor = None
        if protocol_monitor or (protocol_monitor is None and
                                os.environ.get('PSTPU_PROTOCOL_MONITOR', '') not in ('', '0')):
            from petastorm_tpu.analysis.protocol.monitor import monitor_from_env
            self.protocol_monitor = monitor_from_env(protocol_monitor, 'process-pool')

    @property
    def transport(self):
        return self._transport

    def _ring_name(self, worker_id, generation):
        return '/pstpu_{}_{}_{}g{}'.format(os.getpid(), self._run_id, worker_id, generation)

    def _create_rings(self, ring_names):
        from petastorm_tpu.native.shm_ring import ShmRing
        # Rings smaller than requested would break the "one serialized
        # row-group payload must fit" invariant mid-run, so when /dev/shm
        # cannot hold full-size rings (docker often caps it at 64MB) we bail
        # out here and let the caller fall back to zmq instead.
        try:
            st = os.statvfs('/dev/shm')
            avail = st.f_bavail * st.f_frsize
        except OSError:
            # statvfs unavailable: proceed; the pre-faulting create still
            # surfaces exhaustion as a catchable error
            avail = None
        if avail is not None and self._ring_bytes * self._workers_count > avail * 0.9:
            raise OSError(
                '/dev/shm has {} bytes free; {} rings of {} bytes will not fit'.format(
                    avail, self._workers_count, self._ring_bytes))
        for worker_id in range(self._workers_count):
            name = self._ring_name(worker_id, 0)
            with self._ring_lock:
                self._rings.append(ShmRing.create(name, self._ring_bytes))
            ring_names[worker_id] = name

    @property
    def workers_count(self):
        return self._workers_count

    def workers_alive(self):
        """Live worker processes (slots shed by repeated respawn failure are
        None and do not count)."""
        return sum(1 for p in self._processes if p is not None and p.is_alive())

    def _all_slots_shed(self):
        """True when every worker slot was permanently given up on — the only
        state in which the supervised pool declares itself depleted (a dead
        worker mid-respawn does NOT count: it is about to come back)."""
        return bool(self._processes) and all(p is None for p in self._processes)

    def _spawn_worker(self, worker_id, ring_name):
        setup_blob, vent_addr, result_addr, control_addr = self._spawn_info
        ctx = multiprocessing.get_context('spawn')
        p = ctx.Process(
            target=_worker_bootstrap,
            args=(worker_id, os.getpid(), setup_blob, vent_addr, result_addr, control_addr,
                  self._results_hwm, ring_name,
                  self._blob_dir, self._blob_threshold, self._workers_count,
                  self._heartbeat_interval_s if self._supervision else None),
            daemon=True)
        p.start()
        return p

    def start(self, worker_class, worker_setup_args=None, ventilator=None):
        if self._processes:
            raise RuntimeError('Pool already started')
        # flight recorder (docs/observability.md): on by default at counters
        # level, one global check when already enabled
        flight = blackbox.maybe_enable('consumer')
        if flight is not None:
            flight.register_lock('process_pool.state_lock', self._state_lock)
            flight.watch('pool_completed', lambda: self._completed_items)
        self._context = zmq.Context()
        self._ipc_dir = tempfile.mkdtemp(prefix='pstpu_pool_')
        vent_addr = 'ipc://' + os.path.join(self._ipc_dir, 'vent')
        result_addr = 'ipc://' + os.path.join(self._ipc_dir, 'result')
        control_addr = 'ipc://' + os.path.join(self._ipc_dir, 'control')

        self._ventilator_send = self._context.socket(zmq.PUSH)
        self._ventilator_send.setsockopt(zmq.LINGER, 0)
        self._ventilator_send.bind(vent_addr)
        self._control_send = self._context.socket(zmq.PUB)
        self._control_send.setsockopt(zmq.LINGER, 0)
        self._control_send.bind(control_addr)

        ring_names = [None] * self._workers_count
        self._results_receive = None
        if self._transport == 'shm':
            try:
                self._create_rings(ring_names)
            except OSError as e:
                # /dev/shm too small for the requested rings (surfaced as a
                # catchable error by the pre-faulting create, not SIGBUS):
                # degrade to the zmq transport rather than dying later.
                logger.warning('shm ring allocation failed (%s); falling back to zmq transport', e)
                with self._ring_lock:
                    for ring in self._rings:
                        ring.close()
                    self._rings = []
                ring_names = [None] * self._workers_count
                self._transport = 'zmq'
        if self._transport == 'zmq':
            with self._ring_lock:
                self._rings = [None] * self._workers_count
            self._results_receive = self._context.socket(zmq.PULL)
            self._results_receive.setsockopt(zmq.RCVHWM, self._results_hwm)
            self._results_receive.bind(result_addr)

        # per-run /dev/shm blob dir for the large-payload sidechannel: only when
        # the serializer can route payloads in one pass and tmpfs has at least
        # token headroom (workers additionally self-disable after persistent
        # ENOSPC — the capacity can change under us at runtime)
        if (self._blob_threshold and hasattr(self._serializer, 'serialize_parts')
                and os.path.isdir('/dev/shm')):
            _sweep_stale_blob_dirs('/dev/shm')
            try:
                st = os.statvfs('/dev/shm')
                if st.f_bavail * st.f_frsize >= 4 * self._blob_threshold:
                    # owner pid is encoded in the name so a future pool start can
                    # reap dirs orphaned by a hard-killed process (tmpfs never
                    # reclaims them on its own)
                    self._blob_dir = tempfile.mkdtemp(
                        prefix='pstpu_blobs_{}_'.format(os.getpid()), dir='/dev/shm')
            except OSError:
                self._blob_dir = None

        # an installed fault plan rides the setup args into spawned workers,
        # exactly like the telemetry config
        if isinstance(worker_setup_args, dict) and 'fault_plan' not in worker_setup_args \
                and faults.get_plan() is not None:
            worker_setup_args = dict(worker_setup_args, fault_plan=faults.get_plan())
        # the flight-file run dir rides along too, so every worker's recorder
        # lands next to the consumer's and one post-mortem sees the whole pool
        if flight is not None and isinstance(worker_setup_args, dict) \
                and 'flight_dir' not in worker_setup_args:
            worker_setup_args = dict(worker_setup_args,
                                     flight_dir=os.path.dirname(flight.path))
        # an installed chunk fabric ships its fetch-only config the same way:
        # worker processes miss on the same chunkstore and should try pod
        # peers before the object store, exactly like the consumer does
        if isinstance(worker_setup_args, dict) and 'fabric' not in worker_setup_args:
            from petastorm_tpu import fabric
            fabric_cfg = fabric.shippable_config()
            if fabric_cfg is not None:
                worker_setup_args = dict(worker_setup_args, fabric=fabric_cfg)

        # spawn (NOT fork): forked children inherit locked mutexes/threads from
        # Arrow, JAX, etc. (reference process_pool.py:15-17 for the JVM analog)
        setup_blob = pickle.dumps((worker_class, worker_setup_args, self._serializer),
                                  protocol=pickle.HIGHEST_PROTOCOL)
        self._spawn_info = (setup_blob, vent_addr, result_addr, control_addr)
        for worker_id in range(self._workers_count):
            self._processes.append(self._spawn_worker(worker_id, ring_names[worker_id]))

        # startup handshake: wait until every worker connected and reported in
        deadline = time.monotonic() + _WORKER_STARTUP_TIMEOUT_S
        started = 0
        while started < self._workers_count:
            if time.monotonic() > deadline:
                self.stop(); self.join()
                raise TimeoutWaitingForResultError(
                    'Only {} of {} workers started within {}s'.format(
                        started, self._workers_count, _WORKER_STARTUP_TIMEOUT_S))
            msg = self._poll_message(100)
            if msg is not None:
                if msg[0] == MSG_STARTED:
                    started += 1
                elif msg[0] == MSG_HEARTBEAT:
                    self._note_heartbeat(msg[2])
                else:
                    # nothing else can legally precede the handshake (items are
                    # ventilated only after start() returns); PT800-exhaustive
                    logger.warning('dropping pre-handshake message of kind %r', msg[0])

        if ventilator is not None:
            self._ventilator = ventilator
            self._ventilator.start()

    # -- runtime slot grow/retire (the autotuner's worker knob) --------------

    def add_worker_slot(self):
        """Spawn one additional supervised worker slot at runtime (fresh ring
        on the shm transport). The new worker joins the same supervision
        protocol as the originals — heartbeats, claims, respawn — so every
        exactly-once guarantee holds unchanged. Returns the new
        ``workers_count``. Slot ids are never reused (retired/shed slots stay
        as None entries), so ring names stay unique."""
        if self._spawn_info is None or self._stopped:
            raise RuntimeError('Pool not started (or already stopped)')
        worker_id = len(self._processes)
        ring_name = None
        if self._transport == 'shm':
            from petastorm_tpu.native.shm_ring import ShmRing
            ring_name = self._ring_name(worker_id, 0)
            ring = ShmRing.create(ring_name, self._ring_bytes)
            with self._ring_lock:
                self._rings.append(ring)
        else:
            with self._ring_lock:
                self._rings.append(None)
        self._processes.append(self._spawn_worker(worker_id, ring_name))
        self._worker_state[worker_id] = {'pid': self._processes[worker_id].pid,
                                         'busy': None, 'last_hb': time.monotonic(),
                                         'claimed_since_spawn': False}
        with self._state_lock:
            self._workers_count += 1
        logger.info('process pool grew to %d workers (slot %d)',
                    self._workers_count, worker_id)
        return self._workers_count

    def retire_worker_slot(self):
        """Retire one IDLE worker slot at runtime (never below 1 live). The
        slot is marked retiring and terminated; the regular two-stage death
        handling drains its final messages, and the retiring mark sheds the
        slot instead of respawning it — so even a race with a just-claimed
        item is safe (the claim requeues exactly once, like any crash).
        Returns the new target ``workers_count`` (unchanged when every live
        slot was busy this tick)."""
        if self.workers_alive() <= 1:
            return self._workers_count
        for worker_id in reversed(range(len(self._processes))):
            p = self._processes[worker_id]
            if p is None or not p.is_alive() or worker_id in self._retiring:
                continue
            state = self._worker_state.get(worker_id, {})
            if state.get('busy') is not None:
                continue
            self._retiring.add(worker_id)
            p.terminate()
            with self._state_lock:
                self._workers_count -= 1
            logger.info('process pool retiring idle worker slot %d (target %d '
                        'workers)', worker_id, self._workers_count)
            return self._workers_count
        return self._workers_count

    def _poll_message(self, timeout_ms):
        """Next (kind, seq, payload_bytes, slot) from the results transport,
        or None after ``timeout_ms``. shm: round-robin over the per-worker
        rings (including dead workers' retired rings until they drain).
        ``slot`` is the lifetime-registry slot of a zero-copy borrowed
        payload (None for owned payloads): the caller adopts the
        deserialized arrays into it and seals it."""
        if self._transport == 'zmq':
            if not self._results_receive.poll(timeout_ms):
                return None
            kind, seq_bytes, payload = self._results_receive.recv_multipart()
            if kind == MSG_DATA:
                # bytes are immutable and would make the deserializer's views
                # read-only; the ring and blob channels hand out writable
                # views, and the contract must not depend on the transport
                payload = bytearray(payload)
            return kind, (int(seq_bytes) if seq_bytes else None), payload, None
        deadline = time.monotonic() + timeout_ms / 1000.0
        idle = self._idle_wait
        while True:
            with self._ring_lock:
                for ring in self._rings:
                    if ring is None:
                        continue
                    msg = self._ring_take(ring)
                    if msg is not None:
                        idle.reset()
                        return msg
                for ring in self._retired_rings:
                    msg = self._ring_take(ring)
                    if msg is not None:
                        idle.reset()
                        return msg
            if time.monotonic() >= deadline:
                return None
            # spin→yield→sleep escalation (shm_ring.IdleWait): the first
            # misses stay latency-free, then the core is yielded, then the
            # consumer sleeps up to 2ms — many idle consumers on one host no
            # longer burn cores while the producers are quiet, and the spins
            # land in the ring_idle_spins counter
            idle.wait()

    def _ring_take(self, ring):
        """One (kind, seq, payload, slot) off ``ring``, or None when empty.
        Caller holds ``_ring_lock`` (the C ring is single-consumer).

        :borrows: in zero-copy mode a MSG_DATA ``payload`` aliases the ring
            slot; ``slot`` is its ledger entry and MUST be adopted or
            released — dropping both wedges the FIFO release ledger.

        Copy mode: every message lands in a fresh per-message buffer.
        Zero-copy mode: MSG_DATA payloads stay views into the ring slot,
        accounted through the ring's :class:`RingBorrowLedger` — the slot's
        bytes are retired to the producer only when the delivered batch's
        arrays die (FIFO, whatever order the finalizers run in). Non-data
        kinds are copied out and their span released immediately: they are
        consumed inside the dispatch loop, so borrowing them buys nothing.
        """
        if not self._zero_copy:
            view = ring.try_read_view()
            return None if view is None else ring_unpack(view) + (None,)
        item = ring.try_read_zero_copy()
        if item is None:
            return None
        view, span, borrowed = item
        ledger = self._ring_ledgers.get(id(ring))
        if ledger is None:
            ledger = self._ring_ledgers[id(ring)] = RingBorrowLedger(ring)
        slot = ledger.take(view, span, borrowed)
        kind, d, payload = ring_unpack(view)
        if not borrowed:
            # wrapped message: the view is an owned copy; retire the span
            slot.release_now()
            return kind, d, payload, None
        if kind != MSG_DATA:
            # copy the (small) control payload out of the ring, then retire
            payload = memoryview(bytearray(payload))
            slot.release_now()
            return kind, d, payload, None
        return kind, d, payload, slot

    def _close_ring(self, ring):
        """Close a consumer-side ring, deferring the munmap while zero-copy
        borrows into its slots are alive (closing under a live view would
        turn a stale batch read into a segfault). No-op deferral when the
        ring never handed out a borrow."""
        ledger = self._ring_ledgers.pop(id(ring), None)
        if ledger is None:
            ring.close()
        else:
            ledger.close_when_drained(ring.close)

    def ventilate(self, *args, **kwargs):
        seq = kwargs.pop('_seq', None)
        # ventilate runs inside the ventilator's mint block: the captured
        # TraceContext rides the existing ventilation tuple into the worker
        # process — same single send, zero extra channel messages
        ctx = obs.current_trace()
        with self._state_lock:
            self._ventilated_items += 1
            d = self._dispatch_ids.next()
            self._inflight[d] = {'seq': seq, 'args': args, 'kwargs': kwargs,
                                 'attempts': 0, 'published': False, 'trace': ctx}
            if self.protocol_monitor is not None:
                # inside the lock: id allocation and the dispatch event must
                # be atomic or concurrent ventilates report out of order
                self.protocol_monitor.on_dispatch(d, seq)
        with self._vent_lock:
            self._ventilator_send.send_pyobj((d, args, kwargs, ctx))

    def _requeue(self, d, rec):
        """Re-dispatch an in-flight item under a NEW dispatch id (any straggler
        message tagged with the old id is thereby stale and ignored). Does NOT
        touch the ventilated/completed counters: the logical item is still the
        same in-flight unit of work."""
        with self._state_lock:
            if self._inflight.get(d) is not rec:
                return  # resolved concurrently
            del self._inflight[d]
            nd = self._dispatch_ids.next()
            rec['attempts'] += 1
            rec['published'] = False
            self._inflight[nd] = rec
            self._items_requeued += 1
            if self.protocol_monitor is not None:
                self.protocol_monitor.on_requeue(d, nd)
        obs.count('items_requeued')
        with self._vent_lock:
            # the retry keeps the original TraceContext (same logical item)
            self._ventilator_send.send_pyobj((nd, rec['args'], rec['kwargs'],
                                              rec.get('trace')))

    def _complete(self, d, rec, delivered):
        """Exactly-once completion accounting for one logical item:
        ``delivered`` marks whether its payload reached the consumer (drives
        the checkpoint ``done_callback``); either way the epoch's
        completed-items count and the ventilator's in-flight budget advance
        exactly once."""
        with self._state_lock:
            if d is not None and self._inflight.pop(d, None) is None:
                return  # stale duplicate (e.g. MSG_DONE from a pre-requeue attempt)
            self._completed_items += 1
            if self.protocol_monitor is not None and d is not None:
                self.protocol_monitor.on_complete(d, delivered)
        if self._ventilator is not None:
            # the completed item's seq rides along so tenant-aware ventilators
            # (FairShareVentilator) can release the right budget
            self._ventilator.processed_item(rec['seq'] if rec is not None else None)
        if delivered and rec is not None and rec['seq'] is not None \
                and self.done_callback is not None:
            self.done_callback(rec['seq'])

    def get_results(self, timeout_s=None):
        with obs.stage('pool_wait', cat='pool') as sp:
            payload = self._get_results(timeout_s)
            # the item is only known once its frame arrives, so the wait span
            # joins its tree retroactively
            sp.link(self.last_result_trace)
            return payload

    def _get_results(self, timeout_s=None):
        timeout_s = timeout_s if timeout_s is not None else self._results_timeout_s
        deadline = (time.monotonic() + timeout_s) if timeout_s is not None else None
        while True:
            msg = self._poll_message(50)
            if self._supervision and self._processes and (
                    msg is None or time.monotonic() - self._last_supervise > 0.2):
                self._supervise(idle=msg is None)
            if msg is None:
                if self._all_done():
                    if self.protocol_monitor is not None:
                        with self._state_lock:
                            ventilated, completed = (self._ventilated_items,
                                                     self._completed_items)
                        self.protocol_monitor.on_drained(ventilated, completed)
                    raise EmptyResultError()
                if self._supervision and self._all_slots_shed():
                    raise WorkerPoolDepletedError(
                        'All {} worker slots are dead and respawn kept failing; {} items '
                        'in flight will never complete'.format(
                            self._workers_count,
                            self._ventilated_items - self._completed_items))
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutWaitingForResultError(self._timeout_message(timeout_s))
                continue
            kind, d, payload, slot = msg
            if kind == MSG_DATA or kind == MSG_BLOB:
                with self._state_lock:
                    rec = self._inflight.get(d) if d is not None else None
                if self.protocol_monitor is not None and d is not None:
                    self.protocol_monitor.on_message('data', d, live=rec is not None)
                if d is not None and rec is None:
                    # stale duplicate from a requeued attempt: the item was (or
                    # will be) delivered under its new dispatch id
                    if kind == MSG_BLOB:
                        try:
                            os.unlink(bytes(payload).decode())
                        except OSError:
                            pass
                    if slot is not None:
                        slot.release_now()  # dropped borrow must not wedge the ring
                    continue
                if rec is not None:
                    rec['published'] = True
                self.last_result_seq = rec['seq'] if rec is not None else None
                # derived from the inflight record — the data frame itself
                # carries no trace bytes
                self.last_result_trace = obs.root_of(
                    rec.get('trace')) if rec is not None else None
                if kind == MSG_DATA:
                    result = self._serializer.deserialize(payload)
                    if slot is not None:
                        # zero-copy delivery: the batch's arrays ARE ring-slot
                        # views; their finalizers retire the span (lifetime.py)
                        slot.adopt(result)
                        slot.seal()
                    return result
                blob_view, blob_slot = _read_blob(bytes(payload).decode())
                result = self._serializer.deserialize(blob_view)
                blob_slot.adopt(result)
                blob_slot.seal()
                return result
            elif kind == MSG_DONE:
                self._clear_claim(d)
                with self._state_lock:
                    rec = self._inflight.get(d) if d is not None else None
                if self.protocol_monitor is not None and d is not None:
                    self.protocol_monitor.on_message('done', d, live=rec is not None)
                if d is not None and rec is None:
                    continue  # stale duplicate
                self._complete(d, rec, delivered=True)
            elif kind == MSG_METRICS:
                self._absorb_telemetry(payload)
            elif kind == MSG_HEARTBEAT:
                self._note_heartbeat(payload)
            elif kind == MSG_ERROR:
                self._clear_claim(d)
                exc = self._handle_worker_error(d, payload)
                if exc is not None:
                    raise exc
            elif kind == MSG_STARTED:
                pass  # late joiner after the startup handshake already passed
            else:
                # PT800 keeps this dispatch exhaustive over protocol.ALL_KINDS;
                # an unknown byte means a framing bug, never a silent drop
                logger.warning('dropping message with unknown protocol kind %r', kind)

    def _handle_worker_error(self, d, payload):
        """Apply the item-failure policy to a worker-raised exception. Returns
        an exception to raise to the consumer, or None when the item was
        requeued/quarantined and iteration continues."""
        try:
            err = pickle.loads(bytes(payload))
        except Exception as e:  # noqa: BLE001 - a malformed error report must still fail loudly
            err = RuntimeError('worker error report could not be unpickled: {}'.format(e))
        if isinstance(err, dict):
            exc, tb = err.get('exc'), err.get('tb')
            worker_id, pid = err.get('worker_id'), err.get('pid')
        else:  # legacy payload: a bare pickled exception
            exc, tb, worker_id, pid = err, None, None, None
        with self._state_lock:
            rec = self._inflight.get(d) if d is not None else None
        if self.protocol_monitor is not None and d is not None:
            self.protocol_monitor.on_message('error', d, live=rec is not None)
        if d is not None and rec is None:
            return None  # stale report from a pre-requeue attempt
        attempts = (rec['attempts'] if rec is not None else 0) + 1
        seq = rec['seq'] if rec is not None else None
        if rec is not None and rec['published'] and self._policy.on_error != 'raise':
            # The item's payload already reached the consumer — the results
            # channel is FIFO, so its MSG_DATA preceded this MSG_ERROR.
            # Re-running (or quarantining) it would deliver the rows twice (or
            # retract a delivery); it completes delivered instead, exactly as
            # a crash after publish does in _resolve_orphans. Surfaced by the
            # protocol model checker as the requeue_published counterexample.
            logger.warning('Worker %s failed on item seq=%s AFTER its payload was '
                           'delivered; completing the item rather than re-running '
                           'it: %s', worker_id, seq, exc)
            self._complete(d, rec, delivered=True)
            return None
        if rec is not None and self._policy.should_retry_error(attempts):
            logger.warning('Worker %s failed on item seq=%s (attempt %d/%d); requeueing: %s',
                           worker_id, seq, attempts, self._policy.max_item_retries + 1, exc)
            self._requeue(d, rec)
            return None
        if rec is not None and self._policy.quarantines():
            self._quarantine(d, rec, kind='error', error=exc, tb=tb, worker_id=worker_id)
            return None
        # 'raise' (or retry budget exhausted): the item completes undelivered —
        # a checkpoint will re-read it — and the failure surfaces with its
        # worker-side traceback attached
        self._complete(d, rec, delivered=False)
        return attach_remote_context(exc, tb, worker_id=worker_id, seq=seq, pid=pid)

    def _quarantine(self, d, rec, kind, error=None, tb=None, worker_id=None):
        record = quarantine_record(rec['seq'], rec['attempts'] + 1, kind, error=error,
                                   tb=tb, worker_id=worker_id,
                                   item={'args': rec['args'], 'kwargs': rec['kwargs']})
        with self._state_lock:
            self._quarantined.append(record)
        obs.count('items_quarantined')
        logger.error('Quarantining item seq=%s after %d failed attempts (%s): %s',
                     record['seq'], record['attempts'], kind, record['error'])
        self._complete(d, rec, delivered=False)

    # -- supervision --------------------------------------------------------

    def _clear_claim(self, d):
        """A MSG_DONE/MSG_ERROR for dispatch ``d`` implicitly releases its owner's
        claim (the results transport is ordered, so the claim beacon always
        precedes its item's completion) — saving the worker a trailing idle
        beacon per item. Also counts as a liveness proof."""
        if d is None:
            return
        for state in self._worker_state.values():
            if state.get('busy') == d:
                state['busy'] = None
                state['last_hb'] = time.monotonic()
                return

    def _note_heartbeat(self, payload):
        try:
            hb = pickle.loads(bytes(payload))
            worker_id = hb['worker_id']
        except Exception as e:  # noqa: BLE001 - malformed beacon must never kill the read loop
            logger.debug('dropping malformed heartbeat: %s', e)
            return
        self._heartbeats_received += 1
        if self.protocol_monitor is not None and hb.get('busy') is not None:
            # a claim beacon: the referenced dispatch id must have been issued
            # (stale claims are legal — the requeue may have already happened)
            self.protocol_monitor.on_message('claim', hb.get('busy'))
        state = self._worker_state.setdefault(worker_id, {})
        state['pid'] = hb.get('pid')
        state['busy'] = hb.get('busy')
        state['last_hb'] = time.monotonic()
        if state['busy'] is not None:
            state['claimed_since_spawn'] = True

    def _supervise(self, idle):
        """The supervisor tick, run on the consumer thread from the results
        loop: poll exitcodes, respawn the dead, resolve orphaned items, and
        sweep items lost in a dead worker's unclaimed dispatch pipe."""
        now = time.monotonic()
        self._last_supervise = now
        for worker_id, p in enumerate(self._processes):
            if p is not None and p.exitcode is not None and worker_id not in self._dying:
                self._begin_worker_death(worker_id, p, now)
        for worker_id in list(self._dying):
            if self._death_drained(worker_id, now):
                info = self._dying.pop(worker_id)
                self._finish_worker_death(worker_id, info, time.monotonic())
        if self._worker_state:
            ages = [now - s['last_hb'] for s in self._worker_state.values() if 'last_hb' in s]
            if ages:
                obs.gauge_set('heartbeat_age_s', round(max(ages), 3))
        if self._orphans:
            self._resolve_orphans(now)
        if idle:
            self._sweep_lost_items(now)
        else:
            self._idle_sweep_since = None

    def _begin_worker_death(self, worker_id, p, now):
        """Stage 1 of death handling: retire the dead worker's ring so the
        normal poll loop drains its final committed messages (shared memory
        outlives the writer; a partially-written message is invisible — the
        writer commits by index advance). Ownership/respawn decisions wait for
        :meth:`_death_drained` — deciding off a stale worker_state while the
        worker's final claim beacon still sits in its ring would misattribute
        the crash."""
        p.join()  # reap the zombie
        if worker_id in self._retiring:
            logger.info('Retiring worker %d (pid %s) exited; draining its results',
                        worker_id, p.pid)
        else:
            logger.warning('Worker %d (pid %s) died with exitcode %s; draining its results',
                           worker_id, p.pid, p.exitcode)
            # flight-recorder evidence: a negative exitcode names the signal
            # (-11 = SIGSEGV) even when the worker's own file never got a footer
            blackbox.record_event({'event': 'worker_death', 'worker_id': worker_id,
                                   'pid': p.pid, 'exitcode': p.exitcode})
        self._deaths_seen = True
        with self._ring_lock:
            # autotune's grow path appends to _rings concurrently; the index
            # read must sit under the same lock as the retire mutation
            old_ring = self._rings[worker_id] if worker_id < len(self._rings) else None
            if old_ring is not None:
                self._retired_rings.append(old_ring)
                self._rings[worker_id] = None
        self._dying[worker_id] = {'proc': p, 'ring': old_ring, 'at': now}

    def _death_drained(self, worker_id, now):
        """All in-transit messages from the dead worker have been consumed:
        shm — its retired ring is empty (non-consuming probe); zmq — a grace
        period passed (the shared PULL buffer has no per-worker view)."""
        info = self._dying[worker_id]
        ring = info['ring']
        if ring is not None:
            with self._ring_lock:
                return not ring.has_message()
        return now - info['at'] >= _REQUEUE_GRACE_S

    def _finish_worker_death(self, worker_id, info, now):
        """Stage 2: with the dead worker's messages fully absorbed, its
        ownership view is current — orphan what it held, account the respawn
        budget, and bring up a replacement on a FRESH ring."""
        p = info['proc']
        state = self._worker_state.get(worker_id, {})
        owned = state.get('busy')
        if owned is not None:
            logger.warning('Dead worker %d owned item dispatch=%s; scheduling requeue',
                           worker_id, owned)
            blackbox.record_event({'event': 'worker_owned_item', 'worker_id': worker_id,
                                   'pid': p.pid, 'dispatch': owned})
            self._orphans.setdefault(owned, now)
        if worker_id in self._retiring:
            # deliberate retire (autotune shrink): the slot sheds cleanly —
            # no respawn, no respawn-failure accounting, no restart counter
            self._retiring.discard(worker_id)
            self._processes[worker_id] = None
            self._worker_state.pop(worker_id, None)
            logger.info('Worker slot %d retired; pool at %d live workers',
                        worker_id, self.workers_alive())
            return
        # startup death (never claimed an item since this spawn) counts toward
        # the slot's respawn-failure budget; a death while working is
        # item-/environment-attributed and resets it
        if state.get('claimed_since_spawn'):
            self._respawn_failures[worker_id] = 0
        else:
            self._respawn_failures[worker_id] = self._respawn_failures.get(worker_id, 0) + 1
        new_ring_name = None
        if self._respawn_failures[worker_id] >= _MAX_RESPAWN_FAILURES:
            self._processes[worker_id] = None
            logger.error(
                'Worker slot %d died %d consecutive times at startup; shedding the slot. '
                'Pool degraded to %d live workers (of %d configured).',
                worker_id, self._respawn_failures[worker_id],
                self.workers_alive(), self._workers_count)
            self._worker_state.pop(worker_id, None)
            return
        try:
            if info['ring'] is not None:
                from petastorm_tpu.native.shm_ring import ShmRing
                new_ring_name = self._ring_name(worker_id, self._worker_restarts + 1)
                new_ring = ShmRing.create(new_ring_name, self._ring_bytes)
                with self._ring_lock:
                    self._rings[worker_id] = new_ring
            self._processes[worker_id] = self._spawn_worker(worker_id, new_ring_name)
        except Exception as e:  # noqa: BLE001 - respawn failure degrades, never kills the consumer
            with self._ring_lock:
                ring, self._rings[worker_id] = self._rings[worker_id], None
            if ring is not None:
                self._close_ring(ring)
            self._processes[worker_id] = None
            self._respawn_failures[worker_id] = _MAX_RESPAWN_FAILURES
            logger.error('Respawning worker %d failed (%s); shedding the slot. '
                         'Pool degraded to %d live workers.', worker_id, e, self.workers_alive())
            self._worker_state.pop(worker_id, None)
            return
        self._worker_restarts += 1
        obs.count('worker_restarts')
        blackbox.record_event({'event': 'worker_respawned', 'worker_id': worker_id,
                               'pid': self._processes[worker_id].pid})
        self._worker_state[worker_id] = {'pid': self._processes[worker_id].pid, 'busy': None,
                                         'last_hb': now, 'claimed_since_spawn': False}
        logger.warning('Respawned worker %d as pid %s', worker_id,
                       self._processes[worker_id].pid)

    def _retired_rings_drained(self):
        """True when no retired ring holds an unconsumed message (NON-consuming
        probe — the messages belong to the consumer loop); empty retired rings
        are closed and dropped along the way."""
        with self._ring_lock:
            for ring in list(self._retired_rings):
                if not ring.has_message():
                    # has_message() respects the zero-copy peek cursor, so an
                    # all-delivered ring counts as drained even while borrows
                    # are live; _close_ring defers the munmap until they die
                    self._close_ring(ring)
                    self._retired_rings.remove(ring)
                else:
                    return False
        return True

    def _resolve_orphans(self, now):
        """Requeue (or quarantine/poison) the items dead workers owned, once
        the dead workers' in-transit messages have had a chance to land —
        an item whose result already arrived is completed, not re-run."""
        if not self._retired_rings_drained():
            return
        for d, died_at in list(self._orphans.items()):
            if now - died_at < _REQUEUE_GRACE_S:
                continue
            self._orphans.pop(d)
            with self._state_lock:
                rec = self._inflight.get(d)
            if rec is None:
                continue  # its MSG_DONE landed during the grace window
            if rec['published']:
                # payload was delivered; only the completion sentinel was lost
                self._complete(d, rec, delivered=True)
                continue
            self._fail_crashed_item(d, rec)

    def _fail_crashed_item(self, d, rec):
        attempts = rec['attempts'] + 1
        if self._policy.should_retry_crash(attempts):
            logger.warning('Requeueing item seq=%s lost to a dead worker (attempt %d/%d)',
                           rec['seq'], attempts, self._policy.max_item_retries + 1)
            self._requeue(d, rec)
            return
        if self._policy.quarantines():
            self._quarantine(d, rec, kind='crash',
                             error=RuntimeError('item killed {} consecutive worker '
                                                'processes'.format(attempts)))
            return
        self._complete(d, rec, delivered=False)
        raise PoisonItemError(
            'Item seq={} (kwargs={}) killed {} consecutive worker processes; '
            "use on_error='skip' to quarantine poison items instead".format(
                rec['seq'], rec['kwargs'], attempts))

    def _sweep_lost_items(self, now):
        """Recover items lost in a dead worker's UNCLAIMED dispatch pipe: zmq
        PUSH had already routed them to the dead peer, so no claim ever named
        an owner. Detection is by elimination — a death happened, every live
        worker has been provably idle (fresh heartbeats, no claim) for a full
        quiet window, the transport is silent, yet items remain in flight:
        nothing can ever run them, so requeue. Requeued items get new dispatch
        ids, so even a mis-judged sweep delivers exactly once (the stale
        attempt's messages are dropped)."""
        if not self._deaths_seen or self._orphans or not self._supervision:
            return
        with self._state_lock:
            in_flight = len(self._inflight)
        if in_flight == 0 or not self._retired_rings_drained():
            self._idle_sweep_since = None
            return
        hb = self._heartbeat_interval_s or _DEFAULT_HEARTBEAT_S
        for worker_id, p in enumerate(self._processes):
            if p is None:
                continue
            state = self._worker_state.get(worker_id)
            if state is None or state.get('busy') is not None \
                    or now - state.get('last_hb', 0) > 2 * hb + 0.5:
                self._idle_sweep_since = None
                return
        if self._idle_sweep_since is None:
            self._idle_sweep_since = now
            return
        if now - self._idle_sweep_since < max(2 * hb, 1.0):
            return
        self._idle_sweep_since = None
        with self._state_lock:
            lost = list(self._inflight.items())
        logger.warning('Sweeping %d item(s) lost in dead workers\' dispatch pipes', len(lost))
        for d, rec in lost:
            if rec['published']:
                self._complete(d, rec, delivered=True)
            else:
                self._fail_crashed_item(d, rec)

    def _timeout_message(self, timeout_s):
        """The per-worker liveness snapshot for TimeoutWaitingForResultError:
        a bare 'N items in flight' forces the operator to re-run under a
        debugger; alive/exitcode + heartbeat age + ownership usually names the
        culprit directly."""
        with self._state_lock:
            in_flight = self._ventilated_items - self._completed_items
            owned = {d: rec['seq'] for d, rec in self._inflight.items()}
        now = time.monotonic()
        lines = ['No results from worker processes in {}s; {} items in flight.'.format(
            timeout_s, in_flight), 'Worker liveness:']
        for worker_id, p in enumerate(self._processes):
            if p is None:
                lines.append('  worker {}: slot shed after repeated respawn failures'.format(
                    worker_id))
                continue
            state = self._worker_state.get(worker_id, {})
            if p.exitcode is not None:
                status = 'DEAD exitcode={}'.format(p.exitcode)
            else:
                status = 'alive'
            hb_age = ('{:.1f}s ago'.format(now - state['last_hb'])
                      if state.get('last_hb') else 'never')
            busy = state.get('busy')
            owning = ('idle' if busy is None else
                      'processing item seq={}'.format(owned.get(busy, '?')))
            lines.append('  worker {}: pid {} {}, last heartbeat {}, {}'.format(
                worker_id, p.pid, status, hb_age, owning))
        if not self._supervision:
            lines.append('  (supervision disabled: no heartbeat/ownership data)')
        lines.append('Run petastorm-tpu-diagnose against this dataset for a full stall report.')
        return '\n'.join(lines)

    # -- telemetry ----------------------------------------------------------

    def _absorb_telemetry(self, payload):
        """Record a worker's cumulative metrics snapshot and merge its trace
        events into this process's span ring."""
        try:
            rec = pickle.loads(bytes(payload))
        except Exception as e:  # noqa: BLE001 - malformed telemetry must never kill the read loop
            logger.debug('dropping malformed worker telemetry message: %s', e)
            return
        if not isinstance(rec, dict):
            return
        self._telemetry_by_pid[rec.get('pid')] = rec.get('metrics') or {}
        obs.absorb_trace_events(rec.get('events'))

    def telemetry_snapshots(self):
        """Latest cumulative metrics snapshot of every worker process (for
        :func:`petastorm_tpu.observability.merge_snapshots`)."""
        return list(self._telemetry_by_pid.values())

    def _all_done(self):
        # completed() first: once true, the ventilated count is final and the
        # counter comparison below cannot be stale (the reverse order races
        # an epoch ventilating between the two reads; see thread_pool)
        if self._ventilator is not None and not self._ventilator.completed():
            return False
        if self._ventilated_items > self._completed_items:
            return False
        return True

    def stop(self):
        if self._stopped:
            return
        if self._ventilator is not None:
            self._ventilator.stop()
        self._stopped = True
        # slow-joiner-safe: a worker that connects its SUB socket after this
        # publish would miss it, so join() rebroadcasts while draining
        self._control_send.send(CONTROL_FINISHED)

    def join(self):
        if not self._stopped:
            raise RuntimeError('join() must be called after stop()')
        deadline = time.monotonic() + 10
        while any(p is not None and p.is_alive() for p in self._processes) \
                and time.monotonic() < deadline:
            self._control_send.send(CONTROL_FINISHED)
            # drain results so workers blocked on a full transport can exit
            if self._transport == 'zmq':
                while self._results_receive.poll(0):
                    self._results_receive.recv_multipart()
            else:
                with self._ring_lock:
                    for ring in self._rings + self._retired_rings:
                        if ring is None:
                            continue
                        while True:
                            drained = self._ring_take(ring)
                            if drained is None:
                                break
                            if drained[3] is not None:
                                # shutdown drain discards the payload; retire
                                # the span immediately (nothing borrowed it)
                                drained[3].release_now()
            time.sleep(0.05)
        for p in self._processes:
            if p is None:
                continue
            if p.is_alive():
                logger.warning('Terminating unresponsive worker pid=%s', p.pid)
                p.terminate()
            p.join()
        self._processes = []
        with self._ring_lock:
            for ring in self._rings + self._retired_rings:
                if ring is not None:
                    self._close_ring(ring)
            self._rings = []
            self._retired_rings = []
        for sock in (self._ventilator_send, self._results_receive, self._control_send):
            if sock is not None:
                sock.close()
        self._context.term()
        if self._ipc_dir:
            shutil.rmtree(self._ipc_dir, ignore_errors=True)
        if self._blob_dir:
            # sweep unconsumed blobs (already-consumed ones were unlinked on
            # read; live mappings keep their pages regardless)
            shutil.rmtree(self._blob_dir, ignore_errors=True)
            self._blob_dir = None

    @property
    def quarantined_items(self):
        """Structured records of quarantined items (``on_error='skip'``):
        dicts with seq/item/attempts/kind/error/traceback/worker_id."""
        with self._state_lock:
            return list(self._quarantined)

    @property
    def diagnostics(self):
        """The unified pool diagnostics schema (docs/observability.md).
        ``results_queue_depth`` is 0 here: buffered results live in zmq/ring
        transport buffers this process cannot observe."""
        with self._state_lock:
            ventilated = self._ventilated_items
            completed = self._completed_items
            requeued = self._items_requeued
            quarantined = len(self._quarantined)
        out = {'workers_count': self._workers_count,
               'items_ventilated': ventilated,
               'items_completed': completed,
               'items_in_flight': ventilated - completed,
               'results_queue_depth': 0,
               'worker_restarts': self._worker_restarts,
               'items_requeued': requeued,
               'items_quarantined': quarantined,
               'zero_copy': self._zero_copy}
        out.update(lifetime_registry().counters())
        return out

    @property
    def results_qsize(self):
        return 0  # unknown: lives in zmq buffers


# ---------------------------------------------------------------------------
# Worker process side
# ---------------------------------------------------------------------------

def _worker_bootstrap(worker_id, main_pid, setup_blob, vent_addr, result_addr, control_addr,
                      results_hwm, ring_name=None, blob_dir=None, blob_threshold=0,
                      workers_count=1, heartbeat_interval_s=None):
    """Entry point of a spawned worker process. ``ring_name`` selects the shm
    results transport; None = zmq PUSH. ``blob_dir`` enables the large-payload
    /dev/shm sidechannel. ``heartbeat_interval_s`` enables the supervision
    beacons (None = legacy silent worker)."""
    # The native image-decode thread budget is PER-PROCESS state — sibling
    # workers cannot see each other's grants — so each spawned worker gets an
    # equal share of the host's cores (unless the user pinned the env var
    # explicitly, which children inherit and honor).
    if 'PSTPU_IMG_THREADS' not in os.environ:
        os.environ['PSTPU_IMG_THREADS'] = str(
            max(1, (os.cpu_count() or 1) // max(1, workers_count)))

    worker_class, worker_setup_args, serializer = pickle.loads(setup_blob)

    # telemetry rides the worker setup args: configure THIS process's level
    # and ring to match the reader's before any instrumented code runs
    if isinstance(worker_setup_args, dict) and worker_setup_args.get('telemetry') is not None:
        obs.configure(worker_setup_args['telemetry'])
    # fault injection rides the same route; SIGKILL faults are only honored
    # here, in a process whose death the supervisor can absorb
    faults.mark_in_spawned_worker()
    if isinstance(worker_setup_args, dict) and worker_setup_args.get('fault_plan') is not None:
        faults.install(worker_setup_args['fault_plan'])
    # the worker's own flight recorder, in the consumer's run dir: when this
    # process SIGSEGVs mid-item the file names the dying stage and signal.
    # The key is popped — it is pool plumbing, not the worker's setup args.
    flight_run_dir = (worker_setup_args.pop('flight_dir', None)
                      if isinstance(worker_setup_args, dict) else None)
    blackbox.maybe_enable('worker{}'.format(worker_id), run_dir=flight_run_dir)
    # a shipped fabric config installs a fetch-only node (no server, no
    # lease) so this worker's chunk misses try pod peers first. Popped — it
    # is pool plumbing, not the worker's setup args.
    fabric_cfg = (worker_setup_args.pop('fabric', None)
                  if isinstance(worker_setup_args, dict) else None)
    if fabric_cfg is not None:
        from petastorm_tpu import fabric
        try:
            fabric.install_from_config(fabric_cfg)
        except Exception as e:  # noqa: BLE001 - fabric is an optimization tier only
            logger.warning('fabric install failed in worker %s: %s', worker_id, e)

    _start_orphan_monitor(main_pid)

    context = zmq.Context()
    vent_recv = context.socket(zmq.PULL)
    vent_recv.connect(vent_addr)
    control_recv = context.socket(zmq.SUB)
    control_recv.setsockopt(zmq.SUBSCRIBE, b'')
    control_recv.connect(control_addr)

    finished = {'flag': False}

    def check_finished():
        """Also polled while blocked on a full ring, so shutdown never
        deadlocks against an unconsumed results transport."""
        if not finished['flag'] and control_recv.poll(0):
            if control_recv.recv() == CONTROL_FINISHED:
                finished['flag'] = True
        return finished['flag']

    ring = None
    result_send = None
    if ring_name is not None:
        from petastorm_tpu.native.shm_ring import ShmRing
        ring = ShmRing.attach(ring_name)

        def send(kind, seq, payload=b''):
            ring.write2(ring_header(kind, seq), payload, stop_check=check_finished)
    else:
        result_send = context.socket(zmq.PUSH)
        result_send.setsockopt(zmq.SNDHWM, results_hwm)
        result_send.connect(result_addr)

        def send(kind, seq, payload=b''):
            seq_bytes = b'' if seq is None else str(seq).encode()
            result_send.send_multipart([kind, seq_bytes, payload])

    current = {'seq': None}  # dispatch id of the item being processed, for message tagging

    last_hb = {'t': 0.0}

    def send_heartbeat(busy, blocking=False):
        """Liveness + ownership beacon. Claim beacons (``busy`` set, blocking)
        MUST land — they are what makes a crashed item requeueable; idle
        beacons are best-effort and skipped when the transport is congested
        (a congested transport means results are flowing, which is liveness
        evidence in itself)."""
        if heartbeat_interval_s is None:
            return
        payload = pickle.dumps({'worker_id': worker_id, 'pid': os.getpid(), 'busy': busy},
                               protocol=pickle.HIGHEST_PROTOCOL)
        try:
            if ring is not None:
                header = ring_header(MSG_HEARTBEAT, None)
                if blocking:
                    ring.write2(header, payload, stop_check=check_finished)
                else:
                    ring.try_write2(header, payload)
            elif blocking:
                result_send.send_multipart([MSG_HEARTBEAT, b'', payload])
            else:
                result_send.send_multipart([MSG_HEARTBEAT, b'', payload], flags=zmq.NOBLOCK)
        except zmq.Again:
            return
        last_hb['t'] = time.monotonic()

    def _blob_backpressure(incoming):
        """The byte analog of the ring's capacity bound: blobs are unlinked on
        read, so the shared directory's total size IS the pool's unconsumed
        backlog. Block (stop-aware) until the new blob fits the budget."""
        while True:
            try:
                backlog = 0
                for e in os.scandir(blob_dir):
                    try:
                        backlog += e.stat().st_size
                    except FileNotFoundError:
                        # consumer unlinked the blob mid-scan — the normal
                        # contended condition, not a shutdown; keep summing
                        continue
            except OSError:
                return  # dir swept (shutdown race): the write will fail loudly
            if backlog + incoming <= _BLOB_BUDGET_BYTES or backlog == 0:
                return
            if check_finished():
                return
            time.sleep(0.002)

    # persistent tmpfs exhaustion must not degrade into a warn+retry treadmill
    # on every message: give up on the sidechannel after a few consecutive
    # allocation failures (the in-band path keeps working regardless)
    blob_fail = {'consecutive': 0, 'disabled': False}
    _BLOB_DISABLE_AFTER = 3

    def _note_blob_failure(e):
        blob_fail['consecutive'] += 1
        if blob_fail['consecutive'] >= _BLOB_DISABLE_AFTER:
            blob_fail['disabled'] = True
            logger.warning('blob allocation failed %d times (%s); disabling the '
                           '/dev/shm sidechannel for this worker',
                           blob_fail['consecutive'], e)
        else:
            logger.warning('blob allocation failed (%s); payload falling back '
                           'in-band', e)

    def _try_blob_write(parts, total):
        """Write an already-split payload into a fresh /dev/shm blob and send
        its name. False = allocation failed (noted; caller falls back in-band).
        posix_fallocate first: tmpfs exhaustion surfaces as a catchable ENOSPC
        here, NOT as a SIGBUS when an mmap write faults an unbackable page
        (same stance as the ring's pre-faulting create)."""
        import mmap
        _blob_backpressure(total)
        try:
            fd, path = tempfile.mkstemp(prefix='b', dir=blob_dir)
        except OSError as e:  # unwritable/vanished dir: degrade, not die
            _note_blob_failure(e)
            return False
        try:
            try:
                os.posix_fallocate(fd, 0, total)
                mm = mmap.mmap(fd, total)
            except OSError as e:  # ENOSPC / ENOMEM under pressure
                os.close(fd)
                os.unlink(path)
                _note_blob_failure(e)
                return False
            try:
                buf = serializer.write_parts_into(parts, mm)
                buf.release()  # the mmap refuses to close with live views
            finally:
                try:
                    mm.close()
                except BufferError:
                    pass  # a failed fill left live views; GC closes the map
                os.close(fd)
        except BaseException:
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        blob_fail['consecutive'] = 0
        send(MSG_BLOB, current['seq'], path.encode())
        return True

    def reserve_block(meta_entries, payload_max):
        """In-place publish channel (docs/native.md): reserve a CONTIGUOUS
        ring slot, frame the serializer header for a column layout known
        AHEAD of decode, and hand the payload region back so the fused
        native decode assembles the batch directly in the memory the
        consumer maps — the publish is then a header write, not a copy.
        Returns ``(payload_view, commit, abort)`` or None when the transport
        or serializer cannot serve it (callers use the copy path)."""
        if ring is None or not hasattr(serializer, 'frame_for_layout'):
            return None
        prefix = serializer.frame_for_layout(meta_entries)
        if prefix is None:
            return None
        header = ring_header(MSG_DATA, current['seq'])
        total = len(header) + len(prefix) + payload_max
        try:
            mv = ring.reserve(total, stop_check=check_finished)
        except ValueError:
            return None  # can never fit this ring: blob/in-band path instead
        if mv is None:
            return None  # shutdown while waiting for space
        base = len(header) + len(prefix)
        mv[:len(header)] = header
        mv[len(header):base] = prefix

        def commit(actual_payload=payload_max):
            ring.commit(base + actual_payload)

        return mv[base:], commit, ring.abort

    def publish(data):
        # The payload is classified/framed ONCE (serialize_parts); every
        # channel consumes the same parts list. Routing: sub-blob-threshold
        # blocks gather-write STRAIGHT into the shm ring — one copy per byte
        # into warm pages, no b''.join staging, ragged image columns as raw
        # cell buffers instead of a pickle of the pixels. Blocks at/above the
        # threshold ride the /dev/shm blob sidechannel: its consumer views
        # are COW-mmap lazy (no upfront read-out copy), which beats a ring
        # copy-out for multi-MB payloads. Everything else goes in-band.
        blob_live = (blob_dir is not None and not blob_fail['disabled'])
        parts = (serializer.serialize_parts(data)
                 if hasattr(serializer, 'serialize_parts') else None)
        if parts is not None:
            total = serializer.parts_size(parts)
            fits_ring = ring is not None and total + 17 <= ring.capacity  # 9B+8B framing
            if fits_ring and (not blob_live or total < blob_threshold):
                ring.writev([ring_header(MSG_DATA, current['seq'])] + parts,
                            stop_check=check_finished)
                return
            if blob_live and total >= blob_threshold and _try_blob_write(parts, total):
                return
            send(MSG_DATA, current['seq'], serializer.join_parts(parts))
            return
        send(MSG_DATA, current['seq'], serializer.serialize(data))

    # workers probe this attribute for the fused in-place mode; non-ring
    # transports simply leave it returning None from the ring check above
    publish.reserve_block = reserve_block

    def flush_telemetry():
        """Ship this process's cumulative metrics snapshot (and drained trace
        events) to the main process over the results channel. Sent after each
        completed item: row groups are coarse, so the extra ~1KB message is
        noise next to the payloads, and cumulative snapshots make delivery
        loss-tolerant (the latest one supersedes all prior)."""
        if not obs.counters_on():
            return
        try:
            rec = {'pid': os.getpid(), 'metrics': obs.snapshot()}
            if obs.spans_on():
                rec['events'] = obs.drain_trace_events()
            send(MSG_METRICS, None, pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL))
        except Exception as e:  # noqa: BLE001 - telemetry is best-effort: a shutdown
            # race here must not resend MSG_DONE/MSG_ERROR and corrupt item accounting
            logger.debug('telemetry flush failed: %s', e)

    worker = worker_class(worker_id, publish, worker_setup_args)
    send(MSG_STARTED, None)
    send_heartbeat(None)

    poller = zmq.Poller()
    poller.register(vent_recv, zmq.POLLIN)
    poller.register(control_recv, zmq.POLLIN)

    try:
        while True:
            events = dict(poller.poll(100))
            if control_recv in events or finished['flag']:
                if finished['flag'] or control_recv.recv() == CONTROL_FINISHED:
                    break
            if vent_recv in events:
                dispatch, args, kwargs, trace_ctx = vent_recv.recv_pyobj()
                current['seq'] = dispatch
                # claim beacon FIRST: if this item kills the process, the
                # supervisor knows exactly what to requeue
                send_heartbeat(dispatch, blocking=True)
                try:
                    # the item wrapper stage keeps the flight recorder's
                    # activity slot non-empty for the whole item, so a death
                    # before the worker's first inner stage still names a
                    # dying stage (and the hang watchdog covers fault hooks)
                    with obs.stage('item', cat='worker', dispatch=dispatch):
                        faults.on_item(kwargs)
                        # the item's TraceContext (minted in the main process)
                        # becomes this thread's active context: worker stages
                        # land in the item's cross-process span tree, and the
                        # events ship back on the existing MSG_METRICS piggyback
                        with obs.use_trace(trace_ctx):
                            worker.process(*args, **kwargs)
                    send(MSG_DONE, current['seq'])
                    flush_telemetry()
                except Exception:  # noqa: BLE001 - forwarded to the main process
                    exc = sys.exc_info()[1]
                    logger.exception('Worker %d failed', worker_id)
                    tb = format_exception_tb(exc)
                    report = {'tb': tb, 'worker_id': worker_id, 'pid': os.getpid()}
                    try:
                        blob = pickle.dumps(dict(report, exc=exc))
                    except Exception:  # unpicklable exception: forward a summary
                        blob = pickle.dumps(dict(report, exc=RuntimeError(
                            '{}: {}'.format(type(exc).__name__, exc))))
                    # completion accounting for a failed item happens on the
                    # supervisor side (requeue/quarantine/raise) — no MSG_DONE here
                    send(MSG_ERROR, current['seq'], blob)
                    flush_telemetry()
                # no trailing idle beacon: the MSG_DONE/MSG_ERROR message itself
                # clears the claim on the supervisor side (ordered transport),
                # keeping supervision at ONE extra message per item
                current['seq'] = None
            elif heartbeat_interval_s is not None \
                    and time.monotonic() - last_hb['t'] >= heartbeat_interval_s:
                send_heartbeat(None)
    finally:
        worker.shutdown()
        if ring is not None:
            ring.close()
        for sock in (vent_recv, result_send, control_recv):
            if sock is not None:
                sock.close()
        context.term()


def _start_orphan_monitor(main_pid):
    """Kill this worker when the main process disappears
    (reference process_pool.py:324-331)."""

    def monitor():
        while True:
            try:
                os.kill(main_pid, 0)
            except OSError:
                logger.warning('Main process %d is gone; worker exiting', main_pid)
                os._exit(1)
            time.sleep(1.0)

    threading.Thread(target=monitor, daemon=True).start()
