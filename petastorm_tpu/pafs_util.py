"""Shared ``pyarrow.fs.FileSystemHandler`` delegation base.

Three wrappers in this codebase present a python object as a genuine pyarrow
filesystem (``PyFileSystem``): the HA-HDFS failover client
(``hdfs/namenode.py``), the transient-retry object-store wrapper
(``retry.py``), and the fault-injecting test filesystem. The delegation
boilerplate — one method per handler op, plus the compression subtlety on
output opens — lives here ONCE so a pyarrow handler-API change (a new
abstract method, a changed kwarg) is fixed in one place.
"""

from __future__ import annotations

import pyarrow.fs as pafs


class DelegatingHandler(pafs.FileSystemHandler):
    """Delegates every handler op to ``self.fs`` (a pyarrow filesystem or any
    object exposing the same method surface) through the :meth:`_invoke` hook.

    Subclasses override :meth:`_invoke` for cross-cutting behavior (retries,
    failover, fault injection) and individual methods for op-specific behavior.
    """

    def __init__(self, fs):
        self.fs = fs

    def _invoke(self, fn, *args, **kwargs):
        return fn(*args, **kwargs)

    # -- identity ----------------------------------------------------------

    def __eq__(self, other):
        if type(other) is type(self):
            return self.fs == other.fs
        return NotImplemented

    def __ne__(self, other):
        eq = self.__eq__(other)
        return eq if eq is NotImplemented else not eq

    def __hash__(self):
        # keep every handler (and the PyFileSystem wrapping it) hashable:
        # __eq__ without __hash__ would set __hash__ = None (PT600). The
        # delegate fs cannot participate — pyarrow FileSystems are unhashable
        return hash(type(self))

    def get_type_name(self):
        return 'delegating+' + self.fs.type_name

    def normalize_path(self, path):
        return self.fs.normalize_path(path)

    # -- metadata ops ------------------------------------------------------

    def get_file_info(self, paths):
        return self._invoke(self.fs.get_file_info, paths)

    def get_file_info_selector(self, selector):
        return self._invoke(self.fs.get_file_info, selector)

    def create_dir(self, path, recursive):
        self._invoke(self.fs.create_dir, path, recursive=recursive)

    def delete_dir(self, path):
        self._invoke(self.fs.delete_dir, path)

    def delete_dir_contents(self, path, missing_dir_ok=False):
        self._invoke(self.fs.delete_dir_contents, path, missing_dir_ok=missing_dir_ok)

    def delete_root_dir_contents(self):
        self._invoke(self.fs.delete_dir_contents, '/', accept_root_dir=True)

    def delete_file(self, path):
        self._invoke(self.fs.delete_file, path)

    def move(self, src, dest):
        self._invoke(self.fs.move, src, dest)

    def copy_file(self, src, dest):
        self._invoke(self.fs.copy_file, src, dest)

    # -- streams -----------------------------------------------------------

    def open_input_stream(self, path):
        return self._invoke(self.fs.open_input_stream, path)

    def open_input_file(self, path):
        return self._invoke(self.fs.open_input_file, path)

    def open_output_stream(self, path, metadata):
        # compression=None: the outer PyFileSystem already applies
        # suffix-detected compression; the inner default of 'detect' would
        # stack a second compressor on e.g. *.gz paths
        return self._invoke(self.fs.open_output_stream, path,
                            compression=None, metadata=metadata)

    def open_append_stream(self, path, metadata):
        return self._invoke(self.fs.open_append_stream, path,
                            compression=None, metadata=metadata)
