"""User-supplied row/batch transforms executed on the decode workers.

Parity: /root/reference/petastorm/transform.py:19-64 (``TransformSpec``,
``transform_schema``). The transform runs on the CPU host inside the worker pool,
*before* batches are staged toward the TPU, so its cost overlaps device compute.
"""

from __future__ import annotations

from petastorm_tpu.unischema import Unischema, UnischemaField


class TransformSpec(object):
    """Describes a transform applied to each row dict (row readers) or each
    column batch dict (batch readers) on the worker.

    :param func: callable taking a row dict (or dict of column arrays for batch
        readers) and returning the transformed dict. May be ``None`` if only
        field editing/removal is needed.
    :param edit_fields: list of :class:`UnischemaField` (or
        ``(name, numpy_dtype, shape, nullable)`` tuples) added/replaced by ``func``.
    :param removed_fields: names of fields ``func`` removes.
    :param selected_fields: if not ``None``, an explicit post-transform field-name
        whitelist. (Note: the resulting schema's fields are name-sorted, as in any
        Unischema — selection controls membership, not ordering.)
    :param batched: when True, ``func`` receives a dict of whole columns (one
        ``[N, ...]`` array / object column per field) even on row readers, and
        must return the same — no per-row dict is ever materialized, keeping the
        worker's hot path columnar. Batch readers always pass columns to
        ``func`` regardless of this flag.
    :param image_decode_hints: ``{field_name: (min_h, min_w)}`` — a promise that
        ``func`` will downscale these image fields to at most that size, which
        lets the decode worker use scaled JPEG decode (libjpeg m/8 DCT scaling:
        images arrive at the smallest scale still covering the minimum, so most
        pixels of a large photo are never computed). ``func`` must therefore
        accept images of any size >= the hint (or the original size, if
        smaller) — exactly what a resize-to-target transform does. PNG fields
        are unaffected (no scaled decode exists for the format).
    :param image_resize: ``{field_name: (out_h, out_w)}`` — resize these image
        fields to EXACTLY that size during decode, before ``func`` runs (which
        therefore doesn't need its own resize). The whole column decodes +
        area-resamples in one GIL-released native call straight into a single
        ``[N, out_h, out_w, C]`` allocation (OpenCV per-image fallback when the
        native codec is unavailable), removing the per-row Python resize from
        the host hot loop. Implies the scaled-JPEG-decode hint for the field.
        The post-transform schema's shape for the field is updated
        automatically unless ``edit_fields`` overrides it.
    """

    def __init__(self, func=None, edit_fields=None, removed_fields=None, selected_fields=None,
                 batched=False, image_decode_hints=None, image_resize=None):
        self.func = func
        self.edit_fields = [self._as_field(f) for f in (edit_fields or [])]
        self.removed_fields = list(removed_fields or [])
        self.selected_fields = list(selected_fields) if selected_fields is not None else None
        self.batched = batched
        self.image_decode_hints = dict(image_decode_hints or {})
        self.image_resize = {}
        for name, size in (image_resize or {}).items():
            try:
                # a str would pass len()==2 per-character ('24' -> (2, 4))
                ok = (not isinstance(size, (str, bytes))
                      and len(size) == 2 and int(size[0]) >= 1 and int(size[1]) >= 1)
            except (TypeError, ValueError):  # scalar (no len) or non-numeric elements
                ok = False
            if not ok:
                raise ValueError('image_resize[{!r}] must be a positive (out_h, out_w), '
                                 'got {!r}'.format(name, size))
            self.image_resize[name] = (int(size[0]), int(size[1]))
            # resizing to the target IS the downscale promise scaled JPEG
            # decode needs; an explicit hint (if any) wins
            self.image_decode_hints.setdefault(name, self.image_resize[name])

    @staticmethod
    def _as_field(field_or_tuple):
        if isinstance(field_or_tuple, UnischemaField):
            return field_or_tuple
        name, numpy_dtype, shape, nullable = field_or_tuple
        return UnischemaField(name, numpy_dtype, shape, nullable=nullable)


def transform_schema(schema, transform_spec):
    """Derive the post-transform schema (reference transform.py:43-64)."""
    removed = set(transform_spec.removed_fields)
    edited = {f.name: f for f in transform_spec.edit_fields}
    fields = {f.name: f for f in schema if f.name not in removed}
    fields.update(edited)
    for name, (out_h, out_w) in getattr(transform_spec, 'image_resize', {}).items():
        # validate against the ORIGINAL schema (a resized field may legitimately
        # be consumed/removed by func): decode-time resize only happens for
        # codecs that implement it, so anything else must fail loudly here
        # instead of silently yielding unresized data against a lying schema
        src = schema.fields.get(name)
        if src is None:
            raise ValueError('image_resize refers to unknown field {!r}'.format(name))
        if not getattr(src.codec, 'supports_image_resize', False):
            raise ValueError(
                'image_resize[{!r}]: field is stored with {}, which does not support '
                'decode-time resize (only image codecs do); resize it in the transform '
                'func instead'.format(name, type(src.codec).__name__))
        # decode-time resize pins the leading H, W dims; explicit edits win
        f = fields.get(name)
        if f is not None and name not in edited and f.shape is not None and len(f.shape) >= 2:
            fields[name] = UnischemaField(f.name, f.numpy_dtype,
                                          (out_h, out_w) + tuple(f.shape[2:]),
                                          f.codec, f.nullable)
    if transform_spec.selected_fields is not None:
        missing = [n for n in transform_spec.selected_fields if n not in fields]
        if missing:
            raise ValueError('selected_fields not present after transform: {}'.format(missing))
        fields = {n: fields[n] for n in transform_spec.selected_fields}
    return Unischema('{}_transformed'.format(schema.name), list(fields.values()))
