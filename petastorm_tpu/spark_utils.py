"""PySpark ingestion helpers (reference: petastorm/spark_utils.py:23-52).

pyspark is an optional dependency: :func:`dataset_as_rdd` never imports it —
it duck-types the session object it is handed (anything exposing
``sparkContext.defaultParallelism``/``parallelize`` works, which also keeps the
shard arithmetic unit-testable without a pyspark install) and raises TypeError
for non-session arguments. The local analog — reading a dataset into a pandas
DataFrame — needs no Spark and is provided as :func:`dataset_as_dataframe`.
"""

from __future__ import annotations


def dataset_as_rdd(dataset_url, spark_session, schema_fields=None):
    """Dataset -> RDD of decoded row namedtuples (reference spark_utils.py:23-52).

    Each Spark partition opens its own reader over one shard of the dataset
    (share-nothing, matching the reader's ``cur_shard`` arithmetic).
    """
    # duck-typed: anything exposing sparkContext.{defaultParallelism,
    # parallelize} works, which keeps the shard arithmetic unit-testable
    # without a pyspark install (tests/test_tools.py stubs the session)
    sc = getattr(spark_session, 'sparkContext', None)
    if sc is None:
        raise TypeError(
            'dataset_as_rdd needs a SparkSession-like object with a sparkContext '
            '(got {!r}). If pyspark is not installed, use dataset_as_dataframe '
            '(pandas) or make_reader directly.'.format(type(spark_session).__name__))

    from petastorm_tpu.etl.dataset_metadata import get_schema_from_dataset_url

    schema = get_schema_from_dataset_url(dataset_url)
    fields = schema_fields if schema_fields is not None else list(schema.fields)
    num_partitions = sc.defaultParallelism

    def _read_shard(shard_index):
        from petastorm_tpu import make_reader
        from petastorm_tpu.errors import NoDataAvailableError
        try:
            with make_reader(dataset_url, schema_fields=fields, reader_pool_type='dummy',
                             cur_shard=shard_index, shard_count=num_partitions,
                             num_epochs=1) as reader:
                return list(reader)
        except NoDataAvailableError as e:
            # more Spark partitions than row groups: an empty partition is a
            # normal condition here (the reference reader warns and yields
            # nothing, spark_utils.py:23-52) — the Reader's loud no-data
            # contract stays for direct users
            import logging
            logging.getLogger(__name__).warning(
                'Empty shard %d/%d for %s: %s', shard_index, num_partitions, dataset_url, e)
            return []

    return sc.parallelize(range(num_partitions), num_partitions).flatMap(_read_shard)


def dataset_as_dataframe(dataset_url, schema_fields=None):
    """Dataset -> pandas DataFrame (decoded rows). The Spark-free analog of
    :func:`dataset_as_rdd` for local workflows."""
    import pandas as pd

    from petastorm_tpu import make_reader

    with make_reader(dataset_url, schema_fields=schema_fields, num_epochs=1) as reader:
        rows = [row._asdict() for row in reader]
    return pd.DataFrame(rows)
