"""Deterministic, seeded fault injection for the worker and storage planes.

The test-suite and ``bench.py``/``bench_scaling.py --chaos`` companion to the
supervision layer (``docs/robustness.md``): faults fire at well-defined hook
points in the real code paths — never in test doubles — so what recovers in a
chaos run is exactly what recovers in production.

Hook points:

* **item hooks** — every pool's worker loop calls :func:`on_item` with the
  ventilated kwargs immediately before ``worker.process``. A
  :class:`FaultPlan` keyed on ``piece_index`` can kill the worker process
  (``SIGKILL`` mid-item, process pools only) or raise
  :class:`FaultInjectedError` inside decode.
* **storage hook** — :class:`petastorm_tpu.retry.RetryPolicy` consults
  :data:`petastorm_tpu.retry.FAULT_POINT` before every attempt; installing a
  plan with ``storage_fail_first > 0`` makes the first N retried storage
  operations per process raise a transient ``OSError`` — exercising the
  backoff path end to end.

Determinism: one-shot faults (``kill_once``, ``error_times``) coordinate
across worker respawns and spawned processes through sentinel files in
``state_dir`` (``O_CREAT|O_EXCL``: exactly one attempt wins each shot), so a
seeded run replays the identical failure schedule every time. Plans are
picklable and ride the pool's ``worker_setup_args`` into spawned workers.

Usage::

    from petastorm_tpu import faults
    plan = faults.FaultPlan(kill_items=(3,), state_dir=tmpdir)
    faults.install(plan)
    try:
        ...  # build readers / run benches; workers inherit the plan
    finally:
        faults.uninstall()
"""

from __future__ import annotations

import logging
import os
import signal

from petastorm_tpu.errors import PetastormTpuError

logger = logging.getLogger(__name__)


class FaultInjectedError(PetastormTpuError):
    """The deterministic error :func:`on_item` raises for ``error_items`` (and
    for ``kill_items`` outside a spawned worker process, where SIGKILL would
    take down the caller's whole process)."""


class FaultPlan(object):
    """Picklable fault schedule.

    :param kill_items: piece indices whose processing SIGKILLs the worker
        process mid-item (process pools; degrades to
        :class:`FaultInjectedError` in thread/dummy pools).
    :param kill_once: each ``kill_items`` entry fires on its first attempt
        only — the requeued attempt succeeds (the exactly-once recovery
        scenario). Requires ``state_dir``.
    :param error_items: piece indices that raise :class:`FaultInjectedError`
        inside the worker.
    :param error_times: fire each ``error_items`` entry only on its first N
        attempts (requires ``state_dir``); ``None`` = every attempt — a
        *poison* item.
    :param segv_items: piece indices whose processing raises ``SIGSEGV`` in
        the worker process mid-item — a native-crash stand-in (a decoder
        segfault) for the flight-recorder post-mortem path: unlike SIGKILL,
        the crash leaves a faulthandler sidecar behind. Process pools only;
        degrades to :class:`FaultInjectedError` elsewhere. One-shot per
        index (requires ``state_dir``) unless ``segv_once=False``.
    :param hang_items: piece indices whose processing wedges for ``hang_s``
        seconds inside a ``fault.fault_hang`` stage before proceeding — the
        deterministic stall the hang watchdog must catch. One-shot per index
        (requires ``state_dir``) unless ``hang_once=False``.
    :param hang_s: how long each ``hang_items`` entry sleeps.
    :param storage_fail_first: the first N storage operations per process
        routed through :meth:`petastorm_tpu.retry.RetryPolicy.call` raise a
        transient ``OSError(ECONNRESET)``.
    :param state_dir: directory for cross-process one-shot coordination files.
    """

    def __init__(self, kill_items=(), kill_once=True, error_items=(),
                 error_times=None, segv_items=(), segv_once=True,
                 hang_items=(), hang_once=True, hang_s=5.0,
                 storage_fail_first=0, state_dir=None):
        self.kill_items = tuple(kill_items)
        self.kill_once = bool(kill_once)
        self.error_items = tuple(error_items)
        self.error_times = error_times
        self.segv_items = tuple(segv_items)
        self.segv_once = bool(segv_once)
        self.hang_items = tuple(hang_items)
        self.hang_once = bool(hang_once)
        self.hang_s = float(hang_s)
        self.storage_fail_first = int(storage_fail_first)
        self.state_dir = state_dir
        if (self.kill_items and self.kill_once) or \
                (self.error_items and self.error_times is not None) or \
                (self.segv_items and self.segv_once) or \
                (self.hang_items and self.hang_once):
            if not state_dir:
                raise ValueError('one-shot faults (kill_once / error_times / '
                                 'segv_once / hang_once) need a state_dir for '
                                 'cross-process coordination')

    def __repr__(self):
        return ('FaultPlan(kill_items={}, kill_once={}, error_items={}, '
                'error_times={}, segv_items={}, hang_items={}, hang_s={}, '
                'storage_fail_first={})'.format(
                    self.kill_items, self.kill_once, self.error_items,
                    self.error_times, self.segv_items, self.hang_items,
                    self.hang_s, self.storage_fail_first))


#: the process-wide installed plan (None = fault injection disabled, the
#: production state: on_item is one attribute load + None compare per ITEM)
_PLAN = None
_IN_SPAWNED_WORKER = False
_storage_faults_fired = 0


def install(plan):
    """Install ``plan`` process-wide and arm the storage hook. Returns the
    plan. ``install(None)`` is equivalent to :func:`uninstall`."""
    global _PLAN, _storage_faults_fired
    from petastorm_tpu import retry
    _PLAN = plan
    _storage_faults_fired = 0
    retry.FAULT_POINT = _storage_fault_point if (
        plan is not None and plan.storage_fail_first > 0) else None
    return plan


def uninstall():
    """Remove the installed plan and disarm every hook."""
    install(None)


def get_plan():
    return _PLAN


def mark_in_spawned_worker():
    """Called by the process pool's worker bootstrap: SIGKILL faults are only
    honored in a spawned worker process (anywhere else they would kill the
    consumer — thread/dummy pools degrade kills to raised errors)."""
    global _IN_SPAWNED_WORKER
    _IN_SPAWNED_WORKER = True


def _claim_one_shot(state_dir, token):
    """True exactly once per token across all processes sharing state_dir."""
    try:
        fd = os.open(os.path.join(state_dir, token), os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except FileExistsError:
        return False
    except OSError as e:
        # unusable state dir: fail open (no fault) rather than nondeterminism
        logger.warning('fault state_dir unusable (%s); skipping one-shot fault', e)
        return False
    os.close(fd)
    return True


def on_item(kwargs):
    """Item-level fault hook, called by every pool's worker loop with the
    ventilated kwargs right before ``worker.process``. No-op without an
    installed plan."""
    plan = _PLAN
    if plan is None:
        return
    piece_index = kwargs.get('piece_index')
    if piece_index is None:
        return
    if piece_index in plan.kill_items:
        fire = (not plan.kill_once or
                _claim_one_shot(plan.state_dir, 'kill_{}'.format(piece_index)))
        if fire:
            if _IN_SPAWNED_WORKER:
                logger.warning('fault injection: SIGKILL on piece_index=%s (pid %s)',
                               piece_index, os.getpid())
                os.kill(os.getpid(), signal.SIGKILL)
            raise FaultInjectedError(
                'injected kill on piece_index={} (degraded to an error: not a '
                'spawned worker process)'.format(piece_index))
    if piece_index in plan.segv_items:
        fire = (not plan.segv_once or
                _claim_one_shot(plan.state_dir, 'segv_{}'.format(piece_index)))
        if fire:
            if _IN_SPAWNED_WORKER:
                logger.warning('fault injection: SIGSEGV on piece_index=%s (pid %s)',
                               piece_index, os.getpid())
                # a real signal, not a python exception: faulthandler (armed
                # by the flight recorder) writes the crash sidecar exactly as
                # it would for a native decoder bug
                os.kill(os.getpid(), signal.SIGSEGV)
            raise FaultInjectedError(
                'injected segfault on piece_index={} (degraded to an error: not '
                'a spawned worker process)'.format(piece_index))
    if piece_index in plan.hang_items:
        fire = (not plan.hang_once or
                _claim_one_shot(plan.state_dir, 'hang_{}'.format(piece_index)))
        if fire:
            import time
            from petastorm_tpu import observability as obs
            logger.warning('fault injection: hanging %.1fs on piece_index=%s (pid %s)',
                           plan.hang_s, piece_index, os.getpid())
            # wedge inside a named stage so the watchdog's activity slot
            # shows fault.fault_hang in the stack dump it takes
            with obs.stage('fault_hang', cat='fault'):
                time.sleep(plan.hang_s)
    if piece_index in plan.error_items:
        if plan.error_times is None:
            raise FaultInjectedError('injected poison on piece_index={}'.format(piece_index))
        for shot in range(plan.error_times):
            if _claim_one_shot(plan.state_dir, 'err_{}_{}'.format(piece_index, shot)):
                raise FaultInjectedError(
                    'injected transient error {}/{} on piece_index={}'.format(
                        shot + 1, plan.error_times, piece_index))


def _storage_fault_point():
    """The hook :meth:`RetryPolicy.call` invokes before each attempt."""
    global _storage_faults_fired
    plan = _PLAN
    if plan is None or _storage_faults_fired >= plan.storage_fail_first:
        return
    _storage_faults_fired += 1
    import errno
    raise OSError(errno.ECONNRESET,
                  'injected transient storage fault {}/{}'.format(
                      _storage_faults_fired, plan.storage_fail_first))


# ---------------------------------------------------------------------------
# network faults for the chunk-transfer fabric (docs/fabric.md)
# ---------------------------------------------------------------------------

class NetFaultPlan(object):
    """Seeded network faults for the peer-to-peer chunk fabric.

    Each budget arms the first N occurrences of its hook point — connect
    attempts for ``refuse_connects``, payload sends for the rest — so a chaos
    run replays the identical failure schedule. With a ``state_dir`` the
    shots coordinate across processes through the same ``O_CREAT|O_EXCL``
    sentinel files item faults use; without one they count per process.

    :param refuse_connects: first N fabric connect attempts raise
        ``ConnectionRefusedError`` (the peer's port is gone).
    :param reset_payloads: first N payload sends abort mid-transfer with
        ``ConnectionResetError`` after a partial body — the receiver sees a
        torn frame and must discard it.
    :param truncate_payloads: first N payload sends deliver only half the
        body then close cleanly — a byte-level truncation the content hash
        must catch.
    :param corrupt_payloads: first N payload sends flip bytes in the body —
        length-preserving corruption only the hash can catch.
    :param stall_payloads: first N payload sends sleep ``stall_s`` before the
        body — the slow-peer case the client's deadline budget must bound.
    :param stall_s: how long each ``stall_payloads`` shot sleeps.
    :param state_dir: directory for cross-process one-shot coordination.
    """

    def __init__(self, refuse_connects=0, reset_payloads=0,
                 truncate_payloads=0, corrupt_payloads=0, stall_payloads=0,
                 stall_s=5.0, state_dir=None):
        self.refuse_connects = int(refuse_connects)
        self.reset_payloads = int(reset_payloads)
        self.truncate_payloads = int(truncate_payloads)
        self.corrupt_payloads = int(corrupt_payloads)
        self.stall_payloads = int(stall_payloads)
        self.stall_s = float(stall_s)
        self.state_dir = state_dir
        self._fired = {}

    def __repr__(self):
        return ('NetFaultPlan(refuse_connects={}, reset_payloads={}, '
                'truncate_payloads={}, corrupt_payloads={}, stall_payloads={}, '
                'stall_s={})'.format(
                    self.refuse_connects, self.reset_payloads,
                    self.truncate_payloads, self.corrupt_payloads,
                    self.stall_payloads, self.stall_s))


_NET_PLAN = None


def install_net(plan):
    """Install a :class:`NetFaultPlan` process-wide (``None`` disarms)."""
    global _NET_PLAN
    _NET_PLAN = plan
    return plan


def uninstall_net():
    install_net(None)


def get_net_plan():
    return _NET_PLAN


def _claim_counted(plan, kind, budget):
    """True for the first ``budget`` calls with this ``kind`` — coordinated
    across processes when the plan has a state_dir, per-process otherwise."""
    if budget <= 0:
        return False
    if plan.state_dir:
        for shot in range(budget):
            if _claim_one_shot(plan.state_dir, 'net_{}_{}'.format(kind, shot)):
                return True
        return False
    fired = plan._fired.get(kind, 0)
    if fired < budget:
        plan._fired[kind] = fired + 1
        return True
    return False


def on_net_connect():
    """Connect-time hook: the fabric client calls this immediately before
    ``socket.connect``. No-op without an installed net plan."""
    plan = _NET_PLAN
    if plan is None:
        return
    if _claim_counted(plan, 'refuse', plan.refuse_connects):
        raise ConnectionRefusedError(
            'injected connection refusal (fabric net fault)')


def net_payload_action():
    """Payload-send hook: the fabric server consults this once per payload
    and honors the returned action. Returns ``('reset'|'truncate'|'corrupt'|
    'stall', stall_s_or_None)`` or None. At most one action fires per call;
    stalls win over the destructive actions so a stalled transfer can also
    be the one a chaos driver SIGKILLs mid-flight."""
    plan = _NET_PLAN
    if plan is None:
        return None
    if _claim_counted(plan, 'stall', plan.stall_payloads):
        return ('stall', plan.stall_s)
    if _claim_counted(plan, 'reset', plan.reset_payloads):
        return ('reset', None)
    if _claim_counted(plan, 'truncate', plan.truncate_payloads):
        return ('truncate', None)
    if _claim_counted(plan, 'corrupt', plan.corrupt_payloads):
        return ('corrupt', None)
    return None


# ---------------------------------------------------------------------------
# elastic-pod host churn (docs/parallelism.md, "Elastic pod sharding")
# ---------------------------------------------------------------------------

class HostChurnPlan(object):
    """A deterministic kill/join schedule for an elastic pod of
    ``petastorm_tpu.elastic._hostproc`` subprocesses.

    :param kill_host: host id to SIGKILL (``None`` = no kill)
    :param kill_after_commits: fire the kill once the pod's commit
        scoreboard shows at least this many done markers — "mid-epoch" with
        a concrete, replayable definition
    :param join_host: host id to start right after the kill (``None`` = no
        join); the spawner callable is supplied by the driver
    """

    def __init__(self, kill_host=None, kill_after_commits=3, join_host=None):
        self.kill_host = kill_host
        self.kill_after_commits = int(kill_after_commits)
        self.join_host = join_host

    def __repr__(self):
        return ('HostChurnPlan(kill_host={!r}, kill_after_commits={}, '
                'join_host={!r})'.format(self.kill_host,
                                         self.kill_after_commits,
                                         self.join_host))


def count_committed(coord_dir):
    """Pod-wide committed row-group count: done markers across all epochs of
    an elastic coordination directory."""
    epochs_dir = os.path.join(coord_dir, 'epochs')
    total = 0
    try:
        epochs = os.listdir(epochs_dir)
    except OSError:
        return 0
    for epoch in epochs:
        try:
            total += len(os.listdir(os.path.join(epochs_dir, epoch, 'done')))
        except OSError:
            pass
    return total


def drive_host_churn(coord_dir, procs, plan, spawn_joiner=None,
                     timeout_s=60.0, poll_s=0.05):
    """Execute a :class:`HostChurnPlan` against running host subprocesses.

    Watches the pod's commit scoreboard under ``coord_dir``; once
    ``kill_after_commits`` markers exist, SIGKILLs ``procs[plan.kill_host]``
    (real process death: the lease goes stale, nobody cleans up) and then
    calls ``spawn_joiner()`` (which should start ``plan.join_host`` and
    return its process, added to ``procs``). Returns a timeline dict the
    caller can assert over / emit as a bench metric.
    """
    import time
    deadline = time.monotonic() + timeout_s
    timeline = {'plan': repr(plan), 'killed': None, 'joined': None,
                'commits_at_kill': None}
    if plan.kill_host is None and plan.join_host is None:
        return timeline
    while time.monotonic() < deadline:
        committed = count_committed(coord_dir)
        if committed >= plan.kill_after_commits:
            break
        time.sleep(poll_s)
    else:
        raise TimeoutError(
            'pod committed only {} row groups in {}s (wanted {} before the '
            'churn event)'.format(count_committed(coord_dir), timeout_s,
                                  plan.kill_after_commits))
    timeline['commits_at_kill'] = count_committed(coord_dir)
    if plan.kill_host is not None:
        victim = procs[plan.kill_host]
        logger.warning('host churn: SIGKILL %s (pid %s)', plan.kill_host,
                       victim.pid)
        os.kill(victim.pid, signal.SIGKILL)
        victim.wait()
        timeline['killed'] = plan.kill_host
    if plan.join_host is not None and spawn_joiner is not None:
        procs[plan.join_host] = spawn_joiner()
        timeline['joined'] = plan.join_host
    return timeline


__all__ = ['FaultInjectedError', 'FaultPlan', 'HostChurnPlan', 'NetFaultPlan',
           'count_committed', 'drive_host_churn', 'get_net_plan', 'get_plan',
           'install', 'install_net', 'mark_in_spawned_worker',
           'net_payload_action', 'on_item', 'on_net_connect', 'uninstall',
           'uninstall_net']
