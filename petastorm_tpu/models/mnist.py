"""Small MNIST convnet — the framework analog of the reference's
examples/mnist model (which lives in torch there)."""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train=True):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]  # HWC with a single channel
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x
