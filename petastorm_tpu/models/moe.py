"""Mixture-of-experts layer with expert parallelism (ep).

The reference has no model side at all (SURVEY.md §2.9); this exists so the
framework's parallelism story covers ep alongside dp/tp/sp: a GShard/Switch
style dense-dispatch MoE whose expert tensors are sharded over a mesh axis,
letting XLA partition the per-expert FFNs across devices and insert the
dispatch/combine collectives itself — the TPU-idiomatic formulation (einsum
dispatch masks + sharding constraints, no hand-rolled routing runtime).

Math (top-1 "switch" routing, public recipe — GShard arXiv:2006.16668,
Switch Transformer arXiv:2101.03961):

  * gate: softmax(Dense_E(token)); expert = argmax, gate_p = its probability
  * capacity C = ceil(tokens/E * capacity_factor); within each expert, tokens
    beyond C are DROPPED (their output is 0 — the caller's residual connection
    passes them through, the standard behavior)
  * dispatch [N, E, C] one-hot scatters tokens to expert slots; combine =
    dispatch * gate_p gathers expert outputs back
  * aux load-balancing loss = E * sum_e(fraction_tokens_e * mean_prob_e)
    (Switch eq. 4) — add ``aux_weight * aux_loss`` to the training objective
    to keep routing balanced.

With ``mesh``, the [E, C, D] expert tensors and [E, ...] expert weights carry
``P(expert_axis)`` sharding constraints: each device holds E/n experts and XLA
turns the dispatch/combine einsums into all-to-alls over ICI.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax
import jax.numpy as jnp


def expert_capacity(num_tokens, num_experts, capacity_factor):
    """C = ceil(tokens/experts * capacity_factor), clamped to [1, tokens]
    (the documented Switch formula — ceil AFTER the slack multiply, so
    fractional slack is not truncated away)."""
    capacity = int(math.ceil(num_tokens / num_experts * capacity_factor))
    return max(1, min(num_tokens, capacity))


class MoEMlp(nn.Module):
    """Drop-in MLP replacement: [B, T, D] -> ([B, T, D], aux_loss).

    :param num_experts: E; with ``mesh``, must be divisible by the
        ``expert_axis`` size.
    :param d_hidden: per-expert FFN hidden width.
    :param capacity_factor: slack over the perfectly-balanced per-expert load.
    :param mesh: optional ``jax.sharding.Mesh`` for expert parallelism.
    :param expert_axis: mesh axis name the experts shard over.
    """

    num_experts: int
    d_hidden: int
    capacity_factor: float = 1.25
    mesh: object = None
    expert_axis: str = 'expert'
    dtype: jnp.dtype = jnp.float32

    def _constrain(self, t, spec):
        if self.mesh is None:
            return t
        from jax.sharding import NamedSharding, PartitionSpec as P
        return jax.lax.with_sharding_constraint(
            t, NamedSharding(self.mesh, P(*spec)))

    @nn.compact
    def __call__(self, x):  # x: [B, T, D]
        if self.mesh is not None and self.num_experts % self.mesh.shape[self.expert_axis]:
            raise ValueError('num_experts ({}) must be divisible by the {!r} axis size '
                             '({})'.format(self.num_experts, self.expert_axis,
                                           self.mesh.shape[self.expert_axis]))
        b, t, d = x.shape
        n = b * t
        e = self.num_experts
        capacity = expert_capacity(n, e, self.capacity_factor)

        tokens = x.reshape(n, d).astype(jnp.float32)
        gate_logits = nn.Dense(e, dtype=jnp.float32, name='gate')(tokens)  # [N, E]
        probs = jax.nn.softmax(gate_logits, axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)                            # [N]
        gate_p = jnp.take_along_axis(probs, expert_idx[:, None], axis=1)[:, 0]

        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)          # [N, E]
        # slot position of each token within its expert (0-based), FIFO order
        position = jnp.cumsum(onehot, axis=0) * onehot - onehot            # [N, E]
        keep = onehot * (position < capacity)                              # [N, E]
        dispatch = keep[..., None] * jax.nn.one_hot(                       # [N, E, C]
            position.astype(jnp.int32), capacity, dtype=jnp.float32)
        combine = dispatch * gate_p[:, None, None]

        # Switch load-balancing aux loss: E * sum_e f_e * P_e
        frac_tokens = onehot.mean(axis=0)
        mean_probs = probs.mean(axis=0)
        aux_loss = e * jnp.sum(frac_tokens * mean_probs)

        # expert weights [E, ...] and expert tensors [E, C, ...] shard over
        # the expert axis; XLA inserts the dispatch/combine collectives
        w1 = self.param('w1', nn.initializers.lecun_normal(), (e, d, self.d_hidden))
        b1 = self.param('b1', nn.initializers.zeros, (e, self.d_hidden))
        w2 = self.param('w2', nn.initializers.lecun_normal(), (e, self.d_hidden, d))
        b2 = self.param('b2', nn.initializers.zeros, (e, d))
        espec = (self.expert_axis,)
        w1, b1 = self._constrain(w1, espec + (None, None)), self._constrain(b1, espec + (None,))
        w2, b2 = self._constrain(w2, espec + (None, None)), self._constrain(b2, espec + (None,))

        # routing/dispatch stays fp32 (standard — argmax/softmax robustness);
        # the expert FFN einsums, the bulk of the FLOPs, run in self.dtype
        xin = jnp.einsum('nec,nd->ecd', dispatch, tokens)
        xin = self._constrain(xin, espec + (None, None)).astype(self.dtype)
        h = jnp.einsum('ecd,edh->ech', xin, w1.astype(self.dtype)) \
            + b1[:, None, :].astype(self.dtype)
        h = nn.gelu(h)
        h = self._constrain(h, espec + (None, None))
        out = jnp.einsum('ech,ehd->ecd', h, w2.astype(self.dtype)) \
            + b2[:, None, :].astype(self.dtype)
        out = self._constrain(out, espec + (None, None))

        y = jnp.einsum('nec,ecd->nd', combine, out.astype(jnp.float32))
        return y.reshape(b, t, d).astype(x.dtype), aux_loss


class MoESequenceTransformer(nn.Module):
    """The sequence transformer with MoE MLPs — the ep measurement load:
    [B, T, F] NGram window stacks -> [B, num_classes], plus the summed
    load-balancing aux loss (add ``aux_weight`` of it to the objective)."""

    num_classes: int
    num_experts: int
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    capacity_factor: float = 1.25
    mesh: object = None
    expert_axis: str = 'expert'
    attention_fn: object = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=True):  # noqa: ARG002 - train-step parity
        from petastorm_tpu.models.transformer import SelfAttention

        x = x.astype(self.dtype)
        x = nn.Dense(self.d_model, dtype=self.dtype, name='embed')(x)
        pos = self.param('pos_embed', nn.initializers.normal(0.02),
                         (1, x.shape[1], self.d_model))
        x = x + pos.astype(self.dtype)
        aux_total = 0.0
        for i in range(self.num_layers):
            # the attention path is the SHARED SelfAttention sub-block — the
            # dense TransformerBlock uses the identical module, so masking/
            # dtype/validation fixes land in both model families at once
            x = SelfAttention(self.d_model, self.num_heads, self.attention_fn,
                              self.dtype, name='attn{}'.format(i))(x)
            h = nn.LayerNorm(dtype=self.dtype)(x)
            moe_out, aux = MoEMlp(num_experts=self.num_experts,
                                  d_hidden=4 * self.d_model,
                                  capacity_factor=self.capacity_factor,
                                  mesh=self.mesh, expert_axis=self.expert_axis,
                                  dtype=self.dtype, name='moe{}'.format(i))(h)
            x = x + moe_out  # dropped tokens ride the residual (standard)
            aux_total = aux_total + aux
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name='head')(x), aux_total
