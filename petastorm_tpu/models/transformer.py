"""Sequence transformer over NGram windows — the long-context model family.

The reference is a data library with no model side; its long-sequence story
ends at NGram readout (reference ngram.py, SURVEY.md §5). This module closes
the framework's long-context loop on the model side: a compact flax
transformer whose attention is PLUGGABLE — plain softmax attention on one
device, or either of the framework's context-parallel strategies when the
sequence axis is sharded over a mesh: exact blockwise **ring attention**
(petastorm_tpu.ops.ring_attention — each device holds T/n keys, k/v shards
rotate on the ICI ring via ppermute) or **Ulysses all-to-all**
(petastorm_tpu.ops.ulysses_attention — one all_to_all redistributes sequence
shards into head shards, local attention sees the full sequence). Both exact;
pick with ``context_parallelism='ring'|'ulysses'``.

End-to-end: ``make_reader(output='columnar', ngram=...)`` -> JaxDataLoader ->
``stack_ngram_time_axis`` -> [B, T, F] batches staged with
``NamedSharding(mesh, P('data', 'seq', None))`` -> this model under jit; XLA
inserts the data/seq collectives. ``bench_pod.py`` runs exactly this stack.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


def plain_attention(q, k, v):
    """Reference full softmax attention for unsharded runs; [B, H, T, D]."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum('bhqd,bhkd->bhqk', q * scale, k)
    return jnp.einsum('bhqk,bhkd->bhqd', jax.nn.softmax(logits, axis=-1), v)


class SelfAttention(nn.Module):
    """THE attention sub-block (pre-norm qkv -> heads -> ``attention_fn`` ->
    output projection), shared by the dense and MoE transformer blocks so the
    attention path cannot drift between them. Residual is applied here:
    returns ``x + attn_out``."""

    d_model: int
    num_heads: int
    attention_fn: callable = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [B, T, d_model]
        if self.d_model % self.num_heads:
            raise ValueError('d_model ({}) must be divisible by num_heads ({})'.format(
                self.d_model, self.num_heads))
        attn_fn = self.attention_fn or plain_attention
        head_dim = self.d_model // self.num_heads
        h = nn.LayerNorm(dtype=self.dtype)(x)
        qkv = nn.Dense(3 * self.d_model, dtype=self.dtype, name='qkv')(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)

        def heads(t):  # [B, T, d_model] -> [B, H, T, head_dim]
            b, s, _ = t.shape
            return t.reshape(b, s, self.num_heads, head_dim).transpose(0, 2, 1, 3)

        out = attn_fn(heads(q), heads(k), heads(v))
        out = out.transpose(0, 2, 1, 3).reshape(x.shape[0], x.shape[1], self.d_model)
        return x + nn.Dense(self.d_model, dtype=self.dtype, name='attn_out')(out)


class TransformerBlock(nn.Module):
    """Pre-norm block: attention + MLP with residuals. ``attention_fn`` is any
    ``(q, k, v) -> out`` on [B, H, T, D] — plain, ring, or ulysses."""

    d_model: int
    num_heads: int
    mlp_ratio: int = 4
    attention_fn: callable = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):  # x: [B, T, d_model]
        x = SelfAttention(self.d_model, self.num_heads, self.attention_fn,
                          self.dtype, name='attn')(x)
        h = nn.LayerNorm(dtype=self.dtype)(x)
        h = nn.Dense(self.mlp_ratio * self.d_model, dtype=self.dtype, name='mlp_up')(h)
        h = nn.gelu(h)
        x = x + nn.Dense(self.d_model, dtype=self.dtype, name='mlp_down')(h)
        return x


class SequenceTransformer(nn.Module):
    """[B, T, F] continuous features (NGram window stacks) -> [B, num_classes].

    Mean-pools over time for the head; with a seq-sharded input the pool is a
    cross-shard reduction XLA lowers to a psum on the mesh.
    """

    num_classes: int
    d_model: int = 64
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    attention_fn: callable = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=True):  # noqa: ARG002 - train kept for train-step parity
        x = x.astype(self.dtype)
        x = nn.Dense(self.d_model, dtype=self.dtype, name='embed')(x)
        # learned positional embedding over the window length (NGram windows
        # are fixed-length, so T is static under jit)
        pos = self.param('pos_embed', nn.initializers.normal(0.02),
                         (1, x.shape[1], self.d_model))
        x = x + pos.astype(self.dtype)
        for i in range(self.num_layers):
            x = TransformerBlock(self.d_model, self.num_heads, self.mlp_ratio,
                                 attention_fn=self.attention_fn, dtype=self.dtype,
                                 name='block{}'.format(i))(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = jnp.mean(x, axis=1)  # [B, d_model]; psum across seq shards
        return nn.Dense(self.num_classes, dtype=jnp.float32, name='head')(x)


def make_sequence_transformer(num_classes, mesh=None, seq_axis='seq', batch_axis='data',
                              d_model=64, num_heads=4, num_layers=2, dtype=jnp.float32,
                              context_parallelism='ring'):
    """Build the model; with ``mesh`` the attention runs context-parallel over
    ``mesh[seq_axis]``, else plain full attention. The returned module drops
    into ``models.train.create_train_state`` / ``make_train_step`` unchanged.

    ``context_parallelism`` picks the sharded strategy:
      * ``'ring'`` — blockwise ring attention (O(T/n) memory per device,
        k/v shards rotate on the ICI ring; scales to extreme T);
      * ``'ulysses'`` — all-to-all head redistribution (two all-to-all phases,
        full-T k/v per device for H/n heads; needs ``num_heads`` divisible by
        the ``seq_axis`` size).
    Both compute exact attention — they are interchangeable and tested equal.

    SPMD shape constraint (standard shard_map divisibility): every batch fed
    through the mesh-built model — including the ``create_train_state`` sample
    input — must have B divisible by the ``batch_axis`` size and T divisible
    by the ``seq_axis`` size."""
    attention_fn = None
    if mesh is not None:
        if context_parallelism == 'ring':
            from petastorm_tpu.ops.ring_attention import make_sharded_ring_attention
            attention_fn = make_sharded_ring_attention(mesh, seq_axis=seq_axis,
                                                       batch_axis=batch_axis)
        elif context_parallelism == 'ulysses':
            if num_heads % mesh.shape[seq_axis]:
                raise ValueError(
                    "context_parallelism='ulysses' needs num_heads ({}) divisible by "
                    'the {} axis size ({}); use ring'.format(
                        num_heads, seq_axis, mesh.shape[seq_axis]))
            from petastorm_tpu.ops.ulysses_attention import make_sharded_ulysses_attention
            attention_fn = make_sharded_ulysses_attention(mesh, seq_axis=seq_axis,
                                                          batch_axis=batch_axis)
        else:
            raise ValueError("context_parallelism must be 'ring' or 'ulysses', "
                             'got {!r}'.format(context_parallelism))
    return SequenceTransformer(num_classes=num_classes, d_model=d_model,
                               num_heads=num_heads, num_layers=num_layers,
                               attention_fn=attention_fn, dtype=dtype)
