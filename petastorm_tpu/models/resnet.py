"""ResNet in flax.linen, TPU-first.

The reference ships an ImageNet *data* example (examples/imagenet/schema.py) and
leaves the model to torch; here the model is part of the framework so the
BASELINE pipeline (ImageNet-Parquet -> ResNet-50 on TPU) is self-contained.

TPU notes: NHWC layout (XLA's native conv layout on TPU), bfloat16 compute with
float32 batch-norm statistics and output head, stride-2 3x3 convs land on the
MXU as implicit GEMMs.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1), use_bias=False, name='conv1')(x)
        y = self.norm(name='bn1')(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides),
                      use_bias=False, name='conv2')(y)
        y = self.norm(name='bn2')(y)
        y = self.act(y)
        y = self.conv(4 * self.filters, (1, 1), use_bias=False, name='conv3')(y)
        y = self.norm(scale_init=nn.initializers.zeros_init(), name='bn3')(y)
        if residual.shape != y.shape:
            residual = self.conv(4 * self.filters, (1, 1), (self.strides, self.strides),
                                 use_bias=False, name='conv_proj')(residual)
            residual = self.norm(name='bn_proj')(residual)
        return self.act(residual + y)


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = nn.BatchNorm
    act: Callable = nn.relu

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), (self.strides, self.strides),
                      use_bias=False, name='conv1')(x)
        y = self.norm(name='bn1')(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), use_bias=False, name='conv2')(y)
        y = self.norm(scale_init=nn.initializers.zeros_init(), name='bn2')(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), (self.strides, self.strides),
                                 use_bias=False, name='conv_proj')(residual)
            residual = self.norm(name='bn_proj')(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """:param stage_sizes: blocks per stage, e.g. [3, 4, 6, 3] for ResNet-50
    :param block_cls: BottleneckBlock or BasicBlock
    :param num_classes: classifier width
    :param dtype: compute dtype (bfloat16 recommended on TPU). Batch-norm
        statistics/params and the final logits head stay float32; norm compute
        follows ``dtype``.
    """

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        conv = partial(nn.Conv, dtype=self.dtype)
        # compute in self.dtype; statistics/params stay float32 (param_dtype default)
        norm = partial(nn.BatchNorm, use_running_average=not train, momentum=0.9,
                       epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                 use_bias=False, name='conv_init')(x)
        x = norm(name='bn_init')(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(self.num_filters * 2 ** i, strides=strides,
                                   conv=conv, norm=norm,
                                   name='stage{}_block{}'.format(i + 1, j))(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name='head')(x)
        return x


resnet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
resnet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BottleneckBlock)
resnet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=BottleneckBlock)
resnet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=BottleneckBlock)
