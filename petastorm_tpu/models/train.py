"""Mesh-sharded training steps for the reference models.

Scaling design (per the standard JAX recipe: pick a mesh, annotate shardings,
let XLA insert collectives): the batch shards over the ``data`` axis, parameters
replicate except where a rule maps them onto the ``model`` axis (the classifier
head by default — the only big matmul in ResNet worth TP at this scale). The
gradient all-reduce over ``data`` and the head all-gather over ``model`` are
inserted by XLA from the sharding annotations; nothing is hand-written.

The reference has no model-side code at all (SURVEY.md §2.9) — this module is
the TPU-native bridge from its data capabilities to actual training.
"""

from __future__ import annotations

import re
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state


class TrainState(train_state.TrainState):
    batch_stats: Any = None


def create_train_state(model, rng, sample_input, tx=None, learning_rate=0.1):
    """Initialize model variables and the optimizer state."""
    variables = model.init(rng, sample_input, train=False)
    if tx is None:
        tx = optax.sgd(learning_rate, momentum=0.9)
    return TrainState.create(
        apply_fn=model.apply,
        params=variables['params'],
        batch_stats=variables.get('batch_stats'),
        tx=tx)


def _spec_for_path(path, mesh_axis_names):
    """Default TP rules: classifier head kernel shards its output dim on
    'model'; its bias shards on 'model'; everything else replicates."""
    from jax.sharding import PartitionSpec as P
    if 'model' not in mesh_axis_names:
        return P()
    if re.search(r'(^|/)head/kernel$', path):
        return P(None, 'model')
    if re.search(r'(^|/)head/bias$', path):
        return P('model')
    return P()


def state_shardings(state, mesh):
    """NamedSharding tree for a TrainState under ``mesh``."""
    from jax.sharding import NamedSharding

    def path_str(path):
        return '/'.join(str(getattr(k, 'key', getattr(k, 'idx', k))) for k in path)

    def assign(path, leaf):
        return NamedSharding(mesh, _spec_for_path(path_str(path), mesh.axis_names))

    return jax.tree_util.tree_map_with_path(assign, state)


def shard_train_state(state, mesh):
    """Place a host TrainState onto the mesh per the sharding rules."""
    return jax.device_put(state, state_shardings(state, mesh))


def cross_entropy_loss(logits, labels):
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()


def make_train_step(donate=True, preprocess_fn=None, preprocess_seed=0):
    """Jitted (state, images, labels) -> (state, metrics). Sharding follows the
    arguments' placement (shard the state with :func:`shard_train_state` and the
    batch with a ``data`` NamedSharding); XLA inserts the collectives.

    ``preprocess_fn(images, rng) -> images`` runs INSIDE the jitted step —
    device-side input ops (petastorm_tpu.ops normalize/augment) fuse with the
    forward pass, so the host can ship compact uint8 batches. ``rng`` is folded
    from ``preprocess_seed`` and the step counter: augmentation varies per step
    but is reproducible."""

    def train_step(state, images, labels):
        if preprocess_fn is not None:
            rng = jax.random.fold_in(jax.random.key(preprocess_seed), state.step)
            images = preprocess_fn(images, rng)

        def loss_fn(params):
            if state.batch_stats is not None:
                logits, updates = state.apply_fn(
                    {'params': params, 'batch_stats': state.batch_stats},
                    images, train=True, mutable=['batch_stats'])
            else:
                logits = state.apply_fn({'params': params}, images, train=True)
                updates = {}
            return cross_entropy_loss(logits, labels), (logits, updates)

        (loss, (logits, updates)), grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
        new_state = state.apply_gradients(grads=grads)
        if state.batch_stats is not None:
            new_state = new_state.replace(batch_stats=updates['batch_stats'])
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        return new_state, {'loss': loss, 'accuracy': accuracy}

    return jax.jit(train_step, donate_argnums=(0,) if donate else ())


def make_eval_step():
    def eval_step(state, images, labels):
        variables = {'params': state.params}
        if state.batch_stats is not None:
            variables['batch_stats'] = state.batch_stats
        logits = state.apply_fn(variables, images, train=False)
        return {'loss': cross_entropy_loss(logits, labels),
                'accuracy': jnp.mean(jnp.argmax(logits, -1) == labels)}

    return jax.jit(eval_step)
