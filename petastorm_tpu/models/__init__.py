"""Reference models consuming the input pipeline.

The framework's job is feeding TPUs (BASELINE.md: ImageNet-Parquet ResNet-50
examples/sec/chip and input-stall %); these models are the measurement loads:
ResNet-50 (flagship, mirrors the reference's imagenet example) and a small
MNIST convnet (mirrors examples/mnist).
"""

from petastorm_tpu.models.resnet import ResNet, resnet18, resnet50  # noqa: F401
from petastorm_tpu.models.mnist import MnistCNN  # noqa: F401
