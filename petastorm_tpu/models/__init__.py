"""Reference models consuming the input pipeline.

The framework's job is feeding TPUs (BASELINE.md: ImageNet-Parquet ResNet-50
examples/sec/chip and input-stall %); these models are the measurement loads:
ResNet-50 (flagship, mirrors the reference's imagenet example), a small MNIST
convnet (mirrors examples/mnist), and a sequence transformer with pluggable
ring attention (the long-context load: NGram windows over a ('data','seq')
mesh).
"""

from petastorm_tpu.models.resnet import (ResNet, resnet18, resnet50,  # noqa: F401
                                         resnet101, resnet152)
from petastorm_tpu.models.mnist import MnistCNN  # noqa: F401
from petastorm_tpu.models.transformer import (SequenceTransformer,  # noqa: F401
                                              make_sequence_transformer)
from petastorm_tpu.models.moe import MoEMlp, MoESequenceTransformer  # noqa: F401
