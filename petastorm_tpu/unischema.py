"""Unischema: a single schema definition rendered to numpy / Arrow / JAX views.

Parity with the reference (/root/reference/petastorm/unischema.py):
  * ``UnischemaField(name, numpy_dtype, shape, codec, nullable)`` (:35-80)
  * ``Unischema`` with field attribute sugar (:180-186), ``create_schema_view``
    (:188-229), cached namedtuple types (:83-103), ``from_arrow_schema`` (:291-340)
  * ``dict_to_spark_row`` -> here ``encode_row`` (:343-383)
  * ``insert_explicit_nulls`` (:386-401), ``match_unischema_fields`` (:404-441)

TPU-first differences:
  * Schemas serialize to JSON (``to_json``/``from_json``) instead of pickle, so
    dataset metadata is language/version stable.
  * ``as_arrow_schema`` replaces ``as_spark_schema`` — our writer is pyarrow-based.
  * A row's in-memory form targets numpy arrays that can be staged into jax host
    buffers without copies (C-contiguous, native byte order).
"""

from __future__ import annotations

import re
from collections import OrderedDict, namedtuple
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu.codecs import (DataFieldCodec, NdarrayCodec, ScalarCodec, ScalarListCodec,
                                  codec_from_json)
from petastorm_tpu.errors import SchemaError

# ---------------------------------------------------------------------------
# numpy dtype <-> stable JSON token
# ---------------------------------------------------------------------------

_SPECIAL_DTYPE_TOKENS = {
    'string': np.str_,
    'bytes': np.bytes_,
    'decimal': Decimal,
    'bool': np.bool_,
    'datetime64': np.datetime64,
}


def _dtype_to_token(numpy_dtype):
    for token, t in _SPECIAL_DTYPE_TOKENS.items():
        if numpy_dtype is t:
            return token
    return np.dtype(numpy_dtype).str


def _token_to_dtype(token):
    if token in _SPECIAL_DTYPE_TOKENS:
        return _SPECIAL_DTYPE_TOKENS[token]
    return np.dtype(token).type


class UnischemaField(object):
    """A single field: name, numpy dtype, shape (``None`` entries are wildcards),
    codec, nullability.

    Equality/hash compare (name, dtype, shape, nullable) and deliberately ignore
    the codec, mirroring the reference's codec-insensitive semantics
    (unischema.py:58-80): two fields holding the same logical data are equal
    regardless of on-disk storage format.
    """

    __slots__ = ('name', 'numpy_dtype', 'shape', 'codec', 'nullable')

    def __init__(self, name, numpy_dtype, shape=(), codec=None, nullable=False):
        if codec is not None and not isinstance(codec, DataFieldCodec):
            raise SchemaError('codec for field {} must be a DataFieldCodec, got {!r}'.format(name, codec))
        self.name = name
        self.numpy_dtype = numpy_dtype if numpy_dtype is Decimal else np.dtype(numpy_dtype).type
        self.shape = tuple(shape) if shape is not None else None
        self.codec = codec if codec is not None else self._default_codec()
        self.nullable = bool(nullable)

    def _default_codec(self):
        if self.shape == ():
            return ScalarCodec()
        return NdarrayCodec()

    @property
    def is_scalar(self):
        return self.shape == ()

    def to_json(self):
        return {
            'name': self.name,
            'numpy_dtype': _dtype_to_token(self.numpy_dtype),
            'shape': list(self.shape) if self.shape is not None else None,
            'codec': self.codec.to_json(),
            'nullable': self.nullable,
        }

    @classmethod
    def from_json(cls, spec):
        return cls(
            name=spec['name'],
            numpy_dtype=_token_to_dtype(spec['numpy_dtype']),
            shape=tuple(spec['shape']) if spec['shape'] is not None else None,
            codec=codec_from_json(spec['codec']),
            nullable=spec['nullable'],
        )

    def _key(self):
        return (self.name, _dtype_to_token(self.numpy_dtype), self.shape, self.nullable)

    def __eq__(self, other):
        return isinstance(other, UnischemaField) and self._key() == other._key()

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return 'UnischemaField(name={!r}, numpy_dtype={}, shape={}, codec={!r}, nullable={})'.format(
            self.name, _dtype_to_token(self.numpy_dtype), self.shape, self.codec, self.nullable)


class _NamedtupleCache(object):
    """Cache namedtuple types by (schema name, field names) so repeated calls return
    the *same* type object — required for type-identity sensitive consumers
    (reference unischema.py:83-103)."""

    _store = {}

    @classmethod
    def get(cls, parent_name, field_names):
        key = (parent_name, tuple(field_names))
        if key not in cls._store:
            cls._store[key] = namedtuple(parent_name, field_names)
        return cls._store[key]


class Unischema(object):
    """An ordered collection of :class:`UnischemaField`.

    Field access sugar: ``schema.fields['id']`` or ``schema.id``.
    """

    def __init__(self, name, fields):
        self._name = name
        names = [f.name for f in fields]
        dupes = sorted({n for n in names if names.count(n) > 1})
        if dupes:
            raise SchemaError('Duplicate field names in schema {}: {}'.format(name, dupes))
        self._fields = OrderedDict((f.name, f) for f in sorted(fields, key=lambda f: f.name))
        for f in self._fields.values():
            if not hasattr(self, f.name):
                setattr(self, f.name, f)

    @property
    def name(self):
        return self._name

    @property
    def fields(self):
        return self._fields

    def create_schema_view(self, fields_or_patterns):
        """Subset view by exact :class:`UnischemaField` instances, field names, or
        regex patterns (reference unischema.py:188-229)."""
        if isinstance(fields_or_patterns, Unischema):
            raise SchemaError('create_schema_view expects a list of fields or patterns')
        if isinstance(fields_or_patterns, str):
            fields_or_patterns = [fields_or_patterns]
        view_fields = []
        for item in fields_or_patterns:
            if isinstance(item, UnischemaField):
                own = self._fields.get(item.name)
                if own is None:
                    raise SchemaError('Field {} does not belong to schema {}'.format(item.name, self._name))
                if own != item:
                    raise SchemaError(
                        'Field {!r} does not match schema {}\'s definition {!r}'.format(item, self._name, own))
                view_fields.append(own)
            else:
                matched = match_unischema_fields(self, [item])
                if not matched:
                    raise SchemaError('Pattern {!r} matched no fields in schema {}'.format(item, self._name))
                view_fields.extend(matched)
        # de-dup preserving order
        seen = set()
        unique = [f for f in view_fields if not (f.name in seen or seen.add(f.name))]
        return Unischema('{}_view'.format(self._name), unique)

    def make_namedtuple(self, **kwargs):
        """Build a row namedtuple from per-field kwargs."""
        return self.make_namedtuple_from_dict(kwargs)

    def make_namedtuple_from_dict(self, row_dict):
        # star-args construction is ~2x faster than **kwargs in the row hot loop
        return self.namedtuple(*[row_dict[f] for f in self._fields])

    @property
    def namedtuple(self):
        """The cached namedtuple type for rows of this schema."""
        return _NamedtupleCache.get(self._name, list(self._fields))

    def __iter__(self):
        return iter(self._fields.values())

    def __len__(self):
        return len(self._fields)

    def __repr__(self):
        lines = ['Unischema({}, ['.format(self._name)]
        lines.extend('  {!r},'.format(f) for f in self._fields.values())
        lines.append('])')
        return '\n'.join(lines)

    # -- serialization ------------------------------------------------------

    def to_json(self):
        return {'name': self._name, 'fields': [f.to_json() for f in self._fields.values()]}

    @classmethod
    def from_json(cls, spec):
        return cls(spec['name'], [UnischemaField.from_json(f) for f in spec['fields']])

    # -- arrow rendering ----------------------------------------------------

    def as_arrow_schema(self):
        """Physical Arrow schema of the Parquet files this Unischema writes."""
        return pa.schema([pa.field(f.name, f.codec.arrow_type(f), f.nullable) for f in self._fields.values()])

    @classmethod
    def from_arrow_schema(cls, arrow_schema, name='inferred', omit_unsupported_fields=True):
        """Infer a Unischema for a plain (non-petastorm) Parquet store
        (reference unischema.py:291-340). All fields come out as scalar columns;
        list columns become 1-D variable-length arrays."""
        fields = []
        for arrow_field in arrow_schema:
            try:
                f = _unischema_field_from_arrow(arrow_field)
            except SchemaError:
                if omit_unsupported_fields:
                    continue
                raise
            fields.append(f)
        return cls(name, fields)


_ARROW_TO_NUMPY = {
    pa.int8(): np.int8, pa.uint8(): np.uint8,
    pa.int16(): np.int16, pa.uint16(): np.uint16,
    pa.int32(): np.int32, pa.uint32(): np.uint32,
    pa.int64(): np.int64, pa.uint64(): np.uint64,
    pa.float16(): np.float16, pa.float32(): np.float32, pa.float64(): np.float64,
    pa.bool_(): np.bool_,
    pa.string(): np.str_, pa.large_string(): np.str_,
    pa.binary(): np.bytes_, pa.large_binary(): np.bytes_,
    pa.date32(): np.datetime64, pa.date64(): np.datetime64,
}


def _numpy_from_arrow_type(arrow_type):
    """Arrow type -> numpy type (reference unischema.py:444-477)."""
    if arrow_type in _ARROW_TO_NUMPY:
        return _ARROW_TO_NUMPY[arrow_type]
    if pa.types.is_timestamp(arrow_type):
        return np.datetime64
    if pa.types.is_decimal(arrow_type):
        return Decimal
    if pa.types.is_dictionary(arrow_type):
        return _numpy_from_arrow_type(arrow_type.value_type)
    raise SchemaError('Cannot map Arrow type {} to numpy'.format(arrow_type))


def _unischema_field_from_arrow(arrow_field):
    t = arrow_field.type
    if pa.types.is_list(t) or pa.types.is_large_list(t):
        value_numpy = _numpy_from_arrow_type(t.value_type)
        return UnischemaField(arrow_field.name, value_numpy, (None,),
                             ScalarListCodec(), arrow_field.nullable)
    numpy_dtype = _numpy_from_arrow_type(t)
    return UnischemaField(arrow_field.name, numpy_dtype, (), ScalarCodec(), arrow_field.nullable)


# ---------------------------------------------------------------------------
# Row encode / null handling / field matching
# ---------------------------------------------------------------------------

def encode_row(schema, row_dict):
    """Encode an in-memory row dict into the Parquet storage representation,
    validating against the schema (reference ``dict_to_spark_row``,
    unischema.py:343-383)."""
    if not isinstance(row_dict, dict):
        raise SchemaError('row must be a dict, got {}'.format(type(row_dict)))
    unknown = set(row_dict.keys()) - set(schema.fields.keys())
    if unknown:
        raise SchemaError('Row contains fields not in schema {}: {}'.format(schema.name, sorted(unknown)))
    full = dict(row_dict)
    insert_explicit_nulls(schema, full)
    encoded = {}
    for field in schema:
        value = full[field.name]
        if value is None:
            if not field.nullable:
                raise SchemaError('Field {} is not nullable but got None'.format(field.name))
            encoded[field.name] = None
        else:
            encoded[field.name] = field.codec.encode(field, value)
    return encoded


def insert_explicit_nulls(schema, row_dict):
    """Add ``None`` for absent nullable fields, raise on absent non-nullable ones
    (reference unischema.py:386-401)."""
    for field in schema:
        if field.name not in row_dict:
            if field.nullable:
                row_dict[field.name] = None
            else:
                raise SchemaError('Field {} is not nullable but is missing from the row'.format(field.name))


def match_unischema_fields(schema, field_regex):
    """Return fields whose names fully match any of the given regex patterns
    (reference unischema.py:404-441 — fullmatch semantics, no legacy prefix mode)."""
    if isinstance(field_regex, str):
        field_regex = [field_regex]
    compiled = [re.compile(p) for p in field_regex]
    return [f for f in schema if any(p.fullmatch(f.name) for p in compiled)]


def decode_row(row_dict, schema):
    """Decode a storage row dict into the in-memory representation
    (reference utils.py:54-87)."""
    decoded = {}
    for field_name, encoded in row_dict.items():
        field = schema.fields.get(field_name)
        if field is None:
            raise SchemaError('Row contains field {!r} not present in schema {}'.format(field_name, schema.name))
        if encoded is None:
            decoded[field_name] = None
        else:
            decoded[field_name] = field.codec.decode(field, encoded)
    return decoded
