"""Stream-multiplexing worker: ONE worker fleet serving many streams.

The serve daemon owns a single supervised pool whose workers are
:class:`MultiplexWorker` instances. Every ventilated item carries a
``stream_id``; the worker lazily instantiates the stream's REAL worker
(``RowGroupDecoderWorker`` / ``ArrowBatchWorker``) from a spec file the
broker wrote under the service directory before ventilating the stream's
first item, then delegates. Streams attach and detach at daemon runtime
without the pool ever restarting — the broker's spec files are the
side-channel that gets per-stream worker args into already-spawned worker
processes (the daemon is per-host, so a local file is exactly as reachable
as the shm ring the results ride back on).

The inner worker receives this worker's ``publish_func`` unchanged, so the
PR 6 in-place fused publish path (``publish.reserve_block``) keeps working
under multiplexing.

Causal tracing needs no code here: the daemon's pool installs each item's
``TraceContext`` around ``process()`` (``obs.use_trace``), so the delegated
inner worker's spans parent into the item's tree automatically, and clients
derive each frame's trace root from the ``FairShareVentilator``'s
``trace_ns`` (attach reply) + the ring header's seq — see
docs/observability.md "Causal tracing".
"""

from __future__ import annotations

import logging
import os
import pickle
import tempfile

from petastorm_tpu.workers.worker_base import WorkerBase

logger = logging.getLogger(__name__)

#: open inner workers kept per pool worker; beyond this the least-recently
#: used stream's worker is shut down (its spec file re-loads on demand)
_MAX_OPEN_STREAMS = 8

#: batches at least this large are parked in a shared /dev/shm blob and only
#: the path crosses the broadcast ring (the serve analog of the process
#: pool's blob sidechannel): the fused decode lands the batch DIRECTLY in the
#: blob (in-place reserve_block), consumers COW-mmap it with zero upfront
#: copy, and fan-out to K consumers costs no per-consumer copies at all
DEFAULT_SERVE_BLOB_THRESHOLD = 1 << 20


class BlobRef(object):
    """A published batch parked in a shared blob file: what the worker hands
    the pool instead of the block itself. Picklable (process-pool daemons ship
    it over the results transport)."""

    __slots__ = ('path', 'size')

    def __init__(self, path, size):
        self.path = path
        self.size = size

    def __reduce__(self):
        return (BlobRef, (self.path, self.size))


class FusedBlobRef(object):
    """A fused batch decoded DIRECTLY into a shared blob: path + per-column
    layout ``(name, dtype_str, shape, offset, nbytes)``. Consumers build
    numpy views straight over the mapping — zero batch copies anywhere
    between the Parquet pages and the training loop."""

    __slots__ = ('path', 'size', 'rows', 'cols')

    def __init__(self, path, size, rows, cols):
        self.path = path
        self.size = size
        self.rows = rows
        self.cols = cols

    def __reduce__(self):
        return (FusedBlobRef, (self.path, self.size, self.rows, self.cols))


class _BlobPublish(object):
    """Publish wrapper giving a stream's inner worker the serve blob channel:

    * ``publish(block)`` — block payloads at/over the threshold are written
      into a fresh blob (single ``write_parts_into`` copy) and published as a
      :class:`BlobRef`; everything else passes through in-band;
    * ``publish.reserve_block(meta, payload_max)`` — the PR 6 in-place
      contract: the fused native decode writes the batch STRAIGHT into the
      blob's mapping, so qualifying batches reach the consumers with zero
      serialization copies anywhere.

    Callable-object form (not a closure) so the worker-side probe
    ``getattr(publish_func, 'reserve_block', None)`` finds the method.
    """

    def __init__(self, inner_publish, blob_dir, threshold, serializer):
        self._inner = inner_publish
        self._blob_dir = blob_dir
        self._threshold = threshold
        self._serializer = serializer
        self._disabled = False

    def _new_blob(self, total):
        """Fresh writable mapping + path for a ``total``-byte blob.

        :borrows: the caller owns the mapping and must close it (and unlink
            the path on failure) — both exits in :meth:`__call__` do."""
        import mmap
        fd, path = tempfile.mkstemp(prefix='sb', dir=self._blob_dir)
        try:
            os.posix_fallocate(fd, 0, total)  # ENOSPC here, not SIGBUS later
            mm = mmap.mmap(fd, total)
        except OSError:
            os.close(fd)
            try:
                os.unlink(path)
            except OSError:
                pass
            raise
        os.close(fd)
        return mm, path

    def __call__(self, data):
        ser = self._serializer
        if not self._disabled and self._blob_dir is not None \
                and hasattr(ser, 'serialize_parts'):
            parts = ser.serialize_parts(data)
            if parts is not None:
                total = ser.parts_size(parts)
                if total >= self._threshold:
                    # plain buffered writes, not an mmap: one kernel-side copy
                    # per byte and none of the per-page fault churn a fresh
                    # mapping pays on a multi-MB batch
                    fd, path = tempfile.mkstemp(prefix='sb', dir=self._blob_dir)
                    try:
                        with os.fdopen(fd, 'wb') as f:
                            for p in parts:
                                f.write(ser._array_bytes(p)
                                        if not isinstance(p, (bytes, bytearray))
                                        else p)
                        self._inner(BlobRef(path, total))
                        return
                    except OSError as e:
                        logger.warning('serve blob write failed (%s); batch '
                                       'falling back in-band', e)
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                        self._disabled = True
        self._inner(data)

    def reserve_fused(self, total_bound, rows):
        """The direct-decode channel: a writable blob mapping the fused
        native decode lands the whole batch in, published as a column-layout
        descriptor (:class:`FusedBlobRef`) instead of serialized bytes — no
        serializer pass at all. Returns ``(payload_view, finish, abort)`` or
        None. ``PSTPU_SERVE_FUSED_BLOB=0`` disables it (rollback knob: on
        hosts where fresh-mapping fault+zero costs beat the serializer copy,
        the plain blob channel can win)."""
        if self._disabled or self._blob_dir is None:
            return None
        if os.environ.get('PSTPU_SERVE_FUSED_BLOB', '1') in ('0', 'off'):
            return None
        if total_bound < self._threshold:
            return None
        try:
            mm, path = self._new_blob(total_bound)
        except OSError as e:
            logger.warning('serve blob allocation failed (%s); copy path', e)
            self._disabled = True
            return None
        view = memoryview(mm)  # noqa: PT500 - writable blob mapping owned by this reservation

        # as with reserve_block, the mapping is left to die with the caller's
        # views; tmpfs pages are shared-visible the moment they are written
        def finish(cols):
            self._inner(FusedBlobRef(path, total_bound, rows, cols))

        def abort():
            try:
                os.unlink(path)
            except OSError:
                pass

        return view, finish, abort

    def reserve_block(self, meta_entries, payload_max):
        """In-place channel: returns ``(payload_view, commit, abort)`` backed
        by a fresh blob mapping, or None (callers use the copy path)."""
        if self._disabled or self._blob_dir is None \
                or not hasattr(self._serializer, 'frame_for_layout'):
            return None
        prefix = self._serializer.frame_for_layout(meta_entries)
        if prefix is None:
            return None
        total = len(prefix) + payload_max
        if total < self._threshold:
            return None  # small batches take the in-band ring frame
        try:
            mm, path = self._new_blob(total)
        except OSError as e:
            logger.warning('serve blob allocation failed (%s); in-band path', e)
            self._disabled = True
            return None
        view = memoryview(mm)  # noqa: PT500 - writable blob mapping owned by this reservation
        view[:len(prefix)] = prefix

        # NOTE: the mapping is NOT closed on commit/abort — the caller still
        # holds numpy views over the payload slice (mmap.close would raise
        # BufferError); the mapping unmaps when those views die, and tmpfs
        # pages are shared-visible to consumers the moment they are written.
        def commit(actual_payload=payload_max):
            self._inner(BlobRef(path, len(prefix) + actual_payload))

        def abort():
            try:
                os.unlink(path)
            except OSError:
                pass

        return view[len(prefix):], commit, abort


def stream_spec_path(service_dir, stream_id):
    """Canonical location of a stream's pickled (worker_class, worker_args)."""
    return os.path.join(service_dir, 'streams', '{}.pkl'.format(stream_id))


def write_stream_spec(service_dir, stream_id, worker_class, worker_args):
    """Atomically publish a stream's worker spec for the fleet (broker side;
    temp + rename so a worker never loads a half-written pickle)."""
    path = stream_spec_path(service_dir, stream_id)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = '{}.tmp.{}'.format(path, os.getpid())
    with open(tmp, 'wb') as f:
        pickle.dump((worker_class, worker_args), f,
                    protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)


def remove_stream_spec(service_dir, stream_id):
    try:
        os.unlink(stream_spec_path(service_dir, stream_id))
    except OSError:
        pass


class MultiplexWorker(WorkerBase):
    """``args``: ``{'service_dir': path}`` (plus the usual telemetry/fault
    riders). Items are the inner worker's kwargs plus ``stream_id``."""

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._inner = {}   # stream_id -> inner worker (insertion-ordered LRU)

    def _inner_worker(self, stream_id):
        worker = self._inner.pop(stream_id, None)
        if worker is None:
            path = stream_spec_path(self.args['service_dir'], stream_id)
            with open(path, 'rb') as f:
                worker_class, worker_args = pickle.load(f)
            publish = self.publish_func
            blob_dir = self.args.get('blob_dir')
            if blob_dir is not None:
                from petastorm_tpu.serializers import NumpyBlockSerializer
                publish = _BlobPublish(
                    publish, blob_dir,
                    self.args.get('blob_threshold', DEFAULT_SERVE_BLOB_THRESHOLD),
                    NumpyBlockSerializer())
            worker = worker_class(self.worker_id, publish, worker_args)
            if len(self._inner) >= _MAX_OPEN_STREAMS:
                old_id, old = next(iter(self._inner.items()))
                del self._inner[old_id]
                try:
                    old.shutdown()
                except Exception:  # noqa: BLE001 - a stale stream's cleanup must not fail the live one
                    logger.debug('shutdown of idle stream %s worker failed', old_id)
        self._inner[stream_id] = worker  # re-insert: most recently used
        return worker

    def process(self, stream_id, **kwargs):
        self._inner_worker(stream_id).process(**kwargs)

    def shutdown(self):
        for worker in self._inner.values():
            try:
                worker.shutdown()
            except Exception:  # noqa: BLE001 - best-effort fan-in of inner shutdowns
                pass
        self._inner = {}


__all__ = ['BlobRef', 'DEFAULT_SERVE_BLOB_THRESHOLD', 'MultiplexWorker',
           'remove_stream_spec', 'stream_spec_path', 'write_stream_spec']
