"""``petastorm-tpu-serve`` / ``python -m petastorm_tpu.serve`` — run the
per-host shared reader daemon in the foreground (``docs/serve.md``).

Normally consumers spawn the daemon implicitly via
``make_reader(serve='auto')``; this entry point exists for explicit
deployments (CI fixtures, systemd units, containers) and for debugging with
the daemon's logs on a terminal.
"""

from __future__ import annotations

import argparse
import logging
import sys


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='petastorm-tpu-serve',
        description='Per-host shared reader daemon: decode once, serve many '
                    'local consumers over broadcast shm rings (docs/serve.md).')
    parser.add_argument('--service-dir', required=True,
                        help='service directory (control socket, stream specs, '
                             'spawn lock); consumers pass the same path as '
                             'make_reader(serve=...)')
    parser.add_argument('--pool-type', choices=('thread', 'process', 'dummy'),
                        default='thread')
    parser.add_argument('--workers-count', type=int, default=4)
    parser.add_argument('--ring-bytes', type=int, default=None,
                        help='per-stream broadcast ring capacity (default 64 MiB)')
    parser.add_argument('--idle-timeout', type=float, default=None,
                        help='exit after this many seconds with no attached '
                             'tenants (default 60; <= 0 disables)')
    parser.add_argument('--evict-block', type=float, default=None,
                        help='evict the slowest consumer after a publish stays '
                             'blocked this long (default 10s)')
    parser.add_argument('--telemetry', choices=('off', 'counters', 'spans'),
                        default=None,
                        help="daemon telemetry level; 'spans' records the "
                             'causal span tree clients fetch via the trace '
                             'control op (default: keep the process default)')
    parser.add_argument('-v', '--verbose', action='store_true')
    args = parser.parse_args(argv)

    logging.basicConfig(level=logging.DEBUG if args.verbose else logging.INFO,
                        format='%(asctime)s %(levelname)s %(name)s: %(message)s')

    from petastorm_tpu.serve.service import (DEFAULT_EVICT_BLOCK_S,
                                             DEFAULT_IDLE_TIMEOUT_S,
                                             DEFAULT_SERVE_RING_BYTES,
                                             ReaderService)
    idle = args.idle_timeout if args.idle_timeout is not None else DEFAULT_IDLE_TIMEOUT_S
    service = ReaderService(
        args.service_dir,
        pool_type=args.pool_type,
        workers_count=args.workers_count,
        ring_bytes=args.ring_bytes or DEFAULT_SERVE_RING_BYTES,
        idle_timeout_s=None if idle is not None and idle <= 0 else idle,
        evict_block_s=(args.evict_block if args.evict_block is not None
                       else DEFAULT_EVICT_BLOCK_S),
        telemetry=args.telemetry)
    service.start()
    try:
        service.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        service.shutdown()
    return 0


if __name__ == '__main__':
    sys.exit(main())
