"""Shared reader service: one per-host daemon decodes each dataset once and
serves decoded batches to many local consumer processes over broadcast shm
rings (``docs/serve.md``).

Entry points:

* ``make_reader(..., serve='auto' | <service dir>)`` — the drop-in consumer
  path (spawns-or-joins the daemon; returns a :class:`ServedReader`);
* ``petastorm-tpu-serve`` / ``python -m petastorm_tpu.serve`` — run the
  daemon explicitly (CI, systemd, containers);
* :class:`ReaderService` — the embeddable broker, for tests and bespoke
  deployments.
"""

from __future__ import annotations

from petastorm_tpu.serve.client import (ServedReader, connect_service,
                                        default_service_dir, make_served_reader)
from petastorm_tpu.serve.plan import ReadPlan, build_read_plan
from petastorm_tpu.serve.service import ReaderService, canonical_stream_id

__all__ = [
    'ReadPlan', 'ReaderService', 'ServedReader', 'build_read_plan',
    'canonical_stream_id', 'connect_service', 'default_service_dir',
    'make_served_reader',
]
