"""The per-host shared reader service (daemon side) — decode once, serve many.

ONE :class:`ReaderService` per host owns one chunkstore, one supervised worker
fleet (:class:`~petastorm_tpu.serve.worker.MultiplexWorker` pool) and one
:class:`~petastorm_tpu.workers.ventilator.FairShareVentilator`, and serves
decoded batches to many local consumer processes over per-stream broadcast
shm rings (``native/shm_ring.py`` :class:`BcastRing`):

* a **stream** is a distinct (dataset, decode configuration) — its id is the
  hash of the canonical spec. All consumers of one stream share ONE decode:
  the pump republishes each batch once and the ring fans it out.
* a **tenant** is one attached consumer process. Admission control and
  weighted fair-share live in the ventilator (per-stream in-flight budgets,
  starvation-free weighted round-robin); a tenant's weight joins its
  stream's share.
* **eviction**: a consumer lagging far enough to stall the fleet is evicted
  from its ring slot with a loud structured log; everyone else keeps flowing
  and the evictee's next read raises
  :class:`~petastorm_tpu.errors.ConsumerEvictedError` client-side.
* the control plane is a ``multiprocessing.connection`` AF_UNIX listener in
  the service directory; the O_EXCL spawn handshake and the client live in
  ``serve/client.py``.

Every admit/evict/detach actuation runs inside a traced span carrying the
tenant id (lint rule PT1000 enforces this), so a long-lived shared daemon's
decisions are reconstructable from its trace ring.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time

from petastorm_tpu import observability as obs
from petastorm_tpu.errors import EmptyResultError, ServeError
from petastorm_tpu.observability import blackbox
from petastorm_tpu.serializers import NumpyBlockSerializer
from petastorm_tpu.serve.worker import (DEFAULT_SERVE_BLOB_THRESHOLD, BlobRef,
                                        FusedBlobRef, MultiplexWorker,
                                        remove_stream_spec, write_stream_spec)
from petastorm_tpu.workers.protocol import (SERVE_BLOB, SERVE_COLS, SERVE_DATA,
                                            SERVE_DONE, SERVE_END, SERVE_ERROR,
                                            ring_header)
from petastorm_tpu.workers.ventilator import FairShareVentilator

logger = logging.getLogger(__name__)

#: default per-stream broadcast ring capacity
DEFAULT_SERVE_RING_BYTES = 64 << 20
#: a blocked broadcast publish evicts the slowest consumer after this long
DEFAULT_EVICT_BLOCK_S = 10.0
#: daemon exits after this long with zero attached tenants
DEFAULT_IDLE_TIMEOUT_S = 60.0
#: per-stream (= per ventilator tenant) in-flight row-group budget
DEFAULT_STREAM_IN_FLIGHT = 3
#: bound on per-stream blob bytes NOT yet consumed by the whole fleet — the
#: byte-backpressure analog of the ring capacity for the blob plane
DEFAULT_BLOB_BUDGET_BYTES = 256 << 20
#: a blob stays on disk this long after the last cursor passed its frame —
#: covers the consumer-side window between reading the path frame and
#: mmapping the file (microseconds, unless the consumer is preempted)
DEFAULT_BLOB_GC_GRACE_S = 1.0

ENDPOINT_FILE = 'endpoint.json'
LOCK_FILE = 'daemon.lock'


def canonical_stream_id(spec):
    """Stable id of a stream spec: two consumers sending byte-identical
    canonical specs share one decode pipeline."""
    blob = pickle.dumps([(k, spec[k]) for k in sorted(spec)],
                        protocol=pickle.HIGHEST_PROTOCOL)
    return hashlib.sha256(blob).hexdigest()[:16]


def endpoint_path(service_dir):
    return os.path.join(service_dir, ENDPOINT_FILE)


def read_endpoint(service_dir):
    """{'address', 'pid'} of the published daemon, or None."""
    try:
        with open(endpoint_path(service_dir)) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and doc.get('address') and doc.get('pid'):
            return doc
    except (OSError, ValueError):
        pass
    return None


class _Tenant(object):
    __slots__ = ('tenant_id', 'stream_id', 'token', 'weight', 'conn',
                 'attached_at', 'batches', 'bytes', 'shared_hits', 'evicted',
                 'joined_shared')

    def __init__(self, tenant_id, stream_id, token, weight, conn, joined_shared):
        self.tenant_id = tenant_id
        self.stream_id = stream_id
        self.token = token
        self.weight = weight
        self.conn = conn
        self.attached_at = time.monotonic()
        self.batches = 0
        self.bytes = 0
        self.shared_hits = 0
        self.evicted = False
        self.joined_shared = joined_shared  # attached to an already-warm stream

    def stats(self):
        return {'stream_id': self.stream_id, 'weight': self.weight,
                'batches_served': self.batches, 'bytes_served': self.bytes,
                'shared_decode_hits': self.shared_hits,
                'evicted': self.evicted, 'joined_shared': self.joined_shared}


class _Stream(object):
    __slots__ = ('stream_id', 'spec', 'plan', 'ring', 'ring_name', 'tenants',
                 'finished', 'errored', 'write_lock', 'decoded_batches',
                 'blocked_since', 'blobs', 'blob_outstanding')

    def __init__(self, stream_id, spec, plan, ring, ring_name):
        self.stream_id = stream_id
        self.spec = spec
        self.plan = plan
        self.ring = ring
        self.ring_name = ring_name
        self.tenants = {}       # tenant_id -> _Tenant
        self.finished = False
        self.errored = False
        # serializes producer-side ring ops (pump writes vs control-plane
        # joins) — a join's head=tail snapshot must never race a write burst
        self.write_lock = threading.Lock()
        self.decoded_batches = 0
        self.blocked_since = None
        # blob-plane ledger: [frame_end_pos, path, size, eligible_at] entries
        # in publish order (pump thread appends; GC pops from the front)
        self.blobs = []
        self.blob_outstanding = 0


class ReaderService(object):
    """The broker + pump + control plane of one serve daemon. Create, then
    :meth:`start`; :meth:`serve_forever` blocks until idle-timeout/shutdown."""

    def __init__(self, service_dir, pool_type='thread', workers_count=4,
                 ring_bytes=DEFAULT_SERVE_RING_BYTES,
                 evict_block_s=DEFAULT_EVICT_BLOCK_S,
                 idle_timeout_s=DEFAULT_IDLE_TIMEOUT_S,
                 stream_in_flight=DEFAULT_STREAM_IN_FLIGHT,
                 blob_threshold_bytes=DEFAULT_SERVE_BLOB_THRESHOLD,
                 blob_budget_bytes=DEFAULT_BLOB_BUDGET_BYTES,
                 blob_gc_grace_s=DEFAULT_BLOB_GC_GRACE_S,
                 monitor=None, telemetry=None):
        self.service_dir = os.path.abspath(service_dir)
        # applied at start(): 'spans' makes served batches causally traceable
        # end to end (the daemon-side tree is fetched via the 'trace' op)
        self._telemetry = telemetry
        self._pool_type = pool_type
        self._workers_count = workers_count
        self._ring_bytes = ring_bytes
        self._evict_block_s = evict_block_s
        self._idle_timeout_s = idle_timeout_s
        self._stream_in_flight = stream_in_flight
        self._blob_threshold = blob_threshold_bytes
        self._blob_budget = blob_budget_bytes
        self._blob_grace_s = blob_gc_grace_s
        self._blob_dir = None
        self._serializer = NumpyBlockSerializer()
        self._lock = threading.RLock()
        self._streams = {}          # stream_id -> _Stream (live generation)
        self._retired_streams = []  # finished streams with consumers still attached
        self._tenants = {}          # tenant_id -> _Tenant
        self._next_tenant = 0
        self._ring_generation = 0   # ring names are generation-unique: a
        # retired generation's ring may still be linked when a fresh
        # generation of the same stream spec is created
        self._idle_since = time.monotonic()
        self._shutdown = threading.Event()
        self._listener = None
        self._threads = []
        self._evictions = 0
        self._pool = None
        self._ventilator = None
        from petastorm_tpu.analysis.protocol.monitor import serve_monitor_from_env
        self.monitor = serve_monitor_from_env(monitor, 'serve-daemon')

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        os.makedirs(os.path.join(self.service_dir, 'streams'), exist_ok=True)
        obs.configure(self._telemetry)  # None keeps the ambient level
        # before the pool starts so the flight file carries the daemon label
        # (enable() is a per-process singleton; first caller names it)
        flight = blackbox.maybe_enable('serve-daemon')
        if flight is not None:
            flight.register_lock('serve.state_lock', self._lock)
            # re-fetch through the registry each probe: tests reset() the
            # registry, which would orphan a captured Counter object
            flight.watch('serve_published', lambda: obs.get_registry()
                         .counter('serve_batches_published_total').value)
        from petastorm_tpu.reader import _make_pool
        # the fleet is resilient by default: a poison item quarantines (loud,
        # counted) instead of killing every tenant's stream
        self._pool = _make_pool(self._pool_type, self._workers_count,
                                results_queue_size=max(16, 4 * self._workers_count),
                                on_error='skip')
        self._ventilator = FairShareVentilator(self._pool.ventilate,
                                               on_tenant_done=self._on_stream_done)
        # blob plane (docs/serve.md): same naming convention as the process
        # pool's sidechannel, so its stale-dir sweeper reaps orphans of a
        # hard-killed daemon
        if self._blob_threshold and os.path.isdir('/dev/shm'):
            from petastorm_tpu.workers.process_pool import _sweep_stale_blob_dirs
            _sweep_stale_blob_dirs('/dev/shm')
            import tempfile
            try:
                self._blob_dir = tempfile.mkdtemp(
                    prefix='pstpu_blobs_{}_'.format(os.getpid()), dir='/dev/shm')
            except OSError:
                self._blob_dir = None
        worker_args = {'service_dir': self.service_dir,
                       'blob_dir': self._blob_dir,
                       'blob_threshold': self._blob_threshold,
                       'telemetry': obs.configure(None)}
        self._pool.start(MultiplexWorker, worker_args, ventilator=self._ventilator)
        self._start_listener()
        self._pump_thread = threading.Thread(target=self._pump_loop, daemon=True,
                                             name='pstpu-serve-pump')
        self._pump_thread.start()
        self._threads.append(self._pump_thread)
        t = threading.Thread(target=self._housekeeping_loop, daemon=True,
                             name='pstpu-serve-housekeeping')
        t.start()
        self._threads.append(t)
        logger.info('serve daemon up: dir=%s pool=%s x%d', self.service_dir,
                    self._pool_type, self._workers_count)

    def _start_listener(self):
        from multiprocessing.connection import Listener
        address = os.path.join(self.service_dir, 'ctrl.sock')
        try:
            os.unlink(address)
        except OSError:
            pass
        self._listener = Listener(address, family='AF_UNIX')
        tmp = endpoint_path(self.service_dir) + '.tmp'
        with open(tmp, 'w') as f:
            json.dump({'address': address, 'pid': os.getpid()}, f)
        os.replace(tmp, endpoint_path(self.service_dir))
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name='pstpu-serve-accept')
        t.start()
        self._threads.append(t)

    def serve_forever(self):
        """Block until shutdown (idle timeout, explicit op, or fatal error)."""
        self._shutdown.wait()

    def shutdown(self):
        if self._shutdown.is_set():
            return
        logger.info('serve daemon shutting down')
        self._shutdown.set()
        if self._ventilator is not None:
            self._ventilator.stop()   # pump drains to EmptyResultError and exits
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        # the pump must be OUT of the ring write path before rings close (a
        # blocked publish unblocks on the shutdown flag; the drain ends in
        # EmptyResultError once the stopped ventilator's in-flight completes)
        if getattr(self, '_pump_thread', None) is not None \
                and self._pump_thread is not threading.current_thread():
            self._pump_thread.join(timeout=15)
        with self._lock:
            streams = list(self._streams.values()) + list(self._retired_streams)
            self._streams = {}
            self._retired_streams = []
        for stream in streams:
            self._broadcast_error(stream, ServeError('serve daemon shut down'))
            self._gc_blobs(stream, drop_all=True)
            with stream.write_lock:
                stream.ring.close()
            remove_stream_spec(self.service_dir, stream.stream_id)
        if self._pool is not None:
            self._pool.stop()
            self._pool.join()
        if self._blob_dir is not None:
            import shutil
            shutil.rmtree(self._blob_dir, ignore_errors=True)
            self._blob_dir = None
        for name in (ENDPOINT_FILE, LOCK_FILE):
            try:
                os.unlink(os.path.join(self.service_dir, name))
            except OSError:
                pass

    # -- control plane -------------------------------------------------------

    def _accept_loop(self):
        while not self._shutdown.is_set():
            try:
                conn = self._listener.accept()
            except (OSError, EOFError):
                if self._shutdown.is_set():
                    return
                continue
            t = threading.Thread(target=self._client_loop, args=(conn,),
                                 daemon=True, name='pstpu-serve-client')
            t.start()
            self._threads.append(t)

    def _client_loop(self, conn):
        owned = []  # tenant ids attached over this connection
        try:
            while not self._shutdown.is_set():
                try:
                    msg = conn.recv()
                except (EOFError, OSError):
                    break
                try:
                    reply = self._dispatch(msg, conn, owned)
                except Exception as e:  # noqa: BLE001 - a bad request must not kill the daemon
                    logger.exception('serve control request failed')
                    reply = {'ok': False, 'error': '{}: {}'.format(type(e).__name__, e)}
                try:
                    conn.send(reply)
                except (OSError, ValueError, pickle.PicklingError):
                    break
        finally:
            # a client that vanished without DETACH still releases its slots
            for tenant_id in owned:
                self.detach(tenant_id)
            try:
                conn.close()
            except OSError:
                pass

    def _dispatch(self, msg, conn, owned):
        op = msg.get('op')
        if op == 'ping':
            return {'ok': True, 'pid': os.getpid()}
        if op == 'attach':
            reply = self.attach(msg['spec'], weight=msg.get('weight', 1), conn=conn)
            if reply.get('ok'):
                owned.append(reply['tenant_id'])
            return reply
        if op == 'detach':
            tenant_id = msg.get('tenant_id')
            if tenant_id in owned:
                owned.remove(tenant_id)
            return {'ok': self.detach(tenant_id)}
        if op == 'stats':
            return {'ok': True, 'stats': self.stats()}
        if op == 'trace':
            # a SNAPSHOT, not a drain: many tenants may ask, and a drain
            # would hand each one a disjoint slice of the daemon's ring
            return {'ok': True, 'events': obs.get_ring().snapshot()}
        if op == 'shutdown':
            threading.Thread(target=self.shutdown, daemon=True).start()
            return {'ok': True}
        return {'ok': False, 'error': 'unknown op {!r}'.format(op)}

    # -- broker --------------------------------------------------------------

    def attach(self, spec, weight=1, conn=None):
        """Admit one tenant: find-or-create its stream, grant a ring slot,
        register its weight with the fair-share scheduler."""
        stream_id = canonical_stream_id(spec)
        tenant_id = None
        with self._lock:
            stream = self._streams.get(stream_id)
            if stream is not None and (stream.finished or stream.errored):
                # a finished generation cannot be joined mid-void: retire it
                # (its consumers drain/detach on their own) and start fresh
                self._retired_streams.append(stream)
                self._streams.pop(stream_id, None)
                stream = None
            fresh = stream is None
            if fresh:
                stream = self._create_stream(stream_id, spec)
            tenant_id = 't{}'.format(self._next_tenant)
            self._next_tenant += 1
            with obs.span('serve.admit', cat='serve', tenant=tenant_id,
                          stream=stream_id):
                with stream.write_lock:
                    token = stream.ring.join()  # noqa: PT1303 - bcast-ring consumer-slot grant: a nonblocking C call, not a thread join
                tenant = _Tenant(tenant_id, stream_id, token, weight, conn,
                                 joined_shared=not fresh)
                stream.tenants[tenant_id] = tenant
                self._tenants[tenant_id] = tenant
                self._idle_since = None
                if fresh:
                    self._ventilator.add_tenant(
                        stream_id,
                        [dict(item, stream_id=stream_id) for item in stream.plan.items],
                        iterations=stream.plan.num_epochs,
                        weight=self._stream_weight(stream),
                        max_in_flight=self._stream_in_flight,
                        shuffle=stream.plan.shuffle_row_groups,
                        seed=stream.plan.seed)
                else:
                    self._retune_stream_weight(stream)
            if self.monitor is not None:
                self.monitor.on_attach(tenant_id, stream_id)
        obs.count('serve_tenants_attached_total')
        logger.info('serve: tenant %s attached to stream %s (%s, weight %d, '
                    'shared=%s)', tenant_id, stream_id, spec.get('dataset_url'),
                    weight, not fresh)
        return {'ok': True, 'tenant_id': tenant_id, 'stream_id': stream_id,
                'ring_name': stream.ring_name, 'token': token,
                'daemon_pid': os.getpid(),
                # the broker's trace-mint namespace: with it, a client derives
                # every frame's trace root from the seq already in the ring
                # header — causal linkage costs zero extra wire bytes
                'trace_ns': self._ventilator.trace_ns,
                'client_plan': stream.plan.client_plan()}

    def _create_stream(self, stream_id, spec):
        from petastorm_tpu.serve.plan import build_read_plan
        plan = build_read_plan(**spec)
        write_stream_spec(self.service_dir, stream_id, plan.worker_class,
                          dict(plan.worker_args, telemetry=obs.configure(None)))
        from petastorm_tpu.native.shm_ring import BcastRing
        self._ring_generation += 1
        ring_name = '/pstpu_bc_{}_{}g{}'.format(os.getpid(), stream_id[:8],
                                                self._ring_generation)
        ring = BcastRing.create(ring_name, self._ring_bytes)
        stream = _Stream(stream_id, spec, plan, ring, ring_name)
        self._streams[stream_id] = stream
        obs.count('serve_streams_created_total')
        return stream

    def _stream_weight(self, stream):
        return sum(t.weight for t in stream.tenants.values()) or 1

    def _retune_stream_weight(self, stream):
        """A stream's fair share is the sum of its tenants' weights; retune on
        attach/detach (takes effect at the scheduler's next credit refill)."""
        self._ventilator.set_tenant_weight(stream.stream_id,
                                           self._stream_weight(stream))

    def detach(self, tenant_id):
        """Release one tenant: free its ring slot; the stream keeps flowing
        for the remaining tenants, and a stream with no tenants left stops
        being scheduled."""
        with self._lock:
            tenant = self._tenants.pop(tenant_id, None)
            if tenant is None:
                return False
            stream = self._find_stream(tenant.stream_id)
            with obs.span('serve.detach', cat='serve', tenant=tenant_id,
                          stream=tenant.stream_id):
                if stream is not None:
                    stream.tenants.pop(tenant_id, None)
                    with stream.write_lock:
                        stream.ring.leave(tenant.token)
                    self._finish_stream_if_abandoned(stream)
            if not self._tenants:
                self._idle_since = time.monotonic()
            if self.monitor is not None:
                self.monitor.on_detach(tenant_id)
        obs.count('serve_tenants_detached_total')
        logger.info('serve: tenant %s detached from stream %s', tenant_id,
                    tenant.stream_id)
        return True

    def _find_stream(self, stream_id):
        with self._lock:  # RLock: callers already holding it nest freely
            stream = self._streams.get(stream_id)
            if stream is not None:
                return stream
            for s in self._retired_streams:
                if s.stream_id == stream_id:
                    return s
            return None

    def _finish_stream_if_abandoned(self, stream):
        """Under the lock: reclaim a stream nobody is attached to."""
        if stream.tenants:
            self._retune_stream_weight(stream)
            return
        with obs.span('serve.reclaim', cat='serve', tenant=stream.stream_id):
            self._ventilator.remove_tenant(stream.stream_id)
            self._streams.pop(stream.stream_id, None)
            if stream in self._retired_streams:
                self._retired_streams.remove(stream)
            self._gc_blobs(stream, drop_all=True)
            with stream.write_lock:
                # under the write lock: the pump's publish loop either already
                # saw consumer_count()==0 and dropped its frame, or will on a
                # closed handle — never a ring call on freed memory
                stream.ring.close()
            remove_stream_spec(self.service_dir, stream.stream_id)
        logger.info('serve: stream %s reclaimed (no tenants left)', stream.stream_id)

    # -- the pump: shared pool results -> per-stream broadcast rings ---------

    def _pump_loop(self):
        pool = self._pool
        pool.done_callback = self._forward_done
        try:
            while not self._shutdown.is_set():
                try:
                    payload = pool.get_results()
                except EmptyResultError:
                    return  # ventilator stopped (shutdown) and the fleet drained
                seq = pool.last_result_seq
                stream_id = self._ventilator.tenant_of_seq(seq)
                stream = self._find_stream(stream_id) if stream_id is not None else None
                if stream is None:
                    if isinstance(payload, (BlobRef, FusedBlobRef)):
                        try:
                            os.unlink(payload.path)
                        except OSError:
                            pass
                    obs.count('serve_orphan_batches_total')
                    continue  # stream abandoned while its batch was in flight
                if isinstance(payload, FusedBlobRef):
                    # zero-copy plane: the fused decode wrote the batch
                    # STRAIGHT into the shared blob; only the column-layout
                    # descriptor crosses the ring and consumers view the
                    # mapping in place
                    self._publish(stream, SERVE_COLS, seq,
                                  pickle.dumps({'path': payload.path,
                                                'size': payload.size,
                                                'rows': payload.rows,
                                                'cols': payload.cols},
                                               protocol=pickle.HIGHEST_PROTOCOL),
                                  raw=True, blob=payload)
                elif isinstance(payload, BlobRef):
                    # blob plane: the batch sits in shared memory after one
                    # worker-side copy — only the path frame crosses the
                    # ring, and consumers COW-map the bytes
                    self._publish(stream, SERVE_BLOB, seq,
                                  '{}|{}'.format(payload.size,
                                                 payload.path).encode(),
                                  raw=True, blob=payload)
                else:
                    self._publish(stream, SERVE_DATA, seq, payload)
        except Exception as e:  # noqa: BLE001 - the pump dying must fail loudly everywhere
            logger.exception('serve pump failed; shutting the daemon down')
            with self._lock:
                streams = list(self._streams.values())
            for stream in streams:
                self._broadcast_error(stream, e)
            threading.Thread(target=self.shutdown, daemon=True).start()

    def _forward_done(self, seq):
        """Pool completion sentinel -> SERVE_DONE frame on the owning stream
        (fires on the pump thread, inside get_results)."""
        stream_id = self._ventilator.tenant_of_seq(seq)
        stream = self._find_stream(stream_id) if stream_id is not None else None
        if stream is not None:
            self._publish(stream, SERVE_DONE, seq, None)

    def _on_stream_done(self, stream_id):
        """FairShareVentilator: every epoch of the stream fully completed."""
        stream = self._find_stream(stream_id)
        if stream is None:
            return
        stream.finished = True
        self._publish(stream, SERVE_END, None, None)
        if self.monitor is not None:
            self.monitor.on_end(stream_id)
        logger.info('serve: stream %s finished all epochs', stream_id)

    def _broadcast_error(self, stream, exc):
        stream.errored = True
        try:
            self._publish(stream, SERVE_ERROR, None,
                          pickle.dumps(exc, protocol=pickle.HIGHEST_PROTOCOL),
                          raw=True)
        except Exception:  # noqa: BLE001 - last-resort path; client pid-liveness covers the rest
            logger.debug('error broadcast to stream %s failed', stream.stream_id)

    def _publish(self, stream, kind, seq, payload, raw=False, blob=None):
        """Broadcast one frame, evicting the slowest consumer rather than
        stalling the fleet when the write stays blocked (ring full OR the
        blob plane over its byte budget)."""
        header = ring_header(kind, seq)
        if payload is None:
            parts = [header]
        elif raw:
            parts = [header, payload]
        else:
            body = self._serializer.serialize_parts(payload)
            if body is None:
                parts = [header, self._serializer.serialize(payload)]
            else:
                parts = [header] + body
        from petastorm_tpu.native.shm_ring import IdleWait
        idle = IdleWait()
        while True:
            # lock order is always service._lock -> stream.write_lock, so no
            # accounting (which takes service._lock) happens under write_lock
            written = False
            blocked_on_blobs = False
            with stream.write_lock:
                if stream.ring.consumer_count() == 0:
                    # nobody to deliver to (all evicted/detached): drop the
                    # frame instead of spinning on a min-head of tail
                    stream.blocked_since = None
                    if blob is not None:
                        try:
                            os.unlink(blob.path)
                        except OSError:
                            pass
                    return
                if blob is not None and stream.blob_outstanding > self._blob_budget:
                    blocked_on_blobs = True  # backpressure: fleet must catch up
                else:
                    try:
                        written = stream.ring.try_writev(parts)
                    except ValueError:
                        logger.error('serve: frame larger than the broadcast '
                                     'ring; dropping (raise serve ring_bytes)')
                        return
                if written and blob is not None:
                    # ledger entry keyed on the post-write producer position:
                    # the blob is reclaimable once every attached cursor
                    # passes it (min_head >= end), plus the GC grace
                    stream.blobs.append([stream.ring.tail(), blob.path,
                                         blob.size, None])
                    stream.blob_outstanding += blob.size
            if written:
                stream.blocked_since = None
                if kind in (SERVE_DATA, SERVE_BLOB, SERVE_COLS):
                    self._account_publish(stream, parts, blob=blob)
                    if self.monitor is not None:
                        self.monitor.on_publish(stream.stream_id, seq)
                return
            if self._shutdown.is_set():
                return  # teardown: one best-effort attempt, never a block
            self._gc_blobs(stream)
            now = time.monotonic()
            if stream.blocked_since is None:
                stream.blocked_since = now
            elif now - stream.blocked_since > self._evict_block_s:
                self._evict_slowest(stream)
                stream.blocked_since = now
            if blocked_on_blobs:
                time.sleep(0.002)
            else:
                idle.wait()

    def _gc_blobs(self, stream, drop_all=False):
        """Reclaim blob files the whole fleet has consumed past (or every
        blob, on stream teardown). Runs on the pump and housekeeping threads;
        the ledger is guarded by the stream's write lock."""
        now = time.monotonic()
        with stream.write_lock:
            if drop_all:
                doomed, stream.blobs = stream.blobs, []
                stream.blob_outstanding = 0
            else:
                min_head = stream.ring.min_head()
                doomed = []
                keep = []
                for entry in stream.blobs:
                    end, path, size, eligible_at = entry
                    if end <= min_head:
                        if eligible_at is None:
                            entry[3] = now
                            stream.blob_outstanding -= size
                            keep.append(entry)
                        elif now - eligible_at >= self._blob_grace_s:
                            doomed.append(entry)
                        else:
                            keep.append(entry)
                    else:
                        keep.append(entry)
                stream.blobs = keep
        for _end, path, _size, _el in doomed:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _account_publish(self, stream, parts, blob=None):
        import numpy as np
        if blob is not None:
            nbytes = blob.size
        else:
            nbytes = sum(p.nbytes if isinstance(p, np.ndarray) else len(p)
                         for p in parts)
        with self._lock:
            stream.decoded_batches += 1
            first = True
            for tenant in stream.tenants.values():
                tenant.batches += 1
                tenant.bytes += nbytes
                if not first:
                    # every consumer past the first rides a decode that was
                    # already paid for — the shared-cache hit of this design
                    tenant.shared_hits += 1
                    obs.count('serve_shared_decode_hits_total')
                first = False
        obs.count('serve_batches_published_total')
        obs.count('serve_bytes_published_total', nbytes)

    def _evict_slowest(self, stream):
        """The slow-consumer policy: the tenant with the largest ring lag is
        detached with a loud structured log; its next read raises
        ConsumerEvictedError client-side."""
        with self._lock:
            laggards = sorted(((stream.ring.lag(t.token), t)
                               for t in stream.tenants.values() if not t.evicted),
                              key=lambda x: -x[0])
            if not laggards:
                return
            lag, tenant = laggards[0]
            with obs.span('serve.evict', cat='serve', tenant=tenant.tenant_id,
                          stream=stream.stream_id, lag_bytes=int(lag)):
                with stream.write_lock:
                    stream.ring.evict(tenant.token)
                tenant.evicted = True
            self._evictions += 1
            if self.monitor is not None:
                self.monitor.on_evict(tenant.tenant_id)
        obs.count('serve_evictions_total')
        logger.error(
            'serve: EVICTED tenant %s from stream %s (lag %d bytes blocked the '
            'fleet for %.1fs) — the consumer will see ConsumerEvictedError; '
            'consume faster, lower its weight, or raise serve ring_bytes',
            tenant.tenant_id, stream.stream_id, lag, self._evict_block_s)

    # -- housekeeping --------------------------------------------------------

    def _housekeeping_loop(self):
        while not self._shutdown.is_set():
            time.sleep(0.25)
            with self._lock:
                idle_since = self._idle_since
                streams = list(self._streams.values()) + list(self._retired_streams)
            for stream in streams:
                self._gc_blobs(stream)
            if (idle_since is not None and self._idle_timeout_s is not None
                    and time.monotonic() - idle_since > self._idle_timeout_s):
                logger.info('serve daemon idle for %.0fs; exiting',
                            self._idle_timeout_s)
                self.shutdown()
                return

    # -- observability -------------------------------------------------------

    def stats(self):
        """The per-tenant/per-stream serving evidence (docs/serve.md):
        fair-share occupancy, shared-decode hits, eviction counts, pool and
        cache diagnostics."""
        with self._lock:
            fsv = self._ventilator.tenant_stats() if self._ventilator else {}
            total_dispatched = sum(s['dispatched'] for s in fsv.values()) or 1
            streams = {}
            for stream in list(self._streams.values()) + list(self._retired_streams):
                sched = fsv.get(stream.stream_id, {})
                streams[stream.stream_id] = {
                    'dataset_url': stream.spec.get('dataset_url'),
                    'decoded_batches': stream.decoded_batches,
                    'finished': stream.finished,
                    'tenants': {tid: t.stats() for tid, t in stream.tenants.items()},
                    'fair_share': dict(sched,
                                       occupancy=round(sched.get('dispatched', 0)
                                                       / total_dispatched, 4)),
                    'ring_free_bytes': stream.ring.free_space(),
                    'ring_capacity': stream.ring.capacity,
                }
            return {
                'pid': os.getpid(),
                'pool': self._pool.diagnostics if self._pool else {},
                'streams': streams,
                'evictions': self._evictions,
                'tenants_attached': len(self._tenants),
            }


__all__ = ['DEFAULT_EVICT_BLOCK_S', 'DEFAULT_IDLE_TIMEOUT_S',
           'DEFAULT_SERVE_RING_BYTES', 'ReaderService', 'canonical_stream_id',
           'endpoint_path', 'read_endpoint']
