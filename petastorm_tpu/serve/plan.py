"""Read-plan construction shared by :class:`~petastorm_tpu.reader.Reader`
and the serve daemon's broker.

A *read plan* is everything a reader pipeline needs that does NOT depend on
which process runs it: the resolved schemas, the filtered piece list, the
ventilation work items, and the worker setup args. ``Reader.__init__`` builds
the same plan inline for the single-job path; the serve daemon
(``docs/serve.md``) builds one per *stream* (a distinct dataset + decode
configuration) and runs MANY of them over one shared worker fleet, which is
why the construction lives in a standalone function: decode configuration is
data, not reader object state.
"""

from __future__ import annotations

from petastorm_tpu.cache import NullCache
from petastorm_tpu.errors import NoDataAvailableError, PetastormTpuError
from petastorm_tpu.etl import dataset_metadata
from petastorm_tpu.fs import FilesystemResolver
from petastorm_tpu.transform import transform_schema


class ReadPlan(object):
    """One stream's decode configuration, resolved and ready to run.

    Fields mirror the ``worker_setup_args`` contract of
    :class:`~petastorm_tpu.row_worker.RowGroupDecoderWorker` /
    :class:`~petastorm_tpu.batch_worker.ArrowBatchWorker`; ``items`` is the
    ventilation list (kwargs dicts), ``worker_args`` the picklable per-stream
    setup dict. ``client_plan()`` is the subset a remote consumer needs to
    assemble results on its side of the fan-out ring."""

    __slots__ = ('worker_class', 'worker_args', 'items', 'pieces', 'schema',
                 'output_schema', 'transformed_schema', 'ngram',
                 'columnar_ngram', 'chunk_cache_config', 'num_epochs',
                 'shuffle_row_groups', 'seed')

    def client_plan(self):
        """The picklable consumer-side slice of this plan (schemas + readout
        shape) shipped in the daemon's ATTACH reply."""
        return {
            'schema': self.schema,
            'output_schema': self.output_schema,
            'transformed_schema': self.transformed_schema,
            'ngram': self.ngram,
            'columnar_ngram': self.columnar_ngram,
            'num_epochs': self.num_epochs,
        }


def build_work_items(num_pieces, shuffle_row_drop_partitions, worker_predicate):
    """The ventilation item list for a filtered piece set — one kwargs dict
    per (piece, row-drop partition), carrying the worker predicate when one
    survived partition pushdown. Shared by ``Reader.__init__`` and the serve
    broker."""
    items = []
    for piece_index in range(num_pieces):
        for drop_part in range(shuffle_row_drop_partitions):
            item = {'piece_index': piece_index}
            if worker_predicate is not None:
                item['worker_predicate'] = worker_predicate
            if shuffle_row_drop_partitions > 1:
                item['shuffle_row_drop_partition'] = (drop_part,
                                                      shuffle_row_drop_partitions)
            items.append(item)
    return items


def build_read_plan(dataset_url,
                    batch_reader=False,
                    schema_fields=None,
                    seed=None,
                    shuffle_row_groups=True,
                    shuffle_row_drop_partitions=1,
                    predicate=None,
                    rowgroup_selector=None,
                    num_epochs=1,
                    cur_shard=None, shard_count=None,
                    transform_spec=None,
                    ngram=None,
                    columnar_ngram=False,
                    storage_retry_policy=None,
                    chunk_cache=None, chunk_cache_size_limit=None,
                    cache=None):
    """Resolve schemas, list + filter pieces, and assemble worker args for one
    stream. Raises the same errors :func:`petastorm_tpu.make_reader` would
    (missing metadata, empty selection, invalid sharding)."""
    # the Reader staticmethods ARE the canonical filter pipeline; import here
    # to avoid a module-level cycle (reader imports serve for serve=)
    from petastorm_tpu.reader import Reader

    if (cur_shard is None) != (shard_count is None):
        raise ValueError('cur_shard and shard_count must be specified together')
    if cur_shard is not None and not 0 <= cur_shard < shard_count:
        raise ValueError('cur_shard {} out of range for shard_count {}'.format(
            cur_shard, shard_count))
    if shuffle_row_drop_partitions < 1:
        raise ValueError('shuffle_row_drop_partitions must be >= 1')

    if batch_reader:
        from petastorm_tpu.batch_worker import ArrowBatchWorker as worker_class
        schema = dataset_metadata.infer_or_load_unischema(
            dataset_url, retry_policy=storage_retry_policy)
    else:
        from petastorm_tpu.row_worker import RowGroupDecoderWorker as worker_class
        try:
            schema = dataset_metadata.get_schema(dataset_url,
                                                 retry_policy=storage_retry_policy)
        except dataset_metadata.PetastormMetadataError:
            raise PetastormTpuError(
                'Dataset at {} is missing unischema metadata. If it is a plain '
                'Parquet store, use make_batch_reader instead.'.format(dataset_url))

    resolver = FilesystemResolver(dataset_url, retry_policy=storage_retry_policy)
    from petastorm_tpu.chunkstore import resolve_chunk_cache
    chunk_cache_config = resolve_chunk_cache(
        chunk_cache, dataset_url, resolver.is_local,
        size_limit_bytes=chunk_cache_size_limit)

    if ngram is not None:
        ngram.resolve_regex_field_names(schema)
        needed = [n for n in ngram.get_field_names_at_all_timesteps()
                  if n in schema.fields]
        output_schema = schema.create_schema_view([schema.fields[n] for n in needed])
    elif schema_fields is not None:
        output_schema = schema.create_schema_view(schema_fields)
    else:
        output_schema = schema
    transformed_schema = (transform_schema(output_schema, transform_spec)
                          if transform_spec is not None else output_schema)

    if ngram is not None and not ngram.timestamp_overlap and shuffle_row_drop_partitions > 1:
        raise NotImplementedError(
            'shuffle_row_drop_partitions > 1 with timestamp_overlap=False would '
            'duplicate rows across partition-boundary windows')

    pieces = dataset_metadata.load_row_groups(dataset_url, schema=schema,
                                              retry_policy=storage_retry_policy)
    if rowgroup_selector is not None:
        pieces = Reader._apply_rowgroup_selector(dataset_url, pieces,
                                                 rowgroup_selector,
                                                 storage_retry_policy)
    pieces, worker_predicate = Reader._apply_predicate_to_pieces(pieces, predicate)
    pieces = Reader._partition_pieces(pieces, cur_shard, shard_count)
    if not pieces:
        raise NoDataAvailableError(
            'No row groups selected for reading (dataset={}, shard {}/{}). Check '
            'predicate/selector, or reduce shard_count.'.format(
                dataset_url, cur_shard, shard_count))

    plan = ReadPlan()
    plan.worker_class = worker_class
    plan.items = build_work_items(len(pieces), shuffle_row_drop_partitions,
                                  worker_predicate)
    plan.pieces = pieces
    plan.schema = schema
    plan.output_schema = output_schema
    plan.transformed_schema = transformed_schema
    plan.ngram = ngram
    plan.columnar_ngram = columnar_ngram
    plan.chunk_cache_config = chunk_cache_config
    plan.num_epochs = num_epochs
    plan.shuffle_row_groups = shuffle_row_groups
    plan.seed = seed
    plan.worker_args = {
        'dataset_path': resolver.get_dataset_path(),
        'filesystem_factory': resolver.filesystem_factory(),
        'pieces': pieces,
        'schema': schema,
        'output_schema': output_schema,
        'transform_spec': transform_spec,
        'transformed_schema': transformed_schema,
        'ngram': ngram,
        'columnar_ngram': columnar_ngram,
        'cache': cache or NullCache(),
        'chunk_cache': chunk_cache_config,
    }
    return plan


__all__ = ['ReadPlan', 'build_read_plan', 'build_work_items']
