"""Consumer side of the shared reader service: spawn-or-join + ServedReader.

``make_reader(serve='auto' | <service dir>)`` lands here: the client resolves
the service directory, joins the running daemon (or wins the O_EXCL spawn
race and starts one), ATTACHes its stream spec over the control socket, and
gets back a broadcast-ring name + consumer token + the client-side read plan.
:class:`ServedReader` is then a drop-in ``Reader``: the same iterator /
``diagnostics`` / ``stop``/``join`` surface, with the pool replaced by a
facade that reads frames off the fan-out ring.

Failure surface (tests pin all three): a daemon crash raises
:class:`~petastorm_tpu.errors.ServeDaemonDiedError` instead of hanging; an
eviction raises :class:`~petastorm_tpu.errors.ConsumerEvictedError`; a clean
per-tenant end of stream is a normal ``StopIteration``.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time

from petastorm_tpu import observability as obs
from petastorm_tpu.observability import blackbox
from petastorm_tpu.errors import (ConsumerEvictedError, EmptyResultError,
                                  ServeDaemonDiedError, ServeError)
from petastorm_tpu.serializers import NumpyBlockSerializer
from petastorm_tpu.serve.service import (LOCK_FILE, endpoint_path, read_endpoint)
from petastorm_tpu.workers.protocol import (SERVE_BLOB, SERVE_COLS, SERVE_DATA,
                                            SERVE_DONE, SERVE_END, SERVE_ERROR,
                                            ring_unpack)

logger = logging.getLogger(__name__)

_SPAWN_TIMEOUT_S = 30.0
#: liveness-probe period while blocked on a quiet ring
_LIVENESS_PERIOD_S = 1.0


def default_service_dir():
    """Per-user default service directory ('auto'): one daemon per host+user."""
    base = os.environ.get('PSTPU_SERVE_DIR')
    if base:
        return base
    import tempfile
    return os.path.join(tempfile.gettempdir(),
                        'pstpu-serve-{}'.format(os.getuid()))


def _pid_alive(pid):
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError):
        return True
    # signal-0 succeeds on a ZOMBIE too — and a daemon this process spawned
    # becomes exactly that when it dies (nothing reaps it until interpreter
    # exit), which would turn "daemon crashed" into an infinite liveness loop
    try:
        with open('/proc/{}/stat'.format(pid)) as f:
            # field 3 (after the parenthesized comm, which may contain spaces)
            return f.read().rsplit(')', 1)[-1].split()[0] != 'Z'
    except (OSError, IndexError):
        return True  # no procfs: assume alive (the conservative direction)


def _spawn_daemon(service_dir, spawn_args):
    """Launch the daemon process (detached session; logs into the service
    dir). The caller holds the O_EXCL lock."""
    argv = [sys.executable, '-m', 'petastorm_tpu.serve',
            '--service-dir', service_dir]
    for key, flag in (('pool_type', '--pool-type'),
                      ('workers_count', '--workers-count'),
                      ('ring_bytes', '--ring-bytes'),
                      ('idle_timeout_s', '--idle-timeout'),
                      ('evict_block_s', '--evict-block'),
                      ('telemetry', '--telemetry')):
        value = spawn_args.get(key)
        if value is not None:
            argv += [flag, str(value)]
    if spawn_args.get('telemetry') is None and obs.spans_on():
        # a tracing client spawns a tracing daemon: otherwise the served
        # batch's tree has a client half only
        argv += ['--telemetry', 'spans']
    env = dict(os.environ)
    pkg_parent = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env['PYTHONPATH'] = pkg_parent + os.pathsep + env.get('PYTHONPATH', '')
    log_path = os.path.join(service_dir, 'daemon.log')
    with open(log_path, 'ab') as log:
        proc = subprocess.Popen(argv, stdout=log, stderr=log,
                                start_new_session=True, env=env)
    logger.info('spawned serve daemon pid %d (dir=%s, log=%s)', proc.pid,
                service_dir, log_path)
    return proc


def connect_service(service_dir, spawn_args=None, timeout_s=_SPAWN_TIMEOUT_S):
    """Join the service daemon for ``service_dir``, spawning one via the
    O_EXCL handshake when none is running. Returns an open control
    Connection."""
    from multiprocessing.connection import Client
    service_dir = os.path.abspath(service_dir)
    os.makedirs(service_dir, exist_ok=True)
    lock_path = os.path.join(service_dir, LOCK_FILE)
    deadline = time.monotonic() + timeout_s
    spawned = False
    while time.monotonic() < deadline:
        endpoint = read_endpoint(service_dir)
        if endpoint is not None:
            if not _pid_alive(endpoint['pid']):
                # stale endpoint from a dead daemon: clear it (and the lock)
                # so the spawn race can run again
                for p in (endpoint_path(service_dir), lock_path):
                    try:
                        os.unlink(p)
                    except OSError:
                        pass
            else:
                try:
                    conn = Client(endpoint['address'], family='AF_UNIX')
                    conn.send({'op': 'ping'})
                    if conn.recv().get('ok'):
                        return conn
                    conn.close()
                except (OSError, EOFError, ConnectionError):
                    time.sleep(0.05)
                    continue
        if not spawned:
            try:
                fd = os.open(lock_path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                _spawn_daemon(service_dir, spawn_args or {})
                spawned = True
            except FileExistsError:
                # another process won the race (or a daemon is mid-startup);
                # clear a stale lock whose owner died before publishing
                try:
                    with open(lock_path) as f:
                        owner = int(f.read().strip() or '0')
                    if owner and not _pid_alive(owner) \
                            and read_endpoint(service_dir) is None:
                        os.unlink(lock_path)
                except (OSError, ValueError):
                    pass
        time.sleep(0.05)
    raise ServeError('no serve daemon reachable under {} within {}s (see {} '
                     'for daemon-side errors)'.format(
                         service_dir, timeout_s,
                         os.path.join(service_dir, 'daemon.log')))


def _map_blob(path, size, tenant_id):
    """COW-map a served batch blob, returning ``(memoryview, slot)``:
    writable views with zero upfront copy; the mapping (not the name) keeps
    the pages alive past the daemon's reclaim. A vanished blob means this
    consumer fell behind the fleet's GC horizon — surfaced like an eviction,
    never as a hang or torn data.

    :borrows: the view borrows the mapping; the caller adopts the batch's
        arrays into ``slot`` (``native/lifetime.py``) and seals it, so the
        map closes exactly when the batch dies and the live window shows up
        in ``lifetime_live_borrows``."""
    import mmap
    try:
        with open(path, 'rb') as f:
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
        try:
            mm.madvise(mmap.MADV_WILLNEED)  # prefault in-kernel, not per-page
        except (AttributeError, OSError):
            pass
    except OSError as e:
        raise ConsumerEvictedError(
            'served batch blob {} was reclaimed before this consumer mapped '
            'it (consumer far behind the fleet): {} — consume faster or '
            'raise the daemon blob budget (docs/serve.md)'.format(path, e),
            tenant_id=tenant_id)

    def _close():
        try:
            mm.close()
        except BufferError:
            pass  # a straggler export closes it when the GC drops the chain

    from petastorm_tpu.native.lifetime import registry as lifetime_registry
    slot = lifetime_registry().open_slot(on_release=_close, label='serve-blob')
    return memoryview(mm)[:size], slot  # noqa: PT500 - registered with the lifetime registry


class _ServedPoolFacade(object):
    """Duck-types the pool surface the results-queue readers consume
    (``get_results`` / ``last_result_seq`` / ``done_callback``) over a
    broadcast-ring consumer slot."""

    def __init__(self, ring, token, daemon_pid, tenant_id, monitor=None,
                 trace_ns=None):
        self._ring = ring
        self._token = token
        self._daemon_pid = daemon_pid
        self._tenant_id = tenant_id
        # the daemon broker's trace-mint namespace (attach reply): with it
        # every frame's trace root derives from the seq in the ring header,
        # so client-side spans join the daemon-side tree with zero extra
        # wire bytes
        self._trace_ns = trace_ns
        self._serializer = NumpyBlockSerializer()
        self._stopped = False
        self._ended = False
        self.last_result_seq = None
        self.done_callback = None
        self.monitor = monitor
        self.batches_received = 0
        self.bytes_received = 0
        self.last_result_trace = None

    def _note_result(self, seq):
        """Bookkeeping shared by every payload-carrying frame kind."""
        self.last_result_seq = seq
        if self._trace_ns is not None and seq is not None and obs.spans_on():
            self.last_result_trace = obs.trace_root(self._trace_ns, seq)
        self.batches_received += 1

    def get_results(self):
        with obs.stage('pool_wait', cat='pool') as sp:
            payload = self._get_results()
            # the frame's identity is only known after the read, so the wait
            # span joins the batch's tree retroactively
            sp.link(self.last_result_trace)
            return payload

    def _get_results(self):
        from petastorm_tpu.native.shm_ring import BcastConsumerGone
        while True:
            if self._ended:
                raise EmptyResultError()
            try:
                view = self._ring.read_view(self._token,
                                            stop_check=lambda: self._stopped,
                                            timeout_s=_LIVENESS_PERIOD_S)
            except BcastConsumerGone as e:
                if e.evicted:
                    raise ConsumerEvictedError(
                        'this consumer was evicted by the serve daemon (it '
                        'lagged far enough to stall the fleet) — consume '
                        'faster, raise serve ring_bytes, or re-attach '
                        '(docs/serve.md)', tenant_id=self._tenant_id)
                raise ServeError('serve consumer slot was released '
                                 '(detached elsewhere?)')
            if view is None:
                if self._stopped:
                    raise EmptyResultError()
                if not _pid_alive(self._daemon_pid):
                    raise ServeDaemonDiedError(
                        'serve daemon (pid {}) died with this consumer '
                        'attached; re-run make_reader(serve=...) to spawn '
                        'a replacement'.format(self._daemon_pid))
                continue
            kind, seq, payload = ring_unpack(view)
            if kind == SERVE_DATA:
                if self.monitor is not None:
                    self.monitor.on_deliver(seq)
                self._note_result(seq)
                self.bytes_received += len(payload)
                return self._serializer.deserialize(payload)
            elif kind == SERVE_COLS:
                # the zero-copy plane: the fused decode wrote the batch
                # straight into the blob; build typed views over the
                # COW mapping from the layout descriptor
                import pickle
                desc = pickle.loads(bytes(payload))
                if self.monitor is not None:
                    self.monitor.on_deliver(seq)
                self._note_result(seq)
                self.bytes_received += desc['size']
                mv, slot = _map_blob(desc['path'], desc['size'], self._tenant_id)
                import numpy as np
                block = {}
                for name, dtype_str, shape, off, nbytes in desc['cols']:
                    block[name] = np.frombuffer(
                        mv[off:off + nbytes],
                        dtype=np.dtype(dtype_str)).reshape(shape)
                slot.adopt(block)
                slot.seal()
                return block
            elif kind == SERVE_BLOB:
                # the batch sits in a shared /dev/shm blob: COW-map it
                # (writable numpy views, zero upfront copy); the daemon
                # reclaims the file once the fleet's cursors passed this
                # frame (plus a grace covering exactly this window)
                size_s, path = bytes(payload).decode().split('|', 1)
                if self.monitor is not None:
                    self.monitor.on_deliver(seq)
                self._note_result(seq)
                self.bytes_received += int(size_s)
                mv, slot = _map_blob(path, int(size_s), self._tenant_id)
                result = self._serializer.deserialize(mv)
                slot.adopt(result)
                slot.seal()
                return result
            elif kind == SERVE_DONE:
                if self.done_callback is not None and seq is not None:
                    self.done_callback(seq)
            elif kind == SERVE_END:
                if self.monitor is not None:
                    self.monitor.on_consumer_end()
                self._ended = True
                raise EmptyResultError()
            elif kind == SERVE_ERROR:
                import pickle
                try:
                    err = pickle.loads(bytes(payload))
                except Exception:  # noqa: BLE001 - a garbled report must still fail loudly
                    err = ServeError('serve daemon reported an unreadable error')
                raise ServeError('serve daemon stream failed: {}'.format(err))
            else:
                logger.warning('dropping serve frame with unknown kind %r', kind)

    def stop(self):
        self._stopped = True

    @property
    def diagnostics(self):
        from petastorm_tpu.native.lifetime import registry as lifetime_registry
        out = {'serve_batches_received': self.batches_received,
               'serve_bytes_received': self.bytes_received}
        out.update(lifetime_registry().counters())
        return out


class ServedReader(object):
    """Drop-in ``Reader`` over a shared serve daemon (``docs/serve.md``).

    Iterates exactly like the plain reader it replaces (rows, columnar blocks
    or rebatched blocks, per the ``make_reader`` arguments), but the decode
    runs once in the per-host daemon no matter how many local consumers
    attach. Not supported in served mode: ``resume_state`` (the stream is
    shared — there is no private read position), ``autotune`` (the daemon owns
    the fleet) — ``make_reader`` rejects those combinations.
    """

    def __init__(self, conn, reply, results_queue_reader_factory,
                 service_dir, monitor=None):
        self._conn = conn
        self._service_dir = service_dir
        self.tenant_id = reply['tenant_id']
        self.stream_id = reply['stream_id']
        plan = reply['client_plan']
        self.schema = plan['schema']
        self.output_schema = plan['output_schema']
        self.transformed_schema = plan['transformed_schema']
        self.ngram = plan['ngram']
        from petastorm_tpu.native.shm_ring import BcastRing
        self._ring = BcastRing.attach(reply['ring_name'])
        self._facade = _ServedPoolFacade(self._ring, reply['token'],
                                         reply['daemon_pid'], self.tenant_id,
                                         monitor=monitor,
                                         trace_ns=reply.get('trace_ns'))
        self._results_queue_reader = results_queue_reader_factory(
            self.transformed_schema)
        self.last_row_consumed = False
        self._stopped = False
        # flight recorder: a wedged served consumer + a dead daemon pid is the
        # canonical post-mortem pairing (docs/troubleshooting.md)
        flight = blackbox.maybe_enable('serve-client')
        if flight is not None:
            flight.record(blackbox.K_EVENT,
                          {'event': 'serve_attach', 'tenant_id': self.tenant_id,
                           'stream_id': self.stream_id,
                           'daemon_pid': reply['daemon_pid']})

    @property
    def batched_output(self):
        return self._results_queue_reader.batched_output

    def __iter__(self):
        return self

    def __next__(self):
        try:
            return self._results_queue_reader.read_next(self._facade)
        except EmptyResultError:
            self.last_row_consumed = True
            raise StopIteration

    next = __next__

    def reset(self):
        raise ServeError('reset() is not supported on a served reader: the '
                         'stream is shared. Re-attach with make_reader(serve=...) '
                         'for another pass.')

    def state_dict(self):
        raise ServeError('state_dict() is not supported on a served reader: '
                         'the read position belongs to the shared stream, not '
                         'this consumer (docs/serve.md).')

    @property
    def quarantined_items(self):
        return []

    @property
    def diagnostics(self):
        """Client-side counters + this tenant's daemon-side serving stats
        (fair-share occupancy, shared-decode hits — docs/serve.md)."""
        diag = obs.flatten_snapshot(obs.snapshot())
        diag.update(self._facade.diagnostics)
        stats = self.service_stats()
        if stats is not None:
            stream = stats.get('streams', {}).get(self.stream_id, {})
            tenant = stream.get('tenants', {}).get(self.tenant_id, {})
            diag.update({'serve_tenant_' + k: v for k, v in tenant.items()
                         if not isinstance(v, dict)})
            fair = stream.get('fair_share', {})
            if 'occupancy' in fair:
                diag['serve_fair_share_occupancy'] = fair['occupancy']
            diag['serve_stream_decoded_batches'] = stream.get('decoded_batches', 0)
            diag['serve_evictions'] = stats.get('evictions', 0)
        return diag

    @property
    def last_trace(self):
        """Virtual-root TraceContext of the most recently delivered batch
        (derived client-side from the frame seq + the daemon's trace_ns)."""
        return self._facade.last_result_trace

    def service_stats(self):
        """The daemon's full stats document, or None when it is unreachable."""
        if self._conn is None:
            return None
        try:
            self._conn.send({'op': 'stats'})
            reply = self._conn.recv()
            return reply.get('stats') if reply.get('ok') else None
        except (OSError, EOFError, ValueError):
            return None

    def service_trace_events(self, absorb=True):
        """Fetch a snapshot of the daemon's span ring (ventilate + worker +
        daemon pool-wait spans) so a client can reconstruct a served batch's
        full cross-process tree. With ``absorb`` (default) the events merge
        into this process's ring; the list is returned either way. Returns []
        when the daemon is unreachable."""
        if self._conn is None:
            return []
        try:
            self._conn.send({'op': 'trace'})
            reply = self._conn.recv()
        except (OSError, EOFError, ValueError):
            return []
        events = reply.get('events') if reply.get('ok') else None
        events = events or []
        if absorb:
            obs.absorb_trace_events(events)
        return events

    def stop(self):
        if self._stopped:
            return
        self._stopped = True
        self._facade.stop()
        if self._conn is not None:
            try:
                self._conn.send({'op': 'detach', 'tenant_id': self.tenant_id})
                self._conn.recv()
            except (OSError, EOFError, ValueError):
                pass  # daemon already gone: nothing to release
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None

    def join(self):
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        if not self._stopped:
            self.stop()
            self.join()


def make_served_reader(spec, serve, results_queue_reader_factory,
                       weight=1, spawn_args=None, monitor=None):
    """ATTACH ``spec`` to the service for ``serve`` ('auto' or a service
    directory), spawning the daemon when absent. Returns a ServedReader."""
    service_dir = default_service_dir() if serve in (True, 'auto') else str(serve)
    conn = connect_service(service_dir, spawn_args=spawn_args)
    conn.send({'op': 'attach', 'spec': spec, 'weight': weight})
    reply = conn.recv()
    if not reply.get('ok'):
        try:
            conn.close()
        except OSError:
            pass
        raise ServeError('serve attach failed: {}'.format(reply.get('error')))
    from petastorm_tpu.analysis.protocol.monitor import serve_monitor_from_env
    return ServedReader(conn, reply, results_queue_reader_factory, service_dir,
                        monitor=serve_monitor_from_env(monitor, 'serve-consumer'))


__all__ = ['ServedReader', 'connect_service', 'default_service_dir',
           'make_served_reader']
