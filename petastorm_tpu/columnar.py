"""Column-block utilities shared by the decode workers and the JAX loader.

A *column block* is the unit that flows from a decode worker to the consumer:
a plain dict ``{field_name: column}`` where each column holds one decoded value
per row, as either

  * a numpy array with a leading row axis (fields whose cells share one
    shape/dtype — the common case), or
  * a 1-D object array (ragged tensors, strings, Decimals, nullable cells).

Blocks replace the reference's list-of-row-dicts worker output
(/root/reference/petastorm/py_dict_reader_worker.py:121-169): rows stop being
Python objects on the hot path, so per-row cost collapses to numpy slicing.
Rows are materialized (as schema namedtuples) only for users who iterate rows.
"""

from __future__ import annotations

from collections import deque

import numpy as np
import pyarrow as pa


def column_cells(column):
    """ChunkedArray -> list of per-row cell values. Binary columns skip
    ``to_pylist`` (which copies every cell into a bytes object) and hand out
    zero-copy memoryview slices of the Arrow data buffer instead — codecs
    (np.frombuffer, cv2.imdecode) consume memoryviews directly, so the only
    copy left in the decode path is the decode itself."""
    t = column.type
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        out = []
        for chunk in column.chunks:
            n = len(chunk)
            if n == 0:
                continue
            if chunk.null_count:
                out.extend(chunk.to_pylist())
                continue
            off_dtype = np.int64 if pa.types.is_large_binary(t) else np.int32
            _, offsets_buf, data_buf = chunk.buffers()
            offs = np.frombuffer(offsets_buf, dtype=off_dtype, count=n + 1,
                                 offset=chunk.offset * np.dtype(off_dtype).itemsize).tolist()
            mv = memoryview(data_buf)
            out.extend(mv[offs[i]:offs[i + 1]] for i in range(n))
        return out
    return column.to_pylist()


def _object_column(values):
    out = np.empty(len(values), dtype=object)
    for i, v in enumerate(values):
        out[i] = v
    return out


def stack_cells(values):
    """List of decoded cells -> one block column: a stacked ``[N, ...]`` array
    when every cell is an array of one shape/dtype (or a numpy/python scalar),
    else a 1-D object array preserving each cell (including ``None``)."""
    if not values:
        return np.empty(0, dtype=object)
    v0 = values[0]
    if isinstance(v0, np.ndarray) and v0.ndim > 0:
        shape, dtype = v0.shape, v0.dtype
        for v in values:
            if not (isinstance(v, np.ndarray) and v.shape == shape and v.dtype == dtype):
                return _object_column(values)
        if dtype == object:
            return _object_column(values)
        return np.stack(values)
    if isinstance(v0, (np.bool_, np.number)) or type(v0) in (int, float, bool):
        try:
            return np.array(values)
        except ValueError:
            return _object_column(values)
    # str/bytes/Decimal/datetime/None/mixed: object column keeps cells verbatim
    return _object_column(values)


def block_num_rows(block):
    return len(next(iter(block.values()))) if block else 0


def block_to_rows(block, field_order=None):
    """Explode a block into per-row dicts (worker-side transforms and NGram
    assembly still operate on rows)."""
    names = list(field_order) if field_order is not None else list(block)
    cols = [block[name] for name in names]
    n = len(cols[0]) if cols else 0
    return [dict(zip(names, (c[i] for c in cols))) for i in range(n)]


def rows_to_block(rows, field_order=None):
    """Re-collate row dicts into a block (after a per-row transform)."""
    names = list(field_order) if field_order is not None else list(rows[0])
    return {name: stack_cells([r[name] for r in rows]) for name in names}


def take_block(block, indices):
    """Select rows of every column (numpy fancy indexing; object columns too)."""
    return {name: col[indices] for name, col in block.items()}


def concat_columns(parts):
    """Concatenate per-segment arrays of one logical column. Segments may mix a
    stacked 2-D layout with a 1-D object layout (e.g. a list column that is
    uniform in one row group and ragged in the next) — mixed layouts degrade to
    one object column instead of crashing concat."""
    if len(parts) == 1:
        return parts[0]
    uniform = (len({p.ndim for p in parts}) == 1 and
               len({p.shape[1:] for p in parts}) == 1 and
               len({p.dtype == object for p in parts}) == 1)
    if uniform:
        return np.concatenate(parts)
    rows = []
    for p in parts:
        rows.extend(p[i] for i in range(len(p)))
    return _object_column(rows)


def concat_blocks(blocks):
    """Concatenate blocks row-wise (all blocks must share the same field set)."""
    if len(blocks) == 1:
        return blocks[0]
    return {name: concat_columns([b[name] for b in blocks]) for name in blocks[0]}


class BlockResultsReaderBase(object):
    """Shared consumer-side reader for block-per-item pools: one published
    payload per ``read_next``, delivered-callback checkpoint bookkeeping (an
    item counts as delivered the moment its payload is returned; items that
    published nothing deliver via the pool's completion sentinel). Subclasses
    override :meth:`_convert` for their output shape."""

    batched_output = True

    def __init__(self, schema):
        self._schema = schema
        self.delivered_callback = None

    def on_item_done(self, seq):
        if self.delivered_callback is not None:
            self.delivered_callback(seq)

    def _convert(self, payload):
        return payload

    def read_next(self, pool):
        payload = pool.get_results()
        seq = getattr(pool, 'last_result_seq', None)
        if seq is not None and self.delivered_callback is not None:
            self.delivered_callback(seq)
        return self._convert(payload)


class BatchingColumnQueue(object):
    """FIFO queue of column blocks re-chunked to a fixed row count — the ONE
    implementation of block buffering/slicing, shared by
    ``make_batch_reader(batch_size=)`` rebatching (via
    ``rebatch.RebatchingResultsQueueReader``) and the loader's
    :class:`FifoColumnarBuffer`.

    ``put`` accepts a block (dict of equal-length columns); ``get`` returns a
    block with exactly ``batch_size`` rows, preserving input row order
    (reference pyarrow_helpers/batching_table_queue.py:20-79 semantics,
    columnar instead of Arrow tables). Rows are never copied at ``put`` time:
    input columns are buffered as views and only concatenated when a batch
    boundary crosses a buffer segment.
    """

    def __init__(self, batch_size):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1, got {}'.format(batch_size))
        self._batch_size = batch_size
        self._segments = deque()  # (block, tag)
        self._head = 0  # rows of the head segment already consumed
        self._buffered = 0
        self._drained_tags = []  # tags of segments fully consumed by take()

    def __len__(self):
        return self._buffered

    def put(self, batch, tag=None):
        """``tag``: opaque id returned via :meth:`pop_drained_tags` once every
        row of this batch has left the queue (checkpoint bookkeeping)."""
        lengths = {len(v) for v in batch.values()}
        if len(lengths) != 1:
            raise ValueError('ragged batch: column lengths {}'.format(sorted(lengths)))
        n = lengths.pop()
        if n == 0:
            if tag is not None:
                self._drained_tags.append(tag)
            return
        self._segments.append((batch, tag))
        self._buffered += n

    def pop_drained_tags(self):
        """Tags of segments whose rows have all been taken since the last call."""
        tags, self._drained_tags = self._drained_tags, []
        return tags

    def empty(self):
        """True when a full ``batch_size`` batch cannot be produced yet."""
        return self._buffered < self._batch_size

    def get(self):
        assert not self.empty()
        return self.take(self._batch_size)

    def drain(self):
        """Return all remaining rows as one final (possibly short) batch, or
        None if nothing is buffered."""
        if self._buffered == 0:
            return None
        return self.take(self._buffered)

    def take(self, count):
        parts = []  # list of dict-of-views
        taken = 0
        while taken < count:
            head, tag = self._segments[0]
            head_len = len(next(iter(head.values())))
            take = min(count - taken, head_len - self._head)
            parts.append({k: v[self._head:self._head + take] for k, v in head.items()})
            self._head += take
            taken += take
            if self._head == head_len:
                self._segments.popleft()
                self._head = 0
                if tag is not None:
                    self._drained_tags.append(tag)
        self._buffered -= count
        return concat_blocks(parts)

    def clear(self):
        self._segments.clear()
        self._head = 0
        self._buffered = 0
        self._drained_tags = []

    def snapshot_rows(self):
        """Remaining buffered rows as plain row dicts (loader checkpoints)."""
        rows = []
        for i, (seg, _) in enumerate(self._segments):
            start = self._head if i == 0 else 0
            cols = list(seg.items())
            for r in range(start, block_num_rows(seg)):
                rows.append({k: v[r] for k, v in cols})
        return rows


class FifoColumnarBuffer(object):
    """FIFO of column blocks with fixed-size batch extraction — the columnar
    analog of :class:`petastorm_tpu.shuffling_buffer.NoopShufflingBuffer`, a
    thin loader-facing facade over :class:`BatchingColumnQueue`."""

    def __init__(self):
        self._q = BatchingColumnQueue(1)

    @property
    def size(self):
        return len(self._q)

    def add_block(self, block):
        self._q.put(block)

    def can_emit(self, batch_size):
        return len(self._q) >= batch_size

    def emit(self, count):
        return self._q.take(count)

    def finish(self):
        pass

    def clear(self):
        self._q.clear()

    def snapshot_rows(self):
        return self._q.snapshot_rows()


class ShuffledColumnarBuffer(object):
    """Columnar decorrelation buffer: the analog of
    :class:`petastorm_tpu.shuffling_buffer.RandomShufflingBuffer`, but instead
    of per-row random-swap retrieves it keeps buffered blocks intact and
    permutes *row indices* ``(segment, row)`` over them. Emitting a batch
    gathers the selected rows segment-by-segment into one freshly allocated
    batch — exactly one data copy per emitted row, no pool-rebuild copies, no
    per-row Python. Every row is permuted within a window of ~``capacity``
    rows, and the ``min_after`` floor keeps a mixing reservoir alive across
    refills (same decorrelation contract as the row buffer; verified by the
    rank-correlation test in tests/test_shuffle_quality.py).

    Blocks larger than ``capacity`` are accepted whole (a row group may dwarf
    the buffer — same stance as the row buffer's ``extra_capacity``)."""

    def __init__(self, capacity, min_after, seed=None):
        if min_after >= capacity:
            raise ValueError('min_after ({}) must be smaller than capacity ({})'.format(
                min_after, capacity))
        self._capacity = capacity
        self._min_after = min_after
        self._rng = np.random.default_rng(seed)
        self._segments = {}       # seg_id -> block
        self._seg_remaining = {}  # seg_id -> rows not yet emitted
        self._next_seg = 0
        # permuted (segment, row) pairs not yet emitted, consumed from _cursor
        self._order_seg = np.empty(0, dtype=np.int64)
        self._order_row = np.empty(0, dtype=np.int64)
        self._cursor = 0
        self._staged_ids = []     # seg ids not yet folded into the permutation
        self._staged_rows = 0
        self._done = False

    @property
    def size(self):
        return (len(self._order_seg) - self._cursor) + self._staged_rows

    @property
    def rng_state(self):
        """Picklable RNG state, for loader checkpoints: restoring it makes a
        seeded resume reproduce the exact pre-checkpoint batch stream."""
        return self._rng.bit_generator.state

    @rng_state.setter
    def rng_state(self, state):
        self._rng.bit_generator.state = state

    def resize(self, capacity, min_after):
        """Retarget capacity/decorrelation floor at runtime (the autotuner's
        shuffle knob). Buffered rows are kept; ``can_emit`` reflects the new
        bounds from the next call."""
        if min_after >= capacity:
            raise ValueError('min_after ({}) must be smaller than capacity ({})'.format(
                min_after, capacity))
        self._capacity = capacity
        self._min_after = min_after

    def add_block(self, block):
        n = block_num_rows(block)
        if not n:
            return
        sid = self._next_seg
        self._next_seg += 1
        self._segments[sid] = block
        self._seg_remaining[sid] = n
        self._staged_ids.append(sid)
        self._staged_rows += n

    def can_emit(self, batch_size):
        if self._done:
            return self.size > 0
        return self.size - batch_size >= self._min_after

    def emit(self, count):
        count = min(count, self.size)
        if len(self._order_seg) - self._cursor < count:
            self._fold_staged()
        sel_seg = self._order_seg[self._cursor:self._cursor + count]
        sel_row = self._order_row[self._cursor:self._cursor + count]
        self._cursor += count
        out = {}
        plan = []  # (seg block, row indices) in one pass, shared by all columns
        for sid in np.unique(sel_seg):
            rows = sel_row[sel_seg == sid]
            plan.append((self._segments[sid], rows))
            self._seg_remaining[sid] -= len(rows)
            if self._seg_remaining[sid] == 0:
                del self._segments[sid]
                del self._seg_remaining[sid]
        first = plan[0][0]
        for name in first:
            col0 = first[name]
            uniform = (isinstance(col0, np.ndarray) and col0.dtype != object and all(
                isinstance(seg[name], np.ndarray) and seg[name].dtype == col0.dtype
                and seg[name].shape[1:] == col0.shape[1:] for seg, _ in plan))
            if uniform:
                # single-copy gather straight into the batch allocation.
                # Wide rows (images, tensors) copy ~2.5x faster as one plain
                # memcpy per row than through np.take's gather machinery;
                # narrow rows (scalars) vectorize better with take.
                out_col = np.empty((count,) + col0.shape[1:], col0.dtype)
                wide = col0[:1].nbytes >= 4096
                pos = 0
                for seg, rows in plan:
                    src = seg[name]
                    if wide:
                        for row in rows:
                            out_col[pos] = src[row]
                            pos += 1
                    else:
                        np.take(src, rows, axis=0, out=out_col[pos:pos + len(rows)])
                        pos += len(rows)
                out[name] = out_col
            else:
                parts = [seg[name][rows] for seg, rows in plan]
                out[name] = parts[0] if len(parts) == 1 else concat_columns(parts)
        return out

    def _fold_staged(self):
        """Fold staged segments into a fresh permutation together with every
        not-yet-emitted index — index arrays only, no row data is touched."""
        segs = [self._order_seg[self._cursor:]]
        rows = [self._order_row[self._cursor:]]
        for sid in self._staged_ids:
            n = self._seg_remaining[sid]
            segs.append(np.full(n, sid, dtype=np.int64))
            rows.append(np.arange(n, dtype=np.int64))
        all_seg = np.concatenate(segs)
        all_row = np.concatenate(rows)
        perm = self._rng.permutation(len(all_seg))
        self._order_seg = all_seg[perm]
        self._order_row = all_row[perm]
        self._cursor = 0
        self._staged_ids = []
        self._staged_rows = 0

    def finish(self):
        self._done = True

    def clear(self):
        self._segments = {}
        self._seg_remaining = {}
        self._order_seg = np.empty(0, dtype=np.int64)
        self._order_row = np.empty(0, dtype=np.int64)
        self._cursor = 0
        self._staged_ids = []
        self._staged_rows = 0

    def snapshot_rows(self):
        """Remaining buffered rows as plain row dicts (loader checkpoints)."""
        rows = []
        pending = [(self._order_seg[i], self._order_row[i])
                   for i in range(self._cursor, len(self._order_seg))]
        for sid in self._staged_ids:
            pending.extend((sid, r) for r in range(self._seg_remaining[sid]))
        for sid, r in pending:
            block = self._segments[sid]
            rows.append({k: v[r] for k, v in block.items()})
        return rows
