"""Client-side shuffling buffers for stream decorrelation.

Parity: /root/reference/petastorm/reader_impl/shuffling_buffer.py (preallocated
slot array, O(1) random-swap retrieve :158-167, ``min_after_retrieve`` watermark
+ ``finish()`` drain :169-180, ``NoopShufflingBuffer`` :75-100).

Improvement: the RNG is seedable (the reference's ``np.random.randint`` is
unseeded — SURVEY.md §5 reproducibility gap).
"""

from __future__ import annotations

from collections import deque

import numpy as np


def default_min_after(capacity, min_after_retrieve=None):
    """The ONE definition of the decorrelation floor, shared by the row buffer
    factory and the columnar buffers in the JAX/torch loaders."""
    return min_after_retrieve if min_after_retrieve is not None else max(1, capacity // 2)


def make_shuffling_buffer_factory(capacity, min_after_retrieve=None, seed=None,
                                  batch_size=1, batched_reader=False):
    """Factory-of-factories shared by the JAX and torch loaders.

    ``capacity <= 0`` -> FIFO passthrough. For batched (columnar) readers the
    extra headroom is effectively unbounded: a whole row group is added at once
    and may dwarf the capacity (reference pytorch.py:133-137 sizes the buffer
    the same way)."""
    if capacity <= 0:
        return NoopShufflingBuffer
    floor = default_min_after(capacity, min_after_retrieve)
    extra = 10 ** 8 if batched_reader else max(1000, batch_size)
    return lambda: RandomShufflingBuffer(capacity, floor, extra_capacity=extra, seed=seed)


class ShufflingBufferBase(object):
    def add_many(self, items):
        raise NotImplementedError

    def retrieve(self):
        raise NotImplementedError

    def can_add(self):
        raise NotImplementedError

    def can_retrieve(self):
        raise NotImplementedError

    @property
    def size(self):
        raise NotImplementedError

    def finish(self):
        """No more items will be added; drain everything remaining."""
        raise NotImplementedError


class NoopShufflingBuffer(ShufflingBufferBase):
    """FIFO passthrough."""

    def __init__(self):
        self._items = deque()

    def add_many(self, items):
        self._items.extend(items)

    def retrieve(self):
        return self._items.popleft()

    def can_add(self):
        return True

    def can_retrieve(self):
        return len(self._items) > 0

    @property
    def size(self):
        return len(self._items)

    def finish(self):
        pass


class RandomShufflingBuffer(ShufflingBufferBase):
    """
    :param shuffling_buffer_capacity: soft target capacity; ``can_add`` turns
        False once reached (adds beyond it are still accepted — a caller may add
        a whole row group at once)
    :param min_after_retrieve: minimum items that must remain after a retrieve
        (decorrelation floor); until ``finish()``, retrieval stalls below it
    :param extra_capacity: headroom above capacity for bulk adds
    :param seed: RNG seed (None = nondeterministic)
    """

    def __init__(self, shuffling_buffer_capacity, min_after_retrieve, extra_capacity=1000,
                 seed=None):
        if min_after_retrieve >= shuffling_buffer_capacity:
            raise ValueError('min_after_retrieve ({}) must be smaller than capacity ({})'.format(
                min_after_retrieve, shuffling_buffer_capacity))
        self._capacity = shuffling_buffer_capacity
        self._min_after_retrieve = min_after_retrieve
        self._extra_capacity = extra_capacity
        self._items = []
        self._done_adding = False
        self._rng = np.random.default_rng(seed)

    def add_many(self, items):
        if self._done_adding:
            raise RuntimeError('Cannot add after finish()')
        if len(self._items) + len(items) > self._capacity + self._extra_capacity:
            raise RuntimeError(
                'Attempt to add {} items to a buffer holding {} (capacity {} + extra {}). '
                'Increase extra_capacity or add smaller chunks.'.format(
                    len(items), len(self._items), self._capacity, self._extra_capacity))
        self._items.extend(items)

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Buffer cannot retrieve now: size={} min_after_retrieve={}'.format(
                len(self._items), self._min_after_retrieve))
        idx = int(self._rng.integers(0, len(self._items)))
        # O(1): swap the chosen slot with the last element and pop
        self._items[idx], self._items[-1] = self._items[-1], self._items[idx]
        return self._items.pop()

    def can_add(self):
        return len(self._items) < self._capacity and not self._done_adding

    def can_retrieve(self):
        if self._done_adding:
            return len(self._items) > 0
        return len(self._items) > self._min_after_retrieve

    @property
    def size(self):
        return len(self._items)

    @property
    def rng_state(self):
        """Picklable RNG state, for loader checkpoints: restoring it makes a
        seeded resume reproduce the exact pre-checkpoint retrieval stream."""
        return self._rng.bit_generator.state

    @rng_state.setter
    def rng_state(self, state):
        self._rng.bit_generator.state = state

    def resize(self, capacity, min_after):
        """Retarget capacity/decorrelation floor at runtime (the autotuner's
        shuffle knob). Buffered items are kept — a shrink simply stops
        accepting adds until retrieval drains below the new capacity."""
        if min_after >= capacity:
            raise ValueError('min_after ({}) must be smaller than capacity ({})'.format(
                min_after, capacity))
        self._capacity = capacity
        self._min_after_retrieve = min_after

    def finish(self):
        self._done_adding = True
