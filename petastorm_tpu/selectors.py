"""Row-group selectors: query precomputed row-group indexes.

Parity: /root/reference/petastorm/selectors.py:20-100.
"""

from __future__ import annotations

from petastorm_tpu.errors import PetastormTpuError


class RowGroupSelectorBase(object):
    def get_index_names(self):
        """Names of the indexes this selector needs loaded."""
        raise NotImplementedError

    def select_row_groups(self, index_dict):
        """index_dict: index_name -> indexer. Return a set of piece indexes."""
        raise NotImplementedError


class SingleIndexSelector(RowGroupSelectorBase):
    """Union of pieces containing any of ``values`` in the named index."""

    def __init__(self, index_name, values):
        self._index_name = index_name
        self._values = list(values)

    def get_index_names(self):
        return [self._index_name]

    def select_row_groups(self, index_dict):
        if self._index_name not in index_dict:
            raise PetastormTpuError('Index {!r} not found in dataset'.format(self._index_name))
        indexer = index_dict[self._index_name]
        selected = set()
        for value in self._values:
            selected |= indexer.get_row_group_indexes(value)
        return selected


class IntersectIndexSelector(RowGroupSelectorBase):
    """Pieces selected by ALL of the given single-index selectors."""

    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return [name for s in self._selectors for name in s.get_index_names()]

    def select_row_groups(self, index_dict):
        sets = [s.select_row_groups(index_dict) for s in self._selectors]
        return set.intersection(*sets) if sets else set()


class UnionIndexSelector(RowGroupSelectorBase):
    """Pieces selected by ANY of the given single-index selectors."""

    def __init__(self, selectors):
        self._selectors = list(selectors)

    def get_index_names(self):
        return [name for s in self._selectors for name in s.get_index_names()]

    def select_row_groups(self, index_dict):
        selected = set()
        for s in self._selectors:
            selected |= s.select_row_groups(index_dict)
        return selected
