"""Async chunk prefetcher: warms the chunk store ahead of the workers.

Walks the ventilator's exact upcoming row-group order
(``ConcurrentVentilator.upcoming_items``) and fetches each qualifying column
chunk into the chunk store before a worker asks for it, so epoch-1 demand
misses overlap with compute instead of serializing in front of it.

The fetch-ahead is bounded by an **in-flight byte budget**: bytes the
prefetcher has fetched that no reader has consumed yet. Consumption is
detected through the chunk file itself — a demand hit bumps the mirror's
mtime (``ChunkStore.ensure``), and eviction removes it — so the signal works
across processes with no shared memory. When the budget is full the
prefetcher waits; it never blocks a worker (workers fetch on demand
regardless) and never fails the read path (any error is logged and skipped).
"""

from __future__ import annotations

import logging
import threading
from collections import OrderedDict, deque

logger = logging.getLogger(__name__)

#: bound on per-prefetcher open remote file handles (footer metadata is cached
#: in the store, so re-opening an evicted handle is cheap on a warm cache)
_MAX_OPEN_FILES = 4

_POLL_S = 0.05


class ChunkPrefetcher(object):
    """Background thread prefetching the ventilator's upcoming chunks.

    :param ventilator: a started-or-starting ``ConcurrentVentilator``
    :param pieces: the Reader's piece list (items carry ``piece_index``)
    :param column_names: columns the reader will request (non-physical names
        are skipped by qualification)
    :param filesystem_factory: picklable zero-arg filesystem factory
    :param config: :class:`ChunkCacheConfig` (budget + lookahead live here)
    """

    def __init__(self, ventilator, pieces, column_names, filesystem_factory,
                 config):
        self._ventilator = ventilator
        self._pieces = pieces
        self._columns = list(column_names)
        self._fs_factory = filesystem_factory
        self._config = config
        self._stop_event = threading.Event()
        self._thread = None
        # single-threaded state (prefetch thread only): open files, planned
        # row groups, and the fetched-but-unconsumed ledger for the budget
        self._files = OrderedDict()  # path -> ChunkCachedParquetFile | None
        self._done = set()           # (path, row_group) already planned
        self._outstanding = deque()  # (chunk_path, size, populate_mtime_ns)

    def start(self):
        if self._thread is not None:
            raise RuntimeError('ChunkPrefetcher already started')
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name='pstpu-chunk-prefetch')
        self._thread.start()

    def stop(self):
        self._stop_event.set()

    def join(self):
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    # -- internals -----------------------------------------------------------

    def _file(self, path, fs):
        from petastorm_tpu.chunkstore.reader import ChunkCachedParquetFile
        if path in self._files:
            return self._files[path]
        if len(self._files) >= _MAX_OPEN_FILES:
            _, old = self._files.popitem(last=False)
            if old is not None:
                old.close()
        try:
            pf = ChunkCachedParquetFile(path, fs, self._config)
        except Exception as e:  # noqa: BLE001 - prefetch is advisory: never fail the reader
            logger.debug('prefetch open of %s failed: %s', path, e)
            pf = None
        self._files[path] = pf  # None cached too: no per-item retry storm
        return pf

    def _reap_consumed(self):
        """Drop outstanding entries whose mirror was consumed (demand hit
        bumped mtime) or evicted; returns outstanding byte total."""
        import os
        kept = deque()
        total = 0
        while self._outstanding:
            path, size, populate_ns = self._outstanding.popleft()
            try:
                st = os.stat(path)
            except OSError:
                continue  # evicted: no longer in flight
            if st.st_mtime_ns > populate_ns:
                continue  # a reader touched it: consumed
            kept.append((path, size, populate_ns))
            total += size
        self._outstanding = kept
        return total

    def _await_budget(self, next_size):
        """Block (stop-aware) until ``next_size`` more bytes fit the budget.
        A chunk larger than the whole budget is fetched alone."""
        budget = self._config.prefetch_budget_bytes
        while not self._stop_event.is_set():
            total = self._reap_consumed()
            if total == 0 or total + next_size <= budget:
                return True
            self._stop_event.wait(_POLL_S)
        return False

    def _run(self):
        from petastorm_tpu.chunkstore.store import open_store
        try:
            fs = self._fs_factory()
        except Exception as e:  # noqa: BLE001 - advisory thread: log and bow out
            logger.warning('chunk prefetcher could not create filesystem: %s', e)
            return
        store = open_store(self._config)
        while not self._stop_event.is_set():
            try:
                items = self._ventilator.upcoming_items(self._config.prefetch_lookahead)
            except Exception as e:  # noqa: BLE001 - ventilator stopping: bow out
                logger.debug('prefetcher upcoming_items failed: %s', e)
                return
            fetched_any = False
            for item in items:
                if self._stop_event.is_set():
                    return
                piece = self._pieces[item['piece_index']]
                mark = (piece.path, piece.row_group)
                if mark in self._done:
                    continue
                pf = self._file(piece.path, fs)
                if pf is None:
                    self._done.add(mark)
                    continue
                for key, length, fetch_fn in pf.chunk_plan(piece.row_group,
                                                           self._columns):
                    if self._stop_event.is_set():
                        return
                    if store.contains(key, length):
                        continue
                    if not self._await_budget(length):
                        return
                    try:
                        path, mtime_ns, fetched = store.ensure(
                            key, length, fetch_fn, for_prefetch=True)
                    except Exception as e:  # noqa: BLE001 - advisory: workers fetch on demand
                        logger.debug('prefetch of %s failed: %s', key, e)
                        continue
                    if fetched:
                        fetched_any = True
                        self._outstanding.append((path, length, mtime_ns))
                self._done.add(mark)
                if len(self._done) > 100_000:
                    self._done.clear()
            if not fetched_any:
                self._stop_event.wait(_POLL_S)
