"""Content-addressed local mirror of remote column-chunk byte ranges.

The page scanner (``native/pagescan.py``) turns UNCOMPRESSED/PLAIN column
chunks into zero-copy Arrow views — but only over an mmap-able local file, so
remote stores (``s3://``, ``gs://``) silently forfeit the repo's biggest read
win and fall back to Arrow decode over the network. This store closes that
gap at the byte level: each qualifying column chunk of a remote Parquet file
is mirrored once into a local content-addressed file, and every subsequent
read mmaps the mirror and serves views exactly as the local path does.

Parity note: the reference caches DECODED rows (`petastorm/local_disk_cache.py`
via diskcache); this caches the raw chunk BYTES instead, because the zero-copy
path's whole point is that no decoded representation ever exists.

Design invariants:

* **Atomic single-writer population** — chunks are written to a same-directory
  temp file and ``os.replace``-d into place, so concurrent readers (including
  process-pool workers sharing the directory) never observe a partial chunk;
  racing writers both fetch and the last rename wins with identical bytes.
* **Eviction never invalidates a live view** (the PT500-series contract).
  Arrays built over a mirror hold the ``np.memmap`` alive through their
  buffers; the store itself keeps only a *weakref* per mapping. The evictor
  skips any chunk whose weakref is live (a batch still references it) — and
  even for chunks it does unlink, POSIX keeps the mapping valid until the last
  view drops. Mappings are never explicitly unmapped.
* **LRU by mtime** — a demand hit bumps the chunk file's mtime (prefetch does
  not), so recency reflects actual consumption; eviction walks oldest-first
  under the size bound. Bumps are throttled to once per second per chunk —
  sub-second recency adds nothing to LRU or to the prefetcher's consumed
  signal, and an unthrottled ``utime`` per read dominates the warm hot loop.
* **Counters survive process pools** — each process's store flushes its
  cumulative counters to ``<root>/stats/pid-<pid>.json``;
  :meth:`ChunkStore.stats_snapshot` merges every process's file with this
  process's live counters, which is what ``Reader.diagnostics`` reports.
  Flushes are time-throttled (rare events flush immediately) so the
  atomic-replace write never sits in the demand-hit path.
* **Warm reads cost a dict lookup** — a bounded strong-ref pool keeps the
  most recently used mappings alive across batches, so a re-read of a hot
  chunk is a lookup instead of an ``open``+``mmap``+``stat`` round trip.
  The evictor releases a chunk's pool entry before judging it pinned, so
  the pool never blocks eviction — only batches do.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import logging
import os
import tempfile
import threading
import time
import weakref
from collections import OrderedDict

import numpy as np

from petastorm_tpu import observability as obs
from petastorm_tpu.native.lifetime import registry as lifetime_registry

logger = logging.getLogger(__name__)

DEFAULT_SIZE_LIMIT = 10 * 2 ** 30  # 10 GiB, matching LocalDiskCache
DEFAULT_PREFETCH_BUDGET = 64 * 2 ** 20
DEFAULT_PREFETCH_LOOKAHEAD = 8

#: counter names persisted/aggregated; all cumulative since store creation
_COUNTER_KEYS = ('hits', 'misses', 'bytes_fetched', 'bytes_evicted',
                 'chunks_evicted', 'evict_skipped_pinned',
                 'prefetch_chunks', 'prefetch_bytes')

#: min seconds between stats-file flushes for hit-only traffic (rare events —
#: misses, evictions, prefetches — always flush immediately)
_FLUSH_INTERVAL_S = 0.5

#: min seconds between mtime bumps of the same chunk (LRU recency and the
#: prefetcher's consumed signal both work at whole-second granularity)
_BUMP_INTERVAL_S = 1.0

#: recently-used mappings kept alive by the store itself so repeat reads skip
#: the open+mmap round trip; bounded, and released on demand by the evictor
_STRONG_POOL_SIZE = 64

#: chunk-fabric hook (``petastorm_tpu.fabric``): when installed, every miss
#: routes ``(key, length, fetch_fn)`` through the fabric client, which tries
#: a pod peer's mirror first and degrades to ``fetch_fn`` (the object-store
#: read) itself — :meth:`ChunkStore.ensure` then persists whichever bytes
#: came back through the SAME atomic temp+rename path, so a peer-populated
#: mirror is indistinguishable from a fetched one. None (the production
#: default) costs one global load per miss — never per hit.
PEER_SOURCE = None


class ChunkCacheConfig(object):
    """Picklable chunk-cache description shipped into worker processes.

    :param root: local cache directory (created on first use)
    :param size_limit_bytes: total on-disk bound; LRU eviction keeps usage under it
    :param prefetch_budget_bytes: max bytes the async prefetcher may hold
        fetched-but-unconsumed at any moment
    :param prefetch_lookahead: how many upcoming ventilator items the
        prefetcher walks ahead
    """

    def __init__(self, root, size_limit_bytes=DEFAULT_SIZE_LIMIT,
                 prefetch_budget_bytes=DEFAULT_PREFETCH_BUDGET,
                 prefetch_lookahead=DEFAULT_PREFETCH_LOOKAHEAD):
        if not root:
            raise ValueError('chunk cache root must be a non-empty path')
        self.root = os.path.abspath(root)
        self.size_limit_bytes = size_limit_bytes
        self.prefetch_budget_bytes = prefetch_budget_bytes
        self.prefetch_lookahead = prefetch_lookahead

    def set_prefetch_budget(self, n):
        """Retarget the prefetcher's in-flight byte budget at runtime — the
        autotuner's chunk-fetch knob (``docs/autotune.md``). The prefetcher
        re-reads ``prefetch_budget_bytes`` on every budget wait, so the new
        bound takes effect on its next fetch decision."""
        n = int(n)
        if n < 1:
            raise ValueError('prefetch budget must be >= 1 byte')
        self.prefetch_budget_bytes = n

    def _key(self):
        return (self.root, self.size_limit_bytes, self.prefetch_budget_bytes,
                self.prefetch_lookahead)

    def __eq__(self, other):
        return type(self) is type(other) and self._key() == other._key()

    def __hash__(self):
        return hash(self._key())

    def __repr__(self):
        return 'ChunkCacheConfig(root={!r}, size_limit_bytes={})'.format(
            self.root, self.size_limit_bytes)


#: per-process store registry: every component (workers, prefetcher, Reader
#: diagnostics) sharing a root shares ONE instance, so in-process counters and
#: resident-mmap reuse are coherent
_stores = {}
_stores_lock = threading.Lock()


def open_store(config):
    """The per-process :class:`ChunkStore` for ``config`` (created on first use)."""
    with _stores_lock:
        store = _stores.get(config.root)
        if store is None:
            store = ChunkStore(config.root, size_limit_bytes=config.size_limit_bytes)
            _stores[config.root] = store
        return store


class ChunkStore(object):
    """Size-bounded local chunk mirror. Thread-safe; multi-process safe for
    population/eviction (atomic renames; unlink of a mapped file is harmless
    on POSIX). Obtain through :func:`open_store` so counters aggregate."""

    def __init__(self, root, size_limit_bytes=DEFAULT_SIZE_LIMIT):
        self._root = root
        self._size_limit = size_limit_bytes
        self._lock = threading.Lock()
        self._counters = {k: 0 for k in _COUNTER_KEYS}
        self._last_flush = 0.0
        # digest -> (weakref to np.memmap, chunk size, lifetime Slot). Views
        # over the mapping keep the memmap object alive; the memmap is
        # adopted into the slot (native/lifetime.py), so "pinned" is exactly
        # "the slot has live borrows" and blocked evictions land in the
        # process-wide lifetime_blocked_reclaims counter.
        self._mmaps = {}
        # digest -> lifetime Slot holding one manual borrow per in-flight
        # fabric send of a chunk that has no live mapping here: the evictor
        # consults it exactly like the mmap slots, so a mirror being streamed
        # to a peer is refused (counted skip), never truncated mid-transfer
        self._send_pins = {}
        # digest -> np.memmap: bounded LRU of strong refs so hot chunks stay
        # mapped across batches; the evictor pops an entry before judging the
        # weakref, so the pool itself never pins anything against eviction
        self._strong = OrderedDict()
        # digest -> monotonic time of the last mtime bump (throttle)
        self._bumped = {}
        # digest -> [fetch mutex, refcount]: single-flight per chunk. The
        # mutex covers the whole miss path — re-stat, fetch (peer or object
        # store), mirror write — so concurrent demands for the same chunk
        # produce exactly ONE fetch and ONE population per host; followers
        # re-stat under the mutex and account a hit. Entries are refcounted
        # away so the map stays bounded by in-flight fetches, not history.
        self._fetch_locks = {}
        self._stats_dir = os.path.join(root, 'stats')
        os.makedirs(self._stats_dir, exist_ok=True)
        self._stats_path = os.path.join(self._stats_dir,
                                        'pid-{}.json'.format(os.getpid()))

    @property
    def root(self):
        return self._root

    # -- keying --------------------------------------------------------------

    @staticmethod
    def digest(key):
        return hashlib.sha1(key.encode('utf-8')).hexdigest()

    def _entry_path(self, digest):
        return os.path.join(self._root, digest[:2], digest + '.chunk')

    # -- counters ------------------------------------------------------------

    def _count(self, updates):
        """Apply counter deltas; flush to the per-pid stats file at most every
        ``_FLUSH_INTERVAL_S`` for hit traffic (and always on a miss/evict/
        prefetch, the rare events) — the atomic-replace write must never sit
        in the warm demand-hit path."""
        force = any(k != 'hits' for k in updates)
        now = time.monotonic()
        with self._lock:
            for k, v in updates.items():
                self._counters[k] += v
            if not force and now - self._last_flush < _FLUSH_INTERVAL_S:
                return
            self._last_flush = now
            snapshot = dict(self._counters)
        self._write_stats(snapshot)

    def _maybe_bump(self, digest, path):
        """Bump the mirror's mtime (LRU recency + the prefetcher's consumed
        signal), at most once per ``_BUMP_INTERVAL_S`` per chunk. The FIRST
        demand hit always bumps — that is what tells the prefetcher its
        fetched-ahead bytes were consumed."""
        now = time.monotonic()
        with self._lock:
            last = self._bumped.get(digest)
            if last is not None and now - last < _BUMP_INTERVAL_S:
                return
            self._bumped[digest] = now
        try:
            os.utime(path, None)
        except OSError:
            pass  # evicted-but-mapped: recency is moot, the view is safe

    def _write_stats(self, snapshot):
        try:
            fd, tmp = tempfile.mkstemp(dir=self._stats_dir, suffix='.tmp')
            with os.fdopen(fd, 'w') as f:
                json.dump(snapshot, f)
            os.replace(tmp, self._stats_path)
        except OSError as e:
            logger.debug('chunk-store stats flush failed: %s', e)

    def stats_snapshot(self):
        """Cumulative counters across every process sharing this root: other
        processes' persisted stats files plus this process's live counters.
        Adds ``chunks_pinned``/``bytes_pinned`` (live mappings in THIS process)."""
        agg = {k: 0 for k in _COUNTER_KEYS}
        try:
            names = os.listdir(self._stats_dir)
        except OSError:
            names = []
        own = os.path.basename(self._stats_path)
        for name in names:
            if not name.endswith('.json') or name == own:
                continue
            try:
                with open(os.path.join(self._stats_dir, name)) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                continue
            for k in _COUNTER_KEYS:
                v = rec.get(k)
                if isinstance(v, int):
                    agg[k] += v
        pinned_n = pinned_bytes = 0
        with self._lock:
            for k in _COUNTER_KEYS:
                agg[k] += self._counters[k]
            for _ref, size, slot in self._mmaps.values():
                if slot.live:
                    pinned_n += 1
                    pinned_bytes += size
        agg['chunks_pinned'] = pinned_n
        agg['bytes_pinned'] = pinned_bytes
        return agg

    def close(self):
        """Flush counters and release the store's own mapping refs. Mappings
        are never explicitly unmapped (views may be live); each one releases
        with its last referencing array."""
        with self._lock:
            self._strong.clear()
            snapshot = dict(self._counters)
        self._write_stats(snapshot)

    # -- population ----------------------------------------------------------

    def ensure(self, key, length, fetch_fn, for_prefetch=False):
        """Guarantee the chunk for ``key`` (exactly ``length`` bytes, produced
        by ``fetch_fn()`` on a miss) exists on disk.

        Returns ``(path, mtime_ns, fetched)``. A demand hit bumps mtime (LRU
        recency + the prefetcher's consumed signal); a prefetch hit does not.
        """
        digest = self.digest(key)
        path = self._entry_path(digest)
        try:
            st = os.stat(path)
        except OSError:
            st = None
        if st is not None and st.st_size == length:
            if not for_prefetch:
                self._maybe_bump(digest, path)
                self._count({'hits': 1})
                obs.instant('chunk_hit', cat='chunkstore', bytes=length)
            return path, st.st_mtime_ns, False
        # single-flight: the whole miss path — re-stat, fetch, mirror write —
        # runs under a per-digest mutex, so a chunk is fetched and populated
        # exactly once per host no matter how many threads demand it at once
        with self._lock:
            entry = self._fetch_locks.get(digest)
            if entry is None:
                entry = [threading.Lock(), 0]
                self._fetch_locks[digest] = entry
            entry[1] += 1
        try:
            with entry[0]:
                return self._fetch_and_install(key, digest, path, length,
                                               fetch_fn, for_prefetch)
        finally:
            with self._lock:
                entry[1] -= 1
                if not entry[1]:
                    self._fetch_locks.pop(digest, None)

    def _fetch_and_install(self, key, digest, path, length, fetch_fn,
                           for_prefetch):
        """The serialized miss path (caller holds the digest's fetch mutex)."""
        try:
            st = os.stat(path)
        except OSError:
            st = None
        if st is not None and st.st_size == length:
            # single-flight follower: the fetch this thread queued behind
            # already populated the mirror
            if not for_prefetch:
                self._maybe_bump(digest, path)
                self._count({'hits': 1})
                obs.instant('chunk_hit', cat='chunkstore', bytes=length)
            return path, st.st_mtime_ns, False
        # separate stage names: demand fetches happen INSIDE the worker read
        # stage (the stall report subtracts them from read IO), prefetches on
        # the prefetcher's own thread (they must not skew that subtraction)
        with obs.stage('chunk_prefetch' if for_prefetch else 'chunk_fetch',
                       cat='chunkstore', bytes=length):
            peer_source = PEER_SOURCE
            if peer_source is not None:
                data = peer_source(key, length, fetch_fn)
            else:
                data = fetch_fn()
        if data is None:
            # a peer-source single-flight follower (another LOCAL caller of
            # the same client raced this one): re-stat and account the result
            # as a hit (exactly-once population per host)
            try:
                st = os.stat(path)
            except OSError:
                st = None
            if st is not None and st.st_size == length:
                if not for_prefetch:
                    self._count({'hits': 1})
                    obs.instant('chunk_hit', cat='chunkstore', bytes=length)
                return path, st.st_mtime_ns, False
            raise IOError(
                'peer source reported chunk {!r} populated, but no mirror of '
                '{} bytes exists'.format(key, length))
        if len(data) != length:
            raise IOError('chunk fetch for {!r} returned {} bytes, expected {}'.format(
                key, len(data), length))
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path), suffix='.tmp')
        try:
            with os.fdopen(fd, 'wb') as f:
                f.write(data)
            os.replace(tmp, path)  # atomic: readers never see partial chunks
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        try:
            st = os.stat(path)
            mtime_ns = st.st_mtime_ns
        except OSError:
            mtime_ns = 0
        if for_prefetch:
            self._count({'prefetch_chunks': 1, 'prefetch_bytes': length})
        else:
            self._count({'misses': 1, 'bytes_fetched': length})
        self._evict_if_needed()
        return path, mtime_ns, True

    def contains(self, key, length):
        path = self._entry_path(self.digest(key))
        try:
            return os.stat(path).st_size == length
        except OSError:
            return False

    # -- fabric serving ------------------------------------------------------

    @contextlib.contextmanager
    def pin_for_send(self, key):
        """Pin ``key``'s mirror against eviction for the duration of a fabric
        send, yielding its path (or None when the chunk is not mirrored here).

        The pin is a manual borrow on the chunk's lifetime slot — the mmap
        slot when a mapping is live, a dedicated ``fabric-send`` slot
        otherwise — so :meth:`_try_evict_entry`'s ``try_reclaim`` refuses
        (counted skip, ``lifetime_blocked_reclaims``) instead of unlinking a
        file mid-stream and truncating the transfer on the peer's side."""
        digest = self.digest(key)
        path = self._entry_path(digest)
        with self._lock:
            slot = None
            entry = self._mmaps.get(digest)
            if entry is not None:
                try:
                    slot = entry[2].retain()
                except RuntimeError:
                    slot = None  # released between lookup and retain
            if slot is None:
                pin = self._send_pins.get(digest)
                if pin is None or pin.released:
                    pin = lifetime_registry().open_slot(label='fabric-send')
                    self._send_pins[digest] = pin
                slot = pin.retain()
        try:
            present = False
            try:
                present = os.path.exists(path)
            except OSError:
                present = False
            yield path if present else None
        finally:
            with self._lock:
                slot.drop()
                pin = self._send_pins.get(digest)
                if pin is slot and not slot.live:
                    del self._send_pins[digest]
                    slot.seal()  # zero borrows: releases immediately

    # -- mapping -------------------------------------------------------------

    def mmap_chunk(self, key, length, fetch_fn):
        """A read-only ``np.memmap`` over the chunk's local mirror, fetching
        on miss. The caller's arrays pin the mapping simply by referencing it;
        the store additionally keeps the hottest mappings in a bounded
        strong-ref pool so a warm re-read is a dict lookup, not a syscall.

        :borrows: the returned memmap aliases the on-disk mirror; eviction is
            refused (``lifetime_blocked_reclaims``) while it or any array
            built over it is alive."""
        digest = self.digest(key)
        with self._lock:
            mm = self._strong.get(digest)
            if mm is not None:
                self._strong.move_to_end(digest)
            else:
                entry = self._mmaps.get(digest)
                mm = entry[0]() if entry is not None else None
        if mm is not None:
            self._count({'hits': 1})
            self._maybe_bump(digest, self._entry_path(digest))
            return mm
        path, _, _ = self.ensure(key, length, fetch_fn)
        try:
            mm = np.memmap(path, dtype=np.uint8, mode='r')
        except (OSError, ValueError):
            # evicted between ensure and mmap (another process's evictor):
            # repopulate once — the refetched bytes are identical
            path, _, _ = self.ensure(key, length, fetch_fn)
            mm = np.memmap(path, dtype=np.uint8, mode='r')
        # the memmap (an ndarray) is the one borrow: arrays built over it keep
        # it alive through their buffers, so its finalizer firing means no
        # view can reference the mirror anymore
        slot = lifetime_registry().open_slot(label='chunk-mirror')
        slot.adopt(mm)
        slot.seal()
        with self._lock:
            self._mmaps[digest] = (weakref.ref(mm), length, slot)
            self._strong[digest] = mm
            self._strong.move_to_end(digest)
            while len(self._strong) > _STRONG_POOL_SIZE:
                self._strong.popitem(last=False)
        return mm

    # -- eviction ------------------------------------------------------------

    def _try_evict_entry(self, digest, full):
        """Release the store's own strong-pool ref for ``digest``, then — if
        no live batch pins the mapping — unlink the chunk file, ATOMICALLY
        under the store lock. Holding the lock across pin-check + unlink
        closes the race where a concurrent :meth:`mmap_chunk` re-registers
        the digest between the two steps and its freshly pinned chunk is
        unlinked out from under the recency accounting (the mapping itself
        stays POSIX-valid either way — this is about honest bookkeeping).
        Returns True when the file was evicted. A refused reclaim counts in
        the process-wide ``lifetime_blocked_reclaims``."""
        with self._lock:
            self._strong.pop(digest, None)
            pin = self._send_pins.get(digest)
            if pin is not None:
                if not pin.try_reclaim():
                    return False  # a fabric send is streaming this mirror
                del self._send_pins[digest]
            entry = self._mmaps.get(digest)
            if entry is not None:
                if not entry[2].try_reclaim():
                    return False  # pinned by a live batch's views
                del self._mmaps[digest]
            try:
                os.unlink(full)
            except OSError:
                return False
            self._bumped.pop(digest, None)
            return True

    def _evict_if_needed(self):
        entries = []
        total = 0
        for dirpath, dirnames, filenames in os.walk(self._root):
            if os.path.basename(dirpath) == 'stats':
                dirnames[:] = []
                continue
            for name in filenames:
                if not name.endswith('.chunk'):
                    continue
                full = os.path.join(dirpath, name)
                try:
                    st = os.stat(full)
                except OSError:
                    continue
                entries.append((st.st_mtime_ns, st.st_size, name[:-len('.chunk')], full))
                total += st.st_size
        if total <= self._size_limit:
            return
        evicted_n = evicted_b = skipped = 0
        entries.sort()  # oldest mtime first
        for _mtime, size, digest, full in entries:
            if total <= self._size_limit:
                break
            if not self._try_evict_entry(digest, full):
                # a live batch still references this mapping (or the file
                # vanished under us): unlinking would not free disk until the
                # views drop anyway, and the size accounting must stay honest
                # — skip, on record
                skipped += 1
                continue
            total -= size
            evicted_n += 1
            evicted_b += size
        if evicted_n or skipped:
            self._count({'chunks_evicted': evicted_n, 'bytes_evicted': evicted_b,
                         'evict_skipped_pinned': skipped})
