"""Chunk store: zero-copy page scan for REMOTE Parquet stores.

The local read path's biggest win — the first-party page scanner serving
column chunks as zero-copy mmap views — requires a local file, so remote
stores (``s3://``/``gs://``) previously always decoded through Arrow over the
network. This subsystem mirrors raw column-chunk byte ranges into a local
content-addressed cache and lets the page scanner serve the mirror:

* :class:`~petastorm_tpu.chunkstore.store.ChunkStore` — atomic single-writer
  population, size-bounded LRU eviction that refcount-pins live mmaps,
  hit/miss/byte/evict counters aggregated across worker processes;
* :class:`~petastorm_tpu.chunkstore.reader.ChunkCachedParquetFile` — the
  Parquet-file surface workers consume, fast columns via cached mirrors,
  everything else via Arrow over the remote filesystem;
* :class:`~petastorm_tpu.chunkstore.prefetch.ChunkPrefetcher` — walks the
  ventilator's upcoming row-group order and fetches chunks ahead under a
  bounded in-flight byte budget.

Users enable it with ``make_reader(..., chunk_cache='auto'|<path>)``; counters
surface as ``chunk_cache_*`` keys in ``Reader.diagnostics`` (and through
``JaxDataLoader.diagnostics``). See ``docs/cache.md``.
"""

from __future__ import annotations

import hashlib
import os
import tempfile

from petastorm_tpu.chunkstore.store import (ChunkCacheConfig, ChunkStore,  # noqa: F401
                                            open_store)


def resolve_chunk_cache(chunk_cache, dataset_url, is_local,
                        size_limit_bytes=None):
    """Normalize the ``make_reader`` kwarg into a :class:`ChunkCacheConfig`.

    ``None``/``False`` disables. Local datasets never engage (the page scanner
    mmaps them directly — a byte mirror would only double the IO). ``'auto'``
    derives a per-dataset directory under the system temp dir; a string is an
    explicit cache directory; a ready config passes through.
    """
    if chunk_cache in (None, False):
        return None
    if is_local:
        return None
    if isinstance(chunk_cache, ChunkCacheConfig):
        return chunk_cache
    if chunk_cache == 'auto':
        root = os.path.join(tempfile.gettempdir(), 'pstpu_chunk_cache',
                            hashlib.sha1(dataset_url.encode('utf-8')).hexdigest()[:16])
    elif isinstance(chunk_cache, str):
        root = chunk_cache
    else:
        raise ValueError("chunk_cache must be None, 'auto', a directory path, or a "
                         'ChunkCacheConfig, got {!r}'.format(chunk_cache))
    kwargs = {}
    if size_limit_bytes:
        kwargs['size_limit_bytes'] = size_limit_bytes
    return ChunkCacheConfig(root, **kwargs)


def cache_diagnostics(config):
    """Flat ``chunk_cache_*`` counter dict for ``Reader.diagnostics``."""
    snapshot = open_store(config).stats_snapshot()
    return {'chunk_cache_' + k: v for k, v in snapshot.items()}
