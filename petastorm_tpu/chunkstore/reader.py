"""Chunk-cached Parquet file: the remote-store face of the zero-copy page scan.

``ChunkCachedParquetFile`` presents the same surface the workers consume from
``native.open_parquet`` (``read_row_group(i, columns)`` -> ``pyarrow.Table``,
``metadata.row_group(i).num_rows``, ``close``) but over a REMOTE
``pyarrow.fs`` filesystem (including the retry-wrapped object-store handlers
from ``fs.py``/``retry.py``):

* the footer is fetched once and cached in the chunk store, so a warm cache
  opens a file with a single ``get_file_info`` round trip;
* every column chunk that qualifies for the page scan (same strict check as
  the local path — ``pagescan.column_qualifies``) is mirrored byte-for-byte
  into the local chunk store and served as zero-copy Arrow views over the
  mirror's mmap (``pagescan.read_mirrored_chunk``);
* everything else decodes through a plain ``pq.ParquetFile`` over the remote
  filesystem with ``pre_buffer`` coalescing, exactly as before.

Epoch 1 therefore pays one ranged GET per qualifying chunk; epoch 2+ reads at
local page-scan speed with zero remote reads for the cached columns.
"""

from __future__ import annotations

import logging
import os

import pyarrow as pa

from petastorm_tpu.chunkstore.store import open_store
from petastorm_tpu.native import pagescan

logger = logging.getLogger(__name__)

#: first guess at the footer size; one refetch covers larger footers
_FOOTER_GUESS = 64 * 1024

#: slack past the thrift footer so pyarrow's size sanity checks pass on the
#: tail-only buffer (footer + 8-byte trailer + room for the header magic)
_FOOTER_SLACK = 64


class ChunkCachedParquetFile(object):
    """One remote Parquet file served through the local chunk store.

    :param path: in-filesystem path of the Parquet file
    :param filesystem: a ``pyarrow.fs.FileSystem`` (typically retry-wrapped)
    :param config: :class:`petastorm_tpu.chunkstore.store.ChunkCacheConfig`
    """

    def __init__(self, path, filesystem, config):
        from petastorm_tpu import native

        self.path = path
        self._fs = filesystem
        self._store = open_store(config)
        self._lib = native._load_library()  # None -> no fast path, Arrow only
        info = filesystem.get_file_info([path])[0]
        if getattr(info, 'size', None) is None:
            raise IOError('cannot stat {} on {}'.format(path, filesystem))
        self._file_size = info.size
        mtime_ns = getattr(info, 'mtime_ns', None)
        # identity of the remote bytes: a rewritten file must never hit the
        # old mirror. mtime may be unavailable on some stores -> size-only.
        self._file_id = '{}|{}|{}'.format(path, info.size,
                                          mtime_ns if mtime_ns is not None else '-')
        self._meta = self._read_footer_metadata()
        self.metadata = self._meta
        # flat REQUIRED-eligible columns: leaf path == top-level name (same
        # construction as NativeParquetFile._zerocopy_columns)
        self._flat_index = {
            self._meta.schema.column(idx).path: idx
            for idx in range(self._meta.num_columns)
            if '.' not in self._meta.schema.column(idx).path}
        self._arrow_pf = None
        # warm-read memoization: qualification is pure over the (immutable)
        # footer metadata, and a page plan is pure over the chunk's bytes,
        # which are content-addressed — neither needs recomputing per read
        self._qual_cache = {}   # (row_group, tuple(names)) -> _qualifying list
        self._pages_cache = {}  # chunk key -> scan_mirrored_chunk plan
        self._disable_scan = bool(os.environ.get('PSTPU_DISABLE_PAGESCAN'))
        self._fused_plans = {}  # (rg, columns, hints sig) -> FusedPlan | None

    # -- remote IO -----------------------------------------------------------

    def _fetch_range(self, offset, length, deadline_s=None):
        from petastorm_tpu.retry import fetch_range
        return fetch_range(self._fs, self.path, offset, length,
                           deadline_s=deadline_s)

    def _chunk_key(self, offset, length):
        return '{}|{}+{}'.format(self._file_id, offset, length)

    def _read_footer_metadata(self):
        import pyarrow.parquet as pq

        def tail(n):
            n = min(n, self._file_size)
            off = self._file_size - n
            key = self._chunk_key(off, n)
            path, _, _ = self._store.ensure(
                key, n, lambda: self._fetch_range(off, n))
            with open(path, 'rb') as f:
                return f.read()
        try:
            data = tail(_FOOTER_GUESS)
            if len(data) >= 8:
                footer_len = int.from_bytes(data[-8:-4], 'little')
                need = footer_len + 8 + _FOOTER_SLACK
                if need > len(data):
                    data = tail(need)
            return pq.read_metadata(pa.BufferReader(data))
        except Exception as e:  # noqa: BLE001 - odd tail/store: read footer remotely
            logger.debug('footer tail parse failed for %s (%s); remote metadata read',
                         self.path, e)
            return pq.read_metadata(self._fs.open_input_file(self.path))

    def _arrow(self):
        if self._arrow_pf is None:
            import pyarrow.parquet as pq
            self._arrow_pf = pq.ParquetFile(self._fs.open_input_file(self.path),
                                            pre_buffer=True)
        return self._arrow_pf

    # -- qualification / planning --------------------------------------------

    def _qualifying(self, row_group, column_names):
        """[(name, col_meta, schema_col, qual, start, length)] for the columns
        of ``row_group`` the page scan can serve from a cached mirror.
        Memoized — qualification reads only the immutable footer metadata."""
        memo_key = (row_group, tuple(column_names))
        cached = self._qual_cache.get(memo_key)
        if cached is not None:
            return cached
        try:
            rg = self._meta.row_group(row_group)
        except Exception:  # noqa: BLE001 - malformed metadata: Arrow path decides
            return []
        out = []
        for name in column_names:
            idx = self._flat_index.get(name)
            if idx is None:
                continue
            try:
                col = rg.column(idx)
                schema_col = self._meta.schema.column(idx)
                qual = pagescan.column_qualifies(
                    col, schema_col.max_definition_level,
                    schema_col.max_repetition_level)
                if not qual:
                    continue
                start = col.data_page_offset
                length = col.total_compressed_size
            except Exception as e:  # noqa: BLE001 - odd chunk metadata: Arrow serves it
                logger.debug('chunk qualification failed for %s:%s (%s)',
                             self.path, name, e)
                continue
            if start < 0 or length <= 0 or start + length > self._file_size:
                continue
            out.append((name, col, schema_col, qual, start, length))
        self._qual_cache[memo_key] = out
        return out

    def chunk_plan(self, row_group, column_names=None):
        """[(key, length, fetch_fn)] for the cacheable chunks of a row group —
        the prefetcher's work list. Covers BOTH mirror-served decode paths:
        view-qualified chunks (zero-copy page scan) and fused-qualified
        chunks (dictionary/RLE/snappy decoded by ``pstpu_read_fused`` from
        the same mirror, docs/native.md) — since PR 6 made fused chunks
        cacheable, a prefetcher that walked only the view-qualified set left
        exactly the dict/snappy columns to demand-fetch in front of decode.
        Fetches land through the store's ``for_prefetch`` path, so they count
        under the existing ``chunk_cache_prefetch_*`` counters the autotuner's
        prefetch knob watches."""
        names = column_names if column_names is not None else list(self._flat_index)
        plan, seen = [], set()
        for _name, _col, _schema_col, _qual, start, length in \
                self._qualifying(row_group, names):
            key = self._chunk_key(start, length)
            seen.add(key)
            plan.append((key, length, self._range_fetcher(start, length)))
        fused = self.fused_plan(row_group, tuple(names))
        if fused is not None:
            for col in fused.columns:
                key = self._chunk_key(col.chunk_off, col.chunk_len)
                if key in seen:
                    continue
                seen.add(key)
                plan.append((key, col.chunk_len,
                             self._range_fetcher(col.chunk_off, col.chunk_len)))
        return plan

    def _range_fetcher(self, offset, length):
        def fetch(deadline_s=None):
            return self._fetch_range(offset, length, deadline_s=deadline_s)
        # the fabric client hands what remains of its transfer budget to the
        # object-store fallback through this (duck-typed) capability flag
        fetch.supports_deadline = True
        return fetch

    # -- reading -------------------------------------------------------------

    def _zerocopy_cached(self, row_group, column_names):
        if self._lib is None or self._disable_scan:
            return {}
        expected_rows = self._meta.row_group(row_group).num_rows
        out = {}
        for name, col, schema_col, qual, start, length in \
                self._qualifying(row_group, column_names):
            key = self._chunk_key(start, length)
            try:
                mm = self._store.mmap_chunk(
                    key, length, self._range_fetcher(start, length))
                pages = self._pages_cache.get(key)
                if pages is None:
                    pages = pagescan.scan_mirrored_chunk(
                        self._lib, mm, col, has_def_levels=(qual == 'def'))
                    if pages is None:
                        continue
                    # a plan is pure over the chunk's content-addressed bytes:
                    # any future mirror of this key scans identically
                    self._pages_cache[key] = pages
                arrays = pagescan.read_mirrored_chunk(
                    self._lib, mm, col, expected_rows,
                    getattr(schema_col, 'length', 0),
                    has_def_levels=(qual == 'def'),
                    require_exact=(qual != 'def'), pages=pages)
            except Exception as e:  # noqa: BLE001 - fetch/scan surprise: Arrow path serves it
                logger.debug('chunk-cached scan of %s:%s failed (%s); Arrow path',
                             self.path, name, e)
                continue
            if arrays is None:
                continue
            out[name] = pa.chunked_array(arrays)
        return out

    # -- fused batch decode over mirrored chunks (docs/native.md) ------------

    def fused_plan(self, i, columns, schema_fields=None, decode_hints=None,
                   resize_hints=None, include_pagescan=False):
        """Fused-decode plan for one row group of this REMOTE file — the same
        qualification the local path runs, judged from the cached footer.
        Dictionary/RLE/snappy chunks that the view path cannot mirror now
        become cacheable too: the fused kernel decodes them from the local
        mirror, so epoch 2+ touches no remote bytes for them either."""
        if self._lib is None or os.environ.get('PSTPU_DISABLE_FUSED'):
            return None
        from petastorm_tpu.native import fused
        key = (i, tuple(columns), bool(include_pagescan),
               frozenset(n for n in (decode_hints or {}) if decode_hints[n]),
               frozenset(n for n in (resize_hints or {}) if resize_hints[n]))
        if key not in self._fused_plans:
            plan = fused.plan_row_group(self._meta, self._flat_index, i, columns,
                                        schema_fields, decode_hints, resize_hints,
                                        include_pagescan=include_pagescan)
            if plan is not None:
                for p in list(plan.columns):
                    if p.chunk_off + p.chunk_len > self._file_size:
                        plan.columns.remove(p)
                        plan.rest.append(p.name)
                        plan.reasons[p.name] = 'bounds'
            self._fused_plans[key] = plan
        return self._fused_plans[key]

    def _fused_chunks(self, cols):
        """Per-column chunk views served from the content-addressed local
        mirror (fetched once per chunk; warm reads are pure mmap)."""
        chunks = []
        for p in cols:
            key = self._chunk_key(p.chunk_off, p.chunk_len)
            try:
                mm = self._store.mmap_chunk(
                    key, p.chunk_len, self._range_fetcher(p.chunk_off, p.chunk_len))
            except Exception as e:  # noqa: BLE001 - fetch surprise: column falls back
                logger.debug('chunk mirror fetch failed for %s:%s (%s)',
                             self.path, p.name, e)
                mm = None
            chunks.append(mm)
        return chunks

    def read_fused(self, i, columns, schema_fields=None, decode_hints=None,
                   resize_hints=None):
        """Same contract as ``NativeParquetFile.read_fused``, served from
        mirrored chunks."""
        from petastorm_tpu.native import fused
        plan = self.fused_plan(i, columns, schema_fields, decode_hints, resize_hints)
        if plan is None:
            return {}, list(columns)
        if not plan.columns:
            fused.count_fallbacks(plan.reasons)
            return {}, list(columns)
        block, _reasons = fused.read_block(self._lib,
                                           self._fused_chunks(plan.columns),
                                           plan, stage_args={'row_group': i})
        rest = [c for c in columns if c not in block]
        return block, rest

    def read_fused_predicate(self, i, columns, pred_fields, clauses,
                             schema_fields=None, decode_hints=None,
                             resize_hints=None):
        """Same contract as ``NativeParquetFile.read_fused_predicate``, with
        every chunk (output AND predicate columns) served from the local
        mirror — a warm filtered read touches no remote bytes at all."""
        from petastorm_tpu.native import fused
        plan = self.fused_plan(i, columns, schema_fields, decode_hints,
                               resize_hints, include_pagescan=True)
        if plan is None or not plan.columns:
            return None
        got = fused.plan_predicate_columns(self._meta, self._flat_index, i,
                                           pred_fields, schema_fields)
        if got is None:
            fused.count_fallbacks({f: 'predicate' for f in pred_fields})
            return None
        pred_plans, pred_index = got
        for p in pred_plans:
            if p.chunk_off + p.chunk_len > self._file_size:
                fused.count_fallbacks({p.name: 'bounds'})
                return None
        compiled = fused.compile_predicate(clauses, pred_index)
        if isinstance(compiled, str):
            fused.count_fallbacks({f: compiled for f in pred_fields})
            return None
        preds, keepalive = compiled
        res = fused.read_block_pred(
            self._lib, self._fused_chunks(plan.columns), plan,
            self._fused_chunks(pred_plans), pred_plans, preds, keepalive,
            stage_args={'row_group': i})
        if res is None:
            return None
        block, _reasons, sel_mask, n_selected, pages_skipped = res
        rest = [c for c in columns if c not in block]
        return block, rest, sel_mask, n_selected, pages_skipped

    def fused_read_into(self, plan, out_buf, offsets):
        """In-place (shm-ring slot) variant, mirroring the local reader."""
        from petastorm_tpu import observability as obs
        from petastorm_tpu.native import fused
        with obs.stage('fused_decode', cat='native', rows=plan.expected_rows):
            return fused.read_into(self._lib, self._fused_chunks(plan.columns),
                                   plan.columns, plan.expected_rows, out_buf,
                                   offsets)

    def read_row_group(self, i, columns=None):
        """One row group as a ``pyarrow.Table``; qualifying columns are views
        over locally mirrored chunks, the rest decode through Arrow over the
        remote filesystem. Mixed tables split per column, preserving the
        requested order (same contract as ``NativeParquetFile``)."""
        fast = self._zerocopy_cached(i, columns) if columns else {}
        rest = [c for c in columns if c not in fast] if columns is not None else None
        # columns=[] keeps the 0-column N-row semantics of the Arrow path
        # (partition-key-only reads take row counts from it)
        if columns and not rest:
            return pa.table({c: fast[c] for c in columns})
        table = self._arrow().read_row_group(i, columns=rest)
        if not fast:
            return table
        return pa.table({c: (fast[c] if c in fast else table.column(c))
                         for c in columns})

    # -- lifecycle -----------------------------------------------------------

    def close(self):
        if self._arrow_pf is not None:
            try:
                self._arrow_pf.close()
            except Exception:  # noqa: BLE001 - underlying remote stream already broken
                pass
            self._arrow_pf = None

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.close()
