"""Mix several readers with given sampling probabilities.

Parity: /root/reference/petastorm/weighted_sampling_reader.py:20-106 — each
``__next__`` draws one of the underlying readers from the cumulative probability
vector; schemas and batched-ness must match. RNG is seedable here (the
reference's is not).
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.errors import PetastormTpuError


class WeightedSamplingReader(object):
    def __init__(self, readers, probabilities, seed=None):
        if len(readers) != len(probabilities) or not readers:
            raise PetastormTpuError('readers and probabilities must be non-empty, same length')
        total = float(sum(probabilities))
        if total <= 0:
            raise PetastormTpuError('probabilities must sum to a positive value')
        self._readers = list(readers)
        self._cum = np.cumsum(np.asarray(probabilities, dtype=np.float64) / total)
        self._rng = np.random.default_rng(seed)

        first = self._readers[0]
        for other in self._readers[1:]:
            if other.batched_output != first.batched_output:
                raise PetastormTpuError('All mixed readers must agree on batched_output')
            if getattr(other, 'ngram', None) != getattr(first, 'ngram', None):
                raise PetastormTpuError('All mixed readers must use the same NGram spec')
            if list(other.transformed_schema.fields) != list(first.transformed_schema.fields):
                raise PetastormTpuError('All mixed readers must produce the same fields')
        self.batched_output = first.batched_output
        self.ngram = getattr(first, 'ngram', None)
        self.transformed_schema = first.transformed_schema
        self.last_row_consumed = False

    def __iter__(self):
        return self

    def __next__(self):
        choice = int(np.searchsorted(self._cum, self._rng.random(), side='right'))
        choice = min(choice, len(self._readers) - 1)
        try:
            return next(self._readers[choice])
        except StopIteration:
            self.last_row_consumed = True
            raise

    next = __next__

    def stop(self):
        for r in self._readers:
            r.stop()

    def join(self):
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
