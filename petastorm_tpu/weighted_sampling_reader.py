"""Mix several readers with given sampling probabilities.

Parity: /root/reference/petastorm/weighted_sampling_reader.py:20-106 — each
``__next__`` draws one of the underlying readers from the cumulative probability
vector; schemas and batched-ness must match. RNG is seedable here (the
reference's is not).

Beyond the reference: sources usually have DIFFERENT lengths, so "one reader
raised StopIteration" and "the mixture is exhausted" are different events. The
``on_exhausted`` policy makes the distinction explicit — ``'renormalize'``
(default) drops the exhausted source and redistributes its probability mass
over the live ones, so the mixture ends only when every source is dry;
``'stop'`` preserves the reference's behavior (and the proportions: stopping at
the first exhaustion never over-samples the longer sources). The richer
mixture surface (live ``set_weights``, epoch schedules, per-source telemetry)
lives in :class:`petastorm_tpu.sequence.mixture.MixtureReader`, which builds
on this class.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.errors import PetastormTpuError


class WeightedSamplingReader(object):
    """
    :param readers: readers to mix; schemas, batched-ness and NGram specs must
        agree
    :param probabilities: relative sampling weights (normalized internally)
    :param seed: seeds the sampling stream; ``None`` = nondeterministic
    :param on_exhausted: ``'renormalize'`` (default) — when one source
        exhausts, renormalize the remaining probability mass over the live
        sources and keep going until ALL are dry; ``'stop'`` — first
        exhausted source ends the whole mixture (the original petastorm
        behavior).
    """

    def __init__(self, readers, probabilities, seed=None, on_exhausted='renormalize'):
        if len(readers) != len(probabilities) or not readers:
            raise PetastormTpuError('readers and probabilities must be non-empty, same length')
        if on_exhausted not in ('stop', 'renormalize'):
            raise PetastormTpuError(
                "on_exhausted must be 'stop' or 'renormalize', got {!r}".format(on_exhausted))
        total = float(sum(probabilities))
        if total <= 0:
            raise PetastormTpuError('probabilities must sum to a positive value')
        self._readers = list(readers)
        self._weights = np.asarray(probabilities, dtype=np.float64) / total
        self._live = [True] * len(readers)
        self._cum = None
        self._live_indices = None
        self._rebuild_cum()
        self._rng = np.random.default_rng(seed)
        self._on_exhausted = on_exhausted

        first = self._readers[0]
        for other in self._readers[1:]:
            if other.batched_output != first.batched_output:
                raise PetastormTpuError('All mixed readers must agree on batched_output')
            if getattr(other, 'ngram', None) != getattr(first, 'ngram', None):
                raise PetastormTpuError('All mixed readers must use the same NGram spec')
            if list(other.transformed_schema.fields) != list(first.transformed_schema.fields):
                raise PetastormTpuError('All mixed readers must produce the same fields')
        self.batched_output = first.batched_output
        self.ngram = getattr(first, 'ngram', None)
        self.transformed_schema = first.transformed_schema
        self.last_row_consumed = False

    def _rebuild_cum(self):
        """Cumulative probability vector over the LIVE sources only — the
        renormalization step: dead sources' mass redistributes proportionally."""
        self._live_indices = [i for i, alive in enumerate(self._live) if alive]
        if not self._live_indices:
            self._cum = np.empty(0, dtype=np.float64)
            return
        live_w = self._weights[self._live_indices]
        total = float(live_w.sum())
        if total <= 0:  # every live weight is 0 (set_weights zeroed them): uniform
            live_w = np.ones(len(self._live_indices), dtype=np.float64)
            total = float(len(self._live_indices))
        self._cum = np.cumsum(live_w / total)

    def __iter__(self):
        return self

    def __next__(self):
        while self._live_indices:
            pos = int(np.searchsorted(self._cum, self._rng.random(), side='right'))
            pos = min(pos, len(self._live_indices) - 1)
            choice = self._live_indices[pos]
            try:
                row = next(self._readers[choice])
            except StopIteration:
                self._on_source_exhausted(choice)
                if self._on_exhausted == 'stop':
                    break
                continue
            self._on_row(choice, row)
            return row
        self.last_row_consumed = True
        raise StopIteration

    next = __next__

    # -- subclass hooks (MixtureReader telemetry) ---------------------------

    def _on_row(self, choice, row):
        """Called after each successfully drawn row; base class does nothing."""

    def _on_source_exhausted(self, choice):
        """Mark a source dry and renormalize the live mass."""
        self._live[choice] = False
        self._rebuild_cum()

    def stop(self):
        for r in self._readers:
            r.stop()

    def join(self):
        for r in self._readers:
            r.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
