"""NGram: windowed sequence readout over timestamp-ordered rows.

Parity: /root/reference/petastorm/ngram.py:20-339 — fields-per-timestep dict,
``delta_threshold``, ``timestamp_overlap`` (:102-125); sliding-window assembly
with timestamp-delta filtering (:225-270); per-timestep schema views (:215-223);
regex field resolution (:195-203). Windows never cross row-group boundaries
(:85-91) — sequences longer than a row group require larger row groups.

This is the framework's long-sequence primitive: the JAX adapter stacks the
per-timestep rows time-major so a window lands on device as ``[T, ...]`` arrays
ready for scan/attention kernels.
"""

from __future__ import annotations

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.unischema import Unischema, UnischemaField, match_unischema_fields


class NGram(object):
    """
    :param fields: dict mapping integer timestep offset -> list of
        :class:`UnischemaField` or regex pattern strings. Offsets must be
        consecutive integers (any base), e.g. ``{-1: [...], 0: [...], 1: [...]}``.
    :param delta_threshold: maximum allowed timestamp delta between two
        consecutive timesteps in a window; windows violating it are dropped.
    :param timestamp_field: the :class:`UnischemaField` (or name) ordering rows.
    :param timestamp_overlap: if False, consecutive windows never share rows.
    """

    def __init__(self, fields, delta_threshold, timestamp_field, timestamp_overlap=True):
        if not isinstance(fields, dict) or not fields:
            raise PetastormTpuError('fields must be a non-empty dict of offset -> field list')
        offsets = sorted(fields.keys())
        if offsets != list(range(offsets[0], offsets[-1] + 1)):
            raise PetastormTpuError('NGram offsets must be consecutive integers, got {}'.format(offsets))
        self._fields = {k: list(v) for k, v in fields.items()}
        self._delta_threshold = delta_threshold
        self._timestamp_field_name = (timestamp_field.name
                                      if isinstance(timestamp_field, UnischemaField)
                                      else timestamp_field)
        self._timestamp_overlap = timestamp_overlap
        self._min_offset = offsets[0]
        self._max_offset = offsets[-1]

    @property
    def length(self):
        """Window length in timesteps."""
        return self._max_offset - self._min_offset + 1

    @property
    def fields(self):
        return self._fields

    @property
    def delta_threshold(self):
        return self._delta_threshold

    @property
    def timestamp_field_name(self):
        return self._timestamp_field_name

    @property
    def timestamp_overlap(self):
        return self._timestamp_overlap

    def resolve_regex_field_names(self, schema):
        """Replace regex pattern strings in the per-timestep field lists with the
        concrete schema fields they match (reference ngram.py:195-203)."""
        for offset, field_list in self._fields.items():
            resolved = []
            for item in field_list:
                if isinstance(item, UnischemaField):
                    resolved.append(item)
                else:
                    matched = match_unischema_fields(schema, [item])
                    if not matched:
                        raise PetastormTpuError(
                            'NGram pattern {!r} matched no fields in schema {}'.format(item, schema.name))
                    resolved.extend(matched)
            self._fields[offset] = resolved

    def get_field_names_at_timestep(self, offset):
        return [f.name if isinstance(f, UnischemaField) else f for f in self._fields.get(offset, [])]

    def get_field_names_at_all_timesteps(self):
        names = set()
        for offset in self._fields:
            names.update(self.get_field_names_at_timestep(offset))
        names.add(self._timestamp_field_name)
        return sorted(names)

    def get_schema_at_timestep(self, schema, offset):
        """Schema view containing only this timestep's fields
        (reference ngram.py:215-223)."""
        names = [n for n in self.get_field_names_at_timestep(offset) if n in schema.fields]
        return schema.create_schema_view([schema.fields[n] for n in names])

    def form_ngram(self, data, schema):
        """Assemble windows from decoded rows of ONE row group.

        :param data: list of row dicts (will be sorted by the timestamp field)
        :param schema: the (possibly transformed) row schema
        :return: list of dicts offset -> per-timestep row dict (only that
            timestep's fields)
        """
        rows = sorted(data, key=lambda r: r[self._timestamp_field_name])
        length = self.length
        ngrams = []
        start = 0
        while start + length <= len(rows):
            window = rows[start:start + length]
            if self._window_within_threshold(window):
                ngram = {}
                for offset in range(self._min_offset, self._max_offset + 1):
                    row = window[offset - self._min_offset]
                    wanted = self.get_field_names_at_timestep(offset)
                    ngram[offset] = {k: row[k] for k in wanted if k in row}
                ngrams.append(ngram)
                start += length if not self._timestamp_overlap else 1
            else:
                start += 1
        return ngrams

    def form_ngram_columnar(self, block):
        """Assemble windows from ONE row group's decoded *column block* —
        the columnar analog of :meth:`form_ngram`, with identical window
        semantics (stable timestamp sort, delta_threshold filtering, greedy
        non-overlap selection) but no per-row Python: window membership is a
        vectorized cumsum over the sorted timestamp deltas and each timestep's
        fields are one numpy gather.

        :param block: dict ``field -> [N, ...]`` column (must include the
            timestamp field)
        :return: dict ``offset -> {field: [W, ...]}`` for W windows, or ``None``
            when no window qualifies
        """
        import numpy as np

        ts = block[self._timestamp_field_name]
        n = len(ts)
        length = self.length
        if n < length:
            return None
        if isinstance(ts, np.ndarray) and ts.dtype != object:
            order = np.argsort(ts, kind='stable')
            ts_sorted = ts[order]
            if self._delta_threshold is None or n < 2:
                bad = np.zeros(max(n - 1, 0), dtype=bool)
            else:
                bad = np.diff(ts_sorted) > self._delta_threshold
        else:
            # object timestamps (Decimal, datetime objects): python compare,
            # same semantics as the row path
            ts_list = list(ts)
            order = np.array(sorted(range(n), key=ts_list.__getitem__), dtype=np.int64)
            ts_sorted = [ts_list[i] for i in order]
            if self._delta_threshold is None or n < 2:
                bad = np.zeros(max(n - 1, 0), dtype=bool)
            else:
                bad = np.array([b - a > self._delta_threshold
                                for a, b in zip(ts_sorted, ts_sorted[1:])], dtype=bool)
        # window starting at s is valid iff no over-threshold delta occurs
        # among sorted positions [s, s+length-1): prefix-sum the bad deltas
        cs = np.concatenate([[0], np.cumsum(bad)])
        num_starts = n - length + 1
        ok = (cs[length - 1:length - 1 + num_starts] - cs[:num_starts]) == 0
        if self._timestamp_overlap:
            starts = np.flatnonzero(ok)
        else:
            picked = []
            s = 0
            while s < num_starts:  # greedy, like the row path's start += length
                if ok[s]:
                    picked.append(s)
                    s += length
                else:
                    s += 1
            starts = np.asarray(picked, dtype=np.int64)
        if len(starts) == 0:
            return None
        out = {}
        for offset in range(self._min_offset, self._max_offset + 1):
            idx = order[starts + (offset - self._min_offset)]
            wanted = [k for k in self.get_field_names_at_timestep(offset) if k in block]
            out[offset] = {k: block[k][idx] for k in wanted}
        return out

    def _window_within_threshold(self, window):
        if self._delta_threshold is None:
            return True
        ts = [r[self._timestamp_field_name] for r in window]
        for a, b in zip(ts, ts[1:]):
            if b - a > self._delta_threshold:
                return False
        return True

    def make_namedtuple(self, schema, ngram_as_dicts):
        """Convert an ngram of row dicts into offset -> schema-view namedtuple
        (what the reader yields)."""
        result = {}
        for offset, row in ngram_as_dicts.items():
            view = self.get_schema_at_timestep(schema, offset)
            result[offset] = view.make_namedtuple(**{k: row[k] for k in view.fields})
        return result
