"""Device infeed: double-buffered host->device staging.

The BASELINE metric is input-stall % / TPU duty cycle: the device must never
wait for the host. ``prefetch_to_device`` keeps ``size`` batches in flight —
``jax.device_put`` is asynchronous, so transfer of batch N+1 overlaps compute
on batch N (the classic double-buffering at size=2).

Replaces the reference's ``tf.data`` prefetch / torch pin_memory+workers combo.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from petastorm_tpu import observability as obs

#: numpy dtype kinds that can live on device; everything else (strings, objects,
#: datetimes) stays host-side numpy
JAX_COMPATIBLE_KINDS = ('b', 'i', 'u', 'f', 'c')


def stage_batch(batch, target):
    """Recursively move numeric arrays of a (possibly nested) batch dict onto
    ``target`` — a ``jax.Device`` (device_put) or a ``jax.sharding.Sharding``
    (global array assembled from this process's local shard). The single
    canonical host->device staging routine, shared by :class:`JaxDataLoader`,
    :func:`prefetch_to_device`, and ``parallel.make_global_batch``."""
    import jax
    from jax.sharding import Sharding

    def put(x):
        if isinstance(x, dict):
            return {k: put(v) for k, v in x.items()}
        if isinstance(x, np.ndarray) and x.dtype.kind in JAX_COMPATIBLE_KINDS:
            if isinstance(target, Sharding):
                global_shape = (x.shape[0] * jax.process_count(),) + x.shape[1:]
                return jax.make_array_from_process_local_data(target, x, global_shape)
            return jax.device_put(x, target)
        return x

    # per-batch stage timer: device_put is async, so this measures the HOST
    # cost of staging (buffer donation + transfer enqueue), the part that can
    # stall the input pipeline
    with obs.stage('infeed', cat='infeed'):
        return put(batch)


def prefetch_to_device(iterator, target=None, size=2, background=True):
    """Yield batches from ``iterator`` staged onto ``target`` (a device or a
    ``Sharding``; default: the default device), keeping ``size`` transfers in
    flight ahead of the consumer.

    ``background=True`` (default) pulls + stages on a dedicated thread, so the
    loader's batch assembly and the host-side cost of ``device_put`` overlap
    with whatever the consumer thread does between ``next()`` calls (dispatching
    the train step) — on a multi-core host the consumer's wait collapses to a
    queue pop when the pipeline keeps up. ``background=False`` keeps the
    original synchronous refill (deterministic single-thread execution, e.g.
    for profiling the pipeline itself).

    Checkpointing: ``JaxDataLoader.state_dict()`` is safe to call while this
    prefetcher is pumping (the loader serializes batch production against
    snapshots), but batches already staged into the prefetch queue count as
    delivered — a resume continues AFTER them, so a checkpoint taken mid-step
    skips up to ``size`` in-flight batches. Checkpoint at step boundaries with
    the queue drained (or use ``background=False, size=1``) for exact resume.

    :param iterator: iterable of batch dicts (possibly nested, e.g. NGram)
    :param target: ``jax.Device`` | ``jax.sharding.Sharding`` | None
    :param size: prefetch depth; 2 = double buffering
    """
    import jax

    if target is None:
        target = jax.devices()[0]
    if size < 1:
        raise ValueError('size must be >= 1')

    if not background:
        queue = deque()
        it = iter(iterator)
        try:
            while True:
                while len(queue) < size:
                    try:
                        batch = next(it)
                        # causal tracing: when fed a JaxDataLoader (not a bare
                        # generator) the infeed span joins the batch's tree
                        with obs.use_trace(getattr(iterator, 'last_trace', None)):
                            queue.append(stage_batch(batch, target))
                    except StopIteration:
                        while queue:
                            yield queue.popleft()
                        return
                yield queue.popleft()
        finally:
            queue.clear()
        return

    import queue as queue_mod
    import threading

    q = queue_mod.Queue(maxsize=size)
    stop = threading.Event()

    class _Final(object):  # private sentinel: no user batch can be this type
        def __init__(self, exc=None):
            self.exc = exc

    def _pump():
        try:
            for batch in iterator:
                # link the staging span to the batch's trace (loader inputs
                # carry last_trace; plain iterators stage unlinked)
                with obs.use_trace(getattr(iterator, 'last_trace', None)):
                    staged = stage_batch(batch, target)
                while not stop.is_set():
                    try:
                        q.put(staged, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if stop.is_set():
                    return
            _put_final(_Final())
        except BaseException as exc:  # noqa: BLE001 - re-raised on the consumer thread
            _put_final(_Final(exc))

    def _put_final(item):
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return
            except queue_mod.Full:
                continue

    thread = threading.Thread(target=_pump, daemon=True, name='pstpu-prefetch')
    thread.start()
    try:
        while True:
            item = q.get()
            if isinstance(item, _Final):
                if item.exc is not None:
                    raise item.exc
                return
            yield item
    finally:
        stop.set()
        thread.join(timeout=5)
