"""JaxDataLoader: reader rows -> fixed-size batches of (sharded) jax Arrays.

Functional parity with the reference's ``pytorch.DataLoader`` (pytorch.py:94-215):
dtype sanitization, client-side shuffling buffer (row-wise transposition of
batched readers' columnar output, :163-175), fixed-``batch_size`` accumulation,
drain-then-final-batch on exhaustion (:182-192), context-manager stop (:209-215).

TPU-first differences:
  * static shapes by default (``drop_last=True``): XLA recompiles on shape
    change, so ragged final batches are dropped unless asked for;
  * output is a dict of numpy arrays, optionally converted to ``jax.Array``s
    (single device or a ``Sharding``) — non-numeric columns stay numpy;
  * NGram windows batch time-major: offset -> field -> ``[B, ...]`` arrays.
"""

from __future__ import annotations

import logging
import threading
import time
from decimal import Decimal

import numpy as np

from petastorm_tpu import observability as obs
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.observability import blackbox
from petastorm_tpu.jax.infeed import stage_batch
from petastorm_tpu.shuffling_buffer import default_min_after, make_shuffling_buffer_factory

logger = logging.getLogger(__name__)


def _sanitize_value(value, field_name):
    """numpy-ify one row value; Decimal -> float64 (reference pytorch.py:36-66
    promotes torch-hostile dtypes similarly)."""
    if isinstance(value, Decimal):
        return np.float64(value)
    if isinstance(value, np.datetime64):
        return value.astype('datetime64[ns]').astype(np.int64)  # ns ticks
    return value


def collate_rows(rows, field_names=None):
    """Stack a list of row dicts/namedtuples into a dict of [B, ...] arrays.

    Fields with non-uniform shapes raise with guidance (pad/crop in a
    TransformSpec); string/object fields become object arrays (host-only).
    """
    if not rows:
        raise PetastormTpuError('Cannot collate an empty batch')
    # per-row normalization: a batch may mix namedtuples with plain dicts
    # (e.g. checkpoint-restored buffer rows next to freshly-read rows)
    rows = [r._asdict() if hasattr(r, '_asdict') else r for r in rows]
    names = field_names or list(rows[0].keys())
    batch = {}
    for name in names:
        values = [_sanitize_value(r[name], name) for r in rows]
        v0 = values[0]
        if v0 is None or isinstance(v0, (str, bytes)):
            arr = np.empty(len(values), dtype=object)
            arr[:] = values
            batch[name] = arr
            continue
        try:
            batch[name] = np.stack(values)
        except ValueError:
            shapes = {np.shape(v) for v in values}
            if len(shapes) > 1:
                raise PetastormTpuError(
                    'Field {!r} has non-uniform shapes {} within a batch. For '
                    'variable-length sequences, pass collate_spec=CollateSpec('
                    '{{{!r}: PadSpec(...)}}) for per-batch ragged padding '
                    '(petastorm_tpu.sequence, docs/sequence.md); otherwise use a '
                    'TransformSpec to crop/pad to a fixed shape, or exclude the '
                    'field via schema_fields.'.format(name, sorted(shapes), name))
            raise
    return batch


def _sanitize_batch_columns(batch):
    """Column-at-a-time dtype sanitization for the columnar fast path — the
    batch analog of :func:`_sanitize_value`: datetime columns -> int64 ns
    ticks, Decimal object columns -> float64. ``None`` cells (nullable fields)
    are preserved, exactly as the row path's per-value sanitizer preserves
    them — columns containing nulls stay object-typed and host-side."""
    for name in batch:
        col = batch[name]
        if not isinstance(col, np.ndarray):
            continue
        if col.dtype.kind == 'M':
            batch[name] = col.astype('datetime64[ns]').astype(np.int64)
        elif col.dtype == object and col.size:
            v0 = next((v for v in col if v is not None), None)
            has_none = any(v is None for v in col)
            if isinstance(v0, Decimal):
                converted = [None if v is None else np.float64(v) for v in col]
            elif isinstance(v0, np.datetime64):
                converted = [None if v is None
                             else v.astype('datetime64[ns]').astype(np.int64)
                             for v in col]
            else:
                continue
            if has_none:
                out = np.empty(len(converted), dtype=object)
                out[:] = converted
                batch[name] = out
            else:
                batch[name] = np.array(converted)
    return batch


def _flatten_ngram_block(nested):
    """Nested window block {offset: {field: col}} -> flat {(offset, field): col}
    so the columnar buffers (which only see dicts of equal-length columns) can
    shuffle/slice windows like any other rows."""
    return {(off, name): col for off, fields in nested.items()
            for name, col in fields.items()}


def _unflatten_ngram_batch(flat):
    out = {}
    for (off, name), col in flat.items():
        out.setdefault(off, {})[name] = col
    return out


def _rows_from_columnar_batch(batch_namedtuple):
    """Transpose a batched reader's columnar output into row dicts
    (reference pytorch.py:163-175)."""
    d = batch_namedtuple._asdict()
    n = len(next(iter(d.values())))
    return [{k: v[i] for k, v in d.items()} for i in range(n)]


def _to_plain_row(row):
    """Checkpoint-friendly row: schema namedtuple classes are created
    dynamically and do not unpickle, so store plain dicts (collate accepts
    both). NGram windows are dicts of offset -> namedtuple."""
    if hasattr(row, '_asdict'):
        return row._asdict()
    if isinstance(row, dict):
        return {k: (v._asdict() if hasattr(v, '_asdict') else v) for k, v in row.items()}
    return row


class JaxDataLoader(object):
    """
    :param reader: a :class:`petastorm_tpu.reader.Reader` (row or batch oriented)
    :param batch_size: rows per emitted batch
    :param shuffling_queue_capacity: >0 enables a client-side
        :class:`RandomShufflingBuffer` of that capacity
    :param min_after_retrieve: decorrelation floor of the shuffling buffer
        (default capacity//2)
    :param seed: shuffling buffer RNG seed
    :param drop_last: drop the ragged final batch (default True: static shapes
        keep XLA from recompiling)
    :param to_device: ``None`` -> numpy host batches; a ``jax.Device`` -> arrays
        committed to it; a ``jax.sharding.Sharding`` -> global sharded arrays
        (multi-host: each process feeds its local shard)
    :param resume_state: dict from :meth:`state_dict`. Restores the rows that
        were buffered client-side at checkpoint time; construct the underlying
        reader with its own ``resume_state=state['reader']``.
    :param collate_spec: a :class:`petastorm_tpu.sequence.CollateSpec` —
        ragged collation for variable-length fields (docs/sequence.md): each
        batch pads the named fields to a per-batch length (``pad_to``
        rounding / ``buckets`` ladder / ``max_length`` cap), emits
        ``<field>_lengths`` companions, and tracks padding waste
        (``diagnostics['padding_waste_fraction']``). Row-oriented readers
        only; not supported with ngram windows.
    :param bucket_boundaries: with ``collate_spec``, batch by length bucket:
        rows are routed to length buckets and released only in same-bucket
        runs of ``batch_size``, so each padded batch mixes near-equal
        lengths. Deterministic and checkpoint-compatible (``seed`` drives the
        within-bucket shuffle); replaces the shuffling buffer — pass
        ``shuffling_queue_capacity=0``.
    """

    def __init__(self, reader, batch_size, shuffling_queue_capacity=0,
                 min_after_retrieve=None, seed=None, drop_last=True, to_device=None,
                 resume_state=None, collate_spec=None, bucket_boundaries=None):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
        self.reader = reader
        self.batch_size = batch_size
        self._drop_last = drop_last
        self._to_device = to_device
        self._ngram = getattr(reader, 'ngram', None)
        # serializes batch production against state_dict(): prefetch_to_device
        # (background=True) iterates this loader from a pump thread while a
        # checkpoint may be taken from the training thread
        self._state_lock = threading.Lock()
        # columnar fast path: readers that emit column blocks (make_batch_reader,
        # make_reader(output='columnar')) never materialize rows — batches are
        # numpy slices/gathers of whole blocks. NGram columnar readers emit
        # nested window blocks, buffered under flat (offset, field) keys.
        self._columnar = bool(reader.batched_output)
        self._columnar_ngram = self._columnar and self._ngram is not None
        # ragged collation + bucket-by-length batching (docs/sequence.md)
        self._collate_spec = collate_spec
        self._bucket_boundaries = tuple(bucket_boundaries) if bucket_boundaries else None
        self._pad_stats = {'real_tokens': 0, 'padded_tokens': 0}
        if collate_spec is not None:
            if self._columnar:
                raise ValueError(
                    "collate_spec requires a row-oriented reader (output='rows'): "
                    'ragged collation pads per-row cells, and columnar blocks are '
                    'already stacked')
            if self._ngram is not None:
                raise ValueError('collate_spec is not supported with ngram windows '
                                 '(windows collate per offset, not per ragged field)')
        if self._bucket_boundaries is not None:
            if collate_spec is None:
                raise ValueError('bucket_boundaries requires collate_spec: bucketing '
                                 "batches by the spec's length field")
            if shuffling_queue_capacity > 0:
                raise ValueError('bucket_boundaries replaces the shuffling buffer '
                                 '(seed drives the within-bucket shuffle); pass '
                                 'shuffling_queue_capacity=0')
        # shuffle knob state: _make_buffer reads these LIVE, so a runtime
        # set_shuffle_capacity (the autotuner's shuffle knob) applies to the
        # current buffer and to every buffer built for later epochs
        self._shuffle_capacity = shuffling_queue_capacity
        self._min_after_retrieve = min_after_retrieve
        self._shuffle_seed = seed
        self._buffer = None
        self._pending = []
        # diagnostics state exists from construction: the full key set is
        # emitted (as zeros) even before iteration starts, so consumers never
        # need .get guards (pre-fix, rows_emitted/reader_wait_* were absent
        # until the first __iter__)
        self._iter_start = None
        self._reader_wait_s = 0.0
        self._rows_out = 0
        # causal tracing (docs/observability.md): virtual-root TraceContext of
        # the most recent reader item folded into an emitted batch. A shuffled
        # batch mixes rows from many items; the collate/infeed spans link to
        # the LAST contributor — enough to walk one representative tree from
        # dispatch to device without per-row bookkeeping in the hot loop.
        self.last_trace = None
        if resume_state is not None:
            if not isinstance(resume_state, dict) or resume_state.get('version') != 1:
                raise ValueError('Unrecognized resume_state (expected a dict produced by '
                                 'JaxDataLoader.state_dict())')
            self._resume_rows = list(resume_state['rows'])
            self._resume_rng = resume_state.get('buffer_rng')
        else:
            self._resume_rows = None
            self._resume_rng = None
        # closed-loop autotuning (docs/autotune.md): an autotuned reader's
        # controller rebinds its evidence source to THIS loader (whose
        # diagnostics carry the consumer-side reader_wait signal) and gains
        # the shuffle-capacity knob
        tuner = getattr(reader, 'autotuner', None)
        if tuner is not None and hasattr(tuner, 'attach_loader'):
            tuner.attach_loader(self)
        # flight recorder (docs/observability.md): batches emitted are the
        # training loop's progress signal — the watchdog calls a run stalled
        # only when a stage is open AND this stops advancing
        if blackbox.maybe_enable('loader') is not None:
            blackbox.watch_progress('loader_batches', lambda: obs.get_registry()
                                    .counter('loader_batches_total').value)

    def _make_buffer(self):
        """Build the client-side buffer from the CURRENT shuffle knob values
        (one construction site for first iteration and every later epoch)."""
        capacity = self._shuffle_capacity
        if self._bucket_boundaries is not None:
            from petastorm_tpu.sequence.bucket import BucketBatchBuffer
            return BucketBatchBuffer(self._bucket_boundaries, self.batch_size,
                                     self._collate_spec.length_of,
                                     seed=self._shuffle_seed)
        if self._columnar:
            from petastorm_tpu.columnar import FifoColumnarBuffer, ShuffledColumnarBuffer
            if capacity > 0:
                floor = default_min_after(capacity, self._min_after_retrieve)
                return ShuffledColumnarBuffer(capacity, floor, self._shuffle_seed)
            return FifoColumnarBuffer()
        return make_shuffling_buffer_factory(
            capacity, self._min_after_retrieve, self._shuffle_seed,
            self.batch_size, batched_reader=self.reader.batched_output)()

    @property
    def shuffle_capacity(self):
        """The live shuffle-buffer capacity (0 = no shuffling buffer)."""
        return self._shuffle_capacity

    def set_shuffle_capacity(self, capacity):
        """Resize the client-side shuffling buffer at runtime (the autotuner's
        shuffle knob; ``docs/autotune.md``). Applies to the live buffer —
        buffered rows are kept — and to buffers built for later epochs. Only
        valid when the loader was constructed WITH a shuffling buffer
        (``shuffling_queue_capacity > 0``): switching shuffling on/off
        mid-iteration would change delivery semantics, not just performance."""
        capacity = int(capacity)
        if capacity < 2:
            raise ValueError('shuffle capacity must be >= 2 (the decorrelation '
                             'floor must stay below it)')
        if self._shuffle_capacity <= 0:
            raise RuntimeError('loader has no shuffling buffer (constructed with '
                               'shuffling_queue_capacity=0); the shuffle knob is '
                               'unavailable')
        with self._state_lock:
            self._shuffle_capacity = capacity
            # an explicit min_after_retrieve may exceed the new capacity:
            # re-derive the floor from the one shared definition
            self._min_after_retrieve = None
            buffer = self._buffer
            if buffer is not None and hasattr(buffer, 'resize'):
                buffer.resize(capacity, default_min_after(capacity))
        return capacity

    # -- iteration ----------------------------------------------------------

    def __iter__(self):
        # eager (not part of the generator body): a second iter() while rows
        # are in flight would rebind _buffer/_pending and silently drop the
        # first iterator's buffered rows from future state_dict() checkpoints.
        # Buffer creation and resume-row injection are ALSO eager — were they in
        # the generator body, two iter() calls before any next() would both pass
        # this guard, and advancing both would rebind _buffer and orphan the
        # first iterator's rows from checkpoints.
        if (self._buffer is not None and self._buffer.size) or self._pending:
            raise RuntimeError(
                'JaxDataLoader.__iter__ called again while a previous iteration still holds '
                'buffered rows; exhaust the previous iterator (or create a new loader) first.')
        buffer = self._buffer = self._make_buffer()
        self._pending = []
        if self._resume_rng is not None and hasattr(buffer, 'rng_state'):
            buffer.rng_state = self._resume_rng
        self._resume_rng = None
        if self._resume_rows:
            if self._columnar:
                from petastorm_tpu.columnar import rows_to_block
                buffer.add_block(rows_to_block(self._resume_rows))
            else:
                buffer.add_many(self._resume_rows)
        # clear even when empty: a leftover [] would permanently re-route
        # state_dict() to the (now stale) resume branch
        self._resume_rows = None
        gen = (self._iterate_columnar(buffer) if self._columnar
               else self._iterate(buffer, self._pending))
        return gen

    def _iterate_columnar(self, buffer):
        # Locking: the state lock is held only around buffer mutation + batch
        # extraction — NEVER across the blocking next(reader_it) — so a
        # state_dict() taken from another thread (background prefetch pumping
        # this loader) sees a consistent snapshot and cannot hang behind a
        # starved reader.
        #
        # Exactly ONE batch is extracted per yield: a batch leaves the buffer
        # only at the moment it is handed to the consumer. Extracting several
        # batches under the lock and yielding them lazily would park them in a
        # generator-local limbo that state_dict() cannot see — a checkpoint
        # taken then would silently lose those rows.
        self._iter_start = time.perf_counter()
        self._reader_wait_s = 0.0
        self._rows_out = 0
        bs = self.batch_size
        reader_it = iter(self.reader)
        exhausted = False
        while True:
            with self._state_lock:
                batch = None
                if not exhausted:
                    if buffer.can_emit(bs):
                        batch = self._emit_columnar(self._buffer_emit(buffer, bs))
                elif buffer.size >= bs:
                    batch = self._emit_columnar(self._buffer_emit(buffer, bs))
                elif buffer.size and not self._drop_last:
                    batch = self._emit_columnar(self._buffer_emit(buffer, buffer.size))
                else:
                    # drop_last leftovers are intentionally dropped — clear so
                    # an exhausted loader can be iterated again (multi-epoch)
                    buffer.clear()
                    return
            if batch is not None:
                yield batch
                continue
            w0 = time.perf_counter()
            try:
                item = next(reader_it)
            except StopIteration:
                self._reader_wait_s += time.perf_counter() - w0
                with self._state_lock:
                    buffer.finish()
                exhausted = True
                continue
            self._reader_wait_s += time.perf_counter() - w0
            with self._state_lock:
                # block granularity (one row group), never per row: the
                # counters-level overhead contract of the hot loop
                with obs.span('shuffle.add_block', cat='loader',
                              occupancy=buffer.size):
                    if self._columnar_ngram:
                        buffer.add_block(_flatten_ngram_block(item))
                    else:
                        buffer.add_block(dict(item._asdict()))
                obs.gauge_set('shuffle_buffer_occupancy', buffer.size)

    def _buffer_emit(self, buffer, count):
        """One shuffle-buffer batch extraction, traced with its pre-emit
        occupancy (spans level; block granularity)."""
        with obs.span('shuffle.emit', cat='loader', occupancy=buffer.size,
                      rows=count):
            return buffer.emit(count)

    def _emit_columnar(self, batch):
        n = len(next(iter(batch.values()))) if batch else 0
        self._rows_out += n
        self.last_trace = getattr(self.reader, 'last_trace', None)
        with obs.stage('collate', cat='loader', rows=n) as sp:
            sp.link(self.last_trace)
            batch = _sanitize_batch_columns(batch)
            if self._columnar_ngram:
                batch = _unflatten_ngram_batch(batch)
        obs.count('loader_batches_total')
        if self._to_device is not None:
            with obs.use_trace(self.last_trace):
                batch = self._stage(batch)
        return batch

    def _iterate(self, buffer, pending):
        # One batch extracted per yield, same invariant (and for the same
        # checkpoint-correctness reason) as _iterate_columnar. The collate
        # happens under the lock BEFORE the yield: a state_dict() taken while
        # the consumer holds a batch must not count its rows as pending.
        self._iter_start = time.perf_counter()
        self._reader_wait_s = 0.0
        self._rows_out = 0
        bs = self.batch_size
        reader_it = iter(self.reader)
        exhausted = False
        while True:
            with self._state_lock:
                batch = None
                while buffer.can_retrieve() and len(pending) < bs:
                    pending.append(buffer.retrieve())
                if len(pending) == bs:
                    batch = self._emit(pending)
                    pending.clear()
                elif exhausted:
                    if pending and not self._drop_last:
                        batch = self._emit(list(pending))
                        pending.clear()
                    else:
                        # drop_last leftovers are intentionally dropped — clear
                        # so an exhausted loader can be iterated again
                        pending.clear()
                        return
            if batch is not None:
                yield batch
                continue
            w0 = time.perf_counter()
            try:
                item = next(reader_it)
            except StopIteration:
                self._reader_wait_s += time.perf_counter() - w0
                with self._state_lock:
                    buffer.finish()
                exhausted = True
                continue
            self._reader_wait_s += time.perf_counter() - w0
            with self._state_lock:  # mutation only — never across the reader wait
                if self.reader.batched_output:
                    # occupancy at block granularity only: row-oriented readers
                    # land here once per ROW, and the hot-loop contract is no
                    # per-row telemetry work even at the counters level (the
                    # row path's gauge rides the per-batch emit instead)
                    buffer.add_many(_rows_from_columnar_batch(item))
                    obs.gauge_set('shuffle_buffer_occupancy', buffer.size)
                else:
                    buffer.add_many([item])

    # -- checkpoint ---------------------------------------------------------

    def state_dict(self):
        """Loader-level read-position checkpoint: the underlying reader's
        :meth:`Reader.state_dict` plus every row currently buffered client-side
        (shuffling buffer + partial batch), so no yielded-to-loader row is
        lost, and the shuffling buffer's RNG state, so a seeded resume
        reproduces the exact pre-checkpoint stream. Note the state embeds the
        buffered rows — with a large ``shuffling_queue_capacity`` it is
        correspondingly large. Resume with::

            reader = make_reader(url, ..., resume_state=state['reader'])
            loader = JaxDataLoader(reader, ..., resume_state=state)
        """
        with self._state_lock:
            if self._resume_rows is not None:
                # resume-constructed but not yet iterated: the restored rows/RNG
                # still await injection — re-checkpoint them, don't lose them
                rows = list(self._resume_rows)
                rng = self._resume_rng
            else:
                rows = []
                if self._buffer is not None:
                    if self._columnar:
                        rows.extend(self._buffer.snapshot_rows())
                    else:
                        rows.extend(getattr(self._buffer, '_items', []))
                rows.extend(self._pending)
                rng = getattr(self._buffer, 'rng_state', None)
            return {'version': 1,
                    'reader': self.reader.state_dict(),
                    'buffer_rng': rng,
                    'rows': [_to_plain_row(r) for r in rows]}

    def _emit(self, rows):
        self._rows_out += len(rows)
        self.last_trace = getattr(self.reader, 'last_trace', None)
        with obs.stage('collate', cat='loader', rows=len(rows)) as sp:
            sp.link(self.last_trace)
            if self._ngram is not None:
                batch = self._collate_ngram(rows)
            elif self._collate_spec is not None:
                from petastorm_tpu.sequence.collate import (collate_ragged_rows,
                                                            padding_waste_fraction)
                batch = collate_ragged_rows(rows, self._collate_spec, self._pad_stats)
                obs.gauge_set('padding_waste_fraction',
                              padding_waste_fraction(self._pad_stats))
            else:
                batch = collate_rows(rows)
        obs.count('loader_batches_total')
        if self._buffer is not None:
            obs.gauge_set('shuffle_buffer_occupancy', self._buffer.size)
        if self._to_device is not None:
            with obs.use_trace(self.last_trace):
                batch = self._stage(batch)
        return batch

    @property
    def diagnostics(self):
        """Host-side input-pipeline counters (SURVEY.md §5: the reference only
        exposes queue depths; the BASELINE metric is input-stall, so the loader
        tracks it): rows emitted, seconds blocked waiting on the reader, the
        wait fraction of wall time since iteration started, plus the underlying
        reader's diagnostics (unified pool schema + telemetry registry view).

        The loader key set is ALWAYS present — before iteration starts the
        values are zero, never absent, so consumers need no ``.get`` guards.
        Feed this dict to :func:`petastorm_tpu.observability.stall_report` to
        decompose ``reader_wait_s`` into per-stage contributions."""
        out = dict(self.reader.diagnostics)
        if self._iter_start is not None:
            elapsed = max(time.perf_counter() - self._iter_start, 1e-9)
            wait_fraction = round(self._reader_wait_s / elapsed, 4)
        else:
            wait_fraction = 0.0
        if self._collate_spec is not None:
            from petastorm_tpu.sequence.collate import padding_waste_fraction
            waste = padding_waste_fraction(self._pad_stats)
        else:
            waste = 0.0
        out.update({
            'rows_emitted': self._rows_out,
            'reader_wait_s': round(self._reader_wait_s, 4),
            'reader_wait_fraction': wait_fraction,
            'padding_waste_fraction': waste,
        })
        # zero-copy borrow accounting (docs/native.md): the loader's shuffle
        # buffer and prefetched batches are exactly the borrows that keep
        # shm-ring slots / blob maps pinned, so the live count belongs next
        # to the stall metrics. Refreshed here in case the reader's own
        # diagnostics did not carry the family (e.g. a bare facade).
        from petastorm_tpu.native.lifetime import registry as lifetime_registry
        out.update(lifetime_registry().counters())
        return out

    @property
    def quarantined_items(self):
        """Structured records of row groups quarantined under
        ``on_error='skip'`` — passthrough of
        :attr:`petastorm_tpu.reader.Reader.quarantined_items`, surfaced here
        so training loops can log data-quality incidents next to their step
        metrics (docs/robustness.md)."""
        return getattr(self.reader, 'quarantined_items', [])

    def _collate_ngram(self, windows):
        """windows: list of dicts offset -> namedtuple. Returns
        offset -> field -> [B, ...]."""
        out = {}
        for offset in windows[0]:
            out[offset] = collate_rows([w[offset] for w in windows])
        return out

    def _stage(self, batch):
        return stage_batch(batch, self._to_device)

    # -- lifecycle ----------------------------------------------------------

    def stop(self):
        # stamp the final stall attribution into the flight ring so a
        # post-mortem can report the last-known bottleneck without the
        # process's diagnostics surface (which dies with it)
        if blackbox.get_recorder() is not None:
            try:
                blackbox.record_stall(obs.stall_report(self.diagnostics))
            except Exception:  # noqa: BLE001 - teardown forensics must never mask stop()
                pass
            blackbox.unwatch_progress('loader_batches')
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()


def stack_ngram_time_axis(ngram_batch):
    """Collapse a collated NGram batch (offset -> field -> [B, ...]) into
    field -> [B, T, ...] arrays, T being the window length in offset order.

    This is the bridge from the reader's windowed sequence readout to
    sequence-sharded training: the result can be staged with a
    ``NamedSharding(mesh, P('data', 'seq', ...))`` and consumed by
    context-parallel ops (``petastorm_tpu.ops.ring_attention``). Fields absent
    from some timesteps (NGram allows per-timestep field sets) are skipped.
    """
    offsets = sorted(ngram_batch)
    common = set(ngram_batch[offsets[0]])
    for off in offsets[1:]:
        common &= set(ngram_batch[off])
    out = {}
    for name in sorted(common):
        cols = [ngram_batch[off][name] for off in offsets]
        try:
            out[name] = np.stack(cols, axis=1)
        except ValueError:
            shapes = sorted({np.shape(c) for c in cols})
            raise PetastormTpuError(
                'NGram field {!r} has non-uniform shapes across timesteps '
                '{}: {}. Pad/crop it to a fixed shape with a TransformSpec, or '
                'collate ragged fields via petastorm_tpu.sequence '
                '(docs/sequence.md) before stacking the time axis.'.format(
                    name, offsets, shapes))
    return out


def make_jax_dataset(reader, batch_size, **loader_kwargs):
    """Generator of batches — the ``make_petastorm_dataset`` analog
    (reference tf_utils.py:348-402)."""
    loader = JaxDataLoader(reader, batch_size, **loader_kwargs)
    return iter(loader)
