"""Version shims for the JAX API surface the framework uses.

``shard_map`` was promoted from ``jax.experimental.shard_map`` to the
top-level ``jax.shard_map`` (jax >= 0.6); the experimental module is slated
for removal on the other end. Resolve whichever this environment provides so
the sequence/pipeline ops run across the jax versions the fleet actually has.
"""

from __future__ import annotations

import jax

if hasattr(jax, 'shard_map'):
    shard_map = jax.shard_map

    def legacy_shard_map_kwargs():
        return {}
else:  # pre-promotion jax: the experimental home
    from jax.experimental.shard_map import shard_map  # noqa: F401

    def legacy_shard_map_kwargs():
        """Extra shard_map kwargs only the pre-promotion API needs: its
        replication checker false-positives on grad-of-scan carries (the
        error text itself prescribes ``check_rep=False``); the promoted API
        infers these correctly and no longer spells the kwarg this way."""
        return {'check_rep': False}

__all__ = ['legacy_shard_map_kwargs', 'shard_map']
