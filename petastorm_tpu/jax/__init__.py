"""JAX adapter: the framework's primary training-loop interface.

Replaces the reference's TF/torch adapter layer (tf_utils.py / pytorch.py) with
a TPU-first design: batches collate into numpy host buffers, convert to (sharded)
``jax.Array``s, and stream through a double-buffered device prefetch so host
decode overlaps device compute.
"""

from petastorm_tpu.jax.loader import JaxDataLoader, make_jax_dataset  # noqa: F401
from petastorm_tpu.jax.infeed import prefetch_to_device  # noqa: F401
