"""Random schema-conformant datapoint generation.

Parity: reference /root/reference/petastorm/generator.py:21-47 (random datapoint
from a Unischema) — here with a seedable RNG (the framework-wide determinism
story, SURVEY.md §5) and coverage for string/bytes/Decimal/bool fields the
reference's float-cast approach mishandles.
"""

from __future__ import annotations

import string
from decimal import Decimal

import numpy as np

#: dimension used for ``None`` (wildcard) shape entries
LIST_SIZE = 13

_ALPHABET = np.array(list(string.ascii_lowercase))


def _random_value(field, rng, list_size):
    dtype = field.numpy_dtype
    shape = tuple(list_size if d is None else d for d in field.shape)
    if dtype is Decimal:
        return Decimal('{}.{:02d}'.format(int(rng.integers(0, 1000)),
                                          int(rng.integers(0, 100))))
    if dtype is np.str_ or dtype is str:
        def word():
            return ''.join(rng.choice(_ALPHABET, size=rng.integers(1, 12)))
        if shape == ():
            return word()
        return np.asarray([word() for _ in range(int(np.prod(shape)))],
                          dtype=np.str_).reshape(shape)
    if dtype is np.bytes_ or dtype is bytes:
        def token():
            return ''.join(rng.choice(_ALPHABET, size=rng.integers(1, 12))).encode()
        if shape == ():
            return token()
        return np.asarray([token() for _ in range(int(np.prod(shape)))],
                          dtype=np.bytes_).reshape(shape)
    np_dtype = np.dtype(dtype)
    if np_dtype.kind == 'b':
        value = rng.integers(0, 2, size=shape).astype(np.bool_)
    elif np_dtype.kind in 'iu':
        info = np.iinfo(np_dtype)
        value = rng.integers(info.min, info.max, size=shape, dtype=np_dtype,
                             endpoint=True)
    elif np_dtype.kind == 'f':
        value = rng.random(size=shape).astype(np_dtype)
    elif np_dtype.kind == 'M':  # datetime64
        value = (np.datetime64('2020-01-01') +
                 rng.integers(0, 10**6, size=shape).astype('timedelta64[s]'))
        value = value.astype(np_dtype)
    else:
        raise TypeError('generate_datapoint: unsupported dtype {} for field {}'.format(
            np_dtype, field.name))
    if shape == ():
        return value[()] if isinstance(value, np.ndarray) else value
    return value


def generate_datapoint(schema, rng=None, list_size=LIST_SIZE):
    """Generate one random row dict conforming to ``schema``
    (reference generator.py:21-47).

    :param schema: a :class:`~petastorm_tpu.unischema.Unischema`
    :param rng: ``numpy.random.Generator`` (None = fresh nondeterministic one)
    :param list_size: dimension substituted for ``None`` shape wildcards
    """
    rng = rng if rng is not None else np.random.default_rng()
    return {name: _random_value(field, rng, list_size)
            for name, field in schema.fields.items()}
