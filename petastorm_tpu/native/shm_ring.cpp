// First-party shared-memory SPSC ring for worker->main result transport.
//
// The reference delegates its process-pool transport to libzmq (C) over tcp
// loopback (reference workers_pool/process_pool.py:52-74). This is the
// equivalent native component done first-party (SURVEY.md §2.10 plan): one
// single-producer/single-consumer byte ring per worker process in POSIX shared
// memory, so a decoded row-group payload crosses the process boundary with
// exactly one memcpy in and one out — no socket syscalls, no kernel copies.
//
// Layout: [RingHeader][data area of `capacity` bytes]. `head`/`tail` are
// monotonically increasing byte positions (index = pos % capacity). Messages
// are 8-byte little-endian length + payload, wrapping byte-wise. Producer:
// load head (acquire) -> check space -> write -> store tail (release).
// Consumer: load tail (acquire) -> read -> store head (release). Blocking is
// left to the Python callers (sleep-poll), keeping the C side lock-free.
//
// Build: python -m petastorm_tpu.native.build (second, dependency-free target).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;  // consumer position
  std::atomic<uint64_t> tail;  // producer position
  uint64_t capacity;
  uint64_t magic;
  char pad[64 - 4 * sizeof(uint64_t)];  // keep the data area cache-aligned
};

constexpr uint64_t kMagic = 0x70737470755F7268ULL;  // "pstpu_rh"

// Length-prefix flag marking a PAD region (no payload): the producer's
// in-place reservation needs a CONTIGUOUS slot, so when the next message
// would wrap it first emits an 8-byte pad marker whose low bits hold the
// number of dead bytes to skip; consumers jump over pads transparently.
// Real message lengths are < 2^63, so the flag is unambiguous.
constexpr uint64_t kPadFlag = 1ULL << 63;

struct RingHandle {
  RingHeader* hdr;
  uint8_t* data;
  size_t map_len;
  std::string name;
  bool owner;
  // producer-side pending in-place reservation (single producer: plain fields)
  uint64_t pending_tail = 0;
  uint64_t pending_pad = 0;   // pad marker + dead bytes emitted before the slot
  uint64_t pending_max = 0;   // reserved payload capacity
  bool pending = false;
};

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

void copy_in(RingHandle* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(r->data + idx, src, first);
  if (first < len) std::memcpy(r->data, src + first, len - first);
}

void copy_out(RingHandle* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(dst, r->data + idx, first);
  if (first < len) std::memcpy(dst + first, r->data, len - first);
}

}  // namespace

extern "C" {

const char* pstpu_ring_last_error() { return g_error.c_str(); }

// Create (consumer side). Returns NULL on failure.
void* pstpu_ring_create(const char* name, uint64_t capacity) {
  if (capacity < 4096) {
    set_error("ring capacity must be >= 4096 bytes");
    return nullptr;
  }
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(create) failed: ") + std::strerror(errno));
    return nullptr;
  }
  const size_t map_len = sizeof(RingHeader) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    set_error(std::string("ftruncate failed: ") + std::strerror(errno));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // Pre-fault the whole segment NOW: ftruncate on tmpfs succeeds beyond the
  // /dev/shm quota and the first store past it delivers SIGBUS (killing the
  // process uncatchably). posix_fallocate reserves the blocks up front and
  // reports exhaustion as a plain error the caller can fall back from.
  int falloc_rc = posix_fallocate(fd, 0, static_cast<off_t>(map_len));
  if (falloc_rc != 0 && falloc_rc != EOPNOTSUPP && falloc_rc != EINVAL) {
    set_error(std::string("posix_fallocate failed (is /dev/shm large enough?): ") +
              std::strerror(falloc_rc));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) RingHeader();
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->capacity = capacity;
  hdr->magic = kMagic;
  auto* handle = new RingHandle{hdr, reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader),
                                map_len, name, /*owner=*/true};
  return handle;
}

// Attach (producer side). Returns NULL on failure.
void* pstpu_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(attach) failed: ") + std::strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(RingHeader)) {
    set_error("ring shm segment too small");
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    return nullptr;
  }
  auto* hdr = reinterpret_cast<RingHeader*>(mem);
  if (hdr->magic != kMagic ||
      sizeof(RingHeader) + hdr->capacity != static_cast<uint64_t>(st.st_size)) {
    set_error("ring header corrupt (magic/capacity mismatch)");
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* handle = new RingHandle{hdr, reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader),
                                static_cast<size_t>(st.st_size), name, /*owner=*/false};
  return handle;
}

uint64_t pstpu_ring_capacity(void* h) {
  return static_cast<RingHandle*>(h)->hdr->capacity;
}

// Space currently free for writing (bytes, including the 8-byte length prefix).
uint64_t pstpu_ring_free_space(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  return r->hdr->capacity - (tail - head);
}

// Non-blocking write of one message. 1 = written, 0 = would block (not enough
// space right now), -1 = message can never fit this ring.
int pstpu_ring_write(void* h, const void* data, uint64_t len) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;  // assume little-endian host (x86/arm TPU hosts)
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  copy_in(r, tail + 8, static_cast<const uint8_t*>(data), len);
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Gather write: header + payload as ONE message, no caller-side concat copy.
// Same return convention as pstpu_ring_write.
int pstpu_ring_write2(void* h, const void* a, uint64_t a_len, const void* b, uint64_t b_len) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t len = a_len + b_len;
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  copy_in(r, tail + 8, static_cast<const uint8_t*>(a), a_len);
  copy_in(r, tail + 8 + a_len, static_cast<const uint8_t*>(b), b_len);
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Gather write of N segments as ONE message — the generalization of write2
// the serializer's parts channel uses: a whole column block (header + every
// column/cell buffer) lands in the ring with exactly one copy per byte and no
// caller-side join. Same return convention as pstpu_ring_write.
int pstpu_ring_writev(void* h, const void* const* bufs, const uint64_t* lens, int32_t n) {
  auto* r = static_cast<RingHandle*>(h);
  uint64_t len = 0;
  for (int32_t i = 0; i < n; i++) len += lens[i];
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  uint64_t off = tail + 8;
  for (int32_t i = 0; i < n; i++) {
    if (lens[i] == 0) continue;
    copy_in(r, off, static_cast<const uint8_t*>(bufs[i]), lens[i]);
    off += lens[i];
  }
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Reserve a CONTIGUOUS writable region of up to max_len payload bytes inside
// the ring (the in-place channel: a fused batch decode lands its rows
// directly in the slot the consumer will map — the publish is then a header
// write, not a copy). When the slot would wrap, a pad marker is staged first
// so the payload starts at the ring's physical start. Nothing becomes visible
// to the consumer until pstpu_ring_commit. Exactly one reservation may be
// pending per ring (single producer). *status: 1 = reserved (returns the
// payload pointer), 0 = not enough free space right now (retry), -1 = can
// never fit / a reservation is already pending.
void* pstpu_ring_reserve(void* h, uint64_t max_len, int32_t* status) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t cap = r->hdr->capacity;
  if (r->pending || max_len + 16 > cap) {  // worst case: pad marker + header
    set_error(r->pending ? "a reservation is already pending"
                         : "message larger than ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  const uint64_t idx = tail % cap;
  const uint64_t data_start = (idx + 8) % cap;
  uint64_t pad = 0;
  if (data_start + max_len > cap) {
    // dead bytes from after the pad marker to the physical end; the real
    // header then sits so its payload begins at index 0
    pad = 8 + (cap - data_start);
  }
  if (pad + 8 + max_len > cap) {
    // wrapping at this tail position costs more than the ring holds: a drained
    // ring would still never fit it, so retrying is a livelock — fail so the
    // caller takes the copy channel (single producer: tail can't move under us)
    set_error("message larger than ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  if (cap - (tail - head) < pad + 8 + max_len) {
    if (status) *status = 0;
    return nullptr;
  }
  if (pad != 0) {
    uint64_t marker = kPadFlag | (pad - 8);
    copy_in(r, tail, reinterpret_cast<const uint8_t*>(&marker), 8);
  }
  r->pending = true;
  r->pending_tail = tail;
  r->pending_pad = pad;
  r->pending_max = max_len;
  if (status) *status = 1;
  return r->data + ((tail + pad + 8) % cap);
}

// Publish a pending reservation with its actual payload length (<= the
// reserved max). Returns 0, or -1 when no reservation is pending / the
// length exceeds the reservation.
int pstpu_ring_commit(void* h, uint64_t actual_len) {
  auto* r = static_cast<RingHandle*>(h);
  if (!r->pending || actual_len > r->pending_max) {
    set_error(r->pending ? "commit exceeds reservation" : "no pending reservation");
    return -1;
  }
  uint64_t len_le = actual_len;
  copy_in(r, r->pending_tail + r->pending_pad,
          reinterpret_cast<const uint8_t*>(&len_le), 8);
  r->pending = false;
  r->hdr->tail.store(r->pending_tail + r->pending_pad + 8 + actual_len,
                     std::memory_order_release);
  return 0;
}

// Drop a pending reservation; nothing was ever visible to the consumer.
void pstpu_ring_abort(void* h) {
  static_cast<RingHandle*>(h)->pending = false;
}

// Skip any pad markers at the head; returns the head position of the next
// real message, or UINT64_MAX when the readable region is empty.
static uint64_t skip_pads(RingHandle* r) {
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  while (head != tail) {
    uint64_t len_le = 0;
    copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (!(len_le & kPadFlag)) return head;
    head += 8 + (len_le & ~kPadFlag);
    r->hdr->head.store(head, std::memory_order_release);
  }
  return UINT64_MAX;
}

// Length of the next unread message, or -1 when the ring is empty.
int64_t pstpu_ring_next_len(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = skip_pads(r);
  if (head == UINT64_MAX) return -1;
  uint64_t len_le = 0;
  copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
  return static_cast<int64_t>(len_le);
}

// Read one message into buf. Returns its length, -1 when empty, -2 when buf
// is too small (message left in place; call pstpu_ring_next_len first).
int64_t pstpu_ring_read(void* h, void* buf, uint64_t buf_cap) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = skip_pads(r);
  if (head == UINT64_MAX) return -1;
  uint64_t len_le = 0;
  copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
  if (len_le > buf_cap) return -2;
  copy_out(r, head + 8, static_cast<uint8_t*>(buf), len_le);
  r->hdr->head.store(head + 8 + len_le, std::memory_order_release);
  return static_cast<int64_t>(len_le);
}

// Unmap; the creator also unlinks the shm name.
void pstpu_ring_close(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  munmap(r->hdr, r->map_len);
  if (r->owner) shm_unlink(r->name.c_str());
  delete r;
}

}  // extern "C"
