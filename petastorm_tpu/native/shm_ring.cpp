// First-party shared-memory SPSC ring for worker->main result transport.
//
// The reference delegates its process-pool transport to libzmq (C) over tcp
// loopback (reference workers_pool/process_pool.py:52-74). This is the
// equivalent native component done first-party (SURVEY.md §2.10 plan): one
// single-producer/single-consumer byte ring per worker process in POSIX shared
// memory, so a decoded row-group payload crosses the process boundary with
// exactly one memcpy in and one out — no socket syscalls, no kernel copies.
//
// Layout: [RingHeader][data area of `capacity` bytes]. `head`/`tail` are
// monotonically increasing byte positions (index = pos % capacity). Messages
// are 8-byte little-endian length + payload, wrapping byte-wise. Producer:
// load head (acquire) -> check space -> write -> store tail (release).
// Consumer: load tail (acquire) -> read -> store head (release). Blocking is
// left to the Python callers (sleep-poll), keeping the C side lock-free.
//
// Build: python -m petastorm_tpu.native.build (second, dependency-free target).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <string>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct RingHeader {
  std::atomic<uint64_t> head;  // consumer position
  std::atomic<uint64_t> tail;  // producer position
  uint64_t capacity;
  uint64_t magic;
  char pad[64 - 4 * sizeof(uint64_t)];  // keep the data area cache-aligned
};

constexpr uint64_t kMagic = 0x70737470755F7268ULL;  // "pstpu_rh"

// Length-prefix flag marking a PAD region (no payload): the producer's
// in-place reservation needs a CONTIGUOUS slot, so when the next message
// would wrap it first emits an 8-byte pad marker whose low bits hold the
// number of dead bytes to skip; consumers jump over pads transparently.
// Real message lengths are < 2^63, so the flag is unambiguous.
constexpr uint64_t kPadFlag = 1ULL << 63;

struct RingHandle {
  RingHeader* hdr;
  uint8_t* data;
  size_t map_len;
  std::string name;
  bool owner;
  // producer-side pending in-place reservation (single producer: plain fields)
  uint64_t pending_tail = 0;
  uint64_t pending_pad = 0;   // pad marker + dead bytes emitted before the slot
  uint64_t pending_max = 0;   // reserved payload capacity
  bool pending = false;
  // consumer-side zero-copy peek cursor (single consumer: plain field).
  // Invariant head <= peek_head <= tail; bytes in [head, peek_head) are lent
  // out as views and only pstpu_ring_release retires them to the producer.
  uint64_t peek_head = 0;
};

thread_local std::string g_error;

void set_error(const std::string& msg) { g_error = msg; }

void copy_in(RingHandle* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(r->data + idx, src, first);
  if (first < len) std::memcpy(r->data, src + first, len - first);
}

void copy_out(RingHandle* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(dst, r->data + idx, first);
  if (first < len) std::memcpy(dst + first, r->data, len - first);
}

}  // namespace

extern "C" {

const char* pstpu_ring_last_error() { return g_error.c_str(); }

// Create (consumer side). Returns NULL on failure.
void* pstpu_ring_create(const char* name, uint64_t capacity) {
  if (capacity < 4096) {
    set_error("ring capacity must be >= 4096 bytes");
    return nullptr;
  }
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(create) failed: ") + std::strerror(errno));
    return nullptr;
  }
  const size_t map_len = sizeof(RingHeader) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    set_error(std::string("ftruncate failed: ") + std::strerror(errno));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // Pre-fault the whole segment NOW: ftruncate on tmpfs succeeds beyond the
  // /dev/shm quota and the first store past it delivers SIGBUS (killing the
  // process uncatchably). posix_fallocate reserves the blocks up front and
  // reports exhaustion as a plain error the caller can fall back from.
  int falloc_rc = posix_fallocate(fd, 0, static_cast<off_t>(map_len));
  if (falloc_rc != 0 && falloc_rc != EOPNOTSUPP && falloc_rc != EINVAL) {
    set_error(std::string("posix_fallocate failed (is /dev/shm large enough?): ") +
              std::strerror(falloc_rc));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) RingHeader();
  hdr->head.store(0, std::memory_order_relaxed);
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->capacity = capacity;
  hdr->magic = kMagic;
  auto* handle = new RingHandle{hdr, reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader),
                                map_len, name, /*owner=*/true};
  return handle;
}

// Attach (producer side). Returns NULL on failure.
void* pstpu_ring_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(attach) failed: ") + std::strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(RingHeader)) {
    set_error("ring shm segment too small");
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    return nullptr;
  }
  auto* hdr = reinterpret_cast<RingHeader*>(mem);
  if (hdr->magic != kMagic ||
      sizeof(RingHeader) + hdr->capacity != static_cast<uint64_t>(st.st_size)) {
    set_error("ring header corrupt (magic/capacity mismatch)");
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* handle = new RingHandle{hdr, reinterpret_cast<uint8_t*>(mem) + sizeof(RingHeader),
                                static_cast<size_t>(st.st_size), name, /*owner=*/false};
  return handle;
}

uint64_t pstpu_ring_capacity(void* h) {
  return static_cast<RingHandle*>(h)->hdr->capacity;
}

// Space currently free for writing (bytes, including the 8-byte length prefix).
uint64_t pstpu_ring_free_space(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  return r->hdr->capacity - (tail - head);
}

// Non-blocking write of one message. 1 = written, 0 = would block (not enough
// space right now), -1 = message can never fit this ring.
int pstpu_ring_write(void* h, const void* data, uint64_t len) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;  // assume little-endian host (x86/arm TPU hosts)
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  copy_in(r, tail + 8, static_cast<const uint8_t*>(data), len);
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Gather write: header + payload as ONE message, no caller-side concat copy.
// Same return convention as pstpu_ring_write.
int pstpu_ring_write2(void* h, const void* a, uint64_t a_len, const void* b, uint64_t b_len) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t len = a_len + b_len;
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  copy_in(r, tail + 8, static_cast<const uint8_t*>(a), a_len);
  copy_in(r, tail + 8 + a_len, static_cast<const uint8_t*>(b), b_len);
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Gather write of N segments as ONE message — the generalization of write2
// the serializer's parts channel uses: a whole column block (header + every
// column/cell buffer) lands in the ring with exactly one copy per byte and no
// caller-side join. Same return convention as pstpu_ring_write.
int pstpu_ring_writev(void* h, const void* const* bufs, const uint64_t* lens, int32_t n) {
  auto* r = static_cast<RingHandle*>(h);
  uint64_t len = 0;
  for (int32_t i = 0; i < n; i++) len += lens[i];
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than ring capacity");
    return -1;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - head) < need) return 0;
  uint64_t len_le = len;
  copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  uint64_t off = tail + 8;
  for (int32_t i = 0; i < n; i++) {
    if (lens[i] == 0) continue;
    copy_in(r, off, static_cast<const uint8_t*>(bufs[i]), lens[i]);
    off += lens[i];
  }
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Reserve a CONTIGUOUS writable region of up to max_len payload bytes inside
// the ring (the in-place channel: a fused batch decode lands its rows
// directly in the slot the consumer will map — the publish is then a header
// write, not a copy). When the slot would wrap, a pad marker is staged first
// so the payload starts at the ring's physical start. Nothing becomes visible
// to the consumer until pstpu_ring_commit. Exactly one reservation may be
// pending per ring (single producer). *status: 1 = reserved (returns the
// payload pointer), 0 = not enough free space right now (retry), -1 = can
// never fit / a reservation is already pending.
void* pstpu_ring_reserve(void* h, uint64_t max_len, int32_t* status) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t cap = r->hdr->capacity;
  if (r->pending || max_len + 16 > cap) {  // worst case: pad marker + header
    set_error(r->pending ? "a reservation is already pending"
                         : "message larger than ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  const uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  const uint64_t idx = tail % cap;
  const uint64_t data_start = (idx + 8) % cap;
  uint64_t pad = 0;
  if (data_start + max_len > cap) {
    // dead bytes from after the pad marker to the physical end; the real
    // header then sits so its payload begins at index 0
    pad = 8 + (cap - data_start);
  }
  if (pad + 8 + max_len > cap) {
    // wrapping at this tail position costs more than the ring holds: a drained
    // ring would still never fit it, so retrying is a livelock — fail so the
    // caller takes the copy channel (single producer: tail can't move under us)
    set_error("message larger than ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  if (cap - (tail - head) < pad + 8 + max_len) {
    if (status) *status = 0;
    return nullptr;
  }
  if (pad != 0) {
    uint64_t marker = kPadFlag | (pad - 8);
    copy_in(r, tail, reinterpret_cast<const uint8_t*>(&marker), 8);
  }
  r->pending = true;
  r->pending_tail = tail;
  r->pending_pad = pad;
  r->pending_max = max_len;
  if (status) *status = 1;
  return r->data + ((tail + pad + 8) % cap);
}

// Publish a pending reservation with its actual payload length (<= the
// reserved max). Returns 0, or -1 when no reservation is pending / the
// length exceeds the reservation.
int pstpu_ring_commit(void* h, uint64_t actual_len) {
  auto* r = static_cast<RingHandle*>(h);
  if (!r->pending || actual_len > r->pending_max) {
    set_error(r->pending ? "commit exceeds reservation" : "no pending reservation");
    return -1;
  }
  uint64_t len_le = actual_len;
  copy_in(r, r->pending_tail + r->pending_pad,
          reinterpret_cast<const uint8_t*>(&len_le), 8);
  r->pending = false;
  r->hdr->tail.store(r->pending_tail + r->pending_pad + 8 + actual_len,
                     std::memory_order_release);
  return 0;
}

// Drop a pending reservation; nothing was ever visible to the consumer.
void pstpu_ring_abort(void* h) {
  static_cast<RingHandle*>(h)->pending = false;
}

// Skip any pad markers at the head; returns the head position of the next
// real message, or UINT64_MAX when the readable region is empty.
static uint64_t skip_pads(RingHandle* r) {
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  while (head != tail) {
    uint64_t len_le = 0;
    copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (!(len_le & kPadFlag)) return head;
    head += 8 + (len_le & ~kPadFlag);
    r->hdr->head.store(head, std::memory_order_release);
  }
  return UINT64_MAX;
}

// Length of the next unread message, or -1 when the ring is empty.
int64_t pstpu_ring_next_len(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = skip_pads(r);
  if (head == UINT64_MAX) return -1;
  uint64_t len_le = 0;
  copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
  return static_cast<int64_t>(len_le);
}

// Read one message into buf. Returns its length, -1 when empty, -2 when buf
// is too small (message left in place; call pstpu_ring_next_len first).
int64_t pstpu_ring_read(void* h, void* buf, uint64_t buf_cap) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = skip_pads(r);
  if (head == UINT64_MAX) return -1;
  uint64_t len_le = 0;
  copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
  if (len_le > buf_cap) return -2;
  copy_out(r, head + 8, static_cast<uint8_t*>(buf), len_le);
  r->hdr->head.store(head + 8 + len_le, std::memory_order_release);
  return static_cast<int64_t>(len_le);
}

// Zero-copy take of the next message (lifetime-tracked consumer views,
// docs/native.md). Without advancing the SHARED head, locate the next unread
// message past the handle's local peek cursor and advance that cursor over
// it. out[0] = payload address inside the mapped data area, out[1] = payload
// length, out[2] = span (pads + header + payload bytes) the matching
// pstpu_ring_release must retire once every consumer view of the payload
// died. Returns 1 when out holds a contiguous message, 2 when the next
// message wraps the physical end (out[1]/out[2] still filled; the caller
// copies it out via pstpu_ring_peek_copy), 0 when empty, -1 when out_count
// is too small. Only reserve-committed messages are contiguous by
// construction (pad markers); plain writes wrap byte-wise, hence status 2.
long long pstpu_ring_peek(void* h, unsigned long long* out,
                          unsigned long long out_count) {
  if (out_count < 3) {
    set_error("pstpu_ring_peek needs a 3-slot out array");
    return -1;
  }
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t cap = r->hdr->capacity;
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  const uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  if (r->peek_head < head) r->peek_head = head;  // resync after copy reads
  uint64_t pos = r->peek_head;
  while (pos != tail) {
    uint64_t len_le = 0;
    copy_out(r, pos, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (len_le & kPadFlag) {
      pos += 8 + (len_le & ~kPadFlag);
      continue;
    }
    if (len_le > cap) {
      set_error("ring message length exceeds capacity (corrupt header)");
      return -1;
    }
    const uint64_t idx = (pos + 8) % cap;
    out[1] = len_le;
    out[2] = (pos + 8 + len_le) - r->peek_head;
    if (idx + len_le > cap) {
      out[0] = 0;  // physically wrapped: no contiguous view exists
      return 2;
    }
    out[0] = reinterpret_cast<unsigned long long>(r->data + idx);
    r->peek_head = pos + 8 + len_le;
    return 1;
  }
  return 0;
}

// Copy-out companion of pstpu_ring_peek for wrapped messages: copies the
// next message past the peek cursor into dst and advances the cursor;
// *span_out = the span pstpu_ring_release must retire. Returns the payload
// length, -1 when empty, -2 when dst_cap is too small (cursor unmoved).
long long pstpu_ring_peek_copy(void* h, void* dst, unsigned long long dst_cap,
                               unsigned long long* span_out) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  const uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  if (r->peek_head < head) r->peek_head = head;
  uint64_t pos = r->peek_head;
  while (pos != tail) {
    uint64_t len_le = 0;
    copy_out(r, pos, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (len_le & kPadFlag) {
      pos += 8 + (len_le & ~kPadFlag);
      continue;
    }
    if (len_le > dst_cap) return -2;
    copy_out(r, pos + 8, static_cast<uint8_t*>(dst), len_le);
    if (span_out) *span_out = (pos + 8 + len_le) - r->peek_head;
    r->peek_head = pos + 8 + len_le;
    return static_cast<long long>(len_le);
  }
  return -1;
}

// Non-consuming probe that respects the peek cursor: 1 when a payload
// message exists PAST max(peek_head, head), else 0. pstpu_ring_next_len
// probes from the shared head, so under zero-copy peeks it keeps reporting
// already-delivered (but not yet released) messages — drain/close logic
// needs "unread", not "unreleased".
int pstpu_ring_has_unread(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  const uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  uint64_t pos = r->peek_head < head ? head : r->peek_head;
  while (pos != tail) {
    uint64_t len_le = 0;
    copy_out(r, pos, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (!(len_le & kPadFlag)) return 1;
    pos += 8 + (len_le & ~kPadFlag);
  }
  return 0;
}

// Retire span_bytes of peeked-and-released messages: the producer may reuse
// those bytes from here on. Spans MUST be released in take order (the Python
// RingBorrowLedger serializes out-of-order finalizers into FIFO releases).
// Returns 0, or -1 when the release would pass the peek cursor (caller bug:
// the bytes are still lent out).
int pstpu_ring_release(void* h, unsigned long long span_bytes) {
  auto* r = static_cast<RingHandle*>(h);
  const uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  const uint64_t limit = r->peek_head < head ? head : r->peek_head;
  if (head + span_bytes > limit) {
    set_error("ring release span passes the peek cursor");
    return -1;
  }
  r->hdr->head.store(head + span_bytes, std::memory_order_release);
  return 0;
}

// Debug guard (PSTPU_LIFETIME_GUARD=1): remap the fully page-covered bytes
// of [addr, addr+len) to PROT_NONE (prot_none=1) or back to read/write (0),
// so a use-after-release faults loudly instead of reading recycled bytes.
// Returns the number of bytes whose protection changed (0 when the range
// spans no full page), -1 on mprotect failure.
long long pstpu_guard_protect(void* addr, unsigned long long len,
                              int prot_none) {
  const uint64_t page = static_cast<uint64_t>(sysconf(_SC_PAGESIZE));
  const uint64_t a = reinterpret_cast<uint64_t>(addr);
  const uint64_t start = (a + page - 1) & ~(page - 1);
  const uint64_t end = (a + len) & ~(page - 1);
  if (end <= start) return 0;
  const int prot = prot_none ? PROT_NONE : (PROT_READ | PROT_WRITE);
  if (mprotect(reinterpret_cast<void*>(start), end - start, prot) != 0) {
    set_error(std::string("mprotect failed: ") + std::strerror(errno));
    return -1;
  }
  return static_cast<long long>(end - start);
}

// Unmap; the creator also unlinks the shm name.
void pstpu_ring_close(void* h) {
  auto* r = static_cast<RingHandle*>(h);
  munmap(r->hdr, r->map_len);
  if (r->owner) shm_unlink(r->name.c_str());
  delete r;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Broadcast ring: single producer, K attached consumers (the serve daemon's
// fan-out transport, docs/serve.md). A published message is logically
// reference-counted across the attached consumers WITHOUT a per-slot count:
// each consumer owns a head cursor, advancing it IS that consumer's release,
// and the slot is reclaimed when the slowest attached cursor passes it —
// min-head reclamation makes "released exactly once per attached consumer"
// structural rather than accounted. Consumer slots are granted by the
// PRODUCER (pstpu_bcast_join runs daemon-side between writes), so a joiner's
// head=tail snapshot can never race a concurrent write — the control-plane
// ATTACH round trip is the synchronization. Eviction (producer-side) flips a
// slot to EVICTED: its cursor stops constraining the producer, and the
// consumer's next read reports it (seqlock-style post-copy validation keeps a
// torn read from ever being delivered as data).
// ---------------------------------------------------------------------------

namespace {

constexpr uint64_t kBcastMagic = 0x70737470755F6263ULL;  // "pstpu_bc"
constexpr uint64_t kBcastSlots = 8;
// slot states
constexpr uint64_t kSlotFree = 0;
constexpr uint64_t kSlotAttached = 1;
constexpr uint64_t kSlotEvicted = 2;

struct BcastHeader {
  std::atomic<uint64_t> tail;       // producer position
  uint64_t capacity;
  uint64_t magic;
  uint64_t max_consumers;           // == kBcastSlots of the creating build
  std::atomic<uint64_t> epoch;      // bumps on every attach/evict (observability)
  char pad0[24];                    // keep the slot arrays cache-aligned
  std::atomic<uint64_t> heads[8];   // per-slot consumer position
  std::atomic<uint64_t> states[8];  // kSlotFree / kSlotAttached / kSlotEvicted
  std::atomic<uint64_t> gens[8];    // bumps per join: stale tokens are detectable
};

struct BcastHandle {
  BcastHeader* hdr;
  uint8_t* data;
  size_t map_len;
  std::string name;
  bool owner;
  // producer-side pending in-place reservation (single producer: plain fields)
  uint64_t pending_tail = 0;
  uint64_t pending_pad = 0;
  uint64_t pending_max = 0;
  bool pending = false;
};

void bcast_copy_in(BcastHandle* r, uint64_t pos, const uint8_t* src, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(r->data + idx, src, first);
  if (first < len) std::memcpy(r->data, src + first, len - first);
}

void bcast_copy_out(BcastHandle* r, uint64_t pos, uint8_t* dst, uint64_t len) {
  const uint64_t cap = r->hdr->capacity;
  const uint64_t idx = pos % cap;
  const uint64_t first = (idx + len <= cap) ? len : cap - idx;
  std::memcpy(dst, r->data + idx, first);
  if (first < len) std::memcpy(dst + first, r->data, len - first);
}

// Slowest attached cursor; `tail` when no consumer is attached (messages
// published into the void are reclaimed immediately — the Python pump gates
// on consumer_count, so this only covers detach races).
uint64_t bcast_min_head(BcastHeader* h, uint64_t tail) {
  uint64_t m = tail;
  for (uint64_t i = 0; i < kBcastSlots; i++) {
    if (h->states[i].load(std::memory_order_acquire) == kSlotAttached) {
      const uint64_t head = h->heads[i].load(std::memory_order_acquire);
      if (tail - head > tail - m) m = head;  // head furthest behind tail
    }
  }
  return m;
}

// Decompose/validate a consumer token ((gen << 8) | slot). Returns slot index
// or -1 when the token is stale (slot re-granted) or malformed.
int64_t bcast_slot_of(BcastHeader* h, int64_t token) {
  if (token < 0) return -1;
  const uint64_t slot = static_cast<uint64_t>(token) & 0xffULL;
  const uint64_t gen = static_cast<uint64_t>(token) >> 8;
  if (slot >= kBcastSlots) return -1;
  if (h->gens[slot].load(std::memory_order_acquire) != gen) return -1;
  return static_cast<int64_t>(slot);
}

}  // namespace

extern "C" {

// Create (producer side). Returns NULL on failure.
void* pstpu_bcast_create(const char* name, uint64_t capacity) {
  if (capacity < 4096) {
    set_error("bcast ring capacity must be >= 4096 bytes");
    return nullptr;
  }
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(create) failed: ") + std::strerror(errno));
    return nullptr;
  }
  const size_t map_len = sizeof(BcastHeader) + capacity;
  if (ftruncate(fd, static_cast<off_t>(map_len)) != 0) {
    set_error(std::string("ftruncate failed: ") + std::strerror(errno));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  // same pre-faulting stance as pstpu_ring_create: tmpfs exhaustion must be a
  // catchable error here, not a SIGBUS at first touch
  int falloc_rc = posix_fallocate(fd, 0, static_cast<off_t>(map_len));
  if (falloc_rc != 0 && falloc_rc != EOPNOTSUPP && falloc_rc != EINVAL) {
    set_error(std::string("posix_fallocate failed (is /dev/shm large enough?): ") +
              std::strerror(falloc_rc));
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void* mem = mmap(nullptr, map_len, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    shm_unlink(name);
    return nullptr;
  }
  auto* hdr = new (mem) BcastHeader();
  hdr->tail.store(0, std::memory_order_relaxed);
  hdr->capacity = capacity;
  hdr->magic = kBcastMagic;
  hdr->max_consumers = kBcastSlots;
  hdr->epoch.store(0, std::memory_order_relaxed);
  for (uint64_t i = 0; i < kBcastSlots; i++) {
    hdr->heads[i].store(0, std::memory_order_relaxed);
    hdr->states[i].store(kSlotFree, std::memory_order_relaxed);
    hdr->gens[i].store(0, std::memory_order_relaxed);
  }
  auto* handle = new BcastHandle{hdr,
                                 reinterpret_cast<uint8_t*>(mem) + sizeof(BcastHeader),
                                 map_len, name, /*owner=*/true};
  return handle;
}

// Attach a consumer-side mapping. Reads require a token from pstpu_bcast_join
// (granted by the producer over the control plane). Returns NULL on failure.
void* pstpu_bcast_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) {
    set_error(std::string("shm_open(attach) failed: ") + std::strerror(errno));
    return nullptr;
  }
  struct stat st;
  if (fstat(fd, &st) != 0 || static_cast<size_t>(st.st_size) < sizeof(BcastHeader)) {
    set_error("bcast shm segment too small");
    close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ | PROT_WRITE,
                   MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) {
    set_error(std::string("mmap failed: ") + std::strerror(errno));
    return nullptr;
  }
  auto* hdr = reinterpret_cast<BcastHeader*>(mem);
  if (hdr->magic != kBcastMagic || hdr->max_consumers != kBcastSlots ||
      sizeof(BcastHeader) + hdr->capacity != static_cast<uint64_t>(st.st_size)) {
    set_error("bcast header corrupt (magic/capacity/slot-count mismatch)");
    munmap(mem, static_cast<size_t>(st.st_size));
    return nullptr;
  }
  auto* handle = new BcastHandle{hdr,
                                 reinterpret_cast<uint8_t*>(mem) + sizeof(BcastHeader),
                                 static_cast<size_t>(st.st_size), name, /*owner=*/false};
  return handle;
}

uint64_t pstpu_bcast_capacity(void* h) {
  return static_cast<BcastHandle*>(h)->hdr->capacity;
}

// PRODUCER-side slot grant (between writes, so head=tail cannot race a write
// in flight). Returns a consumer token ((gen << 8) | slot), or -1 when every
// slot is taken by an attached consumer.
int64_t pstpu_bcast_join(void* h) {
  auto* r = static_cast<BcastHandle*>(h);
  BcastHeader* hdr = r->hdr;
  for (uint64_t i = 0; i < kBcastSlots; i++) {
    const uint64_t state = hdr->states[i].load(std::memory_order_acquire);
    if (state == kSlotAttached) continue;
    const uint64_t gen = hdr->gens[i].load(std::memory_order_relaxed) + 1;
    hdr->gens[i].store(gen, std::memory_order_release);
    hdr->heads[i].store(hdr->tail.load(std::memory_order_relaxed),
                        std::memory_order_release);
    hdr->states[i].store(kSlotAttached, std::memory_order_seq_cst);
    hdr->epoch.fetch_add(1, std::memory_order_relaxed);
    return static_cast<int64_t>((gen << 8) | i);
  }
  set_error("bcast ring has no free consumer slots");
  return -1;
}

// Graceful detach: the slot stops constraining the producer and is free for
// re-grant. Safe from either side (state writes are monotonic-harmless for
// the producer's min-head scan). Returns 0, or -1 for a stale token.
int64_t pstpu_bcast_leave(void* h, int64_t token) {
  auto* r = static_cast<BcastHandle*>(h);
  const int64_t slot = bcast_slot_of(r->hdr, token);
  if (slot < 0) return -1;
  r->hdr->states[slot].store(kSlotFree, std::memory_order_seq_cst);
  r->hdr->epoch.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

// PRODUCER-side eviction of a lagging consumer: the slot flips to EVICTED
// (its cursor no longer bounds the producer; the consumer's next read reports
// -3). The slot stays EVICTED until the consumer acknowledges by leaving —
// re-grant before that would hand its unread region to a new consumer.
int64_t pstpu_bcast_evict(void* h, int64_t token) {
  auto* r = static_cast<BcastHandle*>(h);
  const int64_t slot = bcast_slot_of(r->hdr, token);
  if (slot < 0) return -1;
  r->hdr->states[slot].store(kSlotEvicted, std::memory_order_seq_cst);
  r->hdr->epoch.fetch_add(1, std::memory_order_relaxed);
  return 0;
}

// Slot state for a token: 1 attached, 2 evicted, 0 freed, -1 stale token.
int64_t pstpu_bcast_state(void* h, int64_t token) {
  auto* r = static_cast<BcastHandle*>(h);
  const int64_t slot = bcast_slot_of(r->hdr, token);
  if (slot < 0) return -1;
  return static_cast<int64_t>(r->hdr->states[slot].load(std::memory_order_acquire));
}

// Unconsumed bytes behind the producer for one consumer (its lag), or -1 for
// a stale token. The producer's eviction policy reads this.
int64_t pstpu_bcast_lag(void* h, int64_t token) {
  auto* r = static_cast<BcastHandle*>(h);
  const int64_t slot = bcast_slot_of(r->hdr, token);
  if (slot < 0) return -1;
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  const uint64_t head = r->hdr->heads[slot].load(std::memory_order_acquire);
  return static_cast<int64_t>(tail - head);
}

int64_t pstpu_bcast_consumer_count(void* h) {
  auto* r = static_cast<BcastHandle*>(h);
  int64_t n = 0;
  for (uint64_t i = 0; i < kBcastSlots; i++) {
    if (r->hdr->states[i].load(std::memory_order_acquire) == kSlotAttached) n++;
  }
  return n;
}

uint64_t pstpu_bcast_free_space(void* h) {
  auto* r = static_cast<BcastHandle*>(h);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  return r->hdr->capacity - (tail - bcast_min_head(r->hdr, tail));
}

// Monotonic producer position (bytes ever published incl. framing/pads).
// The serve daemon's blob GC compares recorded frame-end positions against
// min_head (= tail - max attached lag) to learn when every attached consumer
// has consumed past a frame.
uint64_t pstpu_bcast_tail(void* h) {
  return static_cast<BcastHandle*>(h)->hdr->tail.load(std::memory_order_acquire);
}

// Slowest attached cursor (== tail when no consumer is attached): everything
// below this position has been consumed-or-abandoned by the whole fleet.
uint64_t pstpu_bcast_min_head(void* h) {
  auto* r = static_cast<BcastHandle*>(h);
  const uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  return bcast_min_head(r->hdr, tail);
}

// Non-blocking broadcast write. 1 = written (visible to every attached
// consumer), 0 = a consumer is too far behind (retry / evict), -1 = the
// message can never fit this ring.
int pstpu_bcast_write(void* h, const void* data, uint64_t len) {
  auto* r = static_cast<BcastHandle*>(h);
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than bcast ring capacity");
    return -1;
  }
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - bcast_min_head(r->hdr, tail)) < need) return 0;
  uint64_t len_le = len;
  bcast_copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  bcast_copy_in(r, tail + 8, static_cast<const uint8_t*>(data), len);
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// Gather write of N segments as ONE broadcast message (the serve pump's
// zero-join publish channel). Same return convention as pstpu_bcast_write.
int pstpu_bcast_writev(void* h, const void* const* bufs, const uint64_t* lens, int32_t n) {
  auto* r = static_cast<BcastHandle*>(h);
  uint64_t len = 0;
  for (int32_t i = 0; i < n; i++) len += lens[i];
  const uint64_t need = len + 8;
  if (need > r->hdr->capacity) {
    set_error("message larger than bcast ring capacity");
    return -1;
  }
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  if (r->hdr->capacity - (tail - bcast_min_head(r->hdr, tail)) < need) return 0;
  uint64_t len_le = len;
  bcast_copy_in(r, tail, reinterpret_cast<const uint8_t*>(&len_le), 8);
  uint64_t off = tail + 8;
  for (int32_t i = 0; i < n; i++) {
    if (lens[i] == 0) continue;
    bcast_copy_in(r, off, static_cast<const uint8_t*>(bufs[i]), lens[i]);
    off += lens[i];
  }
  r->hdr->tail.store(tail + need, std::memory_order_release);
  return 1;
}

// In-place reservation on the broadcast ring — identical contract and pad
// scheme to pstpu_ring_reserve (PR 6's in-place channel, preserved for the
// fan-out transport): *status 1 = reserved, 0 = retry, -1 = can never fit /
// reservation already pending.
void* pstpu_bcast_reserve(void* h, uint64_t max_len, int32_t* status) {
  auto* r = static_cast<BcastHandle*>(h);
  const uint64_t cap = r->hdr->capacity;
  if (r->pending || max_len + 16 > cap) {  // worst case: pad marker + header
    set_error(r->pending ? "a reservation is already pending"
                         : "message larger than bcast ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  const uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  const uint64_t idx = tail % cap;
  const uint64_t data_start = (idx + 8) % cap;
  uint64_t pad = 0;
  if (data_start + max_len > cap) {
    pad = 8 + (cap - data_start);
  }
  if (pad + 8 + max_len > cap) {
    // same never-fits-at-this-offset livelock guard as the SPSC ring
    set_error("message larger than bcast ring capacity");
    if (status) *status = -1;
    return nullptr;
  }
  if (cap - (tail - bcast_min_head(r->hdr, tail)) < pad + 8 + max_len) {
    if (status) *status = 0;
    return nullptr;
  }
  if (pad != 0) {
    uint64_t marker = kPadFlag | (pad - 8);
    bcast_copy_in(r, tail, reinterpret_cast<const uint8_t*>(&marker), 8);
  }
  r->pending = true;
  r->pending_tail = tail;
  r->pending_pad = pad;
  r->pending_max = max_len;
  if (status) *status = 1;
  return r->data + ((tail + pad + 8) % cap);
}

int pstpu_bcast_commit(void* h, uint64_t actual_len) {
  auto* r = static_cast<BcastHandle*>(h);
  if (!r->pending || actual_len > r->pending_max) {
    set_error(r->pending ? "commit exceeds reservation" : "no pending reservation");
    return -1;
  }
  uint64_t len_le = actual_len;
  bcast_copy_in(r, r->pending_tail + r->pending_pad,
                reinterpret_cast<const uint8_t*>(&len_le), 8);
  r->pending = false;
  r->hdr->tail.store(r->pending_tail + r->pending_pad + 8 + actual_len,
                     std::memory_order_release);
  return 0;
}

void pstpu_bcast_abort(void* h) {
  static_cast<BcastHandle*>(h)->pending = false;
}

// Length of the next unread message for this consumer, skipping pad markers.
// -1 = empty, -3 = evicted, -4 = stale/freed token.
int64_t pstpu_bcast_next_len(void* h, int64_t token) {
  auto* r = static_cast<BcastHandle*>(h);
  BcastHeader* hdr = r->hdr;
  const int64_t slot = bcast_slot_of(hdr, token);
  if (slot < 0) return -4;
  const uint64_t state = hdr->states[slot].load(std::memory_order_seq_cst);
  if (state == kSlotEvicted) return -3;
  if (state != kSlotAttached) return -4;
  const uint64_t tail = hdr->tail.load(std::memory_order_acquire);
  uint64_t head = hdr->heads[slot].load(std::memory_order_relaxed);
  while (head != tail) {
    uint64_t len_le = 0;
    bcast_copy_out(r, head, reinterpret_cast<uint8_t*>(&len_le), 8);
    if (!(len_le & kPadFlag)) {
      // seqlock validation: only trust the prefix if the slot stayed attached
      // (an eviction lets the producer overwrite the bytes we just read)
      std::atomic_thread_fence(std::memory_order_seq_cst);
      if (hdr->states[slot].load(std::memory_order_seq_cst) != kSlotAttached)
        return -3;
      return static_cast<int64_t>(len_le);
    }
    head += 8 + (len_le & ~kPadFlag);
    hdr->heads[slot].store(head, std::memory_order_release);
  }
  return -1;
}

// Read one message for this consumer into buf. Returns its length; -1 empty,
// -2 buf too small (message left in place), -3 evicted (any partially-copied
// bytes must be discarded), -4 stale/freed token. Advancing the head IS this
// consumer's release of the slot (min-head reclamation).
int64_t pstpu_bcast_read(void* h, int64_t token, void* buf, uint64_t buf_cap) {
  auto* r = static_cast<BcastHandle*>(h);
  BcastHeader* hdr = r->hdr;
  const int64_t n = pstpu_bcast_next_len(h, token);
  if (n < 0) return n;
  if (static_cast<uint64_t>(n) > buf_cap) return -2;
  const int64_t slot = bcast_slot_of(hdr, token);
  if (slot < 0) return -4;
  const uint64_t head = hdr->heads[slot].load(std::memory_order_relaxed);
  bcast_copy_out(r, head + 8, static_cast<uint8_t*>(buf), static_cast<uint64_t>(n));
  // seqlock validation (same fence pairing as next_len): if the producer
  // evicted us mid-copy it may already be overwriting these bytes — report
  // eviction and let the caller discard the torn buffer
  std::atomic_thread_fence(std::memory_order_seq_cst);
  if (hdr->states[slot].load(std::memory_order_seq_cst) != kSlotAttached)
    return -3;
  hdr->heads[slot].store(head + 8 + static_cast<uint64_t>(n),
                         std::memory_order_release);
  return n;
}

// Unmap; the creator also unlinks the shm name.
void pstpu_bcast_close(void* h) {
  auto* r = static_cast<BcastHandle*>(h);
  munmap(r->hdr, r->map_len);
  if (r->owner) shm_unlink(r->name.c_str());
  delete r;
}

}  // extern "C"
