// Native Parquet row-group reader kernel.
//
// The reference delegates all Parquet decode to pyarrow (Arrow C++) through
// Python (reference py_dict_reader_worker.py:254-258, arrow_reader_worker.py).
// This kernel is the framework's first-party native component (SURVEY.md
// §2.10): it opens a Parquet file, reads selected columns of one row group on
// C++ threads (no GIL), and hands the decoded Arrow table back to Python
// zero-copy through the Arrow C Data Interface (ArrowArrayStream).
//
// C ABI only — bound from Python with ctypes (no pybind11 in this image).
//
// Build: python -m petastorm_tpu.native.build  (links pyarrow's bundled
// libarrow/libparquet; C++20 for std::span in Arrow 25 headers).

#include <arrow/api.h>
#include <arrow/c/bridge.h>
#include <arrow/io/file.h>
#include <arrow/util/config.h>
#include <parquet/arrow/reader.h>
#include <parquet/file_reader.h>
#include <parquet/metadata.h>
#include <parquet/properties.h>

// parquet::arrow::FileReader factory/read APIs: Status + out-param in the
// long-stable wheels (<= 22), arrow::Result returns in the newer ones the
// original kernel targeted. Support both; a mismatch merely disables the
// kernel (build failure -> pure-pyarrow fallback), but matching here keeps
// the native path alive across the pyarrow versions the fleet actually runs.
#define PSTPU_ARROW_RESULT_APIS (ARROW_VERSION_MAJOR >= 23)

#include <fcntl.h>

#include <algorithm>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

struct FileHandle {
  std::unique_ptr<parquet::arrow::FileReader> reader;
  std::shared_ptr<parquet::FileMetaData> metadata;
  int fd = -1;  // borrowed from the underlying ReadableFile (it owns closing)
  // parquet::arrow::FileReader is not thread-safe for concurrent reads of the
  // same handle; worker threads each own a handle, but guard anyway so a
  // shared handle degrades to serialized reads instead of corruption.
  std::mutex mutex;
};

// Best-effort page-cache readahead of the column chunks the caller is about
// to decode (the SELECTED columns only — advising the whole group would
// defeat column projection's IO savings on wide tables). A cold-cache decode
// otherwise interleaves demand-paged 64-128KB reads with CPU work; WILLNEED
// lets the kernel stream each chunk's compressed range ahead of the decoder.
// No next-group prefetch: the ventilator shuffles piece order, so "i+1 of
// this file" is almost never what gets read next.
void advise_row_group(FileHandle* h, int i, const int* columns, int n_columns) {
#if defined(POSIX_FADV_WILLNEED)
  if (h->fd < 0 || i < 0 || i >= h->metadata->num_row_groups()) return;
  auto rg = h->metadata->RowGroup(i);
  const bool subset = columns != nullptr && n_columns >= 0;
  const int count = subset ? n_columns : rg->num_columns();
  for (int k = 0; k < count; k++) {
    const int c = subset ? columns[k] : k;
    if (c < 0 || c >= rg->num_columns()) continue;
    auto col = rg->ColumnChunk(c);
    int64_t chunk_start = col->data_page_offset();
    if (col->has_dictionary_page() && col->dictionary_page_offset() > 0) {
      chunk_start = std::min(chunk_start, col->dictionary_page_offset());
    }
    const int64_t len = col->total_compressed_size();
    if (len > 0) (void)posix_fadvise(h->fd, chunk_start, len, POSIX_FADV_WILLNEED);
  }
#else
  (void)h;
  (void)i;
  (void)columns;
  (void)n_columns;
#endif
}

}  // namespace

extern "C" {

const char* pstpu_last_error() { return g_last_error.c_str(); }

// Open a local Parquet file. use_threads!=0 enables Arrow-internal parallel
// column decode; buffer_size>0 enables read coalescing into buffers of that
// size (useful on high-latency storage; 0 = plain reads).
void* pstpu_open(const char* path, int use_threads, long long buffer_size) {
  auto maybe_file = arrow::io::ReadableFile::Open(path);
  if (!maybe_file.ok()) {
    set_error(maybe_file.status().ToString());
    return nullptr;
  }
  parquet::ReaderProperties props = parquet::default_reader_properties();
  if (buffer_size > 0) {
    props.enable_buffered_stream();
    props.set_buffer_size(buffer_size);
  }
  std::unique_ptr<parquet::ParquetFileReader> pq_reader;
  try {
    pq_reader = parquet::ParquetFileReader::Open(*maybe_file, props);
  } catch (const std::exception& e) {
    set_error(e.what());
    return nullptr;
  }
  auto handle = std::make_unique<FileHandle>();
  handle->fd = (*maybe_file)->file_descriptor();
  handle->metadata = pq_reader->metadata();
  parquet::ArrowReaderProperties arrow_props;
  arrow_props.set_use_threads(use_threads != 0);
#if PSTPU_ARROW_RESULT_APIS
  auto maybe_reader = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props);
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return nullptr;
  }
  handle->reader = std::move(*maybe_reader);
#else
  auto st = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props,
      &handle->reader);
  if (!st.ok()) {
    set_error(st.ToString());
    return nullptr;
  }
#endif
  return handle.release();
}

void pstpu_close(void* h) { delete static_cast<FileHandle*>(h); }

int pstpu_num_row_groups(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_row_groups();
}

long long pstpu_num_rows(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_rows();
}

long long pstpu_row_group_num_rows(void* h, int row_group) {
  auto* handle = static_cast<FileHandle*>(h);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  return handle->metadata->RowGroup(row_group)->num_rows();
}

// Number of leaf (physical) parquet columns.
int pstpu_num_columns(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_columns();
}

// Write the dot-joined path of leaf column `i` into buf; returns length or -1.
int pstpu_column_name(void* h, int i, char* buf, int buf_len) {
  auto* handle = static_cast<FileHandle*>(h);
  if (i < 0 || i >= handle->metadata->num_columns()) {
    set_error("column index out of range");
    return -1;
  }
  const std::string name =
      handle->metadata->schema()->Column(i)->path()->ToDotString();
  if (static_cast<int>(name.size()) + 1 > buf_len) {
    set_error("column name buffer too small");
    return -1;
  }
  std::memcpy(buf, name.c_str(), name.size() + 1);
  return static_cast<int>(name.size());
}

// Read one row group (optionally a subset of leaf columns) into an
// ArrowArrayStream. Decode runs on Arrow C++ threads; the stream is consumed
// zero-copy by pyarrow on the Python side.
int pstpu_read_row_group(void* h, int row_group, const int* columns,
                         int n_columns, struct ArrowArrayStream* out) {
  auto* handle = static_cast<FileHandle*>(h);
  std::lock_guard<std::mutex> lock(handle->mutex);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  advise_row_group(handle, row_group, columns, n_columns);
  std::shared_ptr<arrow::Table> table;
#if PSTPU_ARROW_RESULT_APIS
  arrow::Result<std::shared_ptr<arrow::Table>> maybe_table =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(row_group,
                                         std::vector<int>(columns, columns + n_columns))
          : handle->reader->ReadRowGroup(row_group);
  if (!maybe_table.ok()) {
    set_error(maybe_table.status().ToString());
    return -1;
  }
  table = *maybe_table;
#else
  arrow::Status read_st =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(
                row_group, std::vector<int>(columns, columns + n_columns), &table)
          : handle->reader->ReadRowGroup(row_group, &table);
  if (!read_st.ok()) {
    set_error(read_st.ToString());
    return -1;
  }
#endif
  // hand ownership of the decoded batches to the stream
  arrow::TableBatchReader batch_reader(*table);
  std::vector<std::shared_ptr<arrow::RecordBatch>> batches;
  while (true) {
    std::shared_ptr<arrow::RecordBatch> batch;
    auto st = batch_reader.ReadNext(&batch);
    if (!st.ok()) {
      set_error(st.ToString());
      return -1;
    }
    if (batch == nullptr) break;
    batches.push_back(std::move(batch));
  }
  auto maybe_reader =
      arrow::RecordBatchReader::Make(std::move(batches), table->schema());
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return -1;
  }
  auto st = arrow::ExportRecordBatchReader(*maybe_reader, out);
  if (!st.ok()) {
    set_error(st.ToString());
    return -1;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// First-party Parquet page scan — the zero-copy fast path.
//
// For UNCOMPRESSED, PLAIN-encoded, REQUIRED (max_def_level==0) fixed-width
// columns — the layout RawTensorCodec stores produce — a page's values region
// is byte-identical to the Arrow data buffer, so decode is a VIEW over the
// mmapped file instead of Arrow's assemble-and-copy. The only parsing needed
// is the page headers, which are thrift compact-protocol structs; the minimal
// reader below parses exactly the PageHeader/DataPageHeader fields the scan
// needs and generically skips everything else (statistics, crc, ...). No
// Arrow involvement: a parse error or any unsupported feature returns -1 and
// the caller falls back to the Arrow path above.
// ---------------------------------------------------------------------------

namespace {

// Deepest nested container/struct chain the generic skipper will follow. Real
// PageHeaders nest 2-3 levels; a crafted/corrupt header nesting deeper is
// hostile input that must set ok=false (-> Arrow fallback), NOT recurse until
// the C++ stack overflows and kills the process (PT502).
constexpr int kMaxSkipDepth = 32;

struct TReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint8_t byte() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (ok) {
      const uint8_t b = byte();
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { ok = false; break; }
    }
    return v;
  }
  int64_t zigzag() {
    const uint64_t v = varint();
    return int64_t(v >> 1) ^ -int64_t(v & 1);
  }
  void skip_bytes(uint64_t n) {
    if (uint64_t(end - p) < n) { ok = false; return; }
    p += n;
  }
  void skip_value(int type, int depth);  // forward (recursive for containers)
  void skip_struct(int depth) {
    if (depth > kMaxSkipDepth) { ok = false; return; }
    while (ok) {
      const uint8_t head = byte();
      if (head == 0) return;  // STOP
      if ((head & 0x0F) == 0) { ok = false; return; }
      if ((head >> 4) == 0) (void)zigzag();  // long-form field id
      skip_value(head & 0x0F, depth);
    }
  }
};

void TReader::skip_value(int type, int depth) {
  if (depth > kMaxSkipDepth) { ok = false; return; }
  switch (type) {
    case 1: case 2: return;             // bool true/false: value in the nibble
    case 3: skip_bytes(1); return;      // byte (raw, not varint)
    case 4: case 5: case 6: (void)zigzag(); return;  // i16/i32/i64
    case 7: skip_bytes(8); return;      // double
    case 8: skip_bytes(varint()); return;  // binary/string
    case 9: case 10: {                  // list/set
      const uint8_t head = byte();
      uint64_t n = head >> 4;
      if (n == 0xF) n = varint();
      const int elem = head & 0x0F;
      for (uint64_t i = 0; i < n && ok; i++) {
        if (elem == 1 || elem == 2) skip_bytes(1);  // bool element: one byte
        else skip_value(elem, depth + 1);
      }
      return;
    }
    case 11: {                          // map
      const uint64_t n = varint();
      if (n == 0) return;
      const uint8_t kv = byte();
      for (uint64_t i = 0; i < n && ok; i++) {
        skip_value(kv >> 4, depth + 1);
        skip_value(kv & 0x0F, depth + 1);
      }
      return;
    }
    case 12: skip_struct(depth + 1); return;  // struct
    default: ok = false; return;
  }
}

struct PageInfo {
  int32_t page_type = -1;          // 0=DATA_PAGE, 2=DICTIONARY_PAGE, 3=DATA_PAGE_V2
  int64_t uncompressed_size = -1;
  int64_t compressed_size = -1;
  int64_t num_values = -1;
  int32_t encoding = -1;           // DataPageHeader.encoding; 0=PLAIN
  int32_t def_level_encoding = -1; // DataPageHeader field 3; 3=RLE
  uint64_t header_len = 0;
};

// Parse one compact-protocol PageHeader starting at r.p; fills `info`.
bool parse_page_header(TReader& r, PageInfo* info) {
  const uint8_t* start = r.p;
  int16_t last_id = 0;
  while (r.ok) {
    const uint8_t head = r.byte();
    if (head == 0) break;  // STOP
    const int type = head & 0x0F;
    int16_t id;
    if ((head >> 4) == 0) {
      id = int16_t(r.zigzag());
    } else {
      id = int16_t(last_id + (head >> 4));
    }
    last_id = id;
    if (id == 1 && type == 5) {
      info->page_type = int32_t(r.zigzag());
    } else if (id == 2 && type == 5) {
      info->uncompressed_size = r.zigzag();
    } else if (id == 3 && type == 5) {
      info->compressed_size = r.zigzag();
    } else if (id == 5 && type == 12) {  // DataPageHeader
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->encoding = int32_t(r.zigzag());
        else if (iid == 3 && itype == 5) info->def_level_encoding = int32_t(r.zigzag());
        else r.skip_value(itype, 0);
      }
    } else {
      r.skip_value(type, 0);
    }
  }
  info->header_len = uint64_t(r.p - start);
  return r.ok;
}

}  // namespace

extern "C" {

// Scan an in-memory Parquet column chunk of UNCOMPRESSED PLAIN v1 data
// pages. out_offsets[i] = byte offset of page i's VALUES region within
// `chunk`; out_counts[i] = its value count; out_value_lens[i] = the byte
// length of that values region (page end minus values start) — the PER-PAGE
// bound the caller must check count*itemsize against before building a
// zero-copy view (a wrong null_count statistic or a short page would
// otherwise serve the next page's header bytes as tensor data).
// `has_def_levels` != 0 means the column is OPTIONAL (max_def_level == 1):
// each page then leads with a 4-byte-length-prefixed RLE definition-levels
// block which is skipped — the caller is responsible for proving the chunk
// has ZERO nulls (statistics), since a null would make value count <
// num_values. Returns the page count, or -1 on any parse error or
// unsupported feature (dictionary page, v2 page, compression, non-PLAIN
// encoding, non-RLE def levels) — the caller then uses the Arrow path.
long long pstpu_scan_plain_pages(const uint8_t* chunk, unsigned long long chunk_len,
                                 unsigned long long* out_offsets,
                                 long long* out_counts,
                                 unsigned long long* out_value_lens, int max_pages,
                                 int has_def_levels) {
  uint64_t pos = 0;
  int n = 0;
  while (pos < chunk_len) {
    TReader r{chunk + pos, chunk + chunk_len};
    PageInfo info;
    if (!parse_page_header(r, &info)) {
      set_error("page header parse failed");
      return -1;
    }
    if (info.page_type != 0 || info.encoding != 0 || info.num_values < 0 ||
        info.compressed_size < 0 ||
        info.compressed_size != info.uncompressed_size) {
      set_error("unsupported page (type/encoding/compression)");
      return -1;
    }
    uint64_t data_off = pos + info.header_len;
    const uint64_t page_end = pos + info.header_len + uint64_t(info.compressed_size);
    if (page_end > chunk_len) {
      set_error("page overruns chunk");
      return -1;
    }
    if (has_def_levels) {
      if (info.def_level_encoding != 3) {  // RLE; BIT_PACKED legacy unsupported
        set_error("unsupported definition-level encoding");
        return -1;
      }
      if (data_off + 4 > page_end) {
        set_error("def-levels length overruns page");
        return -1;
      }
      uint32_t def_len;
      std::memcpy(&def_len, chunk + data_off, 4);  // little-endian host
      data_off += 4 + def_len;
      if (data_off > page_end) {
        set_error("def-levels block overruns page");
        return -1;
      }
    }
    if (n >= max_pages) {
      set_error("more pages than max_pages");
      return -1;
    }
    out_offsets[n] = data_off;
    out_counts[n] = info.num_values;
    out_value_lens[n] = page_end - data_off;
    n++;
    pos = page_end;
  }
  return n;
}

int pstpu_abi_version() { return 2; }

}  // extern "C"
