// Native Parquet row-group reader kernel.
//
// The reference delegates all Parquet decode to pyarrow (Arrow C++) through
// Python (reference py_dict_reader_worker.py:254-258, arrow_reader_worker.py).
// This kernel is the framework's first-party native component (SURVEY.md
// §2.10): it opens a Parquet file, reads selected columns of one row group on
// C++ threads (no GIL), and hands the decoded Arrow table back to Python
// zero-copy through the Arrow C Data Interface (ArrowArrayStream).
//
// C ABI only — bound from Python with ctypes (no pybind11 in this image).
//
// Build: python -m petastorm_tpu.native.build  (links pyarrow's bundled
// libarrow/libparquet; C++20 for std::span in Arrow 25 headers).

#include <arrow/api.h>
#include <arrow/c/bridge.h>
#include <arrow/io/file.h>
#include <arrow/util/config.h>
#include <parquet/arrow/reader.h>
#include <parquet/file_reader.h>
#include <parquet/metadata.h>
#include <parquet/properties.h>

// parquet::arrow::FileReader factory/read APIs: Status + out-param in the
// long-stable wheels (<= 22), arrow::Result returns in the newer ones the
// original kernel targeted. Support both; a mismatch merely disables the
// kernel (build failure -> pure-pyarrow fallback), but matching here keeps
// the native path alive across the pyarrow versions the fleet actually runs.
#define PSTPU_ARROW_RESULT_APIS (ARROW_VERSION_MAJOR >= 23)

#include <fcntl.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

struct FileHandle {
  std::unique_ptr<parquet::arrow::FileReader> reader;
  std::shared_ptr<parquet::FileMetaData> metadata;
  int fd = -1;  // borrowed from the underlying ReadableFile (it owns closing)
  // parquet::arrow::FileReader is not thread-safe for concurrent reads of the
  // same handle; worker threads each own a handle, but guard anyway so a
  // shared handle degrades to serialized reads instead of corruption.
  std::mutex mutex;
};

// Best-effort page-cache readahead of the column chunks the caller is about
// to decode (the SELECTED columns only — advising the whole group would
// defeat column projection's IO savings on wide tables). A cold-cache decode
// otherwise interleaves demand-paged 64-128KB reads with CPU work; WILLNEED
// lets the kernel stream each chunk's compressed range ahead of the decoder.
// No next-group prefetch: the ventilator shuffles piece order, so "i+1 of
// this file" is almost never what gets read next.
void advise_row_group(FileHandle* h, int i, const int* columns, int n_columns) {
#if defined(POSIX_FADV_WILLNEED)
  if (h->fd < 0 || i < 0 || i >= h->metadata->num_row_groups()) return;
  auto rg = h->metadata->RowGroup(i);
  const bool subset = columns != nullptr && n_columns >= 0;
  const int count = subset ? n_columns : rg->num_columns();
  for (int k = 0; k < count; k++) {
    const int c = subset ? columns[k] : k;
    if (c < 0 || c >= rg->num_columns()) continue;
    auto col = rg->ColumnChunk(c);
    int64_t chunk_start = col->data_page_offset();
    if (col->has_dictionary_page() && col->dictionary_page_offset() > 0) {
      chunk_start = std::min(chunk_start, col->dictionary_page_offset());
    }
    const int64_t len = col->total_compressed_size();
    if (len > 0) (void)posix_fadvise(h->fd, chunk_start, len, POSIX_FADV_WILLNEED);
  }
#else
  (void)h;
  (void)i;
  (void)columns;
  (void)n_columns;
#endif
}

}  // namespace

extern "C" {

const char* pstpu_last_error() { return g_last_error.c_str(); }

// Open a local Parquet file. use_threads!=0 enables Arrow-internal parallel
// column decode; buffer_size>0 enables read coalescing into buffers of that
// size (useful on high-latency storage; 0 = plain reads).
void* pstpu_open(const char* path, int use_threads, long long buffer_size) {
  auto maybe_file = arrow::io::ReadableFile::Open(path);
  if (!maybe_file.ok()) {
    set_error(maybe_file.status().ToString());
    return nullptr;
  }
  parquet::ReaderProperties props = parquet::default_reader_properties();
  if (buffer_size > 0) {
    props.enable_buffered_stream();
    props.set_buffer_size(buffer_size);
  }
  std::unique_ptr<parquet::ParquetFileReader> pq_reader;
  try {
    pq_reader = parquet::ParquetFileReader::Open(*maybe_file, props);
  } catch (const std::exception& e) {
    set_error(e.what());
    return nullptr;
  }
  auto handle = std::make_unique<FileHandle>();
  handle->fd = (*maybe_file)->file_descriptor();
  handle->metadata = pq_reader->metadata();
  parquet::ArrowReaderProperties arrow_props;
  arrow_props.set_use_threads(use_threads != 0);
#if PSTPU_ARROW_RESULT_APIS
  auto maybe_reader = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props);
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return nullptr;
  }
  handle->reader = std::move(*maybe_reader);
#else
  auto st = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props,
      &handle->reader);
  if (!st.ok()) {
    set_error(st.ToString());
    return nullptr;
  }
#endif
  return handle.release();
}

void pstpu_close(void* h) { delete static_cast<FileHandle*>(h); }

int pstpu_num_row_groups(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_row_groups();
}

long long pstpu_num_rows(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_rows();
}

long long pstpu_row_group_num_rows(void* h, int row_group) {
  auto* handle = static_cast<FileHandle*>(h);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  return handle->metadata->RowGroup(row_group)->num_rows();
}

// Number of leaf (physical) parquet columns.
int pstpu_num_columns(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_columns();
}

// Write the dot-joined path of leaf column `i` into buf; returns length or -1.
int pstpu_column_name(void* h, int i, char* buf, int buf_len) {
  auto* handle = static_cast<FileHandle*>(h);
  if (i < 0 || i >= handle->metadata->num_columns()) {
    set_error("column index out of range");
    return -1;
  }
  const std::string name =
      handle->metadata->schema()->Column(i)->path()->ToDotString();
  if (static_cast<int>(name.size()) + 1 > buf_len) {
    set_error("column name buffer too small");
    return -1;
  }
  std::memcpy(buf, name.c_str(), name.size() + 1);
  return static_cast<int>(name.size());
}

// Read one row group (optionally a subset of leaf columns) into an
// ArrowArrayStream. Decode runs on Arrow C++ threads; the stream is consumed
// zero-copy by pyarrow on the Python side.
int pstpu_read_row_group(void* h, int row_group, const int* columns,
                         int n_columns, struct ArrowArrayStream* out) {
  auto* handle = static_cast<FileHandle*>(h);
  std::lock_guard<std::mutex> lock(handle->mutex);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  advise_row_group(handle, row_group, columns, n_columns);
  std::shared_ptr<arrow::Table> table;
#if PSTPU_ARROW_RESULT_APIS
  arrow::Result<std::shared_ptr<arrow::Table>> maybe_table =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(row_group,
                                         std::vector<int>(columns, columns + n_columns))
          : handle->reader->ReadRowGroup(row_group);
  if (!maybe_table.ok()) {
    set_error(maybe_table.status().ToString());
    return -1;
  }
  table = *maybe_table;
#else
  arrow::Status read_st =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(
                row_group, std::vector<int>(columns, columns + n_columns), &table)
          : handle->reader->ReadRowGroup(row_group, &table);
  if (!read_st.ok()) {
    set_error(read_st.ToString());
    return -1;
  }
#endif
  // hand ownership of the decoded batches to the stream
  arrow::TableBatchReader batch_reader(*table);
  std::vector<std::shared_ptr<arrow::RecordBatch>> batches;
  while (true) {
    std::shared_ptr<arrow::RecordBatch> batch;
    auto st = batch_reader.ReadNext(&batch);
    if (!st.ok()) {
      set_error(st.ToString());
      return -1;
    }
    if (batch == nullptr) break;
    batches.push_back(std::move(batch));
  }
  auto maybe_reader =
      arrow::RecordBatchReader::Make(std::move(batches), table->schema());
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return -1;
  }
  auto st = arrow::ExportRecordBatchReader(*maybe_reader, out);
  if (!st.ok()) {
    set_error(st.ToString());
    return -1;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// First-party Parquet page scan — the zero-copy fast path.
//
// For UNCOMPRESSED, PLAIN-encoded, REQUIRED (max_def_level==0) fixed-width
// columns — the layout RawTensorCodec stores produce — a page's values region
// is byte-identical to the Arrow data buffer, so decode is a VIEW over the
// mmapped file instead of Arrow's assemble-and-copy. The only parsing needed
// is the page headers, which are thrift compact-protocol structs; the minimal
// reader below parses exactly the PageHeader/DataPageHeader fields the scan
// needs and generically skips everything else (statistics, crc, ...). No
// Arrow involvement: a parse error or any unsupported feature returns -1 and
// the caller falls back to the Arrow path above.
// ---------------------------------------------------------------------------

namespace {

// Deepest nested container/struct chain the generic skipper will follow. Real
// PageHeaders nest 2-3 levels; a crafted/corrupt header nesting deeper is
// hostile input that must set ok=false (-> Arrow fallback), NOT recurse until
// the C++ stack overflows and kills the process (PT502).
constexpr int kMaxSkipDepth = 32;

struct TReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint8_t byte() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (ok) {
      const uint8_t b = byte();
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { ok = false; break; }
    }
    return v;
  }
  int64_t zigzag() {
    const uint64_t v = varint();
    return int64_t(v >> 1) ^ -int64_t(v & 1);
  }
  void skip_bytes(uint64_t n) {
    if (uint64_t(end - p) < n) { ok = false; return; }
    p += n;
  }
  void skip_value(int type, int depth);  // forward (recursive for containers)
  void skip_struct(int depth) {
    if (depth > kMaxSkipDepth) { ok = false; return; }
    while (ok) {
      const uint8_t head = byte();
      if (head == 0) return;  // STOP
      if ((head & 0x0F) == 0) { ok = false; return; }
      if ((head >> 4) == 0) (void)zigzag();  // long-form field id
      skip_value(head & 0x0F, depth);
    }
  }
};

void TReader::skip_value(int type, int depth) {
  if (depth > kMaxSkipDepth) { ok = false; return; }
  switch (type) {
    case 1: case 2: return;             // bool true/false: value in the nibble
    case 3: skip_bytes(1); return;      // byte (raw, not varint)
    case 4: case 5: case 6: (void)zigzag(); return;  // i16/i32/i64
    case 7: skip_bytes(8); return;      // double
    case 8: skip_bytes(varint()); return;  // binary/string
    case 9: case 10: {                  // list/set
      const uint8_t head = byte();
      uint64_t n = head >> 4;
      if (n == 0xF) n = varint();
      const int elem = head & 0x0F;
      for (uint64_t i = 0; i < n && ok; i++) {
        if (elem == 1 || elem == 2) skip_bytes(1);  // bool element: one byte
        else skip_value(elem, depth + 1);
      }
      return;
    }
    case 11: {                          // map
      const uint64_t n = varint();
      if (n == 0) return;
      const uint8_t kv = byte();
      for (uint64_t i = 0; i < n && ok; i++) {
        skip_value(kv >> 4, depth + 1);
        skip_value(kv & 0x0F, depth + 1);
      }
      return;
    }
    case 12: skip_struct(depth + 1); return;  // struct
    default: ok = false; return;
  }
}

struct PageInfo {
  int32_t page_type = -1;          // 0=DATA_PAGE, 2=DICTIONARY_PAGE, 3=DATA_PAGE_V2
  int64_t uncompressed_size = -1;
  int64_t compressed_size = -1;
  int64_t num_values = -1;
  int32_t encoding = -1;           // DataPageHeader(.V2).encoding; 0=PLAIN
  int32_t def_level_encoding = -1; // DataPageHeader field 3; 3=RLE
  int64_t dict_num_values = -1;    // DictionaryPageHeader field 1
  int32_t dict_encoding = -1;      // DictionaryPageHeader field 2; 0/2=PLAIN
  // DATA_PAGE_V2 only (DataPageHeaderV2, PageHeader field 8): the def/rep
  // level blocks are an UNCOMPRESSED prefix of the page body with explicit
  // byte lengths, and compression (field 7, default true) covers the data
  // region alone
  int64_t v2_num_nulls = -1;
  int64_t v2_def_len = -1;
  int64_t v2_rep_len = -1;
  int32_t v2_is_compressed = 1;
  uint64_t header_len = 0;
};

// Parse one compact-protocol PageHeader starting at r.p; fills `info`.
bool parse_page_header(TReader& r, PageInfo* info) {
  const uint8_t* start = r.p;
  int16_t last_id = 0;
  while (r.ok) {
    const uint8_t head = r.byte();
    if (head == 0) break;  // STOP
    const int type = head & 0x0F;
    int16_t id;
    if ((head >> 4) == 0) {
      id = int16_t(r.zigzag());
    } else {
      id = int16_t(last_id + (head >> 4));
    }
    last_id = id;
    if (id == 1 && type == 5) {
      info->page_type = int32_t(r.zigzag());
    } else if (id == 2 && type == 5) {
      info->uncompressed_size = r.zigzag();
    } else if (id == 3 && type == 5) {
      info->compressed_size = r.zigzag();
    } else if (id == 5 && type == 12) {  // DataPageHeader
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->encoding = int32_t(r.zigzag());
        else if (iid == 3 && itype == 5) info->def_level_encoding = int32_t(r.zigzag());
        else r.skip_value(itype, 0);
      }
    } else if (id == 7 && type == 12) {  // DictionaryPageHeader
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->dict_num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->dict_encoding = int32_t(r.zigzag());
        else r.skip_value(itype, 0);
      }
    } else if (id == 8 && type == 12) {  // DataPageHeaderV2
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->v2_num_nulls = r.zigzag();
        else if (iid == 4 && itype == 5) info->encoding = int32_t(r.zigzag());
        else if (iid == 5 && itype == 5) info->v2_def_len = r.zigzag();
        else if (iid == 6 && itype == 5) info->v2_rep_len = r.zigzag();
        else if (iid == 7 && (itype == 1 || itype == 2)) {
          // compact-protocol bool: the value IS the type nibble (1=true)
          info->v2_is_compressed = itype == 1 ? 1 : 0;
        } else r.skip_value(itype, 0);
      }
    } else {
      r.skip_value(type, 0);
    }
  }
  info->header_len = uint64_t(r.p - start);
  return r.ok;
}

}  // namespace

extern "C" {

// Scan an in-memory Parquet column chunk of UNCOMPRESSED PLAIN v1 data
// pages. out_offsets[i] = byte offset of page i's VALUES region within
// `chunk`; out_counts[i] = its value count; out_value_lens[i] = the byte
// length of that values region (page end minus values start) — the PER-PAGE
// bound the caller must check count*itemsize against before building a
// zero-copy view (a wrong null_count statistic or a short page would
// otherwise serve the next page's header bytes as tensor data).
// `has_def_levels` != 0 means the column is OPTIONAL (max_def_level == 1):
// each page then leads with a 4-byte-length-prefixed RLE definition-levels
// block which is skipped — the caller is responsible for proving the chunk
// has ZERO nulls (statistics), since a null would make value count <
// num_values. Returns the page count, or -1 on any parse error or
// unsupported feature (dictionary page, v2 page, compression, non-PLAIN
// encoding, non-RLE def levels) — the caller then uses the Arrow path.
long long pstpu_scan_plain_pages(const uint8_t* chunk, unsigned long long chunk_len,
                                 unsigned long long* out_offsets,
                                 long long* out_counts,
                                 unsigned long long* out_value_lens, int max_pages,
                                 int has_def_levels) {
  uint64_t pos = 0;
  int n = 0;
  while (pos < chunk_len) {
    TReader r{chunk + pos, chunk + chunk_len};
    PageInfo info;
    if (!parse_page_header(r, &info)) {
      set_error("page header parse failed");
      return -1;
    }
    if (info.page_type != 0 || info.encoding != 0 || info.num_values < 0 ||
        info.compressed_size < 0 ||
        info.compressed_size != info.uncompressed_size) {
      set_error("unsupported page (type/encoding/compression)");
      return -1;
    }
    uint64_t data_off = pos + info.header_len;
    const uint64_t page_end = pos + info.header_len + uint64_t(info.compressed_size);
    if (page_end > chunk_len) {
      set_error("page overruns chunk");
      return -1;
    }
    if (has_def_levels) {
      if (info.def_level_encoding != 3) {  // RLE; BIT_PACKED legacy unsupported
        set_error("unsupported definition-level encoding");
        return -1;
      }
      if (data_off + 4 > page_end) {
        set_error("def-levels length overruns page");
        return -1;
      }
      uint32_t def_len;
      std::memcpy(&def_len, chunk + data_off, 4);  // little-endian host
      data_off += 4 + def_len;
      if (data_off > page_end) {
        set_error("def-levels block overruns page");
        return -1;
      }
    }
    if (n >= max_pages) {
      set_error("more pages than max_pages");
      return -1;
    }
    out_offsets[n] = data_off;
    out_counts[n] = info.num_values;
    out_value_lens[n] = page_end - data_off;
    n++;
    pos = page_end;
  }
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused batch decode — read→decode→collate in ONE native call.
//
// The page scan above still hands each column back to Python (one ctypes call
// + Arrow view + collate per column per batch), and forfeits any dictionary-
// or RLE-encoded chunk to Arrow. pstpu_read_fused removes that tail: for a
// whole batch of columns it walks the page headers, decompresses SNAPPY pages
// first-party, decodes PLAIN *and* dictionary/RLE-bit-packed-hybrid values,
// and writes every column's rows into a caller-provided contiguous batch
// buffer — optionally an shm-ring slot the consumer maps — on C++ worker
// threads with the GIL released. Python touches the result exactly once per
// batch. Binary columns come in two fused flavors: uniform raw cells (npy
// payloads, headers stripped) and encoded images, which are decoded through
// the batched image-codec entry points passed in as function pointers so the
// whole read→decode→collate chain is one transition.
//
// Every parse is bounds-checked against the chunk/page/output regions and
// every failure is a per-column status code — the caller falls back to the
// Arrow path for that column and accounts the reason, never crashes.
// ---------------------------------------------------------------------------

namespace {

// First-party snappy *decompressor* (format_description.txt): varint preamble
// with the uncompressed length, then literal/copy elements. Decode-only — the
// write path never emits snappy from here. All reads are bounds-checked; any
// malformed element returns false and the column falls back to Arrow.
bool read_uvarint(const uint8_t** pp, const uint8_t* end, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  const uint8_t* p = *pp;
  while (p < end && shift <= 28) {  // 5 bytes max: 35 bits covers lengths/runs
    const uint8_t b = *p++;
    v |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *pp = p;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

bool snappy_uncompress(const uint8_t* src, uint64_t n, uint8_t* dst, uint64_t dst_len) {
  const uint8_t* p = src;
  const uint8_t* end = src + n;
  uint64_t expect = 0;
  if (!read_uvarint(&p, end, &expect) || expect != dst_len) return false;
  uint64_t d = 0;
  while (p < end) {
    const uint8_t tag = *p++;
    if ((tag & 3) == 0) {  // literal
      uint64_t len = tag >> 2;
      if (len >= 60) {
        const int extra = int(len) - 59;  // 1..4 little-endian length bytes
        if (end - p < extra) return false;
        len = 0;
        for (int i = 0; i < extra; i++) len |= uint64_t(p[i]) << (8 * i);
        p += extra;
      }
      len += 1;
      if (uint64_t(end - p) < len || dst_len - d < len) return false;
      std::memcpy(dst + d, p, len);
      p += len;
      d += len;
    } else {  // copy
      uint64_t len, off;
      if ((tag & 3) == 1) {
        if (p >= end) return false;
        len = ((tag >> 2) & 7) + 4;
        off = (uint64_t(tag & 0xE0) << 3) | *p++;
      } else if ((tag & 3) == 2) {
        if (end - p < 2) return false;
        len = (tag >> 2) + 1;
        off = uint64_t(p[0]) | (uint64_t(p[1]) << 8);
        p += 2;
      } else {
        if (end - p < 4) return false;
        len = (tag >> 2) + 1;
        off = uint64_t(p[0]) | (uint64_t(p[1]) << 8) |
              (uint64_t(p[2]) << 16) | (uint64_t(p[3]) << 24);
        p += 4;
      }
      if (off == 0 || off > d || dst_len - d < len) return false;
      const uint8_t* s = dst + (d - off);
      if (off >= len) {
        std::memcpy(dst + d, s, len);
      } else {
        for (uint64_t i = 0; i < len; i++) dst[d + i] = s[i];  // overlapping run
      }
      d += len;
    }
  }
  return d == expect;
}

// RLE / bit-packed hybrid decoder (<bit-width:1 byte> precedes this stream in
// dictionary-encoded data pages; def-level blocks carry the width implicitly).
// Emits exactly `count` values; trailing runs may overhang and are clamped.
// Zero-length runs/groups are rejected so progress is guaranteed.
bool decode_hybrid(const uint8_t* p, const uint8_t* end, int bw, int64_t count,
                   std::vector<uint32_t>* out) {
  if (bw < 0 || bw > 32 || count < 0) return false;
  out->clear();
  out->reserve(size_t(count));
  if (bw == 0) {
    out->assign(size_t(count), 0);
    return true;
  }
  const uint32_t mask = (bw == 32) ? 0xFFFFFFFFu : ((1u << bw) - 1);
  const int vbytes = (bw + 7) / 8;
  while (int64_t(out->size()) < count) {
    uint64_t header = 0;
    if (!read_uvarint(&p, end, &header)) return false;
    const uint64_t remaining = uint64_t(count) - out->size();
    if (header & 1) {  // bit-packed: (header>>1) groups of 8 values
      const uint64_t groups = header >> 1;
      if (groups == 0) return false;
      // division form: groups * bw would wrap for a corrupt huge group count,
      // sneaking a tiny nbytes past the bounds check below
      if (groups > uint64_t(end - p) / uint64_t(bw)) return false;
      const uint64_t nbytes = groups * uint64_t(bw);
      const uint64_t take = std::min<uint64_t>(groups * 8, remaining);
      uint64_t bit = 0;
      for (uint64_t i = 0; i < take; i++) {
        const uint64_t byte_idx = bit >> 3;
        uint64_t word = 0;
        const uint64_t avail = nbytes - byte_idx;
        std::memcpy(&word, p + byte_idx, avail < 8 ? size_t(avail) : size_t(8));
        out->push_back(uint32_t(word >> (bit & 7)) & mask);
        bit += uint64_t(bw);
      }
      p += nbytes;
    } else {  // RLE run
      const uint64_t run = header >> 1;
      if (run == 0) return false;
      if (end - p < vbytes) return false;
      uint32_t v = 0;
      for (int i = 0; i < vbytes; i++) v |= uint32_t(p[i]) << (8 * i);
      p += vbytes;
      out->insert(out->end(), size_t(std::min<uint64_t>(run, remaining)), v & mask);
    }
  }
  return true;
}

// per-column status codes — keep in sync with native/fused.py REASONS
enum {
  kColOk = 0,
  kColParse = 1,       // thrift/page/snappy parse failure
  kColPageType = 2,    // v2 page or unknown page type
  kColEncoding = 3,    // unsupported value/level encoding
  kColCompressed = 4,  // unsupported codec / size mismatch
  kColDefLevels = 5,   // def-levels block malformed
  kColPageCap = 6,     // more pages than max_pages
  kColRows = 7,        // decoded rows != expected_rows
  kColBounds = 8,      // values/output region bounds violation
  kColDict = 9,        // dictionary missing/invalid for an indexed page
  kColNonUniform = 10, // binary cells not uniform (raw mode)
  kColImgProbe = 11,
  kColImgDims = 12,
  kColImgDecode = 13,
  kColInternal = 14,   // unexpected native failure (e.g. allocation)
};

enum { kModeFixed = 0, kModeBinaryRaw = 1, kModeBinaryImg = 2 };
enum { kCodecUncompressed = 0, kCodecSnappy = 1 };

}  // namespace

// one column of the fused batch; mirrored field-for-field by the
// ctypes.Structure in native/fused.py (the batch-buffer ABI). File scope (not
// the anonymous namespace): the extern "C" entry point takes it by pointer.
struct FusedCol {
  const uint8_t* chunk;   // column chunk bytes (dictionary page first)
  uint64_t chunk_len;
  uint8_t* out;           // destination region inside the batch buffer
  uint64_t out_cap;       // bounds: the native side never writes past this
  uint8_t* aux_buf;       // small per-column side buffer (npy header copy)
  uint64_t aux_cap;
  int64_t expected_rows;
  int32_t mode;           // kMode*
  int32_t codec;          // kCodec*
  int32_t itemsize;       // kModeFixed: value byte width (FLBA width for FLBA)
  int32_t has_def_levels; // OPTIONAL chunk PROVEN null-free: skip RLE block
  int32_t strip_npy;      // kModeBinaryRaw: strip identical np.save headers
  int32_t img_w, img_h, img_c;  // kModeBinaryImg: expected decoded dims
  int32_t img_threads;
  int32_t status;         // out: kCol*
  uint64_t out_used;      // out: bytes written into `out`
  uint64_t aux0;          // out: raw: per-cell payload len; img: row bytes
  uint64_t aux1;          // out: raw: npy header len in aux_buf
};

namespace {

// batched image-codec entry points (image_codec.cpp), passed as pointers so
// this kernel needs no link-time dependency on the optional image library
using ImgProbeFn = long long (*)(long long, void**, unsigned long long*,
                                 int32_t*, int32_t, int32_t);
using ImgDecodeFn = long long (*)(long long, void**, unsigned long long*,
                                  void**, int32_t*, int, int32_t, int32_t);

struct PageRec {
  int32_t encoding;
  int64_t num_values;
  uint64_t body_off;   // page body offset within the chunk (possibly compressed)
  uint64_t body_len;   // compressed size
  uint64_t plain_len;  // uncompressed size
  bool is_dict;
  // DATA_PAGE_V2: rep+def levels are an uncompressed prefix of the body
  // (skipped by explicit length — num_nulls == 0 is checked at scan time, so
  // the all-ones def levels carry no information), and `v2_compressed`
  // scopes the chunk codec to the data region alone
  bool is_v2 = false;
  bool v2_compressed = false;
  uint64_t levels_len = 0;
};

int scan_fused_pages(const FusedCol& c, int max_pages, std::vector<PageRec>* pages) {
  uint64_t pos = 0;
  while (pos < c.chunk_len) {
    TReader r{c.chunk + pos, c.chunk + c.chunk_len};
    PageInfo info;
    if (!parse_page_header(r, &info)) return kColParse;
    if (info.compressed_size < 0 || info.uncompressed_size < 0) return kColParse;
    const uint64_t body_off = pos + info.header_len;
    const uint64_t page_end = body_off + uint64_t(info.compressed_size);
    if (page_end > c.chunk_len || page_end <= pos) return kColBounds;
    if (c.codec == kCodecUncompressed &&
        info.compressed_size != info.uncompressed_size) {
      return kColCompressed;
    }
    PageRec rec;
    rec.body_off = body_off;
    rec.body_len = uint64_t(info.compressed_size);
    rec.plain_len = uint64_t(info.uncompressed_size);
    if (info.page_type == 2) {  // dictionary page
      if (!pages->empty()) return kColParse;  // must precede the data pages
      if (info.dict_encoding != 0 && info.dict_encoding != 2) return kColEncoding;
      if (info.dict_num_values < 0) return kColParse;
      rec.encoding = 0;
      rec.num_values = info.dict_num_values;
      rec.is_dict = true;
    } else if (info.page_type == 0) {  // data page v1
      if (info.encoding != 0 && info.encoding != 2 && info.encoding != 8) {
        return kColEncoding;
      }
      if (c.has_def_levels && info.def_level_encoding != 3) return kColDefLevels;
      if (info.num_values < 0) return kColParse;
      rec.encoding = info.encoding;
      rec.num_values = info.num_values;
      rec.is_dict = false;
    } else if (info.page_type == 3) {  // data page v2
      if (info.encoding != 0 && info.encoding != 2 && info.encoding != 8) {
        return kColEncoding;
      }
      if (info.num_values < 0 || info.v2_def_len < 0 || info.v2_rep_len < 0) {
        return kColParse;
      }
      // v2 headers state num_nulls explicitly: only a proven-null-free page
      // fuses (the v1 path needs chunk statistics for the same proof), and a
      // flat column's rep levels are zero-length by construction
      if (info.v2_num_nulls != 0) return kColDefLevels;
      const uint64_t levels = uint64_t(info.v2_def_len) + uint64_t(info.v2_rep_len);
      if (levels > rec.body_len || levels > rec.plain_len) return kColDefLevels;
      rec.encoding = info.encoding;
      rec.num_values = info.num_values;
      rec.is_dict = false;
      rec.is_v2 = true;
      rec.v2_compressed = info.v2_is_compressed != 0;
      rec.levels_len = levels;
    } else {
      return kColPageType;  // index / unknown pages: Arrow path
    }
    if (int(pages->size()) >= max_pages) return kColPageCap;
    pages->push_back(rec);
    pos = page_end;
  }
  return kColOk;
}

// Uncompressed VALUES region of one page: decompresses into `scratch` when the
// chunk codec is snappy, then skips the RLE def-levels block when present.
// The returned pointer aliases either the chunk or `scratch` — the caller
// keeps `scratch` alive while the values are in use.
int page_values(const FusedCol& c, const PageRec& pg, std::vector<uint8_t>* scratch,
                const uint8_t** vals, uint64_t* vlen) {
  const uint8_t* base = c.chunk + pg.body_off;
  uint64_t len = pg.body_len;
  if (pg.is_v2) {
    // v2 layout: [rep levels][def levels] UNCOMPRESSED, then the data region
    // (compressed only when the header's is_compressed flag says so). The
    // level lengths were bounds-checked against body/plain size at scan time.
    const uint8_t* data = base + pg.levels_len;
    const uint64_t data_len = len - pg.levels_len;
    const uint64_t plain_data = pg.plain_len - pg.levels_len;
    if (pg.v2_compressed && c.codec == kCodecSnappy) {
      scratch->resize(size_t(plain_data));
      if (!snappy_uncompress(data, data_len, scratch->data(), plain_data)) {
        return kColParse;
      }
      *vals = scratch->data();
      *vlen = plain_data;
      return kColOk;
    }
    if (pg.v2_compressed && c.codec != kCodecUncompressed) return kColCompressed;
    *vals = data;
    *vlen = data_len;
    return kColOk;
  }
  if (c.codec == kCodecSnappy) {
    scratch->resize(size_t(pg.plain_len));
    if (!snappy_uncompress(base, len, scratch->data(), pg.plain_len)) {
      return kColParse;
    }
    base = scratch->data();
    len = pg.plain_len;
  } else if (c.codec != kCodecUncompressed) {
    return kColCompressed;
  }
  if (!pg.is_dict && c.has_def_levels) {
    if (len < 4) return kColDefLevels;
    uint32_t def_len = 0;
    std::memcpy(&def_len, base, 4);  // little-endian host
    if (uint64_t(def_len) + 4 > len) return kColDefLevels;
    base += 4 + def_len;
    len -= 4 + def_len;
  }
  *vals = base;
  *vlen = len;
  return kColOk;
}

int decode_fixed(FusedCol* c, const std::vector<PageRec>& pages) {
  const uint64_t w = uint64_t(c->itemsize);
  if (w == 0 || w > (64u << 20)) return kColParse;
  std::vector<uint8_t> dict_store;       // owns decompressed dictionary values
  const uint8_t* dict_vals = nullptr;
  uint64_t n_dict = 0;
  std::vector<uint8_t> scratch;
  std::vector<uint32_t> idx;
  uint64_t written = 0;
  int64_t rows = 0;
  for (const PageRec& pg : pages) {
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    if (pg.is_dict) {
      int rc = page_values(*c, pg, &dict_store, &vals, &vlen);
      if (rc != kColOk) return rc;
      // division form: num_values * w would wrap for a corrupt huge count
      if (uint64_t(pg.num_values) > vlen / w) return kColDict;
      if (c->codec == kCodecUncompressed) {
        // values point into the chunk; keep them there (no copy needed)
        dict_vals = vals;
      } else {
        dict_vals = dict_store.data();  // scratch persists for the column
      }
      n_dict = uint64_t(pg.num_values);
      continue;
    }
    int rc = page_values(*c, pg, &scratch, &vals, &vlen);
    if (rc != kColOk) return rc;
    if (uint64_t(pg.num_values) > c->out_cap / w) return kColBounds;
    const uint64_t need = uint64_t(pg.num_values) * w;
    if (written + need > c->out_cap) return kColBounds;
    if (pg.encoding == 0) {  // PLAIN: the values region IS the rows
      if (need > vlen) return kColBounds;
      std::memcpy(c->out + written, vals, need);
    } else {  // PLAIN_DICTIONARY / RLE_DICTIONARY indices
      if (dict_vals == nullptr) return kColDict;
      if (vlen < 1) return kColParse;
      const int bw = vals[0];
      if (!decode_hybrid(vals + 1, vals + vlen, bw, pg.num_values, &idx)) {
        return kColParse;
      }
      uint8_t* dst = c->out + written;
      for (int64_t i = 0; i < pg.num_values; i++) {
        const uint32_t k = idx[size_t(i)];
        if (k >= n_dict) return kColDict;
        std::memcpy(dst + uint64_t(i) * w, dict_vals + uint64_t(k) * w, w);
      }
    }
    written += need;
    rows += pg.num_values;
  }
  if (rows != c->expected_rows) return kColRows;
  c->out_used = written;
  return kColOk;
}

// Collect the byte-array cells of a BYTE_ARRAY chunk (PLAIN length-prefixed
// values, or dictionary indices into length-prefixed dictionary entries).
// Cell pointers alias the chunk or the scratch vectors pushed onto
// `scratches` — which the caller must keep alive while the cells are in use.
int collect_cells(const FusedCol& c, const std::vector<PageRec>& pages,
                  std::vector<std::pair<const uint8_t*, uint64_t>>* cells,
                  std::vector<std::vector<uint8_t>>* scratches) {
  std::vector<std::pair<const uint8_t*, uint64_t>> dict_entries;
  std::vector<uint32_t> idx;
  for (const PageRec& pg : pages) {
    scratches->emplace_back();
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    int rc = page_values(c, pg, &scratches->back(), &vals, &vlen);
    if (rc != kColOk) return rc;
    if (pg.is_dict) {
      dict_entries.clear();
      dict_entries.reserve(size_t(pg.num_values));
      uint64_t off = 0;
      for (int64_t i = 0; i < pg.num_values; i++) {
        if (off + 4 > vlen) return kColDict;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColDict;
        dict_entries.emplace_back(vals + off, uint64_t(n));
        off += n;
      }
      continue;
    }
    if (pg.encoding == 0) {  // PLAIN: <u32 len><bytes> per value
      uint64_t off = 0;
      for (int64_t i = 0; i < pg.num_values; i++) {
        if (off + 4 > vlen) return kColBounds;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColBounds;
        cells->emplace_back(vals + off, uint64_t(n));
        off += n;
      }
    } else {  // dictionary indices
      if (dict_entries.empty() && pg.num_values > 0) return kColDict;
      if (vlen < 1) return kColParse;
      if (!decode_hybrid(vals + 1, vals + vlen, vals[0], pg.num_values, &idx)) {
        return kColParse;
      }
      for (int64_t i = 0; i < pg.num_values; i++) {
        const uint32_t k = idx[size_t(i)];
        if (k >= dict_entries.size()) return kColDict;
        cells->push_back(dict_entries[size_t(k)]);
      }
    }
  }
  if (int64_t(cells->size()) != c.expected_rows) return kColRows;
  return kColOk;
}

// np.save header span of one cell: magic + version + 2/4-byte header length.
// Returns 0 when the cell is not a standard v1/v2 npy payload.
uint64_t npy_header_len(const uint8_t* p, uint64_t n) {
  static const uint8_t kMagic[6] = {0x93, 'N', 'U', 'M', 'P', 'Y'};
  if (n < 12 || std::memcmp(p, kMagic, 6) != 0) return 0;
  uint64_t data_off;
  if (p[6] == 1) {
    data_off = 10 + (uint64_t(p[8]) | (uint64_t(p[9]) << 8));
  } else if (p[6] == 2) {
    uint32_t hl = 0;
    std::memcpy(&hl, p + 8, 4);
    data_off = 12 + uint64_t(hl);
  } else {
    return 0;
  }
  return data_off <= n ? data_off : 0;
}

int decode_binary_raw(FusedCol* c, const std::vector<PageRec>& pages) {
  std::vector<std::pair<const uint8_t*, uint64_t>> cells;
  std::vector<std::vector<uint8_t>> scratches;
  int rc = collect_cells(*c, pages, &cells, &scratches);
  if (rc != kColOk) return rc;
  if (cells.empty()) return kColRows;
  const uint64_t cell_len = cells[0].second;
  uint64_t prefix = 0;
  if (c->strip_npy) {
    prefix = npy_header_len(cells[0].first, cell_len);
    if (prefix == 0) return kColNonUniform;
    if (prefix > c->aux_cap || c->aux_buf == nullptr) return kColNonUniform;
    std::memcpy(c->aux_buf, cells[0].first, prefix);
    c->aux1 = prefix;
  }
  const uint64_t payload = cell_len - prefix;
  uint64_t written = 0;
  for (const auto& cell : cells) {
    if (cell.second != cell_len) return kColNonUniform;
    if (prefix != 0 && std::memcmp(cell.first, cells[0].first, prefix) != 0) {
      return kColNonUniform;  // mixed shapes/dtypes within the chunk
    }
    if (written + payload > c->out_cap) return kColBounds;
    std::memcpy(c->out + written, cell.first + prefix, payload);
    written += payload;
  }
  c->aux0 = payload;
  c->out_used = written;
  return kColOk;
}

int decode_binary_img(FusedCol* c, const std::vector<PageRec>& pages,
                      ImgProbeFn probe, ImgDecodeFn decode) {
  if (probe == nullptr || decode == nullptr) return kColImgProbe;
  std::vector<std::pair<const uint8_t*, uint64_t>> cells;
  std::vector<std::vector<uint8_t>> scratches;
  int rc = collect_cells(*c, pages, &cells, &scratches);
  if (rc != kColOk) return rc;
  const long long n = (long long)cells.size();
  if (n == 0) return kColRows;
  const size_t un = size_t(n);
  std::vector<void*> ptrs(un);
  std::vector<unsigned long long> lens(un);
  for (size_t i = 0; i < un; i++) {
    ptrs[i] = const_cast<uint8_t*>(cells[i].first);
    lens[i] = cells[i].second;
  }
  std::vector<int32_t> infos(un * 4);
  if (probe(n, ptrs.data(), lens.data(), infos.data(), 0, 0) != -1) {
    return kColImgProbe;
  }
  const uint64_t row_bytes =
      uint64_t(c->img_h) * uint64_t(c->img_w) * uint64_t(c->img_c);
  for (long long i = 0; i < n; i++) {
    const int32_t* info = &infos[size_t(i) * 4];  // (w, h, c, depth)
    if (info[0] != c->img_w || info[1] != c->img_h || info[2] != c->img_c ||
        info[3] != 8) {
      return kColImgDims;
    }
  }
  // division form: n * row_bytes would wrap for corrupt huge dimensions,
  // sneaking a tiny product past the capacity check (PT903)
  if (row_bytes == 0 || uint64_t(n) > c->out_cap / row_bytes) return kColBounds;
  std::vector<void*> outs(un);
  for (size_t i = 0; i < un; i++) outs[i] = c->out + uint64_t(i) * row_bytes;
  const int threads = c->img_threads > 0 ? c->img_threads : 1;
  if (decode(n, ptrs.data(), lens.data(), outs.data(), infos.data(), threads,
             0, 0) != -1) {
    return kColImgDecode;
  }
  c->aux0 = row_bytes;
  c->out_used = uint64_t(n) * row_bytes;
  return kColOk;
}

void decode_fused_column(FusedCol* c, int max_pages, ImgProbeFn probe,
                         ImgDecodeFn decode) {
  try {
    if (c->chunk == nullptr || c->out == nullptr || c->expected_rows < 0) {
      c->status = kColInternal;
      return;
    }
    std::vector<PageRec> pages;
    int rc = scan_fused_pages(*c, max_pages, &pages);
    if (rc == kColOk) {
      switch (c->mode) {
        case kModeFixed: rc = decode_fixed(c, pages); break;
        case kModeBinaryRaw: rc = decode_binary_raw(c, pages); break;
        case kModeBinaryImg: rc = decode_binary_img(c, pages, probe, decode); break;
        default: rc = kColInternal;
      }
    }
    c->status = rc;
  } catch (...) {  // bad_alloc etc.: fail the column, never the process
    c->status = kColInternal;
  }
}

}  // namespace

extern "C" {

// Decode a whole batch of column chunks into their preallocated regions of
// one contiguous batch buffer. Runs on up to `n_threads` C++ threads (the
// calling thread participates); the caller holds no GIL (ctypes releases it),
// so this is the single Python<->C transition of the batch. Returns the
// number of columns whose status != OK (callers re-read those via Arrow), or
// -1 on invalid arguments.
long long pstpu_read_fused(struct FusedCol* cols, int n_cols, int n_threads,
                           int max_pages, void* img_probe_fn, void* img_decode_fn) {
  if (cols == nullptr || n_cols < 0 || max_pages < 1) {
    set_error("pstpu_read_fused: invalid arguments");
    return -1;
  }
  const ImgProbeFn probe = reinterpret_cast<ImgProbeFn>(img_probe_fn);
  const ImgDecodeFn decode = reinterpret_cast<ImgDecodeFn>(img_decode_fn);
  std::atomic<int> next{0};
  auto run = [&]() {
    while (true) {
      const int i = next.fetch_add(1);
      if (i >= n_cols) return;
      decode_fused_column(&cols[i], max_pages, probe, decode);
    }
  };
  int fanout = n_threads;
  if (fanout < 1) fanout = 1;
  if (fanout > n_cols) fanout = n_cols;
  std::vector<std::thread> pool;
  for (int t = 1; t < fanout; t++) pool.emplace_back(run);
  run();
  for (auto& th : pool) th.join();
  long long failed = 0;
  for (int i = 0; i < n_cols; i++) {
    if (cols[i].status != kColOk) failed++;
  }
  return failed;
}

int pstpu_abi_version() { return 3; }

}  // extern "C"
