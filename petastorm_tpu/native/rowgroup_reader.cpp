// Native Parquet row-group reader kernel.
//
// The reference delegates all Parquet decode to pyarrow (Arrow C++) through
// Python (reference py_dict_reader_worker.py:254-258, arrow_reader_worker.py).
// This kernel is the framework's first-party native component (SURVEY.md
// §2.10): it opens a Parquet file, reads selected columns of one row group on
// C++ threads (no GIL), and hands the decoded Arrow table back to Python
// zero-copy through the Arrow C Data Interface (ArrowArrayStream).
//
// C ABI only — bound from Python with ctypes (no pybind11 in this image).
//
// Build: python -m petastorm_tpu.native.build  (links pyarrow's bundled
// libarrow/libparquet; C++20 for std::span in Arrow 25 headers).

#include <arrow/api.h>
#include <arrow/c/bridge.h>
#include <arrow/io/file.h>
#include <arrow/util/config.h>
#include <parquet/arrow/reader.h>
#include <parquet/file_reader.h>
#include <parquet/metadata.h>
#include <parquet/properties.h>

// parquet::arrow::FileReader factory/read APIs: Status + out-param in the
// long-stable wheels (<= 22), arrow::Result returns in the newer ones the
// original kernel targeted. Support both; a mismatch merely disables the
// kernel (build failure -> pure-pyarrow fallback), but matching here keeps
// the native path alive across the pyarrow versions the fleet actually runs.
#define PSTPU_ARROW_RESULT_APIS (ARROW_VERSION_MAJOR >= 23)

#include <fcntl.h>

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

namespace {

thread_local std::string g_last_error;

void set_error(const std::string& msg) { g_last_error = msg; }

struct FileHandle {
  std::unique_ptr<parquet::arrow::FileReader> reader;
  std::shared_ptr<parquet::FileMetaData> metadata;
  int fd = -1;  // borrowed from the underlying ReadableFile (it owns closing)
  // parquet::arrow::FileReader is not thread-safe for concurrent reads of the
  // same handle; worker threads each own a handle, but guard anyway so a
  // shared handle degrades to serialized reads instead of corruption.
  std::mutex mutex;
};

// Best-effort page-cache readahead of the column chunks the caller is about
// to decode (the SELECTED columns only — advising the whole group would
// defeat column projection's IO savings on wide tables). A cold-cache decode
// otherwise interleaves demand-paged 64-128KB reads with CPU work; WILLNEED
// lets the kernel stream each chunk's compressed range ahead of the decoder.
// No next-group prefetch: the ventilator shuffles piece order, so "i+1 of
// this file" is almost never what gets read next.
void advise_row_group(FileHandle* h, int i, const int* columns, int n_columns) {
#if defined(POSIX_FADV_WILLNEED)
  if (h->fd < 0 || i < 0 || i >= h->metadata->num_row_groups()) return;
  auto rg = h->metadata->RowGroup(i);
  const bool subset = columns != nullptr && n_columns >= 0;
  const int count = subset ? n_columns : rg->num_columns();
  for (int k = 0; k < count; k++) {
    const int c = subset ? columns[k] : k;
    if (c < 0 || c >= rg->num_columns()) continue;
    auto col = rg->ColumnChunk(c);
    int64_t chunk_start = col->data_page_offset();
    if (col->has_dictionary_page() && col->dictionary_page_offset() > 0) {
      chunk_start = std::min(chunk_start, col->dictionary_page_offset());
    }
    const int64_t len = col->total_compressed_size();
    if (len > 0) (void)posix_fadvise(h->fd, chunk_start, len, POSIX_FADV_WILLNEED);
  }
#else
  (void)h;
  (void)i;
  (void)columns;
  (void)n_columns;
#endif
}

}  // namespace

extern "C" {

const char* pstpu_last_error() { return g_last_error.c_str(); }

// Open a local Parquet file. use_threads!=0 enables Arrow-internal parallel
// column decode; buffer_size>0 enables read coalescing into buffers of that
// size (useful on high-latency storage; 0 = plain reads).
void* pstpu_open(const char* path, int use_threads, long long buffer_size) {
  auto maybe_file = arrow::io::ReadableFile::Open(path);
  if (!maybe_file.ok()) {
    set_error(maybe_file.status().ToString());
    return nullptr;
  }
  parquet::ReaderProperties props = parquet::default_reader_properties();
  if (buffer_size > 0) {
    props.enable_buffered_stream();
    props.set_buffer_size(buffer_size);
  }
  std::unique_ptr<parquet::ParquetFileReader> pq_reader;
  try {
    pq_reader = parquet::ParquetFileReader::Open(*maybe_file, props);
  } catch (const std::exception& e) {
    set_error(e.what());
    return nullptr;
  }
  auto handle = std::make_unique<FileHandle>();
  handle->fd = (*maybe_file)->file_descriptor();
  handle->metadata = pq_reader->metadata();
  parquet::ArrowReaderProperties arrow_props;
  arrow_props.set_use_threads(use_threads != 0);
#if PSTPU_ARROW_RESULT_APIS
  auto maybe_reader = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props);
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return nullptr;
  }
  handle->reader = std::move(*maybe_reader);
#else
  auto st = parquet::arrow::FileReader::Make(
      arrow::default_memory_pool(), std::move(pq_reader), arrow_props,
      &handle->reader);
  if (!st.ok()) {
    set_error(st.ToString());
    return nullptr;
  }
#endif
  return handle.release();
}

void pstpu_close(void* h) { delete static_cast<FileHandle*>(h); }

int pstpu_num_row_groups(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_row_groups();
}

long long pstpu_num_rows(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_rows();
}

long long pstpu_row_group_num_rows(void* h, int row_group) {
  auto* handle = static_cast<FileHandle*>(h);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  return handle->metadata->RowGroup(row_group)->num_rows();
}

// Number of leaf (physical) parquet columns.
int pstpu_num_columns(void* h) {
  return static_cast<FileHandle*>(h)->metadata->num_columns();
}

// Write the dot-joined path of leaf column `i` into buf; returns length or -1.
int pstpu_column_name(void* h, int i, char* buf, int buf_len) {
  auto* handle = static_cast<FileHandle*>(h);
  if (i < 0 || i >= handle->metadata->num_columns()) {
    set_error("column index out of range");
    return -1;
  }
  const std::string name =
      handle->metadata->schema()->Column(i)->path()->ToDotString();
  if (static_cast<int>(name.size()) + 1 > buf_len) {
    set_error("column name buffer too small");
    return -1;
  }
  std::memcpy(buf, name.c_str(), name.size() + 1);
  return static_cast<int>(name.size());
}

// Read one row group (optionally a subset of leaf columns) into an
// ArrowArrayStream. Decode runs on Arrow C++ threads; the stream is consumed
// zero-copy by pyarrow on the Python side.
int pstpu_read_row_group(void* h, int row_group, const int* columns,
                         int n_columns, struct ArrowArrayStream* out) {
  auto* handle = static_cast<FileHandle*>(h);
  std::lock_guard<std::mutex> lock(handle->mutex);
  if (row_group < 0 || row_group >= handle->metadata->num_row_groups()) {
    set_error("row group index out of range");
    return -1;
  }
  advise_row_group(handle, row_group, columns, n_columns);
  std::shared_ptr<arrow::Table> table;
#if PSTPU_ARROW_RESULT_APIS
  arrow::Result<std::shared_ptr<arrow::Table>> maybe_table =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(row_group,
                                         std::vector<int>(columns, columns + n_columns))
          : handle->reader->ReadRowGroup(row_group);
  if (!maybe_table.ok()) {
    set_error(maybe_table.status().ToString());
    return -1;
  }
  table = *maybe_table;
#else
  arrow::Status read_st =
      (columns != nullptr && n_columns >= 0)
          ? handle->reader->ReadRowGroup(
                row_group, std::vector<int>(columns, columns + n_columns), &table)
          : handle->reader->ReadRowGroup(row_group, &table);
  if (!read_st.ok()) {
    set_error(read_st.ToString());
    return -1;
  }
#endif
  // hand ownership of the decoded batches to the stream
  arrow::TableBatchReader batch_reader(*table);
  std::vector<std::shared_ptr<arrow::RecordBatch>> batches;
  while (true) {
    std::shared_ptr<arrow::RecordBatch> batch;
    auto st = batch_reader.ReadNext(&batch);
    if (!st.ok()) {
      set_error(st.ToString());
      return -1;
    }
    if (batch == nullptr) break;
    batches.push_back(std::move(batch));
  }
  auto maybe_reader =
      arrow::RecordBatchReader::Make(std::move(batches), table->schema());
  if (!maybe_reader.ok()) {
    set_error(maybe_reader.status().ToString());
    return -1;
  }
  auto st = arrow::ExportRecordBatchReader(*maybe_reader, out);
  if (!st.ok()) {
    set_error(st.ToString());
    return -1;
  }
  return 0;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// First-party Parquet page scan — the zero-copy fast path.
//
// For UNCOMPRESSED, PLAIN-encoded, REQUIRED (max_def_level==0) fixed-width
// columns — the layout RawTensorCodec stores produce — a page's values region
// is byte-identical to the Arrow data buffer, so decode is a VIEW over the
// mmapped file instead of Arrow's assemble-and-copy. The only parsing needed
// is the page headers, which are thrift compact-protocol structs; the minimal
// reader below parses exactly the PageHeader/DataPageHeader fields the scan
// needs and generically skips everything else (statistics, crc, ...). No
// Arrow involvement: a parse error or any unsupported feature returns -1 and
// the caller falls back to the Arrow path above.
// ---------------------------------------------------------------------------

namespace {

// Deepest nested container/struct chain the generic skipper will follow. Real
// PageHeaders nest 2-3 levels; a crafted/corrupt header nesting deeper is
// hostile input that must set ok=false (-> Arrow fallback), NOT recurse until
// the C++ stack overflows and kills the process (PT502).
constexpr int kMaxSkipDepth = 32;

struct TReader {
  const uint8_t* p;
  const uint8_t* end;
  bool ok = true;

  uint8_t byte() {
    if (p >= end) { ok = false; return 0; }
    return *p++;
  }
  uint64_t varint() {
    uint64_t v = 0;
    int shift = 0;
    while (ok) {
      const uint8_t b = byte();
      v |= uint64_t(b & 0x7F) << shift;
      if (!(b & 0x80)) break;
      shift += 7;
      if (shift > 63) { ok = false; break; }
    }
    return v;
  }
  int64_t zigzag() {
    const uint64_t v = varint();
    return int64_t(v >> 1) ^ -int64_t(v & 1);
  }
  void skip_bytes(uint64_t n) {
    if (uint64_t(end - p) < n) { ok = false; return; }
    p += n;
  }
  void skip_value(int type, int depth);  // forward (recursive for containers)
  void skip_struct(int depth) {
    if (depth > kMaxSkipDepth) { ok = false; return; }
    while (ok) {
      const uint8_t head = byte();
      if (head == 0) return;  // STOP
      if ((head & 0x0F) == 0) { ok = false; return; }
      if ((head >> 4) == 0) (void)zigzag();  // long-form field id
      skip_value(head & 0x0F, depth);
    }
  }
};

void TReader::skip_value(int type, int depth) {
  if (depth > kMaxSkipDepth) { ok = false; return; }
  switch (type) {
    case 1: case 2: return;             // bool true/false: value in the nibble
    case 3: skip_bytes(1); return;      // byte (raw, not varint)
    case 4: case 5: case 6: (void)zigzag(); return;  // i16/i32/i64
    case 7: skip_bytes(8); return;      // double
    case 8: skip_bytes(varint()); return;  // binary/string
    case 9: case 10: {                  // list/set
      const uint8_t head = byte();
      uint64_t n = head >> 4;
      if (n == 0xF) n = varint();
      const int elem = head & 0x0F;
      for (uint64_t i = 0; i < n && ok; i++) {
        if (elem == 1 || elem == 2) skip_bytes(1);  // bool element: one byte
        else skip_value(elem, depth + 1);
      }
      return;
    }
    case 11: {                          // map
      const uint64_t n = varint();
      if (n == 0) return;
      const uint8_t kv = byte();
      for (uint64_t i = 0; i < n && ok; i++) {
        skip_value(kv >> 4, depth + 1);
        skip_value(kv & 0x0F, depth + 1);
      }
      return;
    }
    case 12: skip_struct(depth + 1); return;  // struct
    default: ok = false; return;
  }
}

struct PageInfo {
  int32_t page_type = -1;          // 0=DATA_PAGE, 2=DICTIONARY_PAGE, 3=DATA_PAGE_V2
  int64_t uncompressed_size = -1;
  int64_t compressed_size = -1;
  int64_t num_values = -1;
  int32_t encoding = -1;           // DataPageHeader(.V2).encoding; 0=PLAIN
  int32_t def_level_encoding = -1; // DataPageHeader field 3; 3=RLE
  int64_t dict_num_values = -1;    // DictionaryPageHeader field 1
  int32_t dict_encoding = -1;      // DictionaryPageHeader field 2; 0/2=PLAIN
  // DATA_PAGE_V2 only (DataPageHeaderV2, PageHeader field 8): the def/rep
  // level blocks are an UNCOMPRESSED prefix of the page body with explicit
  // byte lengths, and compression (field 7, default true) covers the data
  // region alone
  int64_t v2_num_nulls = -1;
  int64_t v2_def_len = -1;
  int64_t v2_rep_len = -1;
  int32_t v2_is_compressed = 1;
  // page-header Statistics (DataPageHeader field 5 / DataPageHeaderV2 field
  // 8): min_value/max_value point INTO the page-header bytes; -1 len = absent
  const uint8_t* stat_min = nullptr;
  const uint8_t* stat_max = nullptr;
  int64_t stat_min_len = -1;
  int64_t stat_max_len = -1;
  int64_t stat_null_count = -1;
  uint64_t header_len = 0;
};

// Statistics struct fields: 3=null_count(i64), 5=max_value, 6=min_value
// (the untyped legacy min/max at ids 1/2 are deliberately ignored)
void parse_statistics(TReader& r, PageInfo* info) {
  int16_t inner_last = 0;
  while (r.ok) {
    const uint8_t ih = r.byte();
    if (ih == 0) break;
    const int itype = ih & 0x0F;
    int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                 : int16_t(inner_last + (ih >> 4));
    inner_last = iid;
    if (iid == 3 && itype == 6) {
      info->stat_null_count = r.zigzag();
    } else if ((iid == 5 || iid == 6) && itype == 8) {
      const uint64_t len = r.varint();
      if (!r.ok || uint64_t(r.end - r.p) < len) { r.ok = false; return; }
      if (iid == 5) { info->stat_max = r.p; info->stat_max_len = int64_t(len); }
      else { info->stat_min = r.p; info->stat_min_len = int64_t(len); }
      r.skip_bytes(len);
    } else {
      r.skip_value(itype, 0);
    }
  }
}

// Parse one compact-protocol PageHeader starting at r.p; fills `info`.
bool parse_page_header(TReader& r, PageInfo* info) {
  const uint8_t* start = r.p;
  int16_t last_id = 0;
  while (r.ok) {
    const uint8_t head = r.byte();
    if (head == 0) break;  // STOP
    const int type = head & 0x0F;
    int16_t id;
    if ((head >> 4) == 0) {
      id = int16_t(r.zigzag());
    } else {
      id = int16_t(last_id + (head >> 4));
    }
    last_id = id;
    if (id == 1 && type == 5) {
      info->page_type = int32_t(r.zigzag());
    } else if (id == 2 && type == 5) {
      info->uncompressed_size = r.zigzag();
    } else if (id == 3 && type == 5) {
      info->compressed_size = r.zigzag();
    } else if (id == 5 && type == 12) {  // DataPageHeader
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->encoding = int32_t(r.zigzag());
        else if (iid == 3 && itype == 5) info->def_level_encoding = int32_t(r.zigzag());
        else if (iid == 5 && itype == 12) parse_statistics(r, info);
        else r.skip_value(itype, 0);
      }
    } else if (id == 7 && type == 12) {  // DictionaryPageHeader
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->dict_num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->dict_encoding = int32_t(r.zigzag());
        else r.skip_value(itype, 0);
      }
    } else if (id == 8 && type == 12) {  // DataPageHeaderV2
      int16_t inner_last = 0;
      while (r.ok) {
        const uint8_t ih = r.byte();
        if (ih == 0) break;
        const int itype = ih & 0x0F;
        int16_t iid = (ih >> 4) == 0 ? int16_t(r.zigzag())
                                     : int16_t(inner_last + (ih >> 4));
        inner_last = iid;
        if (iid == 1 && itype == 5) info->num_values = r.zigzag();
        else if (iid == 2 && itype == 5) info->v2_num_nulls = r.zigzag();
        else if (iid == 4 && itype == 5) info->encoding = int32_t(r.zigzag());
        else if (iid == 5 && itype == 5) info->v2_def_len = r.zigzag();
        else if (iid == 6 && itype == 5) info->v2_rep_len = r.zigzag();
        else if (iid == 7 && (itype == 1 || itype == 2)) {
          // compact-protocol bool: the value IS the type nibble (1=true)
          info->v2_is_compressed = itype == 1 ? 1 : 0;
        } else if (iid == 8 && itype == 12) parse_statistics(r, info);
        else r.skip_value(itype, 0);
      }
    } else {
      r.skip_value(type, 0);
    }
  }
  info->header_len = uint64_t(r.p - start);
  return r.ok;
}

}  // namespace

extern "C" {

// Scan an in-memory Parquet column chunk of UNCOMPRESSED PLAIN v1 data
// pages. out_offsets[i] = byte offset of page i's VALUES region within
// `chunk`; out_counts[i] = its value count; out_value_lens[i] = the byte
// length of that values region (page end minus values start) — the PER-PAGE
// bound the caller must check count*itemsize against before building a
// zero-copy view (a wrong null_count statistic or a short page would
// otherwise serve the next page's header bytes as tensor data).
// `has_def_levels` != 0 means the column is OPTIONAL (max_def_level == 1):
// each page then leads with a 4-byte-length-prefixed RLE definition-levels
// block which is skipped — the caller is responsible for proving the chunk
// has ZERO nulls (statistics), since a null would make value count <
// num_values. Returns the page count, or -1 on any parse error or
// unsupported feature (dictionary page, v2 page, compression, non-PLAIN
// encoding, non-RLE def levels) — the caller then uses the Arrow path.
long long pstpu_scan_plain_pages(const uint8_t* chunk, unsigned long long chunk_len,
                                 unsigned long long* out_offsets,
                                 long long* out_counts,
                                 unsigned long long* out_value_lens, int max_pages,
                                 int has_def_levels) {
  uint64_t pos = 0;
  int n = 0;
  while (pos < chunk_len) {
    TReader r{chunk + pos, chunk + chunk_len};
    PageInfo info;
    if (!parse_page_header(r, &info)) {
      set_error("page header parse failed");
      return -1;
    }
    if (info.page_type != 0 || info.encoding != 0 || info.num_values < 0 ||
        info.compressed_size < 0 ||
        info.compressed_size != info.uncompressed_size) {
      set_error("unsupported page (type/encoding/compression)");
      return -1;
    }
    uint64_t data_off = pos + info.header_len;
    const uint64_t page_end = pos + info.header_len + uint64_t(info.compressed_size);
    if (page_end > chunk_len) {
      set_error("page overruns chunk");
      return -1;
    }
    if (has_def_levels) {
      if (info.def_level_encoding != 3) {  // RLE; BIT_PACKED legacy unsupported
        set_error("unsupported definition-level encoding");
        return -1;
      }
      if (data_off + 4 > page_end) {
        set_error("def-levels length overruns page");
        return -1;
      }
      uint32_t def_len;
      std::memcpy(&def_len, chunk + data_off, 4);  // little-endian host
      data_off += 4 + def_len;
      if (data_off > page_end) {
        set_error("def-levels block overruns page");
        return -1;
      }
    }
    if (n >= max_pages) {
      set_error("more pages than max_pages");
      return -1;
    }
    out_offsets[n] = data_off;
    out_counts[n] = info.num_values;
    out_value_lens[n] = page_end - data_off;
    n++;
    pos = page_end;
  }
  return n;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Fused batch decode — read→decode→collate in ONE native call.
//
// The page scan above still hands each column back to Python (one ctypes call
// + Arrow view + collate per column per batch), and forfeits any dictionary-
// or RLE-encoded chunk to Arrow. pstpu_read_fused removes that tail: for a
// whole batch of columns it walks the page headers, decompresses SNAPPY pages
// first-party, decodes PLAIN *and* dictionary/RLE-bit-packed-hybrid values,
// and writes every column's rows into a caller-provided contiguous batch
// buffer — optionally an shm-ring slot the consumer maps — on C++ worker
// threads with the GIL released. Python touches the result exactly once per
// batch. Binary columns come in two fused flavors: uniform raw cells (npy
// payloads, headers stripped) and encoded images, which are decoded through
// the batched image-codec entry points passed in as function pointers so the
// whole read→decode→collate chain is one transition.
//
// Every parse is bounds-checked against the chunk/page/output regions and
// every failure is a per-column status code — the caller falls back to the
// Arrow path for that column and accounts the reason, never crashes.
// ---------------------------------------------------------------------------

namespace {

// First-party snappy *decompressor* (format_description.txt): varint preamble
// with the uncompressed length, then literal/copy elements. Decode-only — the
// write path never emits snappy from here. All reads are bounds-checked; any
// malformed element returns false and the column falls back to Arrow.
bool read_uvarint(const uint8_t** pp, const uint8_t* end, uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  const uint8_t* p = *pp;
  while (p < end && shift <= 28) {  // 5 bytes max: 35 bits covers lengths/runs
    const uint8_t b = *p++;
    v |= uint64_t(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *pp = p;
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

bool snappy_uncompress(const uint8_t* src, uint64_t n, uint8_t* dst, uint64_t dst_len) {
  const uint8_t* p = src;
  const uint8_t* end = src + n;
  uint64_t expect = 0;
  if (!read_uvarint(&p, end, &expect) || expect != dst_len) return false;
  uint64_t d = 0;
  while (p < end) {
    const uint8_t tag = *p++;
    if ((tag & 3) == 0) {  // literal
      uint64_t len = tag >> 2;
      if (len >= 60) {
        const int extra = int(len) - 59;  // 1..4 little-endian length bytes
        if (end - p < extra) return false;
        len = 0;
        for (int i = 0; i < extra; i++) len |= uint64_t(p[i]) << (8 * i);
        p += extra;
      }
      len += 1;
      if (uint64_t(end - p) < len || dst_len - d < len) return false;
      std::memcpy(dst + d, p, len);
      p += len;
      d += len;
    } else {  // copy
      uint64_t len, off;
      if ((tag & 3) == 1) {
        if (p >= end) return false;
        len = ((tag >> 2) & 7) + 4;
        off = (uint64_t(tag & 0xE0) << 3) | *p++;
      } else if ((tag & 3) == 2) {
        if (end - p < 2) return false;
        len = (tag >> 2) + 1;
        off = uint64_t(p[0]) | (uint64_t(p[1]) << 8);
        p += 2;
      } else {
        if (end - p < 4) return false;
        len = (tag >> 2) + 1;
        off = uint64_t(p[0]) | (uint64_t(p[1]) << 8) |
              (uint64_t(p[2]) << 16) | (uint64_t(p[3]) << 24);
        p += 4;
      }
      if (off == 0 || off > d || dst_len - d < len) return false;
      const uint8_t* s = dst + (d - off);
      if (off >= len) {
        std::memcpy(dst + d, s, len);
      } else {
        for (uint64_t i = 0; i < len; i++) dst[d + i] = s[i];  // overlapping run
      }
      d += len;
    }
  }
  return d == expect;
}

// RLE / bit-packed hybrid decoder (<bit-width:1 byte> precedes this stream in
// dictionary-encoded data pages; def-level blocks carry the width implicitly).
// Emits exactly `count` values; trailing runs may overhang and are clamped.
// Zero-length runs/groups are rejected so progress is guaranteed.
bool decode_hybrid(const uint8_t* p, const uint8_t* end, int bw, int64_t count,
                   std::vector<uint32_t>* out) {
  if (bw < 0 || bw > 32 || count < 0) return false;
  out->clear();
  out->reserve(size_t(count));
  if (bw == 0) {
    out->assign(size_t(count), 0);
    return true;
  }
  const uint32_t mask = (bw == 32) ? 0xFFFFFFFFu : ((1u << bw) - 1);
  const int vbytes = (bw + 7) / 8;
  while (int64_t(out->size()) < count) {
    uint64_t header = 0;
    if (!read_uvarint(&p, end, &header)) return false;
    const uint64_t remaining = uint64_t(count) - out->size();
    if (header & 1) {  // bit-packed: (header>>1) groups of 8 values
      const uint64_t groups = header >> 1;
      if (groups == 0) return false;
      // division form: groups * bw would wrap for a corrupt huge group count,
      // sneaking a tiny nbytes past the bounds check below
      if (groups > uint64_t(end - p) / uint64_t(bw)) return false;
      const uint64_t nbytes = groups * uint64_t(bw);
      const uint64_t take = std::min<uint64_t>(groups * 8, remaining);
      uint64_t bit = 0;
      for (uint64_t i = 0; i < take; i++) {
        const uint64_t byte_idx = bit >> 3;
        uint64_t word = 0;
        const uint64_t avail = nbytes - byte_idx;
        std::memcpy(&word, p + byte_idx, avail < 8 ? size_t(avail) : size_t(8));
        out->push_back(uint32_t(word >> (bit & 7)) & mask);
        bit += uint64_t(bw);
      }
      p += nbytes;
    } else {  // RLE run
      const uint64_t run = header >> 1;
      if (run == 0) return false;
      if (end - p < vbytes) return false;
      uint32_t v = 0;
      for (int i = 0; i < vbytes; i++) v |= uint32_t(p[i]) << (8 * i);
      p += vbytes;
      out->insert(out->end(), size_t(std::min<uint64_t>(run, remaining)), v & mask);
    }
  }
  return true;
}

// per-column status codes — keep in sync with native/fused.py REASONS
enum {
  kColOk = 0,
  kColParse = 1,       // thrift/page/snappy parse failure
  kColPageType = 2,    // v2 page or unknown page type
  kColEncoding = 3,    // unsupported value/level encoding
  kColCompressed = 4,  // unsupported codec / size mismatch
  kColDefLevels = 5,   // def-levels block malformed
  kColPageCap = 6,     // more pages than max_pages
  kColRows = 7,        // decoded rows != expected_rows
  kColBounds = 8,      // values/output region bounds violation
  kColDict = 9,        // dictionary missing/invalid for an indexed page
  kColNonUniform = 10, // binary cells not uniform (raw mode)
  kColImgProbe = 11,
  kColImgDims = 12,
  kColImgDecode = 13,
  kColInternal = 14,   // unexpected native failure (e.g. allocation)
};

enum { kModeFixed = 0, kModeBinaryRaw = 1, kModeBinaryImg = 2 };
enum { kCodecUncompressed = 0, kCodecSnappy = 1, kCodecZstd = 2,
       kCodecLz4Raw = 3, kCodecLz4 = 4 };

// ---------------------------------------------------------------------------
// first-party ZSTD (RFC 8878) and LZ4 (raw block / frame / hadoop-framed)
// decompressors. Byte-index style throughout: positions are unsigned indexes
// validated against the buffer length before any access, and every output
// write is bounded by the caller-provided destination capacity.

inline int highbit_u64(uint64_t v) { return 63 - __builtin_clzll(v); }

// forward bit reader (FSE table descriptions); LSB-first within bytes
struct FwdBits {
  const uint8_t* base;
  uint64_t nbytes;
  uint64_t bitpos = 0;
  bool ok = true;
  uint64_t read(int nb) {
    if (nb == 0) return 0;
    if (nb > 57 || !ok) { ok = false; return 0; }
    uint64_t end_bit = bitpos + uint64_t(nb);
    if (end_bit > nbytes * 8) { ok = false; return 0; }
    uint64_t first = bitpos >> 3, last = (end_bit - 1) >> 3;
    uint64_t acc = 0;
    for (uint64_t i = last + 1; i > first; i--) acc = (acc << 8) | base[i - 1];
    acc >>= (bitpos & 7);
    bitpos = end_bit;
    return acc & ((uint64_t(1) << nb) - 1);
  }
  void rewind(int nb) { bitpos -= uint64_t(nb); }
  void align() { bitpos = (bitpos + 7) & ~uint64_t(7); }
  uint64_t consumed_bytes() const { return (bitpos + 7) >> 3; }
};

// backward bit reader (huffman streams, sequence execution). The stream ends
// with a 1-bit sentinel in its last nonzero byte; `pos` counts the unread
// bits below the sentinel and is allowed to go negative only via read_pad
// (zero-padding convention used by huffman state reloads).
struct BackBits {
  const uint8_t* base = nullptr;
  int64_t pos = 0;  // bits [0, pos) of the stream remain unread
  bool ok = true;
  bool init(const uint8_t* p, uint64_t n) {
    base = p;
    if (n == 0 || p[n - 1] == 0) return false;
    pos = int64_t((n - 1) * 8) + highbit_u64(p[n - 1]);
    return true;
  }
  uint64_t gather(int64_t lo, int nb) const {
    if (nb == 0) return 0;
    int64_t hi = lo + nb - 1;
    uint64_t acc = 0;
    for (int64_t i = hi >> 3; i >= lo >> 3; i--) acc = (acc << 8) | base[i];
    acc >>= (uint64_t(lo) & 7);
    return acc & ((uint64_t(1) << nb) - 1);
  }
  // exact read: fails when fewer than nb bits remain
  uint64_t read(int nb) {
    if (nb == 0) return 0;
    if (!ok || nb > 57 || pos < int64_t(nb)) { ok = false; return 0; }
    pos -= nb;
    return gather(pos, nb);
  }
  // padded read: missing low bits come back as zero, pos goes negative
  uint64_t read_pad(int nb) {
    if (nb == 0) return 0;
    if (!ok || nb > 57) { ok = false; return 0; }
    if (pos <= 0) { pos -= nb; return 0; }
    if (pos < int64_t(nb)) {
      uint64_t v = gather(0, int(pos)) << (nb - int(pos));
      pos -= nb;
      return v;
    }
    pos -= nb;
    return gather(pos, nb);
  }
};

struct FseTable {
  std::vector<uint8_t> symbol;
  std::vector<uint8_t> nbits;
  std::vector<uint16_t> base;
  int accuracy_log = 0;
};

bool fse_build(FseTable* t, const int16_t* probs, int n_sym, int accuracy_log) {
  // accuracy_log 5 is the spec minimum; 9 covers every table this decoder
  // builds (LL/ML max 9, OF max 8, huffman-weights max 6). The bound also
  // keeps the spread step coprime with the table size.
  if (accuracy_log < 5 || accuracy_log > 9) return false;
  if (n_sym < 1 || n_sym > 256) return false;
  int size = 1 << accuracy_log;
  int64_t total = 0;
  for (int s = 0; s < n_sym; s++) {
    if (probs[s] < -1) return false;
    total += probs[s] == -1 ? 1 : probs[s];
  }
  if (total != size) return false;
  t->symbol.assign(size_t(size), 0);
  t->nbits.assign(size_t(size), 0);
  t->base.assign(size_t(size), 0);
  t->accuracy_log = accuracy_log;
  int high = size;
  for (int s = 0; s < n_sym; s++) {
    if (probs[s] == -1) t->symbol[size_t(--high)] = uint8_t(s);
  }
  int step = (size >> 1) + (size >> 3) + 3;
  int mask = size - 1;
  int pos = 0;
  for (int s = 0; s < n_sym; s++) {
    for (int i = 0; i < probs[s]; i++) {
      t->symbol[size_t(pos)] = uint8_t(s);
      do { pos = (pos + step) & mask; } while (pos >= high);
    }
  }
  if (pos != 0) return false;
  std::vector<int> next;
  next.resize(size_t(n_sym));
  for (int s = 0; s < n_sym; s++) next[size_t(s)] = probs[s] == -1 ? 1 : probs[s];
  for (int i = 0; i < size; i++) {
    int s = t->symbol[size_t(i)];
    int n = next[size_t(s)]++;
    // states run [prob, 2*prob): a symbol with probability above size/2
    // legitimately reaches n >= size (zero-bit transition, base = n - size)
    if (n <= 0 || n >= size * 2) return false;
    int nb = accuracy_log - highbit_u64(uint64_t(n));
    if (nb < 0 || nb > accuracy_log) return false;
    t->nbits[size_t(i)] = uint8_t(nb);
    t->base[size_t(i)] = uint16_t((n << nb) - size);
  }
  return true;
}

bool fse_read_distribution(FwdBits* bits, int16_t* probs, int max_sym,
                           int max_al, int* out_nsym, int* out_al) {
  int al = 5 + int(bits->read(4));
  if (!bits->ok || al > max_al) return false;
  int remaining = 1 << al;
  int symb = 0;
  while (remaining > 0 && symb < max_sym) {
    int nb = highbit_u64(uint64_t(remaining) + 1) + 1;
    uint32_t val = uint32_t(bits->read(nb));
    if (!bits->ok) return false;
    uint32_t lower_mask = (uint32_t(1) << (nb - 1)) - 1;
    uint32_t threshold = (uint32_t(1) << nb) - 1 - uint32_t(remaining + 1);
    if ((val & lower_mask) < threshold) {
      bits->rewind(1);
      val &= lower_mask;
    } else if (val > lower_mask) {
      val -= threshold;
    }
    int proba = int(val) - 1;
    remaining -= proba < 0 ? -proba : proba;
    probs[symb++] = int16_t(proba);
    if (proba == 0) {
      int repeat = int(bits->read(2));
      while (bits->ok) {
        for (int i = 0; i < repeat && symb < max_sym; i++) probs[symb++] = 0;
        if (repeat != 3) break;
        repeat = int(bits->read(2));
      }
      if (!bits->ok) return false;
    }
  }
  if (remaining != 0) return false;
  bits->align();
  *out_nsym = symb;
  *out_al = al;
  return true;
}

struct HufTable {
  std::vector<uint8_t> symbol;
  std::vector<uint8_t> nbits;
  int max_bits = 0;
};

bool huf_build(HufTable* t, const uint8_t* weights, int n_weights) {
  if (n_weights < 1 || n_weights > 255) return false;
  uint64_t weight_sum = 0;
  for (int i = 0; i < n_weights; i++) {
    if (weights[i] > 11) return false;
    if (weights[i] > 0) weight_sum += uint64_t(1) << (weights[i] - 1);
  }
  if (weight_sum == 0) return false;
  int max_bits = highbit_u64(weight_sum) + 1;
  if (max_bits > 11) return false;
  uint64_t left = (uint64_t(1) << max_bits) - weight_sum;
  // the last symbol's weight is implicit: the remainder must be a power of 2
  if (left == 0 || (left & (left - 1)) != 0) return false;
  int n_sym = n_weights + 1;
  uint8_t w[256];
  for (int i = 0; i < n_weights; i++) w[i] = weights[i];
  w[n_weights] = uint8_t(highbit_u64(left) + 1);
  int size = 1 << max_bits;
  int nbits_of[256];
  int rank_count[13] = {0};
  for (int i = 0; i < n_sym; i++) {
    nbits_of[i] = w[i] == 0 ? 0 : max_bits + 1 - int(w[i]);
    if (nbits_of[i] > 0) rank_count[nbits_of[i]]++;
  }
  // longest codes occupy the lowest table indices
  uint32_t rank_idx[14] = {0};
  rank_idx[max_bits] = 0;
  for (int b = max_bits; b >= 1; b--) {
    uint32_t cells = uint32_t(rank_count[b]) * (uint32_t(1) << (max_bits - b));
    rank_idx[b - 1] = rank_idx[b] + cells;
  }
  if (rank_idx[0] != uint32_t(size)) return false;
  t->symbol.assign(size_t(size), 0);
  t->nbits.assign(size_t(size), 0);
  t->max_bits = max_bits;
  for (int i = 0; i < n_sym; i++) {
    if (nbits_of[i] == 0) continue;
    uint32_t code = rank_idx[nbits_of[i]];
    uint32_t len = uint32_t(1) << (max_bits - nbits_of[i]);
    if (code + len > uint32_t(size)) return false;
    for (uint32_t j = 0; j < len; j++) {
      t->symbol[code + j] = uint8_t(i);
      t->nbits[code + j] = uint8_t(nbits_of[i]);
    }
    rank_idx[nbits_of[i]] += len;
  }
  return true;
}

bool huf_decode_stream(const HufTable& t, BackBits* br, uint8_t* out,
                       uint64_t out_len) {
  uint64_t mask = (uint64_t(1) << t.max_bits) - 1;
  uint64_t state = br->read(t.max_bits);
  if (!br->ok) return false;
  for (uint64_t i = 0; i < out_len; i++) {
    out[i] = t.symbol[state];
    int nb = t.nbits[state];
    if (nb == 0) return false;
    state = ((state << nb) | br->read_pad(nb)) & mask;
    if (!br->ok) return false;
  }
  // a well-formed stream is consumed exactly: the final reload ran the
  // reader max_bits past empty (the initial state bits are not "owed back")
  return br->pos == -int64_t(t.max_bits);
}

bool huf_read_table(HufTable* t, const uint8_t* p, uint64_t n,
                    uint64_t* consumed) {
  if (n < 1) return false;
  int hb = p[0];
  uint8_t weights[256];
  int n_weights = 0;
  if (hb >= 128) {
    // direct 4-bit weights, high nibble first
    n_weights = hb - 127;
    uint64_t wbytes = (uint64_t(n_weights) + 1) / 2;
    if (n - 1 < wbytes) return false;
    for (int i = 0; i < n_weights; i++) {
      uint8_t b = p[1 + uint64_t(i >> 1)];
      weights[i] = (i & 1) ? (b & 0xF) : (b >> 4);
    }
    *consumed = 1 + wbytes;
  } else {
    // FSE-compressed weights: two interleaved states over a backward stream
    uint64_t csize = uint64_t(hb);
    if (csize == 0 || n - 1 < csize) return false;
    FwdBits fb{p + 1, csize};
    int16_t probs[256];
    int nsym = 0, al = 0;
    if (!fse_read_distribution(&fb, probs, 255, 6, &nsym, &al)) return false;
    FseTable ft;
    if (!fse_build(&ft, probs, nsym, al)) return false;
    uint64_t hdr = fb.consumed_bytes();
    if (csize <= hdr) return false;
    BackBits bb;
    if (!bb.init(p + 1 + hdr, csize - hdr)) return false;
    uint64_t s1 = bb.read(al), s2 = bb.read(al);
    if (!bb.ok) return false;
    while (true) {
      if (n_weights + 3 > 255) return false;
      weights[n_weights++] = ft.symbol[s1];
      s1 = uint64_t(ft.base[s1]) + bb.read_pad(ft.nbits[s1]);
      if (bb.pos < 0) { weights[n_weights++] = ft.symbol[s2]; break; }
      weights[n_weights++] = ft.symbol[s2];
      s2 = uint64_t(ft.base[s2]) + bb.read_pad(ft.nbits[s2]);
      if (bb.pos < 0) { weights[n_weights++] = ft.symbol[s1]; break; }
    }
    *consumed = 1 + csize;
  }
  return huf_build(t, weights, n_weights);
}

// RFC 8878 predefined sequence distributions and code→(baseline, extra-bits)
const int16_t kLLDefault[36] = {
    4, 3, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 2, 1, 1, 1, 2, 2,
    2, 2, 2, 2, 2, 2, 2, 3, 2, 1, 1, 1, 1, 1, -1, -1, -1, -1};
const int16_t kMLDefault[53] = {
    1, 4, 3, 2, 2, 2, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
    1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, -1,
    -1, -1, -1, -1, -1, -1};
const int16_t kOFDefault[29] = {
    1, 1, 1, 1, 1, 1, 2, 2, 2, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1, 1,
    1, -1, -1, -1, -1, -1};
const uint32_t kLLBase[36] = {
    0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 18,
    20, 22, 24, 28, 32, 40, 48, 64, 128, 256, 512, 1024, 2048, 4096,
    8192, 16384, 32768, 65536};
const uint8_t kLLBits[36] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 1,
    1, 1, 2, 2, 3, 3, 4, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};
const uint32_t kMLBase[53] = {
    3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20,
    21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35, 37,
    39, 41, 43, 47, 51, 59, 67, 83, 99, 131, 259, 515, 1027, 2051,
    4099, 8195, 16387, 32771, 65539};
const uint8_t kMLBits[53] = {
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0,
    0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 0, 1, 1,
    1, 1, 2, 2, 3, 3, 4, 4, 5, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16};

// per-frame decode state: huffman table + sequence tables persist across
// blocks (treeless literals / repeat mode); repeat offsets reset per frame
struct ZstdCtx {
  HufTable huf;
  bool have_huf = false;
  FseTable ll, of, ml;
  bool have_ll = false, have_of = false, have_ml = false;
  uint64_t rep[3] = {1, 4, 8};
  std::vector<uint8_t> lits;
};

bool zstd_literals(ZstdCtx* ctx, const uint8_t* p, uint64_t n,
                   uint64_t* consumed) {
  if (n < 1) return false;
  uint32_t b0 = p[0];
  int ltype = b0 & 3;
  int sf = (b0 >> 2) & 3;
  if (ltype == 0 || ltype == 1) {  // raw / RLE
    uint64_t hlen, rsize;
    if (sf == 0 || sf == 2) {
      hlen = 1;
      rsize = b0 >> 3;
    } else if (sf == 1) {
      if (n < 2) return false;
      hlen = 2;
      rsize = (b0 >> 4) | (uint64_t(p[1]) << 4);
    } else {
      if (n < 3) return false;
      hlen = 3;
      rsize = (b0 >> 4) | (uint64_t(p[1]) << 4) | (uint64_t(p[2]) << 12);
    }
    if (rsize > (uint64_t(1) << 20)) return false;
    if (ltype == 0) {
      if (n - hlen < rsize) return false;
      ctx->lits.assign(p + hlen, p + hlen + rsize);
      *consumed = hlen + rsize;
    } else {
      if (n - hlen < 1) return false;
      ctx->lits.assign(size_t(rsize), p[hlen]);
      *consumed = hlen + 1;
    }
    return true;
  }
  // huffman-compressed (2) or treeless (3, reuses the frame's last table)
  uint64_t hlen, rsize, csize;
  int n_streams;
  if (sf == 0 || sf == 1) {
    if (n < 3) return false;
    uint64_t h = b0 | (uint64_t(p[1]) << 8) | (uint64_t(p[2]) << 16);
    hlen = 3;
    n_streams = sf == 0 ? 1 : 4;
    rsize = (h >> 4) & 0x3FF;
    csize = (h >> 14) & 0x3FF;
  } else if (sf == 2) {
    if (n < 4) return false;
    uint64_t h = b0 | (uint64_t(p[1]) << 8) | (uint64_t(p[2]) << 16) |
                 (uint64_t(p[3]) << 24);
    hlen = 4;
    n_streams = 4;
    rsize = (h >> 4) & 0x3FFF;
    csize = (h >> 18) & 0x3FFF;
  } else {
    if (n < 5) return false;
    uint64_t h = b0 | (uint64_t(p[1]) << 8) | (uint64_t(p[2]) << 16) |
                 (uint64_t(p[3]) << 24) | (uint64_t(p[4]) << 32);
    hlen = 5;
    n_streams = 4;
    rsize = (h >> 4) & 0x3FFFF;
    csize = (h >> 22) & 0x3FFFF;
  }
  if (csize == 0 || n - hlen < csize) return false;
  if (rsize > (uint64_t(1) << 20)) return false;
  const uint8_t* body = p + hlen;
  uint64_t coff = 0;
  if (ltype == 2) {
    uint64_t tree_len = 0;
    if (!huf_read_table(&ctx->huf, body, csize, &tree_len)) return false;
    ctx->have_huf = true;
    coff = tree_len;
  } else if (!ctx->have_huf) {
    return false;
  }
  if (coff >= csize) return false;
  uint64_t slen = csize - coff;
  ctx->lits.assign(size_t(rsize), 0);
  if (n_streams == 1) {
    BackBits bb;
    if (!bb.init(body + coff, slen)) return false;
    if (!huf_decode_stream(ctx->huf, &bb, ctx->lits.data(), rsize)) return false;
  } else {
    if (slen < 6) return false;
    uint64_t s1 = body[coff] | (uint64_t(body[coff + 1]) << 8);
    uint64_t s2 = body[coff + 2] | (uint64_t(body[coff + 3]) << 8);
    uint64_t s3 = body[coff + 4] | (uint64_t(body[coff + 5]) << 8);
    if (s1 == 0 || s2 == 0 || s3 == 0) return false;
    if (s1 + s2 + s3 > slen - 6) return false;
    uint64_t s4 = slen - 6 - s1 - s2 - s3;
    if (s4 == 0) return false;
    uint64_t rchunk = (rsize + 3) / 4;
    if (3 * rchunk > rsize) return false;
    uint64_t sizes[4] = {s1, s2, s3, s4};
    uint64_t rsizes[4] = {rchunk, rchunk, rchunk, rsize - 3 * rchunk};
    uint64_t soff = coff + 6, roff = 0;
    for (int i = 0; i < 4; i++) {
      BackBits bb;
      if (!bb.init(body + soff, sizes[i])) return false;
      if (!huf_decode_stream(ctx->huf, &bb, ctx->lits.data() + roff, rsizes[i]))
        return false;
      soff += sizes[i];
      roff += rsizes[i];
    }
  }
  *consumed = hlen + csize;
  return true;
}

bool seq_table_for_mode(FseTable* t, bool* have, int mode,
                        const int16_t* defaults, int n_defaults, int default_al,
                        int max_al, int max_sym, const uint8_t* p, uint64_t n,
                        uint64_t* ip) {
  if (mode == 0) {  // predefined
    *have = fse_build(t, defaults, n_defaults, default_al);
    return *have;
  }
  if (mode == 1) {  // RLE: one symbol, zero-bit table
    if (*ip >= n) return false;
    uint8_t sym = p[*ip];
    *ip += 1;
    if (int(sym) >= max_sym) return false;
    t->symbol.assign(1, sym);
    t->nbits.assign(1, 0);
    t->base.assign(1, 0);
    t->accuracy_log = 0;
    *have = true;
    return true;
  }
  if (mode == 2) {  // FSE-described
    if (*ip >= n) return false;
    FwdBits fb{p + *ip, n - *ip};
    int16_t probs[64];
    int nsym = 0, al = 0;
    if (!fse_read_distribution(&fb, probs, max_sym, max_al, &nsym, &al))
      return false;
    if (!fse_build(t, probs, nsym, al)) return false;
    *ip += fb.consumed_bytes();
    *have = true;
    return true;
  }
  return *have;  // repeat: reuse the frame's previous table
}

bool zstd_sequences(ZstdCtx* ctx, const uint8_t* p, uint64_t n, uint8_t* dst,
                    uint64_t dst_cap, uint64_t* d_io, uint64_t frame_base) {
  uint64_t d = *d_io;
  uint64_t ip = 0;
  if (n < 1) return false;
  uint64_t nseq;
  uint32_t b0 = p[0];
  if (b0 < 128) {
    nseq = b0;
    ip = 1;
  } else if (b0 < 255) {
    if (n < 2) return false;
    nseq = ((uint64_t(b0) - 128) << 8) + p[1];
    ip = 2;
  } else {
    if (n < 3) return false;
    nseq = p[1] + (uint64_t(p[2]) << 8) + 0x7F00;
    ip = 3;
  }
  const uint64_t lit_total = ctx->lits.size();
  if (nseq == 0) {
    if (ip != n) return false;
    if (dst_cap - d < lit_total) return false;
    std::memcpy(dst + d, ctx->lits.data(), size_t(lit_total));
    *d_io = d + lit_total;
    return true;
  }
  if (n - ip < 1) return false;
  uint32_t modes = p[ip++];
  if ((modes & 3) != 0) return false;  // reserved bits
  int ll_mode = (modes >> 6) & 3;
  int of_mode = (modes >> 4) & 3;
  int ml_mode = (modes >> 2) & 3;
  if (!seq_table_for_mode(&ctx->ll, &ctx->have_ll, ll_mode, kLLDefault, 36, 6,
                          9, 36, p, n, &ip))
    return false;
  if (!seq_table_for_mode(&ctx->of, &ctx->have_of, of_mode, kOFDefault, 29, 5,
                          8, 32, p, n, &ip))
    return false;
  if (!seq_table_for_mode(&ctx->ml, &ctx->have_ml, ml_mode, kMLDefault, 53, 6,
                          9, 53, p, n, &ip))
    return false;
  if (ip >= n) return false;
  BackBits bb;
  if (!bb.init(p + ip, n - ip)) return false;
  uint64_t sll = bb.read(ctx->ll.accuracy_log);
  uint64_t sof = bb.read(ctx->of.accuracy_log);
  uint64_t sml = bb.read(ctx->ml.accuracy_log);
  if (!bb.ok) return false;
  uint64_t lit_off = 0;
  for (uint64_t seq = 0; seq < nseq; seq++) {
    uint32_t ll_code = ctx->ll.symbol[sll];
    uint32_t of_code = ctx->of.symbol[sof];
    uint32_t ml_code = ctx->ml.symbol[sml];
    if (ll_code > 35 || ml_code > 52 || of_code > 31) return false;
    uint64_t of_value = (uint64_t(1) << of_code) + bb.read(int(of_code));
    uint64_t ml_value = kMLBase[ml_code] + bb.read(kMLBits[ml_code]);
    uint64_t ll_value = kLLBase[ll_code] + bb.read(kLLBits[ll_code]);
    if (!bb.ok) return false;
    if (seq + 1 < nseq) {  // no state reload after the final sequence
      sll = uint64_t(ctx->ll.base[sll]) + bb.read(ctx->ll.nbits[sll]);
      sml = uint64_t(ctx->ml.base[sml]) + bb.read(ctx->ml.nbits[sml]);
      sof = uint64_t(ctx->of.base[sof]) + bb.read(ctx->of.nbits[sof]);
      if (!bb.ok) return false;
    }
    uint64_t offset;
    if (of_value > 3) {
      offset = of_value - 3;
      ctx->rep[2] = ctx->rep[1];
      ctx->rep[1] = ctx->rep[0];
      ctx->rep[0] = offset;
    } else {
      uint64_t idx = of_value - 1 + (ll_value == 0 ? 1 : 0);
      if (idx == 0) {
        offset = ctx->rep[0];
      } else {
        offset = idx < 3 ? ctx->rep[idx] : ctx->rep[0] - 1;
        if (idx > 1) ctx->rep[2] = ctx->rep[1];
        ctx->rep[1] = ctx->rep[0];
        ctx->rep[0] = offset;
      }
    }
    if (offset == 0) return false;
    if (lit_total - lit_off < ll_value || lit_off > lit_total) return false;
    if (dst_cap - d < ll_value) return false;
    std::memcpy(dst + d, ctx->lits.data() + lit_off, size_t(ll_value));
    lit_off += ll_value;
    d += ll_value;
    if (offset > d - frame_base) return false;
    if (dst_cap - d < ml_value) return false;
    for (uint64_t i = 0; i < ml_value; i++) dst[d + i] = dst[d + i - offset];
    d += ml_value;
  }
  if (bb.pos != 0) return false;  // the sequence bitstream must be exact
  uint64_t tail = lit_total - lit_off;
  if (dst_cap - d < tail) return false;
  std::memcpy(dst + d, ctx->lits.data() + lit_off, size_t(tail));
  *d_io = d + tail;
  return true;
}

bool zstd_frame(ZstdCtx* ctx, const uint8_t* src, uint64_t src_len,
                uint64_t* ip_io, uint8_t* dst, uint64_t dst_len,
                uint64_t* d_io) {
  uint64_t ip = *ip_io;
  uint64_t d = *d_io;
  const uint64_t frame_base = d;  // match offsets may not cross frames
  if (src_len - ip < 1) return false;
  uint32_t fhd = src[ip++];
  if (fhd & 0x08) return false;  // reserved bit
  int fcs_code = fhd >> 6;
  bool single_segment = (fhd & 0x20) != 0;
  bool has_checksum = (fhd & 0x04) != 0;
  static const int kDidBytes[4] = {0, 1, 2, 4};
  int dbytes = kDidBytes[fhd & 3];
  if (!single_segment) {
    if (src_len - ip < 1) return false;
    ip++;  // window descriptor: all writes are bounded by dst_len instead
  }
  if (dbytes > 0) {
    if (src_len - ip < uint64_t(dbytes)) return false;
    uint64_t did = 0;
    for (int i = 0; i < dbytes; i++) did |= uint64_t(src[ip + i]) << (8 * i);
    ip += uint64_t(dbytes);
    if (did != 0) return false;  // dictionaries unsupported
  }
  int fcs_bytes;
  if (fcs_code == 0) fcs_bytes = single_segment ? 1 : 0;
  else if (fcs_code == 1) fcs_bytes = 2;
  else if (fcs_code == 2) fcs_bytes = 4;
  else fcs_bytes = 8;
  bool have_fcs = fcs_bytes > 0;
  uint64_t content_size = 0;
  if (have_fcs) {
    if (src_len - ip < uint64_t(fcs_bytes)) return false;
    for (int i = 0; i < fcs_bytes; i++)
      content_size |= uint64_t(src[ip + i]) << (8 * i);
    if (fcs_bytes == 2) content_size += 256;
    ip += uint64_t(fcs_bytes);
    if (content_size > dst_len - frame_base) return false;
  }
  ctx->rep[0] = 1;
  ctx->rep[1] = 4;
  ctx->rep[2] = 8;
  ctx->have_huf = ctx->have_ll = ctx->have_of = ctx->have_ml = false;
  bool last = false;
  while (!last) {
    if (src_len - ip < 3) return false;
    uint32_t bh = src[ip] | (uint32_t(src[ip + 1]) << 8) |
                  (uint32_t(src[ip + 2]) << 16);
    ip += 3;
    last = (bh & 1) != 0;
    int btype = (bh >> 1) & 3;
    uint64_t bsize = bh >> 3;
    if (btype == 0) {  // raw
      if (src_len - ip < bsize || dst_len - d < bsize) return false;
      std::memcpy(dst + d, src + ip, size_t(bsize));
      ip += bsize;
      d += bsize;
    } else if (btype == 1) {  // RLE
      if (src_len - ip < 1 || dst_len - d < bsize) return false;
      std::memset(dst + d, src[ip], size_t(bsize));
      ip += 1;
      d += bsize;
    } else if (btype == 2) {  // compressed
      if (bsize < 1 || src_len - ip < bsize) return false;
      uint64_t lit_consumed = 0;
      if (!zstd_literals(ctx, src + ip, bsize, &lit_consumed)) return false;
      if (lit_consumed > bsize) return false;
      if (!zstd_sequences(ctx, src + ip + lit_consumed, bsize - lit_consumed,
                          dst, dst_len, &d, frame_base))
        return false;
      ip += bsize;
    } else {
      return false;  // reserved block type
    }
  }
  if (has_checksum) {
    if (src_len - ip < 4) return false;
    ip += 4;  // xxhash not verified; bounds are the contract here
  }
  if (have_fcs && d - frame_base != content_size) return false;
  *ip_io = ip;
  *d_io = d;
  return true;
}

bool zstd_uncompress(const uint8_t* src, uint64_t src_len, uint8_t* dst,
                     uint64_t dst_len) {
  ZstdCtx ctx;
  uint64_t ip = 0, d = 0;
  while (ip < src_len) {
    if (src_len - ip < 4) return false;
    uint32_t magic = src[ip] | (uint32_t(src[ip + 1]) << 8) |
                     (uint32_t(src[ip + 2]) << 16) |
                     (uint32_t(src[ip + 3]) << 24);
    ip += 4;
    if ((magic & 0xFFFFFFF0u) == 0x184D2A50u) {  // skippable frame
      if (src_len - ip < 4) return false;
      uint64_t fsize = src[ip] | (uint32_t(src[ip + 1]) << 8) |
                       (uint32_t(src[ip + 2]) << 16) |
                       (uint32_t(src[ip + 3]) << 24);
      ip += 4;
      if (src_len - ip < fsize) return false;
      ip += fsize;
      continue;
    }
    if (magic != 0xFD2FB528u) return false;
    if (!zstd_frame(&ctx, src, src_len, &ip, dst, dst_len, &d)) return false;
  }
  return d == dst_len;
}

// LZ4 raw block. `hist_base` bounds how far back matches may reach (0 when
// the caller's earlier output is legal history, the block start otherwise).
bool lz4_block_uncompress(const uint8_t* src, uint64_t src_len, uint8_t* dst,
                          uint64_t dst_cap, uint64_t* d_io, uint64_t hist_base) {
  uint64_t s = 0, d = *d_io;
  while (s < src_len) {
    uint32_t token = src[s++];
    uint64_t lit = token >> 4;
    if (lit == 15) {
      while (true) {
        if (s >= src_len) return false;  // unterminated length extension
        uint32_t b = src[s++];
        lit += b;
        if (b != 255) break;
      }
    }
    if (src_len - s < lit || dst_cap - d < lit) return false;
    std::memcpy(dst + d, src + s, size_t(lit));
    s += lit;
    d += lit;
    if (s == src_len) break;  // final sequence carries literals only
    if (src_len - s < 2) return false;
    uint64_t offset = src[s] | (uint64_t(src[s + 1]) << 8);
    s += 2;
    if (offset == 0 || offset > d - hist_base) return false;
    uint64_t mlen = (token & 0xF) + 4;
    if ((token & 0xF) == 15) {
      while (true) {
        if (s >= src_len) return false;
        uint32_t b = src[s++];
        mlen += b;
        if (b != 255) break;
      }
    }
    if (dst_cap - d < mlen) return false;
    for (uint64_t i = 0; i < mlen; i++) dst[d + i] = dst[d + i - offset];
    d += mlen;
  }
  *d_io = d;
  return true;
}

bool lz4_frame_uncompress(const uint8_t* src, uint64_t src_len, uint8_t* dst,
                          uint64_t dst_len) {
  if (src_len < 7) return false;
  uint32_t magic = src[0] | (uint32_t(src[1]) << 8) | (uint32_t(src[2]) << 16) |
                   (uint32_t(src[3]) << 24);
  if (magic != 0x184D2204u) return false;
  uint64_t ip = 4;
  uint32_t flg = src[ip], bd = src[ip + 1];
  ip += 2;
  if (((flg >> 6) & 3) != 1) return false;  // version must be 01
  if (flg & 0x02) return false;             // reserved FLG bit
  if (flg & 0x01) return false;             // dictionaries unsupported
  bool b_checksum = (flg & 0x10) != 0;
  bool c_size = (flg & 0x08) != 0;
  bool c_checksum = (flg & 0x04) != 0;
  if (bd & 0x8F) return false;  // reserved BD bits
  if (c_size) {
    if (src_len - ip < 8) return false;
    uint64_t csz = 0;
    for (int i = 0; i < 8; i++) csz |= uint64_t(src[ip + i]) << (8 * i);
    ip += 8;
    if (csz != dst_len) return false;
  }
  if (src_len - ip < 1) return false;
  ip += 1;  // header-checksum byte (not verified)
  uint64_t d = 0;
  while (true) {
    if (src_len - ip < 4) return false;
    uint32_t bsz = src[ip] | (uint32_t(src[ip + 1]) << 8) |
                   (uint32_t(src[ip + 2]) << 16) | (uint32_t(src[ip + 3]) << 24);
    ip += 4;
    if (bsz == 0) break;  // EndMark
    bool stored = (bsz & 0x80000000u) != 0;
    uint64_t blen = bsz & 0x7FFFFFFFu;
    if (src_len - ip < blen) return false;
    if (stored) {
      if (dst_len - d < blen) return false;
      std::memcpy(dst + d, src + ip, size_t(blen));
      d += blen;
    } else {
      if (!lz4_block_uncompress(src + ip, blen, dst, dst_len, &d, 0))
        return false;
    }
    ip += blen;
    if (b_checksum) {
      if (src_len - ip < 4) return false;
      ip += 4;
    }
  }
  if (c_checksum) {
    if (src_len - ip < 4) return false;
    ip += 4;
  }
  return d == dst_len;
}

// hadoop-framed LZ4 (what parquet's legacy LZ4 codec writes): repeated
// [u32 BE decompressed size][u32 BE compressed size][raw block]
bool lz4_hadoop_uncompress(const uint8_t* src, uint64_t src_len, uint8_t* dst,
                           uint64_t dst_len) {
  uint64_t ip = 0, d = 0;
  while (ip < src_len) {
    if (src_len - ip < 8) return false;
    uint64_t want = (uint64_t(src[ip]) << 24) | (uint64_t(src[ip + 1]) << 16) |
                    (uint64_t(src[ip + 2]) << 8) | uint64_t(src[ip + 3]);
    uint64_t clen = (uint64_t(src[ip + 4]) << 24) |
                    (uint64_t(src[ip + 5]) << 16) |
                    (uint64_t(src[ip + 6]) << 8) | uint64_t(src[ip + 7]);
    ip += 8;
    if (src_len - ip < clen) return false;
    if (dst_len - d < want) return false;
    uint64_t d0 = d;
    if (!lz4_block_uncompress(src + ip, clen, dst, d0 + want, &d, d0))
      return false;
    if (d - d0 != want) return false;
    ip += clen;
  }
  return d == dst_len;
}

// 'LZ4' parquet metadata is ambiguous in the wild: try hadoop framing, then
// the lz4 frame format, then a bare raw block
bool lz4_auto_uncompress(const uint8_t* src, uint64_t src_len, uint8_t* dst,
                         uint64_t dst_len) {
  if (lz4_hadoop_uncompress(src, src_len, dst, dst_len)) return true;
  if (src_len >= 4) {
    uint32_t magic = src[0] | (uint32_t(src[1]) << 8) |
                     (uint32_t(src[2]) << 16) | (uint32_t(src[3]) << 24);
    if (magic == 0x184D2204u)
      return lz4_frame_uncompress(src, src_len, dst, dst_len);
  }
  uint64_t d = 0;
  return lz4_block_uncompress(src, src_len, dst, dst_len, &d, 0) &&
         d == dst_len;
}

bool decompress_page(int codec, const uint8_t* src, uint64_t src_len,
                     uint8_t* dst, uint64_t dst_len) {
  if (codec == kCodecSnappy) return snappy_uncompress(src, src_len, dst, dst_len);
  if (codec == kCodecZstd) return zstd_uncompress(src, src_len, dst, dst_len);
  if (codec == kCodecLz4Raw) {
    uint64_t d = 0;
    return lz4_block_uncompress(src, src_len, dst, dst_len, &d, 0) &&
           d == dst_len;
  }
  if (codec == kCodecLz4) return lz4_auto_uncompress(src, src_len, dst, dst_len);
  return false;
}

}  // namespace

// one column of the fused batch; mirrored field-for-field by the
// ctypes.Structure in native/fused.py (the batch-buffer ABI). File scope (not
// the anonymous namespace): the extern "C" entry point takes it by pointer.
struct FusedCol {
  const uint8_t* chunk;   // column chunk bytes (dictionary page first)
  uint64_t chunk_len;
  uint8_t* out;           // destination region inside the batch buffer
  uint64_t out_cap;       // bounds: the native side never writes past this
  uint8_t* aux_buf;       // small per-column side buffer (npy header copy)
  uint64_t aux_cap;
  int64_t expected_rows;
  int32_t mode;           // kMode*
  int32_t codec;          // kCodec*
  int32_t itemsize;       // kModeFixed: value byte width (FLBA width for FLBA)
  int32_t has_def_levels; // OPTIONAL chunk PROVEN null-free: skip RLE block
  int32_t strip_npy;      // kModeBinaryRaw: strip identical np.save headers
  int32_t img_w, img_h, img_c;  // kModeBinaryImg: expected decoded dims
  int32_t img_threads;
  int32_t status;         // out: kCol*
  uint64_t out_used;      // out: bytes written into `out`
  uint64_t aux0;          // out: raw: per-cell payload len; img: row bytes
  uint64_t aux1;          // out: raw: npy header len in aux_buf
};

// one native predicate clause; mirrored field-for-field by the
// ctypes.Structure in native/fused.py. `col` indexes the pred_cols array of
// pstpu_read_fused_pred; operands are little-endian scalars of the column's
// physical width, and range bounds are packed [lo][hi] in `values`.
struct FusedPred {
  const uint8_t* values;  // kPredIn: `count` packed operands; kPredRange: [lo][hi]
  uint64_t values_cap;    // bounds: operand reads never pass this
  int64_t count;          // kPredIn: number of operands
  int32_t col;
  int32_t op;             // kPred* op
  int32_t dtype;          // kPred* physical dtype
  int32_t negate;
  int32_t has_lo, has_hi;
  int32_t lo_incl, hi_incl;
  int32_t status;         // out: kCol* status of the clause's column
  int32_t pages_skipped;  // out: stat-skipped pages of the clause's column
};

namespace {

// batched image-codec entry points (image_codec.cpp), passed as pointers so
// this kernel needs no link-time dependency on the optional image library
using ImgProbeFn = long long (*)(long long, void**, unsigned long long*,
                                 int32_t*, int32_t, int32_t);
using ImgDecodeFn = long long (*)(long long, void**, unsigned long long*,
                                  void**, int32_t*, int, int32_t, int32_t);

struct PageRec {
  int32_t encoding;
  int64_t num_values;
  uint64_t body_off;   // page body offset within the chunk (possibly compressed)
  uint64_t body_len;   // compressed size
  uint64_t plain_len;  // uncompressed size
  bool is_dict;
  // DATA_PAGE_V2: rep+def levels are an uncompressed prefix of the body
  // (skipped by explicit length — num_nulls == 0 is checked at scan time, so
  // the all-ones def levels carry no information), and `v2_compressed`
  // scopes the chunk codec to the data region alone
  bool is_v2 = false;
  bool v2_compressed = false;
  uint64_t levels_len = 0;
  // page-header statistics (pointers into the chunk's header bytes, which
  // outlive the PageRec within a fused call; -1 length = stat absent)
  const uint8_t* stat_min = nullptr;
  const uint8_t* stat_max = nullptr;
  int64_t stat_min_len = -1;
  int64_t stat_max_len = -1;
  int64_t stat_null_count = -1;
};

int scan_fused_pages(const FusedCol& c, int max_pages, std::vector<PageRec>* pages) {
  if (c.codec < kCodecUncompressed || c.codec > kCodecLz4) return kColCompressed;
  uint64_t pos = 0;
  while (pos < c.chunk_len) {
    TReader r{c.chunk + pos, c.chunk + c.chunk_len};
    PageInfo info;
    if (!parse_page_header(r, &info)) return kColParse;
    if (info.compressed_size < 0 || info.uncompressed_size < 0) return kColParse;
    // cap the per-page scratch a hostile uncompressed_size can demand
    if (info.uncompressed_size > (int64_t(1) << 30)) return kColParse;
    const uint64_t body_off = pos + info.header_len;
    const uint64_t page_end = body_off + uint64_t(info.compressed_size);
    if (page_end > c.chunk_len || page_end <= pos) return kColBounds;
    if (c.codec == kCodecUncompressed &&
        info.compressed_size != info.uncompressed_size) {
      return kColCompressed;
    }
    PageRec rec;
    rec.body_off = body_off;
    rec.body_len = uint64_t(info.compressed_size);
    rec.plain_len = uint64_t(info.uncompressed_size);
    rec.stat_min = info.stat_min;
    rec.stat_max = info.stat_max;
    rec.stat_min_len = info.stat_min_len;
    rec.stat_max_len = info.stat_max_len;
    rec.stat_null_count = info.stat_null_count;
    if (info.page_type == 2) {  // dictionary page
      if (!pages->empty()) return kColParse;  // must precede the data pages
      if (info.dict_encoding != 0 && info.dict_encoding != 2) return kColEncoding;
      if (info.dict_num_values < 0) return kColParse;
      rec.encoding = 0;
      rec.num_values = info.dict_num_values;
      rec.is_dict = true;
    } else if (info.page_type == 0) {  // data page v1
      if (info.encoding != 0 && info.encoding != 2 && info.encoding != 8) {
        return kColEncoding;
      }
      if (c.has_def_levels && info.def_level_encoding != 3) return kColDefLevels;
      if (info.num_values < 0) return kColParse;
      rec.encoding = info.encoding;
      rec.num_values = info.num_values;
      rec.is_dict = false;
    } else if (info.page_type == 3) {  // data page v2
      if (info.encoding != 0 && info.encoding != 2 && info.encoding != 8) {
        return kColEncoding;
      }
      if (info.num_values < 0 || info.v2_def_len < 0 || info.v2_rep_len < 0) {
        return kColParse;
      }
      // v2 headers state num_nulls explicitly: only a proven-null-free page
      // fuses (the v1 path needs chunk statistics for the same proof), and a
      // flat column's rep levels are zero-length by construction
      if (info.v2_num_nulls != 0) return kColDefLevels;
      const uint64_t levels = uint64_t(info.v2_def_len) + uint64_t(info.v2_rep_len);
      if (levels > rec.body_len || levels > rec.plain_len) return kColDefLevels;
      rec.encoding = info.encoding;
      rec.num_values = info.num_values;
      rec.is_dict = false;
      rec.is_v2 = true;
      rec.v2_compressed = info.v2_is_compressed != 0;
      rec.levels_len = levels;
    } else {
      return kColPageType;  // index / unknown pages: Arrow path
    }
    if (int(pages->size()) >= max_pages) return kColPageCap;
    pages->push_back(rec);
    pos = page_end;
  }
  return kColOk;
}

// Uncompressed VALUES region of one page: decompresses into `scratch` when the
// chunk codec is snappy/zstd/lz4, then skips the RLE def-levels block when
// present. The returned pointer aliases either the chunk or `scratch` — the
// caller keeps `scratch` alive while the values are in use.
int page_values(const FusedCol& c, const PageRec& pg, std::vector<uint8_t>* scratch,
                const uint8_t** vals, uint64_t* vlen) {
  const uint8_t* base = c.chunk + pg.body_off;
  uint64_t len = pg.body_len;
  if (pg.is_v2) {
    // v2 layout: [rep levels][def levels] UNCOMPRESSED, then the data region
    // (compressed only when the header's is_compressed flag says so). The
    // level lengths were bounds-checked against body/plain size at scan time.
    const uint8_t* data = base + pg.levels_len;
    const uint64_t data_len = len - pg.levels_len;
    const uint64_t plain_data = pg.plain_len - pg.levels_len;
    if (pg.v2_compressed && c.codec != kCodecUncompressed) {
      scratch->resize(size_t(plain_data));
      if (!decompress_page(c.codec, data, data_len, scratch->data(), plain_data)) {
        return kColParse;
      }
      *vals = scratch->data();
      *vlen = plain_data;
      return kColOk;
    }
    *vals = data;
    *vlen = data_len;
    return kColOk;
  }
  if (c.codec != kCodecUncompressed) {
    scratch->resize(size_t(pg.plain_len));
    if (!decompress_page(c.codec, base, len, scratch->data(), pg.plain_len)) {
      return kColParse;
    }
    base = scratch->data();
    len = pg.plain_len;
  }
  if (!pg.is_dict && c.has_def_levels) {
    if (len < 4) return kColDefLevels;
    uint32_t def_len = 0;
    std::memcpy(&def_len, base, 4);  // little-endian host
    if (uint64_t(def_len) + 4 > len) return kColDefLevels;
    base += 4 + def_len;
    len -= 4 + def_len;
  }
  *vals = base;
  *vlen = len;
  return kColOk;
}

int decode_fixed(FusedCol* c, const std::vector<PageRec>& pages) {
  const uint64_t w = uint64_t(c->itemsize);
  if (w == 0 || w > (64u << 20)) return kColParse;
  std::vector<uint8_t> dict_store;       // owns decompressed dictionary values
  const uint8_t* dict_vals = nullptr;
  uint64_t n_dict = 0;
  std::vector<uint8_t> scratch;
  std::vector<uint32_t> idx;
  uint64_t written = 0;
  int64_t rows = 0;
  for (const PageRec& pg : pages) {
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    if (pg.is_dict) {
      int rc = page_values(*c, pg, &dict_store, &vals, &vlen);
      if (rc != kColOk) return rc;
      // division form: num_values * w would wrap for a corrupt huge count
      if (uint64_t(pg.num_values) > vlen / w) return kColDict;
      if (c->codec == kCodecUncompressed) {
        // values point into the chunk; keep them there (no copy needed)
        dict_vals = vals;
      } else {
        dict_vals = dict_store.data();  // scratch persists for the column
      }
      n_dict = uint64_t(pg.num_values);
      continue;
    }
    int rc = page_values(*c, pg, &scratch, &vals, &vlen);
    if (rc != kColOk) return rc;
    if (uint64_t(pg.num_values) > c->out_cap / w) return kColBounds;
    const uint64_t need = uint64_t(pg.num_values) * w;
    if (written + need > c->out_cap) return kColBounds;
    if (pg.encoding == 0) {  // PLAIN: the values region IS the rows
      if (need > vlen) return kColBounds;
      std::memcpy(c->out + written, vals, need);
    } else {  // PLAIN_DICTIONARY / RLE_DICTIONARY indices
      if (dict_vals == nullptr) return kColDict;
      if (vlen < 1) return kColParse;
      const int bw = vals[0];
      if (!decode_hybrid(vals + 1, vals + vlen, bw, pg.num_values, &idx)) {
        return kColParse;
      }
      uint8_t* dst = c->out + written;
      for (int64_t i = 0; i < pg.num_values; i++) {
        const uint32_t k = idx[size_t(i)];
        if (k >= n_dict) return kColDict;
        std::memcpy(dst + uint64_t(i) * w, dict_vals + uint64_t(k) * w, w);
      }
    }
    written += need;
    rows += pg.num_values;
  }
  if (rows != c->expected_rows) return kColRows;
  c->out_used = written;
  return kColOk;
}

// Collect the byte-array cells of a BYTE_ARRAY chunk (PLAIN length-prefixed
// values, or dictionary indices into length-prefixed dictionary entries).
// Cell pointers alias the chunk or the scratch vectors pushed onto
// `scratches` — which the caller must keep alive while the cells are in use.
int collect_cells(const FusedCol& c, const std::vector<PageRec>& pages,
                  std::vector<std::pair<const uint8_t*, uint64_t>>* cells,
                  std::vector<std::vector<uint8_t>>* scratches) {
  std::vector<std::pair<const uint8_t*, uint64_t>> dict_entries;
  std::vector<uint32_t> idx;
  for (const PageRec& pg : pages) {
    scratches->emplace_back();
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    int rc = page_values(c, pg, &scratches->back(), &vals, &vlen);
    if (rc != kColOk) return rc;
    if (pg.is_dict) {
      dict_entries.clear();
      dict_entries.reserve(size_t(pg.num_values));
      uint64_t off = 0;
      for (int64_t i = 0; i < pg.num_values; i++) {
        if (off + 4 > vlen) return kColDict;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColDict;
        dict_entries.emplace_back(vals + off, uint64_t(n));
        off += n;
      }
      continue;
    }
    if (pg.encoding == 0) {  // PLAIN: <u32 len><bytes> per value
      uint64_t off = 0;
      for (int64_t i = 0; i < pg.num_values; i++) {
        if (off + 4 > vlen) return kColBounds;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColBounds;
        cells->emplace_back(vals + off, uint64_t(n));
        off += n;
      }
    } else {  // dictionary indices
      if (dict_entries.empty() && pg.num_values > 0) return kColDict;
      if (vlen < 1) return kColParse;
      if (!decode_hybrid(vals + 1, vals + vlen, vals[0], pg.num_values, &idx)) {
        return kColParse;
      }
      for (int64_t i = 0; i < pg.num_values; i++) {
        const uint32_t k = idx[size_t(i)];
        if (k >= dict_entries.size()) return kColDict;
        cells->push_back(dict_entries[size_t(k)]);
      }
    }
  }
  if (int64_t(cells->size()) != c.expected_rows) return kColRows;
  return kColOk;
}

// np.save header span of one cell: magic + version + 2/4-byte header length.
// Returns 0 when the cell is not a standard v1/v2 npy payload.
uint64_t npy_header_len(const uint8_t* p, uint64_t n) {
  static const uint8_t kMagic[6] = {0x93, 'N', 'U', 'M', 'P', 'Y'};
  if (n < 12 || std::memcmp(p, kMagic, 6) != 0) return 0;
  uint64_t data_off;
  if (p[6] == 1) {
    data_off = 10 + (uint64_t(p[8]) | (uint64_t(p[9]) << 8));
  } else if (p[6] == 2) {
    uint32_t hl = 0;
    std::memcpy(&hl, p + 8, 4);
    data_off = 12 + uint64_t(hl);
  } else {
    return 0;
  }
  return data_off <= n ? data_off : 0;
}

// Collate pre-collected byte-array cells (all rows, or the predicate-selected
// subset) into the column's output region; the first cell defines the npy
// header when stripping.
int decode_binary_raw_cells(
    FusedCol* c, const std::vector<std::pair<const uint8_t*, uint64_t>>& cells) {
  if (cells.empty()) return kColRows;
  const uint64_t cell_len = cells[0].second;
  uint64_t prefix = 0;
  if (c->strip_npy) {
    prefix = npy_header_len(cells[0].first, cell_len);
    if (prefix == 0) return kColNonUniform;
    if (prefix > c->aux_cap || c->aux_buf == nullptr) return kColNonUniform;
    std::memcpy(c->aux_buf, cells[0].first, prefix);
    c->aux1 = prefix;
  }
  const uint64_t payload = cell_len - prefix;
  uint64_t written = 0;
  for (const auto& cell : cells) {
    if (cell.second != cell_len) return kColNonUniform;
    if (prefix != 0 && std::memcmp(cell.first, cells[0].first, prefix) != 0) {
      return kColNonUniform;  // mixed shapes/dtypes within the chunk
    }
    if (written + payload > c->out_cap) return kColBounds;
    std::memcpy(c->out + written, cell.first + prefix, payload);
    written += payload;
  }
  c->aux0 = payload;
  c->out_used = written;
  return kColOk;
}

int decode_binary_raw(FusedCol* c, const std::vector<PageRec>& pages) {
  std::vector<std::pair<const uint8_t*, uint64_t>> cells;
  std::vector<std::vector<uint8_t>> scratches;
  int rc = collect_cells(*c, pages, &cells, &scratches);
  if (rc != kColOk) return rc;
  return decode_binary_raw_cells(c, cells);
}

int decode_binary_img_cells(
    FusedCol* c, const std::vector<std::pair<const uint8_t*, uint64_t>>& cells,
    ImgProbeFn probe, ImgDecodeFn decode) {
  if (probe == nullptr || decode == nullptr) return kColImgProbe;
  const long long n = (long long)cells.size();
  if (n == 0) return kColRows;
  const size_t un = size_t(n);
  std::vector<void*> ptrs(un);
  std::vector<unsigned long long> lens(un);
  for (size_t i = 0; i < un; i++) {
    ptrs[i] = const_cast<uint8_t*>(cells[i].first);
    lens[i] = cells[i].second;
  }
  std::vector<int32_t> infos(un * 4);
  if (probe(n, ptrs.data(), lens.data(), infos.data(), 0, 0) != -1) {
    return kColImgProbe;
  }
  const uint64_t row_bytes =
      uint64_t(c->img_h) * uint64_t(c->img_w) * uint64_t(c->img_c);
  for (long long i = 0; i < n; i++) {
    const int32_t* info = &infos[size_t(i) * 4];  // (w, h, c, depth)
    if (info[0] != c->img_w || info[1] != c->img_h || info[2] != c->img_c ||
        info[3] != 8) {
      return kColImgDims;
    }
  }
  // division form: n * row_bytes would wrap for corrupt huge dimensions,
  // sneaking a tiny product past the capacity check (PT903)
  if (row_bytes == 0 || uint64_t(n) > c->out_cap / row_bytes) return kColBounds;
  std::vector<void*> outs(un);
  for (size_t i = 0; i < un; i++) outs[i] = c->out + uint64_t(i) * row_bytes;
  const int threads = c->img_threads > 0 ? c->img_threads : 1;
  if (decode(n, ptrs.data(), lens.data(), outs.data(), infos.data(), threads,
             0, 0) != -1) {
    return kColImgDecode;
  }
  c->aux0 = row_bytes;
  c->out_used = uint64_t(n) * row_bytes;
  return kColOk;
}

int decode_binary_img(FusedCol* c, const std::vector<PageRec>& pages,
                      ImgProbeFn probe, ImgDecodeFn decode) {
  std::vector<std::pair<const uint8_t*, uint64_t>> cells;
  std::vector<std::vector<uint8_t>> scratches;
  int rc = collect_cells(*c, pages, &cells, &scratches);
  if (rc != kColOk) return rc;
  return decode_binary_img_cells(c, cells, probe, decode);
}

void decode_fused_column(FusedCol* c, int max_pages, ImgProbeFn probe,
                         ImgDecodeFn decode) {
  try {
    if (c->chunk == nullptr || c->out == nullptr || c->expected_rows < 0) {
      c->status = kColInternal;
      return;
    }
    std::vector<PageRec> pages;
    int rc = scan_fused_pages(*c, max_pages, &pages);
    if (rc == kColOk) {
      switch (c->mode) {
        case kModeFixed: rc = decode_fixed(c, pages); break;
        case kModeBinaryRaw: rc = decode_binary_raw(c, pages); break;
        case kModeBinaryImg: rc = decode_binary_img(c, pages, probe, decode); break;
        default: rc = kColInternal;
      }
    }
    c->status = rc;
  } catch (...) {  // bad_alloc etc.: fail the column, never the process
    c->status = kColInternal;
  }
}

// ---------------------------------------------------------------------------
// native predicate pushdown: evaluate equality/set/range clauses against the
// decoded predicate columns, emit a row-selection bitmap, and gate the output
// collation on it — all inside the same GIL-released call.

enum { kPredIn = 0, kPredRange = 1 };
enum { kPredI32 = 0, kPredI64 = 1, kPredU32 = 2, kPredU64 = 3,
       kPredF32 = 4, kPredF64 = 5 };

inline int pred_width(int dtype) {
  switch (dtype) {
    case kPredI32: case kPredU32: case kPredF32: return 4;
    case kPredI64: case kPredU64: case kPredF64: return 8;
    default: return 0;
  }
}

// -1/0/+1 three-way compare of two little-endian scalars; -2 when either
// float operand is NaN (float order is partial — callers must not trust it)
int pred_cmp(int dtype, const uint8_t* a, const uint8_t* b) {
  switch (dtype) {
    case kPredI32: {
      int32_t x, y;
      std::memcpy(&x, a, 4);
      std::memcpy(&y, b, 4);
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    case kPredI64: {
      int64_t x, y;
      std::memcpy(&x, a, 8);
      std::memcpy(&y, b, 8);
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    case kPredU32: {
      uint32_t x, y;
      std::memcpy(&x, a, 4);
      std::memcpy(&y, b, 4);
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    case kPredU64: {
      uint64_t x, y;
      std::memcpy(&x, a, 8);
      std::memcpy(&y, b, 8);
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    case kPredF32: {
      float x, y;
      std::memcpy(&x, a, 4);
      std::memcpy(&y, b, 4);
      if (x != x || y != y) return -2;
      return x < y ? -1 : (x > y ? 1 : 0);
    }
    case kPredF64: {
      double x, y;
      std::memcpy(&x, a, 8);
      std::memcpy(&y, b, 8);
      if (x != x || y != y) return -2;
      return x < y ? -1 : (x > y ? 1 : 0);
    }
  }
  return -2;
}

// does one decoded value satisfy the clause? (NaN matches nothing before
// negation — the vectorized numpy fallback behaves identically)
bool pred_match_value(const FusedPred& p, const uint8_t* v, int w) {
  bool m;
  if (p.op == kPredIn) {
    m = false;
    for (int64_t i = 0; i < p.count; i++) {
      const uint8_t* e = p.values + uint64_t(i) * uint64_t(w);
      if (pred_cmp(p.dtype, v, e) == 0) { m = true; break; }
    }
  } else {
    m = true;
    if (p.has_lo) {
      const int c = pred_cmp(p.dtype, v, p.values);
      if (c == -2 || c < 0 || (c == 0 && !p.lo_incl)) m = false;
    }
    if (m && p.has_hi) {
      const int c = pred_cmp(p.dtype, v, p.values + uint64_t(w));
      if (c == -2 || c > 0 || (c == 0 && !p.hi_incl)) m = false;
    }
  }
  return p.negate ? !m : m;
}

// page-stat verdict for one clause: 1 = every row matches, -1 = none does,
// 0 = undecided (decode required). Sound only because fused qualification
// already proved the chunk null-free; an explicit positive null_count (or
// absent/NaN/odd-width min-max) always degrades to "decode everything".
int pred_stats_verdict(const FusedPred& p, const PageRec& pg, int w) {
  if (pg.stat_null_count > 0) return 0;
  if (pg.stat_min == nullptr || pg.stat_max == nullptr) return 0;
  if (pg.stat_min_len != w || pg.stat_max_len != w) return 0;
  if (pred_cmp(p.dtype, pg.stat_min, pg.stat_max) == -2) return 0;
  int v = 0;
  if (p.op == kPredRange) {
    bool none = false, all = true;
    if (p.has_lo) {
      const int cmax = pred_cmp(p.dtype, pg.stat_max, p.values);
      const int cmin = pred_cmp(p.dtype, pg.stat_min, p.values);
      if (cmax == -2 || cmin == -2) return 0;
      if (cmax < 0 || (cmax == 0 && !p.lo_incl)) none = true;
      if (cmin < 0 || (cmin == 0 && !p.lo_incl)) all = false;
    }
    if (p.has_hi) {
      const int cmin = pred_cmp(p.dtype, pg.stat_min, p.values + uint64_t(w));
      const int cmax = pred_cmp(p.dtype, pg.stat_max, p.values + uint64_t(w));
      if (cmin == -2 || cmax == -2) return 0;
      if (cmin > 0 || (cmin == 0 && !p.hi_incl)) none = true;
      if (cmax > 0 || (cmax == 0 && !p.hi_incl)) all = false;
    }
    v = none ? -1 : (all ? 1 : 0);
  } else {  // kPredIn
    bool any_inside = false;
    for (int64_t i = 0; i < p.count; i++) {
      const uint8_t* e = p.values + uint64_t(i) * uint64_t(w);
      const int cl = pred_cmp(p.dtype, e, pg.stat_min);
      const int ch = pred_cmp(p.dtype, e, pg.stat_max);
      if (cl == -2 || ch == -2) continue;  // a NaN operand matches nothing
      if (cl >= 0 && ch <= 0) { any_inside = true; break; }
    }
    if (!any_inside) {
      v = -1;
    } else if (pred_cmp(p.dtype, pg.stat_min, pg.stat_max) == 0) {
      v = 1;  // single-valued page whose value is in the set
    }
  }
  return p.negate ? -v : v;
}

inline bool sel_get(const uint8_t* sel, uint64_t i) {
  return (sel[i >> 3] >> (i & 7)) & 1;
}
inline void sel_clear(uint8_t* sel, uint64_t i) {
  sel[i >> 3] = uint8_t(sel[i >> 3] & ~(uint32_t(1) << (i & 7)));
}
inline bool sel_any(const uint8_t* sel, uint64_t row0, uint64_t n) {
  for (uint64_t i = 0; i < n; i++) {
    if (sel_get(sel, row0 + i)) return true;
  }
  return false;
}

// Phase 1 over one predicate column: page row-spans come from the cumulative
// value counts; pages the statistics prove irrelevant (or that an earlier
// clause already fully deselected) skip decode entirely.
int eval_pred_column(FusedCol* pc, const std::vector<FusedPred*>& clauses,
                     uint8_t* sel, int max_pages, long long* pages_skipped) {
  if (clauses.empty()) return kColInternal;
  const int w = pred_width(clauses[0]->dtype);
  for (const FusedPred* p : clauses) {
    if (pred_width(p->dtype) != w) return kColParse;
  }
  if (w == 0 || pc->mode != kModeFixed || pc->itemsize != w) return kColParse;
  std::vector<PageRec> pages;
  int rc = scan_fused_pages(*pc, max_pages, &pages);
  if (rc != kColOk) return rc;
  std::vector<uint8_t> dict_store, scratch;
  std::vector<uint32_t> idx;
  const uint8_t* dict_vals = nullptr;
  uint64_t n_dict = 0;
  uint64_t row0 = 0;
  const uint64_t uw = uint64_t(w);
  for (const PageRec& pg : pages) {
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    if (pg.is_dict) {
      rc = page_values(*pc, pg, &dict_store, &vals, &vlen);
      if (rc != kColOk) return rc;
      if (uint64_t(pg.num_values) > vlen / uw) return kColDict;
      dict_vals = pc->codec == kCodecUncompressed ? vals : dict_store.data();
      n_dict = uint64_t(pg.num_values);
      continue;
    }
    const uint64_t nv = uint64_t(pg.num_values);
    if (row0 + nv > uint64_t(pc->expected_rows)) return kColRows;
    bool page_none = false, page_all = true;
    for (const FusedPred* p : clauses) {
      const int v = pred_stats_verdict(*p, pg, w);
      if (v < 0) page_none = true;
      if (v <= 0) page_all = false;
    }
    if (page_none) {
      for (uint64_t i = 0; i < nv; i++) sel_clear(sel, row0 + i);
      (*pages_skipped)++;
      row0 += nv;
      continue;
    }
    if (page_all || !sel_any(sel, row0, nv)) {
      (*pages_skipped)++;
      row0 += nv;
      continue;
    }
    rc = page_values(*pc, pg, &scratch, &vals, &vlen);
    if (rc != kColOk) return rc;
    if (pg.encoding == 0) {  // PLAIN
      if (nv > vlen / uw) return kColBounds;
      for (uint64_t i = 0; i < nv; i++) {
        if (!sel_get(sel, row0 + i)) continue;
        const uint8_t* v = vals + i * uw;
        for (const FusedPred* p : clauses) {
          if (!pred_match_value(*p, v, w)) { sel_clear(sel, row0 + i); break; }
        }
      }
    } else {  // dictionary indices
      if (dict_vals == nullptr) return kColDict;
      if (vlen < 1) return kColParse;
      if (!decode_hybrid(vals + 1, vals + vlen, vals[0], pg.num_values, &idx)) {
        return kColParse;
      }
      for (uint64_t i = 0; i < nv; i++) {
        if (!sel_get(sel, row0 + i)) continue;
        const uint32_t k = idx[size_t(i)];
        if (k >= n_dict) return kColDict;
        const uint8_t* v = dict_vals + uint64_t(k) * uw;
        for (const FusedPred* p : clauses) {
          if (!pred_match_value(*p, v, w)) { sel_clear(sel, row0 + i); break; }
        }
      }
    }
    row0 += nv;
  }
  if (row0 != uint64_t(pc->expected_rows)) return kColRows;
  return kColOk;
}

// Phase 2 fixed-width gather: only the selected rows reach the output region;
// pages with no selected rows skip decompression entirely.
int decode_fixed_gather(FusedCol* c, const std::vector<PageRec>& pages,
                        const uint8_t* sel, long long n_selected,
                        long long* pages_skipped) {
  const uint64_t w = uint64_t(c->itemsize);
  if (w == 0 || w > (64u << 20)) return kColParse;
  std::vector<uint8_t> dict_store, scratch;
  std::vector<uint32_t> idx;
  const uint8_t* dict_vals = nullptr;
  uint64_t n_dict = 0;
  uint64_t written = 0;
  uint64_t row0 = 0;
  for (const PageRec& pg : pages) {
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    if (pg.is_dict) {
      int rc = page_values(*c, pg, &dict_store, &vals, &vlen);
      if (rc != kColOk) return rc;
      const uint64_t dict_n = uint64_t(pg.num_values);
      if (dict_n > vlen / w) return kColDict;
      dict_vals = c->codec == kCodecUncompressed ? vals : dict_store.data();
      n_dict = dict_n;
      continue;
    }
    const uint64_t nv = uint64_t(pg.num_values);
    if (row0 + nv > uint64_t(c->expected_rows)) return kColRows;
    if (!sel_any(sel, row0, nv)) {
      (*pages_skipped)++;
      row0 += nv;
      continue;
    }
    int rc = page_values(*c, pg, &scratch, &vals, &vlen);
    if (rc != kColOk) return rc;
    if (pg.encoding == 0) {  // PLAIN
      if (nv > vlen / w) return kColBounds;
      for (uint64_t i = 0; i < nv; i++) {
        if (!sel_get(sel, row0 + i)) continue;
        if (c->out_cap - written < w) return kColBounds;
        std::memcpy(c->out + written, vals + i * w, w);
        written += w;
      }
    } else {  // dictionary indices
      if (dict_vals == nullptr) return kColDict;
      if (vlen < 1) return kColParse;
      if (!decode_hybrid(vals + 1, vals + vlen, vals[0], pg.num_values, &idx)) {
        return kColParse;
      }
      for (uint64_t i = 0; i < nv; i++) {
        if (!sel_get(sel, row0 + i)) continue;
        const uint32_t k = idx[size_t(i)];
        if (k >= n_dict) return kColDict;
        if (c->out_cap - written < w) return kColBounds;
        std::memcpy(c->out + written, dict_vals + uint64_t(k) * w, w);
        written += w;
      }
    }
    row0 += nv;
  }
  if (row0 != uint64_t(c->expected_rows)) return kColRows;
  if (written != uint64_t(n_selected) * w) return kColRows;
  c->out_used = written;
  return kColOk;
}

// Phase 2 byte-array gather: dictionary pages always decode (any row may
// reference them); data pages with no selected rows are skipped.
int collect_cells_gather(const FusedCol& c, const std::vector<PageRec>& pages,
                         const uint8_t* sel, long long n_selected,
                         std::vector<std::pair<const uint8_t*, uint64_t>>* cells,
                         std::vector<std::vector<uint8_t>>* scratches,
                         long long* pages_skipped) {
  std::vector<std::pair<const uint8_t*, uint64_t>> dict_entries;
  std::vector<uint32_t> idx;
  uint64_t row0 = 0;
  for (const PageRec& pg : pages) {
    if (pg.is_dict) {
      scratches->emplace_back();
      const uint8_t* vals = nullptr;
      uint64_t vlen = 0;
      int rc = page_values(c, pg, &scratches->back(), &vals, &vlen);
      if (rc != kColOk) return rc;
      dict_entries.clear();
      dict_entries.reserve(size_t(pg.num_values));
      uint64_t off = 0;
      for (int64_t i = 0; i < pg.num_values; i++) {
        if (off + 4 > vlen) return kColDict;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColDict;
        dict_entries.emplace_back(vals + off, uint64_t(n));
        off += n;
      }
      continue;
    }
    const uint64_t nv = uint64_t(pg.num_values);
    if (row0 + nv > uint64_t(c.expected_rows)) return kColRows;
    if (!sel_any(sel, row0, nv)) {
      (*pages_skipped)++;
      row0 += nv;
      continue;
    }
    scratches->emplace_back();
    const uint8_t* vals = nullptr;
    uint64_t vlen = 0;
    int rc = page_values(c, pg, &scratches->back(), &vals, &vlen);
    if (rc != kColOk) return rc;
    if (pg.encoding == 0) {  // PLAIN: <u32 len><bytes>; walk all, keep selected
      uint64_t off = 0;
      for (uint64_t i = 0; i < nv; i++) {
        if (off + 4 > vlen) return kColBounds;
        uint32_t n = 0;
        std::memcpy(&n, vals + off, 4);
        off += 4;
        if (uint64_t(n) > vlen - off) return kColBounds;
        if (sel_get(sel, row0 + i)) cells->emplace_back(vals + off, uint64_t(n));
        off += n;
      }
    } else {  // dictionary indices
      if (dict_entries.empty() && nv > 0) return kColDict;
      if (vlen < 1) return kColParse;
      if (!decode_hybrid(vals + 1, vals + vlen, vals[0], pg.num_values, &idx)) {
        return kColParse;
      }
      for (uint64_t i = 0; i < nv; i++) {
        if (!sel_get(sel, row0 + i)) continue;
        const uint32_t k = idx[size_t(i)];
        if (k >= dict_entries.size()) return kColDict;
        cells->push_back(dict_entries[size_t(k)]);
      }
    }
    row0 += nv;
  }
  if (row0 != uint64_t(c.expected_rows)) return kColRows;
  if (int64_t(cells->size()) != int64_t(n_selected)) return kColRows;
  return kColOk;
}

void decode_fused_column_gather(FusedCol* c, const uint8_t* sel,
                                long long n_selected, int max_pages,
                                ImgProbeFn probe, ImgDecodeFn decode,
                                std::atomic<long long>* pages_skipped) {
  try {
    if (c->chunk == nullptr || c->out == nullptr || c->expected_rows < 0) {
      c->status = kColInternal;
      return;
    }
    std::vector<PageRec> pages;
    int rc = scan_fused_pages(*c, max_pages, &pages);
    long long skipped = 0;
    if (rc == kColOk && c->mode == kModeFixed) {
      rc = decode_fixed_gather(c, pages, sel, n_selected, &skipped);
    } else if (rc == kColOk &&
               (c->mode == kModeBinaryRaw || c->mode == kModeBinaryImg)) {
      std::vector<std::pair<const uint8_t*, uint64_t>> cells;
      std::vector<std::vector<uint8_t>> scratches;
      rc = collect_cells_gather(*c, pages, sel, n_selected, &cells, &scratches,
                                &skipped);
      if (rc == kColOk) {
        rc = c->mode == kModeBinaryRaw
                 ? decode_binary_raw_cells(c, cells)
                 : decode_binary_img_cells(c, cells, probe, decode);
      }
    } else if (rc == kColOk) {
      rc = kColInternal;
    }
    pages_skipped->fetch_add(skipped);
    c->status = rc;
  } catch (...) {
    c->status = kColInternal;
  }
}

}  // namespace

extern "C" {

// Decode a whole batch of column chunks into their preallocated regions of
// one contiguous batch buffer. Runs on up to `n_threads` C++ threads (the
// calling thread participates); the caller holds no GIL (ctypes releases it),
// so this is the single Python<->C transition of the batch. Returns the
// number of columns whose status != OK (callers re-read those via Arrow), or
// -1 on invalid arguments.
long long pstpu_read_fused(struct FusedCol* cols, int n_cols, int n_threads,
                           int max_pages, void* img_probe_fn, void* img_decode_fn) {
  if (cols == nullptr || n_cols < 0 || max_pages < 1) {
    set_error("pstpu_read_fused: invalid arguments");
    return -1;
  }
  const ImgProbeFn probe = reinterpret_cast<ImgProbeFn>(img_probe_fn);
  const ImgDecodeFn decode = reinterpret_cast<ImgDecodeFn>(img_decode_fn);
  std::atomic<int> next{0};
  auto run = [&]() {
    while (true) {
      const int i = next.fetch_add(1);
      if (i >= n_cols) return;
      decode_fused_column(&cols[i], max_pages, probe, decode);
    }
  };
  int fanout = n_threads;
  if (fanout < 1) fanout = 1;
  if (fanout > n_cols) fanout = n_cols;
  std::vector<std::thread> pool;
  for (int t = 1; t < fanout; t++) pool.emplace_back(run);
  run();
  for (auto& th : pool) th.join();
  long long failed = 0;
  for (int i = 0; i < n_cols; i++) {
    if (cols[i].status != kColOk) failed++;
  }
  return failed;
}

// Predicate-pushdown variant of pstpu_read_fused: decode the predicate
// columns (`pred_cols`, indexed by preds[i].col — they never collate), AND
// every clause into the caller's `sel` bitmap with page-stat skipping, then
// gather only the selected rows of the output columns — one GIL-released
// call for the whole filtered batch. Returns the number of columns/clauses
// whose status != OK (callers fall back to the unfused path for the block),
// or -1 on invalid arguments.
long long pstpu_read_fused_pred(struct FusedCol* cols, int n_cols,
                                struct FusedCol* pred_cols, int n_pred_cols,
                                struct FusedPred* preds, int n_preds,
                                uint8_t* sel, unsigned long long sel_cap,
                                long long total_rows, int n_threads,
                                int max_pages, void* img_probe_fn,
                                void* img_decode_fn, long long* out_selected,
                                long long* out_pages_skipped) {
  if (cols == nullptr || pred_cols == nullptr || preds == nullptr ||
      sel == nullptr || out_selected == nullptr || out_pages_skipped == nullptr ||
      n_cols < 0 || n_pred_cols < 1 || n_preds < 1 || total_rows < 0 ||
      max_pages < 1) {
    set_error("pstpu_read_fused_pred: invalid arguments");
    return -1;
  }
  const uint64_t sel_bytes = (uint64_t(total_rows) + 7) / 8;
  if (sel_cap < sel_bytes) {
    set_error("pstpu_read_fused_pred: selection bitmap too small");
    return -1;
  }
  std::vector<std::vector<FusedPred*>> by_col;
  by_col.resize(size_t(n_pred_cols));
  for (int i = 0; i < n_preds; i++) {
    FusedPred* p = &preds[i];
    const int w = pred_width(p->dtype);
    if (p->col < 0 || p->col >= n_pred_cols || w == 0 ||
        (p->op != kPredIn && p->op != kPredRange) || p->values == nullptr) {
      set_error("pstpu_read_fused_pred: invalid predicate clause");
      return -1;
    }
    if (p->op == kPredIn) {
      // division form: count * w would wrap for a hostile operand count
      if (p->count < 0 || uint64_t(p->count) > p->values_cap / uint64_t(w)) {
        set_error("pstpu_read_fused_pred: operand buffer too small");
        return -1;
      }
    } else if (p->values_cap / uint64_t(w) < 2) {  // packed [lo][hi]
      set_error("pstpu_read_fused_pred: range buffer too small");
      return -1;
    }
    by_col[size_t(p->col)].push_back(p);
  }
  // all rows start selected; the tail bits of the last byte stay clear so the
  // popcount below is exact
  std::memset(sel, 0xFF, size_t(sel_bytes));
  if (total_rows & 7) {
    sel[sel_bytes - 1] = uint8_t((1u << (total_rows & 7)) - 1);
  }
  // phase 1 (serial): narrow the bitmap one predicate column at a time
  long long skipped_total = 0;
  long long pred_failed = 0;
  for (int ci = 0; ci < n_pred_cols; ci++) {
    FusedCol* pc = &pred_cols[ci];
    long long col_skipped = 0;
    int rc;
    if (by_col[size_t(ci)].empty()) {
      rc = kColOk;
    } else if (pc->chunk == nullptr || pc->expected_rows != total_rows) {
      rc = kColInternal;
    } else {
      try {
        rc = eval_pred_column(pc, by_col[size_t(ci)], sel, max_pages,
                              &col_skipped);
      } catch (...) {
        rc = kColInternal;
      }
    }
    pc->status = rc;
    for (FusedPred* p : by_col[size_t(ci)]) {
      p->status = rc;
      p->pages_skipped = int32_t(col_skipped);
    }
    skipped_total += col_skipped;
    if (rc != kColOk) pred_failed++;
  }
  long long n_selected = 0;
  for (uint64_t i = 0; i < sel_bytes; i++) {
    n_selected += __builtin_popcount(sel[i]);
  }
  *out_selected = n_selected;
  if (pred_failed > 0) {
    // callers treat any failure as whole-block fallback: make sure no output
    // column looks spuriously decoded
    for (int i = 0; i < n_cols; i++) cols[i].status = kColInternal;
    *out_pages_skipped = skipped_total;
    return pred_failed + n_cols;
  }
  // phase 2 (parallel): gather the selected rows of every output column
  std::atomic<long long> skipped2{0};
  if (n_selected == 0) {
    // nothing survived: every data page of every output column is skipped
    // work; callers build an empty block without touching the buffers
    for (int i = 0; i < n_cols; i++) {
      cols[i].status = kColOk;
      cols[i].out_used = 0;
      cols[i].aux0 = 0;
      cols[i].aux1 = 0;
    }
  } else {
    const ImgProbeFn probe = reinterpret_cast<ImgProbeFn>(img_probe_fn);
    const ImgDecodeFn decode = reinterpret_cast<ImgDecodeFn>(img_decode_fn);
    std::atomic<int> next{0};
    auto run = [&]() {
      while (true) {
        const int i = next.fetch_add(1);
        if (i >= n_cols) return;
        decode_fused_column_gather(&cols[i], sel, n_selected, max_pages, probe,
                                   decode, &skipped2);
      }
    };
    int fanout = n_threads;
    if (fanout < 1) fanout = 1;
    if (fanout > n_cols) fanout = n_cols;
    std::vector<std::thread> pool;
    for (int t = 1; t < fanout; t++) pool.emplace_back(run);
    run();
    for (auto& th : pool) th.join();
  }
  *out_pages_skipped = skipped_total + skipped2.load();
  long long failed = 0;
  for (int i = 0; i < n_cols; i++) {
    if (cols[i].status != kColOk) failed++;
  }
  return failed;
}

int pstpu_abi_version() { return 4; }

}  // extern "C"
