"""Zero-copy Parquet column reads via the first-party page scanner.

The reference (and this framework's Arrow path, ``rowgroup_reader.cpp``)
decodes every column through Arrow C++, which ASSEMBLES a fresh contiguous
buffer per column chunk — for the decode-free ``RawTensorCodec`` training
stores (uncompressed, PLAIN, fixed-width), that assembly is the entire host
cost of a read (~84% of profile on the raw ImageNet store). This module
removes it: the C++ scanner (``pstpu_scan_plain_pages``,
``rowgroup_reader.cpp``) parses the thrift-compact page headers first-party,
and each page's values region becomes an Arrow array VIEW over the mmapped
file — zero bytes copied; the OS page cache is the only storage layer.

Qualification is strict and checked per column chunk from the Parquet
metadata: local file, UNCOMPRESSED codec, PLAIN-only encodings (plus the
level encodings), flat non-nested path with ``max_definition_level == 0``
(REQUIRED) — or ``== 1`` when the chunk statistics PROVE null_count == 0, in
which case the page's RLE definition-levels block is skipped (the
nullable-by-default layout ordinary writers produce) — and physical type
FIXED_LEN_BYTE_ARRAY / INT32 / INT64 / FLOAT / DOUBLE (BOOLEAN is
bit-packed, INT96 is legacy — both excluded). Anything else returns None and
the caller uses the Arrow path; mixed tables split per column, so one
dictionary-encoded label column does not forfeit the zero-copy image column
next to it.

Parity note: no reference counterpart — the reference reads everything
through pyarrow (py_dict_reader_worker.py:254-258). This is the SURVEY §2.10
"first-party Parquet reader" earned for the hot case, with Arrow kept for the
long tail.
"""

from __future__ import annotations

import ctypes
import logging
import threading

import numpy as np
import pyarrow as pa

from petastorm_tpu import observability as obs

logger = logging.getLogger(__name__)

#: physical-type -> (arrow type factory, itemsize); FLBA handled separately
_PHYSICAL_FIXED = {
    'INT32': (pa.int32, 4),
    'INT64': (pa.int64, 8),
    'FLOAT': (pa.float32, 4),
    'DOUBLE': (pa.float64, 8),
}

_MAX_PAGES = 4096

#: per-thread scratch for the scanner's out-arrays — allocating (and zeroing)
#: 64KB of ctypes arrays per call measured at 0.33ms on the bench host,
#: comparable to the scan itself
_scratch = threading.local()

#: chunks that overflowed _MAX_PAGES warn once per process (the counter keeps
#: counting; the log line just must not spam every batch of a pathological
#: store)
_page_cap_warned = False


def _scratch_arrays():
    arrays = getattr(_scratch, 'arrays', None)
    if arrays is None:
        arrays = ((ctypes.c_ulonglong * _MAX_PAGES)(),
                  (ctypes.c_longlong * _MAX_PAGES)(),
                  (ctypes.c_ulonglong * _MAX_PAGES)())
        _scratch.arrays = arrays
    return arrays


def _note_scan_failure(lib, where):
    """A scan returned -1: most causes are ordinary qualification gaps the
    caller already accounts, but overflowing the ``_MAX_PAGES`` cap is a
    CONFIGURATION edge (a chunk with more pages silently losing the fast path
    forever) — it gets a labelled counter and a one-time warning instead of a
    silent fallback."""
    global _page_cap_warned
    err = lib.pstpu_last_error().decode('utf-8', 'replace')
    if 'max_pages' not in err:
        return
    obs.count('pagescan_fallback_reason:page-cap')
    if not _page_cap_warned:
        _page_cap_warned = True
        logger.warning(
            'page scan of %s hit the %d-page-per-chunk cap and fell back to '
            'Arrow; this store writes unusually small pages — rewrite it with '
            'a larger data_page_size to recover the zero-copy path', where,
            _MAX_PAGES)


class _MmapPool(object):
    """One long-lived read-only mmap per file path. Arrays built over it hold
    a reference to the mmap object through ``pa.py_buffer``'s base, so the
    mapping outlives the pool entry; dropping the pool entry on close only
    stops NEW views."""

    def __init__(self):
        self._maps = {}

    def get(self, path):
        """The pool's read-only mapping of ``path``, created on first use.

        :borrows: every array served zero-copy from ``path`` aliases this
            mapping; the registry slot keeps it visible in
            ``lifetime_live_borrows`` until the last such array dies."""
        mm = self._maps.get(path)
        if mm is None:
            mm = np.memmap(path, dtype=np.uint8, mode='r')
            from petastorm_tpu.native.lifetime import registry
            slot = registry().open_slot(label='pagescan-mmap')
            slot.adopt(mm)
            slot.seal()
            self._maps[path] = mm
        return mm

    def close(self):
        self._maps.clear()


def _column_qualifies(meta_col, max_def_level, max_rep_level):
    """True/False, or the string 'def' for OPTIONAL columns the statistics
    PROVE null-free — their pages lead with an RLE def-levels block the
    scanner skips (nullable-by-default writers are the common real-world
    case; an actual null would desynchronize the values region). Any
    repetition (legacy top-level `repeated` primitives have a dot-free path
    AND max_def_level 1, but their pages lead with a repetition-levels block
    too) disqualifies."""
    if max_rep_level != 0 or max_def_level > 1:
        return False
    if max_def_level == 1:
        stats = meta_col.statistics
        if stats is None or stats.null_count is None or stats.null_count != 0:
            return False
    if meta_col.compression != 'UNCOMPRESSED':
        return False
    # PLAIN data pages only; RLE appears as the level encoding
    if any(e not in ('PLAIN', 'RLE', 'BIT_PACKED') for e in meta_col.encodings):
        return False
    if meta_col.has_dictionary_page:
        return False
    pt = meta_col.physical_type
    if pt != 'FIXED_LEN_BYTE_ARRAY' and pt not in _PHYSICAL_FIXED:
        return False
    return 'def' if max_def_level == 1 else True


#: public alias: the chunk store qualifies remote chunks with the exact same
#: strict check the local path uses (chunkstore/reader.py)
def column_qualifies(meta_col, max_def_level, max_rep_level):
    return _column_qualifies(meta_col, max_def_level, max_rep_level)


def scan_mirrored_chunk(lib, mm, meta_col, has_def_levels=False):
    """Page plan ``[(offset_in_mirror, num_values, values_region_len)]`` for a
    byte-exact LOCAL MIRROR of a column chunk (the chunk bytes alone, at
    offset 0), or ``None``. The mirror must be exactly
    ``total_compressed_size`` bytes — a truncated or over-long mirror means
    the cache entry does not match the footer metadata, so it is unusable.

    The plan depends only on the mirror's bytes, which are content-addressed
    and immutable in the chunk store — callers cache it per chunk key and
    skip the re-scan on every warm read."""
    length = int(mm.size)
    if length <= 0 or length != meta_col.total_compressed_size:
        return None
    offs, counts, vlens = _scratch_arrays()
    n = lib.pstpu_scan_plain_pages(
        mm.ctypes.data_as(ctypes.c_void_p), length, offs, counts, vlens,
        _MAX_PAGES, 1 if has_def_levels else 0)
    if n < 0:
        _note_scan_failure(lib, 'mirrored chunk')
        return None
    return [(offs[i], counts[i], vlens[i]) for i in range(n)]


def read_mirrored_chunk(lib, mm, meta_col, expected_rows, flba_width,
                        has_def_levels=False, require_exact=True, pages=None):
    """Arrow arrays (one per page) for a column chunk served from a byte-exact
    LOCAL MIRROR ``mm`` — the chunk bytes alone, at offset 0 — rather than the
    whole mmapped file. This is how a REMOTE chunk, cached once by the chunk
    store (``petastorm_tpu.chunkstore``), rides the identical zero-copy path
    as a local file: same page scan, same per-page bounds checks
    (``_chunk_to_arrays``), same Arrow-path fallback on any mismatch.

    ``pages`` is an optional precomputed :func:`scan_mirrored_chunk` plan
    (valid for any mirror of the same content-addressed chunk); omitted, the
    mirror is scanned here. Returns ``None`` when the chunk cannot be served.
    """
    if pages is None:
        pages = scan_mirrored_chunk(lib, mm, meta_col,
                                    has_def_levels=has_def_levels)
    if pages is None:
        return None
    return _chunk_to_arrays(mm, meta_col, pages, expected_rows, flba_width,
                            require_exact=require_exact)


def _scan_chunk(lib, mm, meta_col, has_def_levels=False):
    """[(values_offset_in_file, num_values, values_region_len)] for one column
    chunk, or None. The region length is the scanner-verified byte span from
    the values start to the page end — the per-page bound a view must fit."""
    start = meta_col.data_page_offset
    length = meta_col.total_compressed_size
    if start < 0 or length <= 0 or start + length > mm.size:
        return None
    chunk = mm[start:start + length]
    offs, counts, vlens = _scratch_arrays()
    n = lib.pstpu_scan_plain_pages(
        chunk.ctypes.data_as(ctypes.c_void_p), length, offs, counts, vlens,
        _MAX_PAGES, 1 if has_def_levels else 0)
    if n < 0:
        _note_scan_failure(lib, getattr(meta_col, 'path_in_schema', 'chunk'))
        return None
    return [(start + offs[i], counts[i], vlens[i]) for i in range(n)]


def _chunk_to_arrays(mm, meta_col, pages, expected_rows, flba_width,
                     require_exact=True):
    """One Arrow array per page, each a view over the mmap.

    Every view is bounds-checked against its PAGE's values region, not just
    the file: a wrong null_count statistic (buggy third-party writer) or a
    short page would otherwise silently serve the next page's header/level
    bytes as tensor data. REQUIRED columns (``require_exact``) must fill the
    region exactly; def-skipped OPTIONAL columns may leave a tail (the levels
    block precedes the values, but be permissive about writer padding). Any
    mismatch returns None — the Arrow path serves the column."""
    pt = meta_col.physical_type
    if pt == 'FIXED_LEN_BYTE_ARRAY':
        if not flba_width or flba_width <= 0:
            return None
        arrow_type = pa.binary(flba_width)
        itemsize = flba_width
    else:
        factory, itemsize = _PHYSICAL_FIXED[pt]
        arrow_type = factory()
    arrays = []
    total = 0
    for off, count, region_len in pages:
        nbytes = count * itemsize
        if nbytes > region_len or (require_exact and nbytes != region_len):
            return None
        if off + nbytes > mm.size:
            return None
        buf = pa.py_buffer(memoryview(mm)[off:off + nbytes])
        arrays.append(pa.Array.from_buffers(arrow_type, count, [None, buf]))
        total += count
    if total != expected_rows:
        return None
    return arrays


def read_columns_zerocopy(path, pq_metadata, row_group, column_names,
                          name_to_index, mmap_pool, lib):
    """``{name: pyarrow.ChunkedArray}`` for the subset of ``column_names``
    servable zero-copy from ``path``'s row group, ``{}`` when none qualify.
    ``name_to_index`` maps a top-level column name to its (single) leaf index;
    nested columns are simply absent from it and fall to the Arrow path."""
    out = {}
    try:
        rg = pq_metadata.row_group(row_group)
    except Exception:  # noqa: BLE001 - malformed metadata: Arrow path decides
        return out
    expected_rows = rg.num_rows
    mm = None
    for name in column_names:
        idx = name_to_index.get(name)
        if idx is None:
            continue
        try:
            col = rg.column(idx)
            schema_col = pq_metadata.schema.column(idx)
            qual = _column_qualifies(col, schema_col.max_definition_level,
                                     schema_col.max_repetition_level)
            if not qual:
                continue
            if mm is None:
                mm = mmap_pool.get(path)
            pages = _scan_chunk(lib, mm, col, has_def_levels=(qual == 'def'))
            if pages is None:
                continue
            # the FLBA byte width lives on the schema column (``length``)
            arrays = _chunk_to_arrays(mm, col, pages, expected_rows,
                                      getattr(schema_col, 'length', 0),
                                      require_exact=(qual != 'def'))
            if arrays is None:
                continue
            out[name] = pa.chunked_array(arrays)
        except Exception as e:  # noqa: BLE001 - any surprise: Arrow path serves it
            logger.debug('zero-copy scan of %s:%s failed (%s); Arrow path', path, name, e)
            continue
    return out
