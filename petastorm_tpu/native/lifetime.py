"""Slot-lifetime registry: runtime half of the shared-plane borrow checker.

Every consumer-facing view backed by shared-plane memory — an shm-ring
message slot (``ShmRing.try_read_zero_copy``), a COW-mapped serve blob, a
chunkstore mirror mmap — is a *borrow*: the bytes belong to the producer
side and are reclaimed (slot overwritten, blob unlinked, mirror evicted) on
its schedule, not the view's. This module makes every borrow *accounted*:

* a :class:`Slot` holds the refcount of one reclaimable resource; views
  registered with :meth:`Slot.adopt` carry ``weakref.finalize`` callbacks
  that decrement it, so the refcount is exact without any consumer-side
  discipline;
* reclamation asks the slot first — :meth:`Slot.try_reclaim` refuses while
  borrows are live (counted in ``lifetime_blocked_reclaims``) and the caller
  keeps its existing escalation path (slow-consumer eviction, LRU pressure);
* :meth:`Slot.force_reclaim` is that escalation: with
  ``PSTPU_LIFETIME_GUARD=1`` the slot's pages are remapped ``PROT_NONE``
  (``pstpu_guard_protect``) so a use-after-release faults loudly instead of
  yielding torn data — the sanitizer lane (tests/test_sanitized_native.py)
  and tests/test_lifetime.py prove the fault fires;
* :class:`RingBorrowLedger` specializes the registry for the SPSC shm ring,
  where releases must retire the shared head **in FIFO order** no matter
  what order consumer finalizers run in.

The static half (``petastorm_tpu/analysis/lifetime.py``, rules
PT1100–PT1103) proves at lint time that every borrow in the tree either
flows through this registry or is explicitly copied; this module makes the
same property observable at runtime (``registry().counters()`` surfaces the
``lifetime_*`` family through reader/pool diagnostics).
"""

from __future__ import annotations

import ctypes
import os
import threading
import weakref

import numpy as np

#: diagnostics keys this module owns (one family, every subsystem)
COUNTER_KEYS = ('lifetime_live_borrows', 'lifetime_blocked_reclaims',
                'lifetime_guard_faults')


def guard_enabled():
    """True when ``PSTPU_LIFETIME_GUARD=1``: force-reclaimed slots are
    remapped ``PROT_NONE`` so use-after-release faults instead of reading
    recycled bytes. Debug/test mode — the fault is a hard SIGSEGV."""
    return os.environ.get('PSTPU_LIFETIME_GUARD', '') == '1'


def _guard_lib():
    from petastorm_tpu.native import shm_ring
    return shm_ring._load_library()


def buffer_region(obj):
    """(address, nbytes) of the memory behind a memoryview/ndarray, for use
    as a :class:`Slot` guard region. Returns None when it cannot be
    resolved (no guard — reclamation still proceeds)."""
    try:
        if isinstance(obj, np.ndarray):
            return int(obj.ctypes.data), int(obj.nbytes)
        mv = memoryview(obj)
        if mv.nbytes == 0:
            return None
        arr = np.frombuffer(mv, dtype=np.uint8)
        return int(arr.ctypes.data), int(arr.nbytes)
    except (TypeError, ValueError, BufferError):
        return None


class Slot(object):
    """Refcount of one reclaimable shared-plane resource.

    Lifecycle: ``open_slot`` -> ``adopt``/``retain`` (borrows attach) ->
    ``seal`` (producer-side: no more borrows will attach) -> the LAST
    borrow's finalizer (or ``seal`` itself, when nothing attached) runs
    ``on_release`` exactly once. ``try_reclaim``/``force_reclaim`` are the
    reclaimer-side entry points and may run before the borrows die.
    """

    __slots__ = ('_registry', '_lock', '_refs', '_sealed', '_released',
                 '_reclaimed', '_on_release', '_guard_region', 'label',
                 '__weakref__')

    def __init__(self, registry, on_release=None, guard_region=None, label=''):
        self._registry = registry
        self._lock = threading.Lock()
        self._refs = 0
        self._sealed = False
        self._released = False
        self._reclaimed = False
        self._on_release = on_release
        self._guard_region = guard_region
        self.label = label

    @property
    def live(self):
        """Number of live borrows attached to this slot."""
        with self._lock:
            return self._refs

    @property
    def released(self):
        with self._lock:
            return self._released

    def retain(self):
        """Manually add one borrow (paired with :meth:`drop`) for holders
        that cannot carry a weakref (e.g. a ledger entry)."""
        with self._lock:
            if self._released:
                raise RuntimeError('retain() on a released slot ({})'.format(self.label))
            self._refs += 1
        return self

    def drop(self):
        """Release one manual borrow."""
        self._dec()

    def adopt(self, obj):
        """Attach a finalizer-borrow to every ndarray reachable in ``obj``
        (dicts/lists/tuples walked; derived numpy views keep their base
        alive, so adopting the delivered batch covers user-made slices).
        Returns ``obj``. Objects that cannot carry a weakref are skipped —
        callers hand in the structures the data plane actually delivers."""
        for arr in _iter_arrays(obj):
            try:
                with self._lock:
                    if self._released:
                        break
                    self._refs += 1
                weakref.finalize(arr, self._dec)
            except TypeError:
                self._dec()
        return obj

    def seal(self):
        """Producer side is done attaching borrows. A slot with zero borrows
        releases immediately; otherwise the last finalizer releases it."""
        run = False
        with self._lock:
            self._sealed = True
            if self._refs == 0 and not self._released:
                self._released = True
                run = True
        if run:
            self._fire()

    def release_now(self):
        """Synchronous release regardless of refcount — for payloads the
        caller fully copied out before returning."""
        run = False
        with self._lock:
            if not self._released:
                self._released = True
                self._sealed = True
                run = True
        if run:
            self._fire()

    def try_reclaim(self):
        """Reclaimer-side: release if no borrows are live; otherwise count a
        blocked reclaim and return False (caller escalates or retries)."""
        with self._lock:
            if self._refs > 0:
                self._registry._note_blocked()
                return False
            if not self._released:
                self._released = True
                self._sealed = True
                run = True
            else:
                run = False
        if run:
            self._fire()
        return True

    def force_reclaim(self):
        """Escalation path: reclaim NOW even over live borrows (the existing
        slow-consumer eviction / LRU-pressure semantics). Live borrows are
        counted as guard faults, and under ``PSTPU_LIFETIME_GUARD=1`` the
        slot's pages go ``PROT_NONE`` so the next touch faults loudly."""
        with self._lock:
            had_live = self._refs > 0
            run = not self._released
            self._released = True
            self._sealed = True
            self._reclaimed = True
        if had_live:
            self._registry._note_fault()
            if guard_enabled():
                self.guard_protect()
        if run:
            self._fire()

    def guard_protect(self):
        """Remap this slot's guard region ``PROT_NONE`` (full pages only).
        Returns protected byte count (0 = no region / no native lib)."""
        region = self._guard_region
        lib = _guard_lib()
        if region is None or lib is None:
            return 0
        addr, nbytes = region
        n = lib.pstpu_guard_protect(ctypes.c_void_p(addr), nbytes, 1)
        return max(0, int(n))

    def guard_unprotect(self):
        """Undo :meth:`guard_protect` (the reclaimer reuses the pages)."""
        region = self._guard_region
        lib = _guard_lib()
        if region is None or lib is None:
            return 0
        addr, nbytes = region
        n = lib.pstpu_guard_protect(ctypes.c_void_p(addr), nbytes, 0)
        return max(0, int(n))

    def _dec(self):
        run = False
        with self._lock:
            if self._refs > 0:
                self._refs -= 1
            if self._refs == 0 and self._sealed and not self._released:
                self._released = True
                run = True
        if run:
            self._fire()

    def _fire(self):
        self._registry._forget(self)
        cb = self._on_release
        self._on_release = None
        if cb is not None:
            cb()


class SlotRegistry(object):
    """Process-wide ledger of open slots + the ``lifetime_*`` counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._slots = set()
        self._blocked_reclaims = 0
        self._guard_faults = 0

    def open_slot(self, on_release=None, guard_region=None, label=''):
        slot = Slot(self, on_release=on_release, guard_region=guard_region,
                    label=label)
        with self._lock:
            self._slots.add(slot)
        return slot

    def live_borrows(self):
        with self._lock:
            slots = list(self._slots)
        return sum(s.live for s in slots)

    def counters(self):
        """The diagnostics family every subsystem surfaces (docs/native.md)."""
        with self._lock:
            blocked, faults = self._blocked_reclaims, self._guard_faults
        return {'lifetime_live_borrows': self.live_borrows(),
                'lifetime_blocked_reclaims': blocked,
                'lifetime_guard_faults': faults}

    def _note_blocked(self):
        with self._lock:
            self._blocked_reclaims += 1

    def _note_fault(self):
        with self._lock:
            self._guard_faults += 1

    def _forget(self, slot):
        with self._lock:
            self._slots.discard(slot)


_registry = SlotRegistry()


def registry():
    """The process-global registry (workers/serve/chunkstore all share it so
    ``lifetime_live_borrows`` is one number per process)."""
    return _registry


class RingBorrowLedger(object):
    """FIFO release ledger for one SPSC shm-ring consumer.

    ``try_read_zero_copy`` hands out views straight into the ring's data
    area; the producer may only reuse those bytes once the shared head
    passes them, and the head must advance IN ORDER even though consumer
    finalizers run in whatever order the GC pleases. The ledger queues one
    entry per taken message ``(span_bytes, released?)`` and, whenever the
    front entry is released, retires every released prefix through
    ``ring.release`` in one pass. Holding a borrow therefore applies natural
    backpressure (the producer stalls when the ring fills) instead of
    corrupting the slot.

    ``close_when_drained`` defers the ring's munmap until every borrow died
    — closing under a live view would turn a stale read into a segfault.
    """

    def __init__(self, ring, registry_=None):
        self._ring = ring
        self._registry = registry_ or registry()
        self._lock = threading.Lock()
        self._pending = []  # [span, released] in take order
        self._deferred_close = None

    @property
    def live(self):
        with self._lock:
            return sum(1 for e in self._pending if not e[1])

    def take(self, view, span, borrowed):
        """Account one message taken off the ring. Returns the
        :class:`Slot` whose release retires ``span`` bytes (for borrowed
        views the caller adopts the deserialized arrays into it; for owned
        copies it calls ``release_now()``)."""
        entry = [int(span), False]
        guard = buffer_region(view) if borrowed else None
        slot = self._registry.open_slot(
            on_release=lambda: self._mark(entry), guard_region=guard,
            label='ring-msg')
        with self._lock:
            self._pending.append(entry)
        return slot

    def _mark(self, entry):
        close_fn = None
        with self._lock:
            entry[1] = True
            while self._pending and self._pending[0][1]:
                span, _ = self._pending.pop(0)
                self._ring.release(span)
            if not self._pending and self._deferred_close is not None:
                close_fn, self._deferred_close = self._deferred_close, None
        if close_fn is not None:
            close_fn()

    def close_when_drained(self, close_fn):
        """Run ``close_fn`` (typically ``ring.close``) once every borrow is
        released — immediately when none are live. A blocked close counts as
        a blocked reclaim (the diagnostics tell you a consumer is sitting on
        a dead ring's memory)."""
        with self._lock:
            if self._pending:
                self._deferred_close = close_fn
                blocked = True
            else:
                blocked = False
        if blocked:
            self._registry._note_blocked()
        else:
            close_fn()
        return not blocked


def _iter_arrays(obj, _depth=0):
    if _depth > 4:
        return
    if isinstance(obj, np.ndarray):
        yield obj
    elif isinstance(obj, dict):
        for v in obj.values():
            yield from _iter_arrays(v, _depth + 1)
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            yield from _iter_arrays(v, _depth + 1)


__all__ = ['COUNTER_KEYS', 'RingBorrowLedger', 'Slot', 'SlotRegistry',
           'buffer_region', 'guard_enabled', 'registry']
