"""Fused native read→decode→collate — one GIL touch per batch.

The zero-copy page scan (``native/pagescan.py``) removed Arrow's
assemble-and-copy for the strictest column layout, but every qualifying
column still crossed the Python↔C boundary separately (one ctypes call +
Arrow view + collate per column per batch), and any dictionary- or
RLE-encoded chunk forfeited the native path entirely. This module drives the
``pstpu_read_fused`` kernel (``rowgroup_reader.cpp``): a whole batch of
column chunks is page-walked, snappy-decompressed, PLAIN- **and**
dictionary/RLE-bit-packed-hybrid-decoded, and written straight into one
preallocated contiguous batch buffer — optionally an shm-ring slot the
consumer maps (``native/shm_ring.py``) — on C++ threads with the GIL
released. Python sees the finished columns as numpy views over the batch
buffer: read, decode and collate are ONE native transition.

Three fused column flavors:

* **fixed** — INT32/INT64/FLOAT/DOUBLE/FLBA values (PLAIN or
  dictionary-encoded): rows land as the final ``[N, ...]`` array.
* **raw cells** — BYTE_ARRAY columns whose cells are uniform
  (``NdarrayCodec`` np.save payloads — headers verified identical and
  stripped natively — or legacy raw tensors): one contiguous copy, no
  per-cell Python loop.
* **images** — ``CompressedImageCodec`` columns with a fully-specified
  shape: the batched image decoder (``image_codec.cpp``) is invoked through
  function pointers INSIDE the fused call, so pixels decode directly into
  the batch buffer rows.

Qualification is judged per column chunk from the Parquet metadata; every
disqualification is recorded as a labelled ``fused_fallback_reason:*``
counter (plus a per-column ``fused_fallback_column:*`` counter) so a
non-zero Arrow-fallback count is always explainable — see
``docs/native.md`` for the full matrix and ``petastorm-tpu-diagnose`` for
the rendered table.
"""

from __future__ import annotations

import ctypes
import logging
import os

import numpy as np

from petastorm_tpu import observability as obs

logger = logging.getLogger(__name__)

#: hard page-count cap per chunk, shared with the page scanner; overflowing it
#: is a LOUD per-column fallback (status ``page-cap``), never silent
MAX_PAGES = 4096

#: the batch-buffer ABI version this module's ctypes mirrors describe. MUST
#: equal the ``pstpu_abi_version()`` literal in rowgroup_reader.cpp — the
#: loader refuses a kernel reporting anything else (stale build cache), and
#: lint rule PT900 keeps the two literals in sync statically.
EXPECTED_ABI = 4

# modes / codecs — keep in sync with rowgroup_reader.cpp
MODE_FIXED = 0
MODE_BINARY_RAW = 1
MODE_BINARY_IMG = 2
CODEC_UNCOMPRESSED = 0
CODEC_SNAPPY = 1
CODEC_ZSTD = 2
CODEC_LZ4_RAW = 3
CODEC_LZ4 = 4      # parquet legacy LZ4: hadoop-framed / frame / raw auto-detect

#: parquet metadata compression string -> kernel codec id. Every codec here
#: has a first-party bounds-checked decompressor in rowgroup_reader.cpp;
#: anything else (GZIP, BROTLI, LZO) stays an Arrow-path ``compression``
#: fallback.
CODEC_BY_NAME = {
    'UNCOMPRESSED': CODEC_UNCOMPRESSED,
    'SNAPPY': CODEC_SNAPPY,
    'ZSTD': CODEC_ZSTD,
    'LZ4_RAW': CODEC_LZ4_RAW,
    'LZ4': CODEC_LZ4,
}

# predicate ops / comparison dtypes — keep in sync with rowgroup_reader.cpp
PRED_IN = 0
PRED_RANGE = 1
_PRED_DTYPE_CODES = {('i', 4): 0, ('i', 8): 1, ('u', 4): 2, ('u', 8): 3,
                     ('f', 4): 4, ('f', 8): 5}

#: native per-column status -> fallback reason label (rowgroup_reader.cpp)
REASON_BY_STATUS = {
    1: 'parse', 2: 'page-type', 3: 'encoding', 4: 'compression',
    5: 'def-levels', 6: 'page-cap', 7: 'rows', 8: 'bounds', 9: 'dict',
    10: 'nonuniform', 11: 'image-probe', 12: 'image-dims', 13: 'image-decode',
    14: 'internal',
}

_PHYS_DTYPE = {'INT32': np.dtype(np.int32), 'INT64': np.dtype(np.int64),
               'FLOAT': np.dtype(np.float32), 'DOUBLE': np.dtype(np.float64)}

_OK_ENCODINGS = frozenset(('PLAIN', 'RLE', 'BIT_PACKED', 'PLAIN_DICTIONARY',
                           'RLE_DICTIONARY'))

#: size of the per-column side buffer the kernel copies a cell's np.save
#: header into (v1 headers are 64-byte padded; 256 covers every sane shape)
_AUX_BYTES = 256


class FusedColStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct FusedCol`` (the batch-buffer ABI)."""

    _fields_ = [
        ('chunk', ctypes.c_void_p),
        ('chunk_len', ctypes.c_uint64),
        ('out', ctypes.c_void_p),
        ('out_cap', ctypes.c_uint64),
        ('aux_buf', ctypes.c_void_p),
        ('aux_cap', ctypes.c_uint64),
        ('expected_rows', ctypes.c_int64),
        ('mode', ctypes.c_int32),
        ('codec', ctypes.c_int32),
        ('itemsize', ctypes.c_int32),
        ('has_def_levels', ctypes.c_int32),
        ('strip_npy', ctypes.c_int32),
        ('img_w', ctypes.c_int32),
        ('img_h', ctypes.c_int32),
        ('img_c', ctypes.c_int32),
        ('img_threads', ctypes.c_int32),
        ('status', ctypes.c_int32),
        ('out_used', ctypes.c_uint64),
        ('aux0', ctypes.c_uint64),
        ('aux1', ctypes.c_uint64),
    ]


class FusedPredStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct FusedPred`` (the batch-buffer ABI)."""

    _fields_ = [
        ('values', ctypes.c_void_p),
        ('values_cap', ctypes.c_uint64),
        ('count', ctypes.c_int64),
        ('col', ctypes.c_int32),
        ('op', ctypes.c_int32),
        ('dtype', ctypes.c_int32),
        ('negate', ctypes.c_int32),
        ('has_lo', ctypes.c_int32),
        ('has_hi', ctypes.c_int32),
        ('lo_incl', ctypes.c_int32),
        ('hi_incl', ctypes.c_int32),
        ('status', ctypes.c_int32),
        ('pages_skipped', ctypes.c_int32),
    ]


def register_abi(lib):
    """ctypes signature of the fused entry points (called from native.__init__)."""
    lib.pstpu_read_fused.restype = ctypes.c_longlong
    lib.pstpu_read_fused.argtypes = [
        ctypes.POINTER(FusedColStruct), ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p]
    lib.pstpu_read_fused_pred.restype = ctypes.c_longlong
    lib.pstpu_read_fused_pred.argtypes = [
        ctypes.POINTER(FusedColStruct), ctypes.c_int,
        ctypes.POINTER(FusedColStruct), ctypes.c_int,
        ctypes.POINTER(FusedPredStruct), ctypes.c_int,
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_longlong, ctypes.c_int,
        ctypes.c_int, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_longlong), ctypes.POINTER(ctypes.c_longlong)]


class ColumnPlan(object):
    """One column's fused-decode recipe, derived from the chunk metadata."""

    __slots__ = ('name', 'mode', 'codec', 'itemsize', 'has_def', 'strip_npy',
                 'img', 'chunk_off', 'chunk_len', 'out_bound', 'known_size',
                 'phys_dtype', 'field_dtype', 'field_shape', 'out_dtype',
                 'out_shape')

    def __init__(self, name):
        self.name = name
        self.mode = MODE_FIXED
        self.codec = CODEC_UNCOMPRESSED
        self.itemsize = 0
        self.has_def = False
        self.strip_npy = False
        self.img = None          # (h, w, c) for MODE_BINARY_IMG
        self.chunk_off = 0
        self.chunk_len = 0
        self.out_bound = 0       # bytes reserved in the batch buffer
        self.known_size = True   # False: out_bound is an upper bound (raw cells)
        self.phys_dtype = None
        self.field_dtype = None  # final dtype (None: keep phys/decoded dtype)
        self.field_shape = None  # trailing row shape (None: flat / discovered)
        self.out_dtype = None    # dtype the raw out bytes are viewed as
        self.out_shape = None


class FusedPlan(object):
    """Plan for one (row group, column selection): the fused candidates, the
    columns that must ride Arrow, and the reason each one fell back."""

    __slots__ = ('columns', 'rest', 'reasons', 'expected_rows')

    def __init__(self, columns, rest, reasons, expected_rows):
        self.columns = columns
        self.rest = rest
        self.reasons = reasons
        self.expected_rows = expected_rows

    @property
    def inplace_ok(self):
        """True when every fused column's byte size is known ahead of the
        decode — the precondition for assembling the batch in an shm-ring
        slot (the serializer header must be written before the payload)."""
        return bool(self.columns) and all(c.known_size for c in self.columns)

    def payload_bytes(self):
        return sum(c.out_bound for c in self.columns)


def _np_dtype(maybe_dtype):
    """numpy dtype of a Unischema field's numpy_dtype, or None for the flavors
    numpy cannot type (Decimal, str/bytes classes ride the per-cell path)."""
    try:
        dt = np.dtype(maybe_dtype)
    except TypeError:
        return None
    return None if dt.kind in 'OUSMm' else dt


def _chunk_span(meta_col):
    start = meta_col.data_page_offset
    if meta_col.has_dictionary_page and meta_col.dictionary_page_offset is not None \
            and 0 <= meta_col.dictionary_page_offset < start:
        start = meta_col.dictionary_page_offset
    return start, meta_col.total_compressed_size


def _qualify_chunk(meta_col, schema_col):
    """Chunk-level gate shared by every mode: returns (codec, has_def) or a
    reason string."""
    if schema_col.max_repetition_level != 0 or schema_col.max_definition_level > 1:
        return 'nesting'
    has_def = schema_col.max_definition_level == 1
    if has_def:
        stats = meta_col.statistics
        if stats is None or stats.null_count is None or stats.null_count != 0:
            return 'nullable'
    codec = CODEC_BY_NAME.get(meta_col.compression)
    if codec is None:
        return 'compression'
    if any(e not in _OK_ENCODINGS for e in meta_col.encodings):
        return 'encoding'
    return codec, has_def


def _logical_numeric_dtype(schema_col, phys):
    """Final numpy dtype of a fixed-width column judged from the Parquet
    LOGICAL type alone (no Unischema): plain columns keep their physical
    dtype, INT-annotated columns narrow/unsign to the declared width (the raw
    int32/int64 rows are sign/zero-extended, so a same-width astype recovers
    the values exactly). Anything else (TIMESTAMP/DATE/TIME/DECIMAL) returns
    None — Arrow materializes those flavors."""
    lt = getattr(schema_col, 'logical_type', None)
    lt_type = getattr(lt, 'type', 'NONE')
    if lt_type == 'NONE':
        return phys
    if lt_type != 'INT':
        return None
    try:
        import json
        spec = json.loads(lt.to_json())
        bits = int(spec.get('bitWidth', phys.itemsize * 8))
        signed = bool(spec.get('isSigned', True))
        return np.dtype('{}{}'.format('i' if signed else 'u', bits // 8))
    except Exception:  # noqa: BLE001 - odd annotation: Arrow path decides
        return None


def _pagescan_eligible(meta_col):
    """True when the strict zero-copy VIEW path (native/pagescan.py) already
    serves this chunk: uncompressed, dictionary-free, PLAIN-only. Fusing such
    a column would trade a zero-copy view for a copy, so the default plan
    leaves it alone (reason ``pagescan`` — not a fallback); the in-place ring
    mode fuses it anyway, where the copy lands directly in the slot."""
    return (meta_col.compression == 'UNCOMPRESSED'
            and not meta_col.has_dictionary_page
            and all(e in ('PLAIN', 'RLE', 'BIT_PACKED') for e in meta_col.encodings))


def _plan_column(name, meta_col, schema_col, field, expected_rows,
                 decode_hints, resize_hints, include_pagescan=False):
    """ColumnPlan for one column, or a reason string when it must ride Arrow.
    ``field`` is the Unischema field (None for plain/batch-reader stores,
    where only numeric fixed-width columns fuse)."""
    gate = _qualify_chunk(meta_col, schema_col)
    if isinstance(gate, str):
        return gate
    codec, has_def = gate
    plan = ColumnPlan(name)
    plan.codec = codec
    plan.has_def = has_def
    plan.chunk_off, plan.chunk_len = _chunk_span(meta_col)
    if plan.chunk_len <= 0 or plan.chunk_off < 0:
        return 'parse'
    pt = meta_col.physical_type

    codec_obj = getattr(field, 'codec', None)
    codec_id = getattr(codec_obj, 'codec_id', None)

    if pt in _PHYS_DTYPE:
        if not include_pagescan and _pagescan_eligible(meta_col):
            return 'pagescan'
        phys = _PHYS_DTYPE[pt]
        if field is not None:
            if codec_id != 'scalar':
                return 'codec'
            dtype = _np_dtype(field.numpy_dtype)
            if dtype is None or dtype.kind not in 'iufb':
                return 'codec'  # str/Decimal/datetime flavors: per-cell path
            plan.field_dtype = dtype
        else:
            # no Unischema field (batch reader): the raw-column contract is
            # whatever Arrow would materialize, so only plain numerics fuse —
            # annotated columns (timestamp/date/decimal) keep the Arrow path,
            # and INT annotations recover the narrow/unsigned numpy dtype
            dtype = _logical_numeric_dtype(schema_col, phys)
            if dtype is None:
                return 'codec'
            plan.field_dtype = dtype
        plan.mode = MODE_FIXED
        plan.itemsize = phys.itemsize
        plan.phys_dtype = phys
        plan.out_dtype = phys
        plan.out_bound = expected_rows * phys.itemsize
        plan.out_shape = (expected_rows,)
        return plan

    if pt == 'FIXED_LEN_BYTE_ARRAY':
        if not include_pagescan and _pagescan_eligible(meta_col):
            return 'pagescan'
        if field is None or codec_id != 'raw_tensor':
            return 'codec'
        width = getattr(schema_col, 'length', 0)
        dtype = _np_dtype(field.numpy_dtype)
        shape = tuple(field.shape or ())
        if dtype is None or not width or any(d is None for d in shape):
            return 'codec'
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if count * dtype.itemsize != width:
            return 'codec'
        plan.mode = MODE_FIXED
        plan.itemsize = width
        plan.out_dtype = dtype
        plan.out_shape = (expected_rows,) + shape
        plan.out_bound = expected_rows * width
        return plan

    if pt == 'BYTE_ARRAY':
        if field is None:
            return 'codec'
        if codec_id == 'ndarray':
            plan.mode = MODE_BINARY_RAW
            plan.strip_npy = True
            plan.out_bound = meta_col.total_uncompressed_size
            plan.known_size = False
            return plan
        if codec_id == 'raw_tensor':
            # pre-round-5 stores wrote raw tensors as variable binary
            dtype = _np_dtype(field.numpy_dtype)
            shape = tuple(field.shape or ())
            if dtype is None or any(d is None for d in shape):
                return 'codec'
            count = int(np.prod(shape, dtype=np.int64)) if shape else 1
            plan.mode = MODE_BINARY_RAW
            plan.itemsize = count * dtype.itemsize
            plan.out_dtype = dtype
            plan.out_shape = (expected_rows,) + shape
            plan.out_bound = expected_rows * plan.itemsize
            return plan
        if codec_id == 'compressed_image':
            from petastorm_tpu.native import image_codec
            if not image_codec.is_available():
                return 'image-codec-unavailable'
            if (decode_hints or {}).get(name) or (resize_hints or {}).get(name):
                return 'image-hints'  # scaled/resized decode: columnar path
            dtype = _np_dtype(field.numpy_dtype)
            shape = tuple(field.shape or ())
            if dtype != np.uint8 or any(d is None for d in shape) \
                    or len(shape) not in (2, 3):
                return 'codec'
            h, w = int(shape[0]), int(shape[1])
            c = int(shape[2]) if len(shape) == 3 else 1
            plan.mode = MODE_BINARY_IMG
            plan.img = (h, w, c)
            plan.out_dtype = np.dtype(np.uint8)
            plan.out_shape = (expected_rows,) + shape
            plan.out_bound = expected_rows * h * w * c
            return plan
        return 'codec'

    return 'physical-type'


def plan_row_group(pq_meta, flat_index, row_group, column_names, schema_fields,
                   decode_hints=None, resize_hints=None, include_pagescan=False):
    """Build the :class:`FusedPlan` for one row group. ``flat_index`` maps a
    flat top-level column name to its leaf index (nested columns are absent
    and fall back with reason ``nesting``); ``schema_fields`` maps field name
    -> Unischema field (None for plain stores). ``include_pagescan`` also
    fuses columns the zero-copy view path would serve (the in-place ring
    mode, where the one copy lands directly in the consumer's slot)."""
    try:
        rg = pq_meta.row_group(row_group)
    except Exception:  # noqa: BLE001 - malformed metadata: Arrow path decides
        return None
    expected_rows = rg.num_rows
    columns, rest, reasons = [], [], {}
    for name in column_names:
        idx = flat_index.get(name)
        if idx is None:
            rest.append(name)
            reasons[name] = 'nesting'
            continue
        try:
            field = schema_fields.get(name) if schema_fields is not None else None
            plan = _plan_column(name, rg.column(idx), pq_meta.schema.column(idx),
                                field, expected_rows, decode_hints, resize_hints,
                                include_pagescan=include_pagescan)
        except Exception as e:  # noqa: BLE001 - odd metadata: Arrow serves it
            logger.debug('fused qualification of %s failed (%s); Arrow path', name, e)
            plan = 'parse'
        if isinstance(plan, str):
            rest.append(name)
            reasons[name] = plan
        else:
            columns.append(plan)
    return FusedPlan(columns, rest, reasons, expected_rows)


def count_fallbacks(reasons):
    """Labelled fallback accounting: one aggregate counter per reason plus a
    per-column counter, so a shrinking (or stubbornly non-zero) Arrow-fallback
    count is explainable from ``Reader.diagnostics`` alone. ``pagescan`` is
    not a fallback — those columns are served zero-copy by the view path."""
    for name, reason in reasons.items():
        if reason == 'pagescan':
            continue
        obs.count('fused_fallback_total')
        obs.count('fused_fallback_reason:{}'.format(reason))
        obs.count('fused_fallback_column:{}:{}'.format(name, reason))


def _pred_domain(plan):
    """``(dtype_code, comparison dtype, logical dtype)`` for one predicate
    column plan, or None when the column's values cannot be compared natively
    (binary modes, FLBA tensors, non-numeric logicals). Integer comparisons
    run at the PHYSICAL width; they go unsigned only when the logical dtype is
    unsigned at full physical width — narrower unsigned logicals zero-extend
    into the positive signed range, where the signed compare is already
    exact."""
    phys = plan.phys_dtype
    if plan.mode != MODE_FIXED or phys is None or phys.itemsize != plan.itemsize:
        return None
    logical = plan.field_dtype or phys
    if logical.kind == 'u' and logical.itemsize == phys.itemsize:
        cmp_dtype = np.dtype('u{}'.format(phys.itemsize))
    else:
        cmp_dtype = phys
    code = _PRED_DTYPE_CODES.get((cmp_dtype.kind, cmp_dtype.itemsize))
    if code is None:
        return None
    return code, cmp_dtype, logical


def _pred_operand(value, logical, cmp_dtype):
    """``value`` encoded as ``cmp_dtype`` bytes, or None when it is not
    EXACTLY representable in the column's logical domain — the native compare
    must agree bit-for-bit with the numpy fallback, so a rounding cast is
    never acceptable."""
    try:
        v0 = np.asarray(value)
        if v0.shape != () or v0.dtype.kind not in 'iufb':
            return None
        with np.errstate(all='ignore'):
            c = v0.astype(logical)
            if not bool(c == v0):
                return None
            return c.astype(cmp_dtype).tobytes()
    except (TypeError, ValueError, OverflowError):
        return None


def compile_predicate(clauses, pred_index):
    """Map protocol clause dicts (``PredicateBase.native_clauses``) onto a
    ctypes ``FusedPred`` array. ``pred_index`` maps predicate column name ->
    ``(descriptor index, ColumnPlan)``. Returns ``(preds, keepalive)`` — the
    struct array plus the operand buffers it points into, which MUST stay
    referenced across the kernel call — or the string ``'predicate'`` when any
    clause shape is not natively evaluable (the caller counts the fallback and
    rides the Arrow predicate path)."""
    entries = []
    keepalive = []
    for cl in clauses or ():
        hit = pred_index.get(cl.get('field'))
        if hit is None:
            return 'predicate'
        idx, plan = hit
        dom = _pred_domain(plan)
        if dom is None:
            return 'predicate'
        code, cmp_dtype, logical = dom
        w = cmp_dtype.itemsize
        e = {'col': idx, 'dtype': code, 'negate': 1 if cl.get('negate') else 0,
             'has_lo': 0, 'has_hi': 0, 'lo_incl': 0, 'hi_incl': 0}
        op = cl.get('op')
        if op == 'in':
            packed = set()
            for v in cl.get('values', ()):
                b = _pred_operand(v, logical, cmp_dtype)
                # an unrepresentable operand can never equal a column value:
                # dropping it is exact, matching the numpy fallback
                if b is not None:
                    packed.add(b)
            data = b''.join(sorted(packed))
            buf = np.frombuffer(bytearray(data or b'\x00'), dtype=np.uint8)
            e.update(op=PRED_IN, count=len(data) // w, values=buf)
        elif op == 'range':
            bounds = []
            for key, flag, incl in (('lo', 'has_lo', 'lo_incl'),
                                    ('hi', 'has_hi', 'hi_incl')):
                v = cl.get(key)
                if v is None:
                    bounds.append(b'\x00' * w)
                    continue
                b = _pred_operand(v, logical, cmp_dtype)
                if b is None:
                    return 'predicate'
                bounds.append(b)
                e[flag] = 1
                e[incl] = 1 if cl.get(key + '_incl', True) else 0
            buf = np.frombuffer(bytearray(b''.join(bounds)), dtype=np.uint8)
            e.update(op=PRED_RANGE, count=0, values=buf)
        else:
            return 'predicate'
        keepalive.append(e['values'])
        entries.append(e)
    if not entries:
        return 'predicate'
    preds = (FusedPredStruct * len(entries))()
    for p, e in zip(preds, entries):
        buf = e['values']
        p.values = buf.ctypes.data
        p.values_cap = buf.nbytes
        p.count = e['count']
        p.col = e['col']
        p.op = e['op']
        p.dtype = e['dtype']
        p.negate = e['negate']
        p.has_lo = e['has_lo']
        p.has_hi = e['has_hi']
        p.lo_incl = e['lo_incl']
        p.hi_incl = e['hi_incl']
    return preds, keepalive


def plan_predicate_columns(pq_meta, flat_index, row_group, pred_fields,
                           schema_fields):
    """ColumnPlans for the predicate columns — always planned with
    ``include_pagescan`` (the zero-copy view path cannot gate collation) —
    plus the name -> (descriptor index, plan) map ``compile_predicate``
    consumes. Returns None when any predicate column does not qualify
    natively."""
    plan = plan_row_group(pq_meta, flat_index, row_group, list(pred_fields),
                          schema_fields, include_pagescan=True)
    if plan is None or plan.rest:
        return None
    index = {}
    for i, p in enumerate(plan.columns):
        if _pred_domain(p) is None:
            return None
        index[p.name] = (i, p)
    return plan.columns, index


def _invoke_read_fused(lib, descs, n_cols, n_threads, img_probe, img_decode):
    """THE single Python<->C transition of a fused batch (ctypes releases the
    GIL for the call's duration). Isolated so the structural one-GIL-touch
    test can count invocations."""
    return lib.pstpu_read_fused(descs, n_cols, n_threads, MAX_PAGES,
                                img_probe, img_decode)


def read_into(lib, chunks, plans, expected_rows, out_buf, offsets):
    """Run the fused kernel over ``plans`` writing each column at its offset
    inside ``out_buf`` (any writable contiguous buffer — a numpy array or an
    shm-ring slot view). Returns the list of per-column native results.

    ``chunks[i]`` is column i's chunk bytes as a numpy uint8 view — a slice of
    the mmapped local file, or a chunk-store mirror mmap (remote stores ride
    the identical kernel). The views are anchored here for the call's
    duration; the kernel re-checks every page and value region against its
    chunk/out capacities (``out_cap``/``chunk_len`` bounds in the ABI).
    """
    n = len(plans)
    descs = (FusedColStruct * n)()
    base = np.frombuffer(out_buf, dtype=np.uint8)  # noqa: PT500 - writable batch buffer owned by the caller
    total = base.nbytes
    aux_bufs = []
    has_img = any(p.mode == MODE_BINARY_IMG for p in plans)
    probe_addr = decode_addr = None
    if has_img:
        from petastorm_tpu.native import image_codec
        addrs = image_codec.batch_fn_addrs()
        if addrs is None:
            return [(11, 0, 0, 0, b'')] * n  # image-probe: codec unavailable
        probe_addr, decode_addr = addrs
    for i, p in enumerate(plans):
        d = descs[i]
        # always appended, even for prechecked-out columns, so aux_bufs stays
        # index-aligned with descs when results are gathered below
        aux = np.zeros(_AUX_BYTES, dtype=np.uint8)
        aux_bufs.append(aux)
        chunk = chunks[i]
        if chunk is None or chunk.nbytes != p.chunk_len \
                or offsets[i] + p.out_bound > total:
            # planning bound violated (stale metadata): fail the column loudly
            d.status = 8
            continue
        d.chunk = chunk.ctypes.data
        d.chunk_len = p.chunk_len
        d.out = base.ctypes.data + offsets[i]
        d.out_cap = p.out_bound
        d.aux_buf = aux.ctypes.data
        d.aux_cap = aux.nbytes
        d.expected_rows = expected_rows
        d.mode = p.mode
        d.codec = p.codec
        d.itemsize = p.itemsize
        d.has_def_levels = 1 if p.has_def else 0
        d.strip_npy = 1 if p.strip_npy else 0
        if p.img is not None:
            d.img_h, d.img_w, d.img_c = p.img
        d.status = 0
    if has_img:
        from petastorm_tpu.native import image_codec
        with image_codec._thread_grant(None) as grant:
            for i in range(n):
                descs[i].img_threads = grant
            _invoke_read_fused(lib, descs, n, _column_threads(n), probe_addr,
                               decode_addr)
    else:
        _invoke_read_fused(lib, descs, n, _column_threads(n), None, None)
    # chunks and aux_bufs anchored through the call above; statuses carry the result
    results = [(descs[i].status, descs[i].out_used, descs[i].aux0, descs[i].aux1,
                bytes(aux_bufs[i][:descs[i].aux1]) if descs[i].aux1 else b'')
               for i in range(n)]
    return results


def read_block(lib, chunks, plan, stage_args=None):
    """Allocate one contiguous batch buffer, run the fused kernel, and build
    the numpy columns — the shared heap-mode driver behind both the local
    (``NativeParquetFile.read_fused``) and chunk-cached (remote mirror)
    readers. Returns ``(block, reasons)``: decoded columns plus the fallback
    reason of every column that did NOT decode (plan-time and kernel-time
    fallbacks merged); counters are accounted here."""
    offsets, total = [], 0
    for p in plan.columns:
        offsets.append(total)
        total += p.out_bound
    out = np.empty(total, dtype=np.uint8)
    with obs.stage('fused_decode', cat='native', rows=plan.expected_rows,
                   **(stage_args or {})):
        results = read_into(lib, chunks, plan.columns, plan.expected_rows,
                            out, offsets)
    block = {}
    reasons = dict(plan.reasons)
    for p, res, off in zip(plan.columns, results, offsets):
        col = build_column(p, res, out, off, plan.expected_rows)
        if col is None:
            reasons[p.name] = REASON_BY_STATUS.get(res[0], 'post-validate')
        else:
            block[p.name] = col
    if block:
        obs.count('fused_columns_total', len(block))
        obs.count('fused_batches_total')
    count_fallbacks({n: r for n, r in reasons.items() if n not in block})
    return block, reasons


def _invoke_read_fused_pred(lib, descs, n_cols, pred_descs, n_pred_cols, preds,
                            n_preds, sel_ptr, sel_cap, total_rows, n_threads,
                            img_probe, img_decode, out_selected, out_skipped):
    """THE single Python<->C transition of a fused *filtered* batch: predicate
    evaluation, page-stat skipping and selected-row collation all run inside
    this one GIL-released call. Isolated so the structural one-GIL-touch test
    can count invocations."""
    return lib.pstpu_read_fused_pred(
        descs, n_cols, pred_descs, n_pred_cols, preds, n_preds, sel_ptr,
        sel_cap, total_rows, n_threads, MAX_PAGES, img_probe, img_decode,
        out_selected, out_skipped)


def _fill_desc(d, plan, chunk, out_ptr, out_cap, aux, expected_rows):
    d.chunk = chunk.ctypes.data
    d.chunk_len = plan.chunk_len
    d.out = out_ptr
    d.out_cap = out_cap
    if aux is not None:
        d.aux_buf = aux.ctypes.data
        d.aux_cap = aux.nbytes
    d.expected_rows = expected_rows
    d.mode = plan.mode
    d.codec = plan.codec
    d.itemsize = plan.itemsize
    d.has_def_levels = 1 if plan.has_def else 0
    d.strip_npy = 1 if plan.strip_npy else 0
    if plan.img is not None:
        d.img_h, d.img_w, d.img_c = plan.img
    d.status = 0


def _narrow_plan(plan, full_rows, n_selected):
    """Shallow copy of ``plan`` with the row-dependent bounds rescaled from
    the planned full row group to the ``n_selected`` rows the gather kept."""
    q = ColumnPlan(plan.name)
    for slot in ColumnPlan.__slots__:
        setattr(q, slot, getattr(plan, slot))
    if plan.out_shape is not None:
        q.out_shape = (n_selected,) + tuple(plan.out_shape[1:])
    if plan.known_size and full_rows:
        q.out_bound = plan.out_bound // full_rows * n_selected
    return q


def read_block_pred(lib, chunks, plan, pred_chunks, pred_plans, preds,
                    keepalive, stage_args=None):
    """Filtered fused batch: evaluate the compiled predicate clauses against
    the predicate column chunks (skipping whole pages via min/max page
    statistics first), then collate ONLY the selected rows of every output
    column — one GIL-released call end to end, strictly less decode work than
    an unfiltered read whenever pages can be skipped.

    Returns ``(block, reasons, sel_mask, n_selected, pages_skipped)`` —
    ``sel_mask`` is the boolean row mask over the full row group, used by the
    caller to filter the non-fused (Arrow) columns consistently — or None when
    the kernel declined (any clause or column failed natively); the caller
    then falls back to the unfused predicate path for the whole block."""
    rows = plan.expected_rows
    offsets, total = [], 0
    for p in plan.columns:
        offsets.append(total)
        total += p.out_bound
    out = np.empty(total, dtype=np.uint8)
    n = len(plan.columns)
    npred = len(pred_plans)
    if n == 0 or npred == 0 or len(preds) == 0:
        return None
    descs = (FusedColStruct * n)()
    pred_descs = (FusedColStruct * npred)()
    aux_bufs = []
    has_img = any(p.mode == MODE_BINARY_IMG for p in plan.columns)
    probe_addr = decode_addr = None
    if has_img:
        from petastorm_tpu.native import image_codec
        addrs = image_codec.batch_fn_addrs()
        if addrs is None:
            return None
        probe_addr, decode_addr = addrs
    for i, p in enumerate(plan.columns):
        aux = np.zeros(_AUX_BYTES, dtype=np.uint8)
        aux_bufs.append(aux)
        chunk = chunks[i]
        if chunk is None or chunk.nbytes != p.chunk_len:
            return None
        _fill_desc(descs[i], p, chunk, out.ctypes.data + offsets[i],
                   p.out_bound, aux, rows)
    for i, p in enumerate(pred_plans):
        chunk = pred_chunks[i]
        if chunk is None or chunk.nbytes != p.chunk_len:
            return None
        _fill_desc(pred_descs[i], p, chunk, None, 0, None, rows)
    sel = np.zeros((rows + 7) // 8 or 1, dtype=np.uint8)
    out_selected = ctypes.c_longlong(0)
    out_skipped = ctypes.c_longlong(0)
    with obs.stage('fused_predicate', cat='native', rows=rows,
                   **(stage_args or {})):
        if has_img:
            from petastorm_tpu.native import image_codec
            with image_codec._thread_grant(None) as grant:
                for i in range(n):
                    descs[i].img_threads = grant
                ret = _invoke_read_fused_pred(
                    lib, descs, n, pred_descs, npred, preds, len(preds),
                    sel.ctypes.data, sel.nbytes, rows, _column_threads(n),
                    probe_addr, decode_addr, ctypes.byref(out_selected),
                    ctypes.byref(out_skipped))
        else:
            ret = _invoke_read_fused_pred(
                lib, descs, n, pred_descs, npred, preds, len(preds),
                sel.ctypes.data, sel.nbytes, rows, _column_threads(n),
                None, None, ctypes.byref(out_selected),
                ctypes.byref(out_skipped))
    # chunks / aux_bufs / keepalive operand buffers anchored through the call
    del keepalive
    # the kernel's return counts FAILED OUTPUT COLUMNS — those degrade
    # per-column to the Arrow path below, exactly like the unfiltered pass.
    # Only a failed predicate stage (any clause or predicate column status
    # nonzero) invalidates the selection itself and fails the whole block.
    if ret < 0:
        return None
    if any(pred_descs[i].status != 0 for i in range(npred)):
        return None
    if any(pr.status != 0 for pr in preds):
        return None
    n_selected = int(out_selected.value)
    pages_skipped = int(out_skipped.value)
    sel_mask = np.unpackbits(sel, bitorder='little')[:rows].astype(bool)
    block = {}
    reasons = dict(plan.reasons)
    if n_selected == 0:
        for p in plan.columns:
            if p.out_shape is None:
                # npy-stripped cells: the row shape is only discoverable from
                # a decoded cell, and there are none — Arrow serves the column
                # (zero rows either way)
                reasons[p.name] = 'post-validate'
                continue
            dtype = p.field_dtype if p.field_dtype is not None else p.out_dtype
            block[p.name] = np.empty((0,) + tuple(p.out_shape[1:]), dtype=dtype)
    else:
        for i, p in enumerate(plan.columns):
            res = (descs[i].status, descs[i].out_used, descs[i].aux0,
                   descs[i].aux1,
                   bytes(aux_bufs[i][:descs[i].aux1]) if descs[i].aux1 else b'')
            col = build_column(_narrow_plan(p, rows, n_selected), res, out,
                               offsets[i], n_selected)
            if col is None:
                reasons[p.name] = REASON_BY_STATUS.get(res[0], 'post-validate')
            else:
                block[p.name] = col
    count_fallbacks({n: r for n, r in reasons.items() if n not in block})
    if not block:
        return None  # nothing fused: the unfiltered Arrow pushdown is simpler
    obs.count('fused_pred_batches_total')
    obs.count('fused_pred_pages_skipped_total', pages_skipped)
    obs.count('fused_pred_rows_selected', n_selected)
    obs.count('fused_columns_total', len(block))
    obs.count('fused_batches_total')
    return block, reasons, sel_mask, n_selected, pages_skipped


def _column_threads(n_cols):
    return max(1, min(n_cols, os.cpu_count() or 1))


def _parse_npy(header_bytes):
    """(dtype, shape) from the np.save header the kernel copied out, or None
    (fortran order and non-standard headers fall back to the per-cell path)."""
    from petastorm_tpu.codecs import _parse_npy_header
    parsed = _parse_npy_header(header_bytes)
    if parsed is None:
        return None
    dtype, fortran, shape, _off = parsed
    if fortran:
        return None
    return dtype, shape


def column_region(plan, result, expected_rows):
    """``(dtype_str, row_shape, nbytes)`` describing one successfully-decoded
    column's bytes IN PLACE (no array built) — the layout descriptor the serve
    blob fan-out ships to consumers, who view the shared mapping directly.
    Mirrors :func:`build_column`'s validation; None rejects the column (the
    caller falls back to the copy path). Columns needing a post-decode astype
    decline: a dtype conversion is a copy, which this path exists to avoid."""
    status, out_used, aux0, _aux1, aux_header = result
    if status != 0:
        return None
    if plan.mode == MODE_BINARY_RAW and plan.strip_npy:
        parsed = _parse_npy(aux_header)
        if parsed is None:
            return None
        dtype, shape = parsed
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if count * dtype.itemsize != aux0 or out_used != expected_rows * aux0:
            return None
        return dtype.str, (expected_rows,) + shape, out_used
    if plan.out_dtype is None or plan.out_shape is None:
        return None
    if plan.known_size and out_used != plan.out_bound:
        return None
    if plan.mode == MODE_BINARY_RAW and aux0 != plan.itemsize:
        return None
    if plan.field_dtype is not None and plan.field_dtype != plan.out_dtype:
        return None
    return plan.out_dtype.str, plan.out_shape, out_used


def build_column(plan, result, out_buf, offset, expected_rows):
    """numpy column for one successfully-decoded plan: a typed view over the
    batch buffer region (fresh writable memory, so the decode()'s
    writable-array contract holds with zero extra copies). Returns None when
    post-decode validation rejects the bytes (caller re-reads via Arrow)."""
    status, out_used, aux0, _aux1, aux_header = result
    if status != 0:
        return None
    mv = memoryview(out_buf)
    if mv.readonly:
        # decode()'s contract hands out writable arrays; the batch buffer is
        # always fresh writable memory, but an immutable caller buffer must
        # degrade to a copy rather than a transport-dependent read-only view
        mv = memoryview(bytearray(mv))
    region = mv[offset:offset + out_used]
    if plan.mode == MODE_BINARY_RAW and plan.strip_npy:
        parsed = _parse_npy(aux_header)
        if parsed is None:
            return None
        dtype, shape = parsed
        count = int(np.prod(shape, dtype=np.int64)) if shape else 1
        if count * dtype.itemsize != aux0 or out_used != expected_rows * aux0:
            return None
        arr = np.frombuffer(region, dtype=dtype)
        return arr.reshape((expected_rows,) + shape)
    if plan.out_dtype is None or plan.out_shape is None:
        return None
    expected_bytes = plan.out_bound if plan.known_size else None
    if expected_bytes is not None and out_used != expected_bytes:
        return None
    if plan.mode == MODE_BINARY_RAW and aux0 != plan.itemsize:
        return None  # legacy raw cells must match the schema's cell width
    arr = np.frombuffer(region, dtype=plan.out_dtype).reshape(plan.out_shape)
    if plan.field_dtype is not None and plan.field_dtype != arr.dtype:
        arr = arr.astype(plan.field_dtype)
    return arr
