// First-party batched PNG/JPEG decoder for CompressedImageCodec.
//
// The reference decodes images one-at-a-time through Python + OpenCV
// (reference codecs.py:92-111): each cell pays a Python round-trip, a cv2
// dispatch, and a BGR->RGB conversion pass. That per-image overhead is the
// measured input-pipeline bottleneck on the image path (round-1 duty-cycle
// benchmark: ~96% input stall feeding ResNet-50). This module decodes a whole
// column's worth of encoded cells in ONE native call against the system
// libjpeg-turbo / libpng:
//   * the GIL is released for the entire column, so reader pool threads decode
//     row groups truly in parallel;
//   * pixels land directly in caller-allocated numpy memory in RGB order
//     (libjpeg/libpng native order) — no BGR swap pass, no intermediate copy;
//   * an optional internal thread pool fans decode out across images for
//     single-threaded callers (dummy pool, benchmarks).
//
// Supported: JPEG gray/RGB (8-bit), PNG gray/RGB (8/16-bit, incl. 1/2/4-bit
// gray expansion and interlace). Anything else (palette, alpha, CMYK, exotic
// formats) returns the failing index and the Python caller falls back to the
// per-image OpenCV path — matching what CompressedImageCodec.encode can write.
//
// Build: python -m petastorm_tpu.native.build (third target; links -ljpeg -lpng).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include <jpeglib.h>
#include <libdeflate.h>
#include <png.h>

#if defined(__SSE2__)
#include <emmintrin.h>
#endif

namespace {

// ---------------------------------------------------------------------------
// Probing: header-only dimension/type sniffing, no decode.
// info layout per image: [width, height, channels, bit_depth]
// ---------------------------------------------------------------------------

constexpr uint8_t kPngMagic[8] = {137, 'P', 'N', 'G', 13, 10, 26, 10};

uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) | (uint32_t(p[2]) << 8) | p[3];
}

uint16_t be16(const uint8_t* p) { return (uint16_t(p[0]) << 8) | p[1]; }

// 0 = ok, -1 = unsupported/corrupt
int probe_png(const uint8_t* data, uint64_t len, int32_t* info) {
  if (len < 33) return -1;  // signature + IHDR
  // IHDR must be the first chunk: length(4) type(4) at offset 8
  if (be32(data + 8) != 13 || std::memcmp(data + 12, "IHDR", 4) != 0) return -1;
  const uint32_t w = be32(data + 16);
  const uint32_t h = be32(data + 20);
  const int bit_depth = data[24];
  const int color_type = data[25];
  if (w == 0 || h == 0 || w > (1u << 24) || h > (1u << 24)) return -1;
  if (uint64_t(w) * h > (1ull << 28)) return -1;  // cap output allocations
  int channels;
  switch (color_type) {
    case PNG_COLOR_TYPE_GRAY:
      channels = 1;
      // depth drives the decode-side bpp; an invalid value here would size the
      // unfilter wider than the Python-allocated output buffer
      if (bit_depth != 1 && bit_depth != 2 && bit_depth != 4 && bit_depth != 8 &&
          bit_depth != 16) return -1;
      break;
    case PNG_COLOR_TYPE_RGB:
      channels = 3;
      if (bit_depth != 8 && bit_depth != 16) return -1;
      break;
    default:
      return -1;  // palette/alpha -> caller falls back to OpenCV
  }
  // A tRNS chunk on gray/RGB would add an alpha channel under cv2 semantics;
  // it is legal-but-rare — scan the chunk list and bail out if present.
  uint64_t pos = 33;
  while (pos + 8 <= len) {
    const uint32_t chunk_len = be32(data + pos);
    if (std::memcmp(data + pos + 4, "tRNS", 4) == 0) return -1;
    if (std::memcmp(data + pos + 4, "IDAT", 4) == 0) break;  // past metadata
    pos += 12ull + chunk_len;
  }
  info[0] = int32_t(w);
  info[1] = int32_t(h);
  info[2] = channels;
  info[3] = bit_depth < 8 ? 8 : bit_depth;  // 1/2/4-bit gray expands to 8
  return 0;
}

// Scaled JPEG decode: libjpeg decodes at m/8 of full size (m=1..8) nearly for
// free — the IDCT simply produces fewer samples, so most pixels are never
// computed. Given a minimum output size, pick the smallest m whose scaled
// dims still cover it (so the only remaining host resize is a small downscale).
// m=8 == full size; an image already smaller than the minimum stays full size.
int jpeg_choose_scale(int full_w, int full_h, int min_w, int min_h) {
  if (min_w <= 0 || min_h <= 0) return 8;
  for (int m = 1; m < 8; m++) {
    // jdiv_round_up, exactly as jpeg_calc_output_dimensions computes it
    const long w = (long(full_w) * m + 7) / 8;
    const long h = (long(full_h) * m + 7) / 8;
    if (w >= min_w && h >= min_h) return m;
  }
  return 8;
}

int probe_jpeg(const uint8_t* data, uint64_t len, int32_t* info) {
  if (len < 4 || data[0] != 0xFF || data[1] != 0xD8) return -1;
  uint64_t pos = 2;
  while (pos + 4 <= len) {
    if (data[pos] != 0xFF) return -1;
    uint8_t marker = data[pos + 1];
    if (marker == 0xFF) { pos++; continue; }  // fill bytes
    if (marker == 0xD8 || (marker >= 0xD0 && marker <= 0xD7)) { pos += 2; continue; }
    const uint64_t seg_len = be16(data + pos + 2);
    const bool is_sof = (marker >= 0xC0 && marker <= 0xCF) &&
                        marker != 0xC4 && marker != 0xC8 && marker != 0xCC;
    if (is_sof) {
      if (pos + 2 + seg_len > len || seg_len < 8) return -1;
      const int precision = data[pos + 4];
      const uint16_t h = be16(data + pos + 5);
      const uint16_t w = be16(data + pos + 7);
      const int ncomp = data[pos + 9];
      if (precision != 8 || w == 0 || h == 0) return -1;
      if (ncomp != 1 && ncomp != 3) return -1;  // CMYK etc. -> fallback
      info[0] = w;
      info[1] = h;
      info[2] = ncomp;
      info[3] = 8;
      return 0;  // caller applies jpeg_choose_scale to info when a hint is set
    }
    pos += 2 + seg_len;
  }
  return -1;
}

int probe_one(const uint8_t* data, uint64_t len, int32_t* info, int min_w, int min_h) {
  if (len >= 8 && std::memcmp(data, kPngMagic, 8) == 0) return probe_png(data, len, info);
  const int rc = probe_jpeg(data, len, info);
  if (rc != 0) return rc;
  // report post-scale output dims so the caller allocates the scaled buffer
  const int m = jpeg_choose_scale(info[0], info[1], min_w, min_h);
  if (m < 8) {
    info[0] = int32_t((long(info[0]) * m + 7) / 8);
    info[1] = int32_t((long(info[1]) * m + 7) / 8);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Fast PNG path: whole-IDAT inflate with libdeflate (~2x zlib's streaming
// inflate) + first-party row unfiltering. Covers what our encoder writes:
// non-interlaced 8/16-bit gray/RGB. Interlaced or sub-8-bit images take the
// libpng path below.
// ---------------------------------------------------------------------------

inline uint8_t paeth(uint8_t a, uint8_t b, uint8_t c) {
  // branchless (cmov-friendly): the Paeth chain is the serial bottleneck of
  // filtered rows, so mispredicted branches here dominate whole-image decode
  const int p = int(a) + b - c;
  const int pa = std::abs(p - a);
  const int pb = std::abs(p - b);
  const int pc = std::abs(p - c);
  const uint8_t bc = pb <= pc ? b : c;
  const int pbc = pb <= pc ? pb : pc;
  return pa <= pbc ? a : bc;
}

// Per-filter row reconstruction, templated on bytes-per-pixel so the inner
// loops unroll with constant stride and the BPP independent channel chains
// overlap in the pipeline. src points past the filter byte; prev is the
// reconstructed previous row (nullptr on the first row).
template <int BPP>
void unfilter_sub(const uint8_t* src, uint8_t* cur, uint64_t rowbytes) {
  std::memcpy(cur, src, BPP);
  for (uint64_t i = BPP; i < rowbytes; i++) cur[i] = src[i] + cur[i - BPP];
}

template <int BPP>
void unfilter_avg(const uint8_t* src, const uint8_t* prev, uint8_t* cur, uint64_t rowbytes) {
  if (!prev) {
    std::memcpy(cur, src, BPP);
    for (uint64_t i = BPP; i < rowbytes; i++) cur[i] = src[i] + uint8_t(cur[i - BPP] >> 1);
    return;
  }
  for (int i = 0; i < BPP; i++) cur[i] = src[i] + uint8_t(prev[i] >> 1);
  for (uint64_t i = BPP; i < rowbytes; i++) {
    cur[i] = src[i] + uint8_t((cur[i - BPP] + prev[i]) >> 1);
  }
}

#if defined(__SSE2__)
// Vectorized Paeth for the RGB8 hot case: one pixel per iteration in 16-bit
// lanes, branchless predictor select. The pixel chain is inherently serial,
// but doing the per-pixel |..| / min / select math in one register pass beats
// the three interleaved scalar cmov chains by ~2x.
inline __m128i abs_i16(__m128i x) {
  return _mm_max_epi16(x, _mm_sub_epi16(_mm_setzero_si128(), x));
}

inline __m128i load_px4(const uint8_t* p) {  // 4 bytes -> 16-bit lanes
  int32_t v;
  std::memcpy(&v, p, 4);  // unaligned-safe; compiles to a single mov
  return _mm_unpacklo_epi8(_mm_cvtsi32_si128(v), _mm_setzero_si128());
}

// BPP must be 3: loads read 4 bytes per pixel and stores write 4, so the last
// pixel of the row is handled by the scalar caller (no out-of-bounds access).
inline void paeth3_px_sse2(const uint8_t* src_px, const uint8_t* prev_px, uint8_t* cur_px,
                           __m128i& a, __m128i& c) {
  const __m128i b = load_px4(prev_px);
  const __m128i x = load_px4(src_px);
  const __m128i p_a = _mm_sub_epi16(b, c);                 // p - a
  const __m128i p_b = _mm_sub_epi16(a, c);                 // p - b
  const __m128i pa = abs_i16(p_a);
  const __m128i pb = abs_i16(p_b);
  const __m128i pc = abs_i16(_mm_add_epi16(p_a, p_b));
  const __m128i mn = _mm_min_epi16(pc, _mm_min_epi16(pa, pb));
  const __m128i use_a = _mm_cmpeq_epi16(mn, pa);
  const __m128i use_b = _mm_andnot_si128(use_a, _mm_cmpeq_epi16(mn, pb));
  const __m128i pred = _mm_or_si128(
      _mm_and_si128(use_a, a),
      _mm_or_si128(_mm_and_si128(use_b, b),
                   _mm_andnot_si128(_mm_or_si128(use_a, use_b), c)));
  const __m128i d = _mm_and_si128(_mm_add_epi16(x, pred), _mm_set1_epi16(0xFF));
  const int32_t packed = _mm_cvtsi128_si32(_mm_packus_epi16(d, _mm_setzero_si128()));
  std::memcpy(cur_px, &packed, 4);
  a = d;
  c = b;
}
#endif  // __SSE2__

template <int BPP>
void unfilter_paeth(const uint8_t* src, const uint8_t* prev, uint8_t* cur, uint64_t rowbytes) {
  if (!prev) {  // paeth(a,0,0) == a: degenerates to Sub
    unfilter_sub<BPP>(src, cur, rowbytes);
    return;
  }
  for (int i = 0; i < BPP; i++) cur[i] = src[i] + prev[i];  // paeth(0,b,0) == b
#if defined(__SSE2__)
  if (BPP == 3 && rowbytes >= 8) {
    const uint64_t n_px = rowbytes / 3;
    // zero-padded temp: cur[3] is not written yet, and prev[3] belongs to the
    // next pixel — loading them directly would read uninitialized/irrelevant
    // bytes into lane 3 (harmless for the math, but UB and MSan-hostile)
    uint8_t first_px[4] = {cur[0], cur[1], cur[2], 0};
    uint8_t first_prev[4] = {prev[0], prev[1], prev[2], 0};
    __m128i a = load_px4(first_px);
    __m128i c = load_px4(first_prev);
    // stop one pixel early: the 4-byte loads/stores of the vector path would
    // touch one byte past the row at the final pixel
    for (uint64_t px = 1; px + 1 < n_px; px++) {
      paeth3_px_sse2(src + px * 3, prev + px * 3, cur + px * 3, a, c);
    }
    for (uint64_t i = (n_px - 1) * 3; i < rowbytes; i++) {
      cur[i] = src[i] + paeth(cur[i - 3], prev[i], prev[i - 3]);
    }
    return;
  }
#endif
  for (uint64_t i = BPP; i < rowbytes; i++) {
    cur[i] = src[i] + paeth(cur[i - BPP], prev[i], prev[i - BPP]);
  }
}

template <int BPP>
int unfilter_row_t(uint8_t filter, const uint8_t* src, const uint8_t* prev, uint8_t* cur,
                   uint64_t rowbytes) {
  switch (filter) {
    case 0:
      std::memcpy(cur, src, rowbytes);
      return 0;
    case 1:
      unfilter_sub<BPP>(src, cur, rowbytes);
      return 0;
    case 2:  // Up
      if (!prev) {
        std::memcpy(cur, src, rowbytes);
      } else {
        for (uint64_t i = 0; i < rowbytes; i++) cur[i] = src[i] + prev[i];
      }
      return 0;
    case 3:
      unfilter_avg<BPP>(src, prev, cur, rowbytes);
      return 0;
    case 4:
      unfilter_paeth<BPP>(src, prev, cur, rowbytes);
      return 0;
    default:
      return -1;
  }
}

int unfilter_row(uint8_t filter, const uint8_t* src, const uint8_t* prev, uint8_t* cur,
                 uint64_t rowbytes, int bpp) {
  switch (bpp) {  // every gray/RGB x 8/16-bit combination
    case 1: return unfilter_row_t<1>(filter, src, prev, cur, rowbytes);
    case 2: return unfilter_row_t<2>(filter, src, prev, cur, rowbytes);
    case 3: return unfilter_row_t<3>(filter, src, prev, cur, rowbytes);
    case 6: return unfilter_row_t<6>(filter, src, prev, cur, rowbytes);
    default: return -1;
  }
}

thread_local libdeflate_decompressor* g_inflater = nullptr;

// 1 = decoded, 0 = not eligible (caller uses libpng), -1 = error (err set)
int decode_png_fast(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
                    std::string* err) {
  const int bit_depth = data[24];
  const int interlace = data[28];
  if (interlace != 0 || bit_depth < 8) return 0;
  const uint64_t w = info[0], h = info[1];
  const int channels = info[2];
  const int bpp = channels * (bit_depth / 8);
  const uint64_t rowbytes = w * bpp;

  // gather the IDAT payload spans (one zlib stream split across chunks)
  std::vector<std::pair<const uint8_t*, uint64_t>> spans;
  uint64_t zlen = 0;
  uint64_t pos = 8;
  while (pos + 12 <= len) {
    const uint32_t chunk_len = be32(data + pos);
    if (pos + 12ull + chunk_len > len) { *err = "truncated png chunk"; return -1; }
    if (std::memcmp(data + pos + 4, "IDAT", 4) == 0) {
      spans.emplace_back(data + pos + 8, chunk_len);
      zlen += chunk_len;
    } else if (std::memcmp(data + pos + 4, "IEND", 4) == 0) {
      break;
    }
    pos += 12ull + chunk_len;
  }
  if (spans.empty()) { *err = "png has no IDAT"; return -1; }

  const uint8_t* zdata;
  std::vector<uint8_t> zconcat;
  if (spans.size() == 1) {
    zdata = spans[0].first;
  } else {
    zconcat.resize(zlen);
    uint64_t off = 0;
    for (auto& s : spans) {
      std::memcpy(zconcat.data() + off, s.first, s.second);
      off += s.second;
    }
    zdata = zconcat.data();
  }

  const uint64_t raw_len = h * (rowbytes + 1);
  std::vector<uint8_t> raw(raw_len);
  if (!g_inflater) g_inflater = libdeflate_alloc_decompressor();
  size_t actual = 0;
  const libdeflate_result rc = libdeflate_zlib_decompress(
      g_inflater, zdata, zlen, raw.data(), raw_len, &actual);
  if (rc != LIBDEFLATE_SUCCESS || actual != raw_len) {
    *err = "png idat inflate failed";
    return -1;
  }

  const uint8_t* prev = nullptr;
  for (uint64_t y = 0; y < h; y++) {
    const uint8_t* src = raw.data() + y * (rowbytes + 1);
    uint8_t* cur = out + y * rowbytes;
    if (unfilter_row(src[0], src + 1, prev, cur, rowbytes, bpp) != 0) {
      *err = "bad png filter byte";
      return -1;
    }
    prev = cur;
  }
  if (bit_depth == 16) {  // PNG samples are big-endian; numpy wants LE
    const uint64_t n16 = h * rowbytes / 2;
    uint16_t* p = reinterpret_cast<uint16_t*>(out);
    for (uint64_t i = 0; i < n16; i++) p[i] = uint16_t((p[i] >> 8) | (p[i] << 8));
  }
  return 1;
}

// ---------------------------------------------------------------------------
// PNG decode (full libpng API; the simplified png_image API gamma-converts
// 16-bit samples, which would break raw-value parity with cv2).
// ---------------------------------------------------------------------------

struct MemReader {
  const uint8_t* data;
  uint64_t len;
  uint64_t pos;
};

void png_mem_read(png_structp png, png_bytep out, png_size_t n) {
  auto* r = static_cast<MemReader*>(png_get_io_ptr(png));
  if (r->pos + n > r->len) {
    png_error(png, "read past end of buffer");
    return;
  }
  std::memcpy(out, r->data + r->pos, n);
  r->pos += n;
}

void png_on_error(png_structp png, png_const_charp msg) {
  auto* err = static_cast<std::string*>(png_get_error_ptr(png));
  *err = msg ? msg : "png error";
  longjmp(png_jmpbuf(png), 1);
}

void png_on_warning(png_structp, png_const_charp) {}

// 0 ok; fills `err` otherwise. Decodes into out (row-major, tightly packed).
int decode_png(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
               std::string* err) {
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, err, png_on_error,
                                           png_on_warning);
  if (!png) { *err = "png_create_read_struct failed"; return -1; }
  png_infop pinfo = png_create_info_struct(png);
  if (!pinfo) {
    png_destroy_read_struct(&png, nullptr, nullptr);
    *err = "png_create_info_struct failed";
    return -1;
  }
  std::vector<png_bytep> rows;
  if (setjmp(png_jmpbuf(png))) {  // error path: libpng longjmps here
    png_destroy_read_struct(&png, &pinfo, nullptr);
    return -1;
  }
  MemReader reader{data, len, 0};
  png_set_read_fn(png, &reader, png_mem_read);
  png_read_info(png, pinfo);

  const int color_type = png_get_color_type(png, pinfo);
  const int bit_depth = png_get_bit_depth(png, pinfo);
  if (color_type == PNG_COLOR_TYPE_GRAY && bit_depth < 8) {
    png_set_expand_gray_1_2_4_to_8(png);
  }
  if (bit_depth == 16) png_set_swap(png);  // PNG is big-endian; numpy wants LE
  png_set_interlace_handling(png);
  png_read_update_info(png, pinfo);

  const uint64_t w = png_get_image_width(png, pinfo);
  const uint64_t h = png_get_image_height(png, pinfo);
  const uint64_t rowbytes = png_get_rowbytes(png, pinfo);
  const uint64_t expect_row =
      uint64_t(info[0]) * info[2] * (info[3] / 8);
  if (w != uint64_t(info[0]) || h != uint64_t(info[1]) || rowbytes != expect_row) {
    *err = "png dimensions changed between probe and decode";
    png_destroy_read_struct(&png, &pinfo, nullptr);
    return -1;
  }
  rows.resize(h);
  for (uint64_t y = 0; y < h; y++) rows[y] = out + y * rowbytes;
  png_read_image(png, rows.data());
  png_read_end(png, nullptr);
  png_destroy_read_struct(&png, &pinfo, nullptr);
  return 0;
}

// ---------------------------------------------------------------------------
// JPEG decode
// ---------------------------------------------------------------------------

struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
  std::string* msg;
};

void jpeg_on_error(j_common_ptr cinfo) {
  auto* e = reinterpret_cast<JpegErr*>(cinfo->err);
  char buf[JMSG_LENGTH_MAX];
  (*cinfo->err->format_message)(cinfo, buf);
  *e->msg = buf;
  longjmp(e->jump, 1);
}

int decode_jpeg(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
                std::string* err, int min_w, int min_h) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  jerr.msg = err;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = jpeg_on_error;
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  jpeg_read_header(&cinfo, TRUE);
  cinfo.out_color_space = (info[2] == 1) ? JCS_GRAYSCALE : JCS_RGB;
  // same scale selection as probe_one, so output dims match the allocation
  cinfo.scale_num = jpeg_choose_scale(int(cinfo.image_width), int(cinfo.image_height),
                                      min_w, min_h);
  cinfo.scale_denom = 8;
  // (Measured dead ends, round 5: do_fancy_upsampling=FALSE and
  // JDCT_IFAST change nothing at m/8 scales — merged upsampling requires
  // unscaled geometry and the scaled IDCTs ignore dct_method — so the
  // defaults stay, keeping full-size decode byte-identical to cv2.imdecode
  // per the fuzz suite's exact-match contract.)
  jpeg_start_decompress(&cinfo);
  if (int(cinfo.output_width) != info[0] || int(cinfo.output_height) != info[1] ||
      int(cinfo.output_components) != info[2]) {
    *err = "jpeg dimensions changed between probe and decode";
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  const uint64_t stride = uint64_t(info[0]) * info[2];
  // hand the library a batch of row pointers per call: per-scanline call
  // overhead is measurable at 1/8-scale where rows are tiny
  JSAMPROW rows[8];
  while (cinfo.output_scanline < cinfo.output_height) {
    const JDIMENSION base = cinfo.output_scanline;
    const int want = int(std::min<JDIMENSION>(8, cinfo.output_height - base));
    for (int r = 0; r < want; r++) rows[r] = out + uint64_t(base + r) * stride;
    jpeg_read_scanlines(&cinfo, rows, want);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int decode_one(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
               std::string* err, int min_w, int min_h);

// -- area resampling (separable, contribution-based; the cv2 INTER_AREA
// analog) -- used by the fused decode+resize path so the per-row Python
// resize transform disappears from the host hot loop.

void area_contribs(int in_len, int out_len, std::vector<int>& starts,
                   std::vector<int>& counts, std::vector<float>& weights,
                   int& max_count) {
  const double scale = double(in_len) / out_len;
  starts.resize(out_len);
  counts.resize(out_len);
  max_count = int(std::ceil(scale)) + 1;
  weights.assign(size_t(out_len) * max_count, 0.0f);
  for (int o = 0; o < out_len; o++) {
    const double lo = o * scale;
    const double hi = std::min(double(in_len), (o + 1) * scale);
    int s = std::min(in_len - 1, int(lo));
    int e = std::max(s + 1, std::min(in_len, int(std::ceil(hi))));
    starts[o] = s;
    counts[o] = e - s;
    const double span = hi - lo;
    float wsum = 0.0f;
    for (int p = s; p < e; p++) {
      // overlap of input pixel [p, p+1) with the output footprint [lo, hi)
      const double ov = std::min(double(p + 1), hi) - std::max(double(p), lo);
      const float w = float(std::max(0.0, ov) / (span > 0.0 ? span : 1.0));
      weights[size_t(o) * max_count + (p - s)] = w;
      wsum += w;
    }
    if (wsum > 0.0f) {  // normalize away float drift
      for (int k = 0; k < e - s; k++) weights[size_t(o) * max_count + k] /= wsum;
    }
  }
}

void resize_area(const uint8_t* src, int sw, int sh, int c, uint8_t* dst, int dw, int dh) {
  std::vector<int> xs, xc, ys, yc;
  std::vector<float> xw, yw;
  int xmax = 0, ymax = 0;
  area_contribs(sw, dw, xs, xc, xw, xmax);
  area_contribs(sh, dh, ys, yc, yw, ymax);
  // horizontal pass: [sh, sw, c] -> float [sh, dw, c]
  std::vector<float> tmp(size_t(sh) * dw * c);
  for (int y = 0; y < sh; y++) {
    const uint8_t* row = src + size_t(y) * sw * c;
    float* trow = tmp.data() + size_t(y) * dw * c;
    for (int ox = 0; ox < dw; ox++) {
      const int s = xs[ox], cnt = xc[ox];
      const float* w = xw.data() + size_t(ox) * xmax;
      for (int ch = 0; ch < c; ch++) {
        float acc = 0.0f;
        for (int k = 0; k < cnt; k++) acc += w[k] * row[(s + k) * c + ch];
        trow[ox * c + ch] = acc;
      }
    }
  }
  // vertical pass: float [sh, dw, c] -> uint8 [dh, dw, c]
  for (int oy = 0; oy < dh; oy++) {
    const int s = ys[oy], cnt = yc[oy];
    const float* w = yw.data() + size_t(oy) * ymax;
    uint8_t* drow = dst + size_t(oy) * dw * c;
    for (int x = 0; x < dw * c; x++) {
      float acc = 0.0f;
      for (int k = 0; k < cnt; k++) acc += w[k] * tmp[size_t(s + k) * dw * c + x];
      const int v = int(acc + 0.5f);
      drow[x] = uint8_t(v < 0 ? 0 : (v > 255 ? 255 : v));
    }
  }
}

// -- bilinear (half-pixel centers, cv2 INTER_LINEAR semantics) -- the policy
// pairs it with area: bilinear for mild ratios (< 2x both axes, where area's
// support collapses to bilinear's anyway), area for real decimation.

void bilinear_axis(int in_len, int out_len, std::vector<int>& lo, std::vector<float>& frac) {
  lo.resize(out_len);
  frac.resize(out_len);
  const double scale = double(in_len) / out_len;
  for (int o = 0; o < out_len; o++) {
    double f = (o + 0.5) * scale - 0.5;
    int i = int(std::floor(f));
    float w = float(f - i);
    if (i < 0) { i = 0; w = 0.0f; }
    if (i >= in_len - 1) { i = in_len >= 2 ? in_len - 2 : 0; w = in_len >= 2 ? 1.0f : 0.0f; }
    lo[o] = i;
    frac[o] = w;
  }
}

// Fixed-point separable bilinear (cv2 INTER_LINEAR arithmetic: Q11 coeffs,
// horizontal pass into Q11-scaled int32 rows, vertical blend in Q22 with
// round-half-up >> 22). Horizontal-resized source rows are cached in a 2-row
// rolling window — each source row is h-resized ONCE even though consecutive
// output rows share taps — and the vertical blend is a contiguous int32 loop
// the compiler auto-vectorizes.
constexpr int kResizeBits = 11;
constexpr int kResizeScale = 1 << kResizeBits;  // 2048, cv2's INTER_RESIZE_COEF_SCALE

void resize_bilinear(const uint8_t* src, int sw, int sh, int c, uint8_t* dst, int dw, int dh) {
  std::vector<int> xlo, ylo;
  std::vector<float> xw, yw;
  bilinear_axis(sw, dw, xlo, xw);
  bilinear_axis(sh, dh, ylo, yw);
  const int row_len = dw * c;

  std::vector<int16_t> xcoef(size_t(dw) * 2);
  for (int ox = 0; ox < dw; ox++) {
    const int w1 = int(xw[ox] * kResizeScale + 0.5f);
    xcoef[size_t(ox) * 2] = int16_t(kResizeScale - w1);
    xcoef[size_t(ox) * 2 + 1] = int16_t(w1);
  }

  // rolling cache: h-resized (Q11) versions of the two source rows feeding
  // the current output row
  std::vector<int32_t> hbuf(size_t(row_len) * 2);
  int cached[2] = {-1, -1};

  // precomputed per-output-x source offsets keep the hot loops free of the
  // min() clamp and the *c multiply
  std::vector<int> xs0(dw), xs1(dw);
  for (int ox = 0; ox < dw; ox++) {
    xs0[ox] = xlo[ox] * c;
    xs1[ox] = std::min(xlo[ox] + 1, sw - 1) * c;
  }

  auto hresize = [&](int sy, int slot) {
    const uint8_t* srow = src + size_t(sy) * sw * c;
    int32_t* out = hbuf.data() + size_t(slot) * row_len;
    if (c == 3) {  // the dominant case: unrolled channel chain
      for (int ox = 0; ox < dw; ox++) {
        const uint8_t* a = srow + xs0[ox];
        const uint8_t* b = srow + xs1[ox];
        const int w0 = xcoef[size_t(ox) * 2], w1 = xcoef[size_t(ox) * 2 + 1];
        int32_t* o = out + ox * 3;
        o[0] = w0 * a[0] + w1 * b[0];
        o[1] = w0 * a[1] + w1 * b[1];
        o[2] = w0 * a[2] + w1 * b[2];
      }
    } else {
      for (int ox = 0; ox < dw; ox++) {
        const uint8_t* a = srow + xs0[ox];
        const uint8_t* b = srow + xs1[ox];
        const int w0 = xcoef[size_t(ox) * 2], w1 = xcoef[size_t(ox) * 2 + 1];
        for (int ch = 0; ch < c; ch++) {
          out[ox * c + ch] = w0 * a[ch] + w1 * b[ch];
        }
      }
    }
    cached[slot] = sy;
  };

  for (int oy = 0; oy < dh; oy++) {
    const int y0 = ylo[oy];
    const int y1 = std::min(y0 + 1, sh - 1);
    // keep an already-resized row when the window slides by one (y0 ==
    // previous y1): move it to slot 0 by swapping the slot roles
    int slot0 = (cached[0] == y0) ? 0 : (cached[1] == y0 ? 1 : -1);
    if (slot0 < 0) {
      hresize(y0, 0);
      slot0 = 0;
    }
    const int other = 1 - slot0;
    int slot1 = (y1 == y0) ? slot0 : ((cached[other] == y1) ? other : -1);
    if (slot1 < 0) {
      hresize(y1, other);
      slot1 = other;
    }
    const int32_t* r0 = hbuf.data() + size_t(slot0) * row_len;
    const int32_t* r1 = hbuf.data() + size_t(slot1) * row_len;
    const int w1 = int(yw[oy] * kResizeScale + 0.5f);
    const int w0 = kResizeScale - w1;
    uint8_t* drow = dst + size_t(oy) * dw * c;
    constexpr int kRound = 1 << (2 * kResizeBits - 1);
    for (int i = 0; i < row_len; i++) {
      // Q11*Q11 = Q22; max 2048*2048*255 < 2^31 — no overflow
      const int32_t v = (w0 * r0[i] + w1 * r1[i] + kRound) >> (2 * kResizeBits);
      drow[i] = uint8_t(v < 0 ? 0 : (v > 255 ? 255 : v));
    }
  }
}

// mirror of the python-side policy (codecs._mild_ratio): keep in sync.
// Mixed down+up shapes use bilinear (area needs decimation on both axes).
bool mild_ratio(int in_h, int in_w, int out_h, int out_w) {
  if (out_h > in_h || out_w > in_w) return true;
  return in_h < 2 * out_h && in_w < 2 * out_w;
}

int decode_resize_one(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
                      std::string* err, int min_w, int min_h, int out_w, int out_h) {
  try {
    const int sw = info[0], sh = info[1], c = info[2];
    if (info[3] != 8) {
      *err = "fused resize supports 8-bit images only";
      return -1;
    }
    if (sw == out_w && sh == out_h) {
      return decode_one(data, len, info, out, err, min_w, min_h);
    }
    std::vector<uint8_t> scratch(size_t(sw) * sh * c);
    if (decode_one(data, len, info, scratch.data(), err, min_w, min_h) != 0) return -1;
    if (mild_ratio(sh, sw, out_h, out_w)) {
      resize_bilinear(scratch.data(), sw, sh, c, out, out_w, out_h);
    } else {
      resize_area(scratch.data(), sw, sh, c, out, out_w, out_h);
    }
    return 0;
  } catch (const std::exception& e) {
    *err = e.what();
    return -1;
  } catch (...) {
    *err = "unknown C++ exception during image decode+resize";
    return -1;
  }
}

int decode_one(const uint8_t* data, uint64_t len, const int32_t* info, uint8_t* out,
               std::string* err, int min_w, int min_h) {
  // C++ exceptions (bad_alloc from the scratch vectors, etc.) must not cross
  // the extern "C" boundary — that would std::terminate the worker process
  // instead of letting Python fall back to the per-image path.
  try {
    if (len >= 8 && std::memcmp(data, kPngMagic, 8) == 0) {
      const int rc = decode_png_fast(data, len, info, out, err);
      if (rc != 0) return rc == 1 ? 0 : -1;
      return decode_png(data, len, info, out, err);
    }
    return decode_jpeg(data, len, info, out, err, min_w, min_h);
  } catch (const std::exception& e) {
    *err = e.what();
    return -1;
  } catch (...) {
    *err = "unknown C++ exception during image decode";
    return -1;
  }
}

thread_local std::string g_error;

// Shared fan-out scaffolding for the batch entry points: run fn(i, &err) for
// every index, inline when threads <= 1, else over an internal pool with
// first-failure reporting. Returns -1 on success, else the lowest failed index
// (g_error carries its message).
template <typename Fn>
int64_t run_batch(int64_t n, int threads, Fn&& fn) {
  if (n <= 0) return -1;
  if (threads <= 1 || n == 1) {
    for (int64_t i = 0; i < n; i++) {
      std::string err;
      if (fn(i, &err) != 0) {
        g_error = err;
        return i;
      }
    }
    return -1;
  }
  const int nt = int(std::min<int64_t>(threads, n));
  std::atomic<int64_t> next{0};
  std::atomic<bool> any_fail{false};
  std::mutex fail_mutex;
  int64_t fail_idx = -1;
  std::string fail_err;
  std::vector<std::thread> pool;
  pool.reserve(nt);
  try {
    for (int t = 0; t < nt; t++) {
      pool.emplace_back([&]() {
        for (;;) {
          const int64_t i = next.fetch_add(1, std::memory_order_relaxed);
          if (i >= n) return;
          if (any_fail.load(std::memory_order_relaxed)) return;  // stop early
          std::string err;
          if (fn(i, &err) != 0) {
            any_fail.store(true, std::memory_order_relaxed);
            std::lock_guard<std::mutex> lock(fail_mutex);
            if (fail_idx < 0 || i < fail_idx) {
              fail_idx = i;
              fail_err = err;
            }
          }
        }
      });
    }
  } catch (...) {  // thread spawn failed: join what started, run inline
    for (auto& th : pool) th.join();
    for (int64_t i = 0; i < n; i++) {
      std::string err;
      if (fn(i, &err) != 0) {
        g_error = err;
        return i;
      }
    }
    return -1;
  }
  for (auto& th : pool) th.join();
  if (fail_idx >= 0) {
    g_error = fail_err.empty() ? "image decode failed" : fail_err;
    return fail_idx;
  }
  return -1;
}

}  // namespace

extern "C" {

const char* pstpu_img_last_error() { return g_error.c_str(); }

// Probe n images; infos is n*4 int32 [w,h,c,bit_depth]. Returns -1 when all
// probed fine, else the index of the first unsupported/corrupt image.
// min_w/min_h > 0 turn on scaled JPEG decode: reported dims are the smallest
// m/8 DCT scale still covering (min_w, min_h); PNG dims are unaffected.
int64_t pstpu_img_probe_batch2(int64_t n, const uint8_t* const* datas, const uint64_t* lens,
                               int32_t* infos, int32_t min_w, int32_t min_h) {
  for (int64_t i = 0; i < n; i++) {
    if (probe_one(datas[i], lens[i], infos + i * 4, min_w, min_h) != 0) return i;
  }
  return -1;
}

int64_t pstpu_img_probe_batch(int64_t n, const uint8_t* const* datas, const uint64_t* lens,
                              int32_t* infos) {
  return pstpu_img_probe_batch2(n, datas, lens, infos, 0, 0);
}

// Decode n images into caller-allocated buffers (outs[i] sized from infos).
// `threads` <= 1 decodes inline on the calling thread (callers inside a reader
// worker pool want this — the pool already parallelizes across row groups);
// higher values fan out across an internal thread pool. min_w/min_h must match
// the probe call that sized the outputs. Returns -1 on success, else the index
// of the first failure (pstpu_img_last_error has the message).
int64_t pstpu_img_decode_batch2(int64_t n, const uint8_t* const* datas, const uint64_t* lens,
                                uint8_t* const* outs, const int32_t* infos, int threads,
                                int32_t min_w, int32_t min_h) {
  return run_batch(n, threads, [&](int64_t i, std::string* err) {
    return decode_one(datas[i], lens[i], infos + i * 4, outs[i], err, min_w, min_h);
  });
}

// Standalone area resample of one decoded 8-bit image (OpenCV-less
// deployments use this where cv2.resize would run). Returns 0, or -1 on
// invalid dims.
int64_t pstpu_img_resize_area(const uint8_t* src, int32_t sw, int32_t sh, int32_t c,
                              uint8_t* dst, int32_t dw, int32_t dh) {
  if (sw < 1 || sh < 1 || dw < 1 || dh < 1 || c < 1) return -1;
  try {
    resize_area(src, sw, sh, c, dst, dw, dh);
    return 0;
  } catch (...) {
    g_error = "resize failed";
    return -1;
  }
}

// Standalone bilinear resample (half-pixel centers; the mild-ratio half of
// the shared resize policy).
int64_t pstpu_img_resize_bilinear(const uint8_t* src, int32_t sw, int32_t sh, int32_t c,
                                  uint8_t* dst, int32_t dw, int32_t dh) {
  if (sw < 1 || sh < 1 || dw < 1 || dh < 1 || c < 1) return -1;
  try {
    resize_bilinear(src, sw, sh, c, dst, dw, dh);
    return 0;
  } catch (...) {
    g_error = "resize failed";
    return -1;
  }
}

// Fused decode+resize: each image is decoded at its probed dims (JPEG: the
// min_w/min_h DCT scale, matching the probe) then resampled — bilinear for
// mild ratios, area for >= 2x decimation (the shared policy) — into its
// caller-allocated out_h x out_w output — one GIL-released call replaces the
// per-row Python resize transform. 8-bit images only.
int64_t pstpu_img_decode_resize_batch(int64_t n, const uint8_t* const* datas,
                                      const uint64_t* lens, uint8_t* const* outs,
                                      const int32_t* infos, int threads,
                                      int32_t min_w, int32_t min_h,
                                      int32_t out_w, int32_t out_h) {
  return run_batch(n, threads, [&](int64_t i, std::string* err) {
    return decode_resize_one(datas[i], lens[i], infos + i * 4, outs[i], err,
                             min_w, min_h, out_w, out_h);
  });
}

int64_t pstpu_img_decode_batch(int64_t n, const uint8_t* const* datas, const uint64_t* lens,
                               uint8_t* const* outs, const int32_t* infos, int threads) {
  return pstpu_img_decode_batch2(n, datas, lens, outs, infos, threads, 0, 0);
}

}  // extern "C"
