"""ctypes bindings for the batched PNG/JPEG decoder (image_codec.cpp).

One native call decodes a whole column's worth of encoded image cells with the
GIL released, replacing the reference's per-image Python+OpenCV loop
(reference codecs.py:92-111) — the measured input-pipeline bottleneck on the
image path. Availability is probed like the other native targets: any
build/load failure makes :func:`is_available` False and
``CompressedImageCodec`` stays on its per-image OpenCV path.

Threading: ``PSTPU_IMG_THREADS`` is the per-PROCESS native decode thread
budget (default: CPU count), shared cooperatively across concurrent calls
(:func:`_thread_grant`): a lone caller (dummy pool, benchmark, narrow reader)
fans its column out across all idle cores, while a full worker pool's
concurrent calls each take the free remainder (floor 1) — total decode
threads stay ~budget instead of pool_width x budget. Pass ``threads=N``
explicitly to bypass the accounting.
"""

from __future__ import annotations

import contextlib
import ctypes
import logging
import os
import threading

import numpy as np

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


class NativeDecodeError(RuntimeError):
    """Native probe/decode refused the payload; callers fall back to OpenCV."""

    def __init__(self, message, index=None):
        super().__init__(message)
        self.index = index


def _load_library():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            from petastorm_tpu.native.build import build_img
            lib = ctypes.CDLL(build_img(quiet=True))
        except Exception as e:  # noqa: BLE001 - fall back to the OpenCV path
            logger.info('native image codec unavailable (%s); using OpenCV per-image decode', e)
            _load_failed = True
            return None
        lib.pstpu_img_last_error.restype = ctypes.c_char_p
        lib.pstpu_img_probe_batch2.restype = ctypes.c_int64
        lib.pstpu_img_probe_batch2.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int32, ctypes.c_int32]
        lib.pstpu_img_decode_batch2.restype = ctypes.c_int64
        lib.pstpu_img_decode_batch2.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int32, ctypes.c_int32]
        lib.pstpu_img_decode_resize_batch.restype = ctypes.c_int64
        lib.pstpu_img_decode_resize_batch.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32]
        lib.pstpu_img_resize_area.restype = ctypes.c_int64
        lib.pstpu_img_resize_area.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        lib.pstpu_img_resize_bilinear.restype = ctypes.c_int64
        lib.pstpu_img_resize_bilinear.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        _lib = lib
        return _lib


def is_available():
    return _load_library() is not None


def batch_fn_addrs():
    """Raw C addresses of the batched probe/decode entry points, for the fused
    row-group kernel (``pstpu_read_fused``) to call THROUGH — image decode then
    happens inside the same native transition as the page scan and value
    decode, with no link-time coupling between the two libraries. Returns
    ``(probe_addr, decode_addr)`` or None when the codec is unavailable."""
    lib = _load_library()
    if lib is None:
        return None
    return (ctypes.cast(lib.pstpu_img_probe_batch2, ctypes.c_void_p).value,
            ctypes.cast(lib.pstpu_img_decode_batch2, ctypes.c_void_p).value)


def _default_threads():
    """The per-PROCESS native decode thread budget (``PSTPU_IMG_THREADS``).
    Not a per-call fan-out: concurrent callers share it through
    :func:`_thread_grant`.

    Unset: CPU count in a top-level process; 1 in a multiprocessing CHILD not
    configured by our own pool bootstrap (torch DataLoader workers, user
    process fan-outs) — sibling processes cannot see each other's grants, so
    each claiming the full budget would oversubscribe cores by the sibling
    count. Set-but-unparseable degrades to 1 (the safe floor), never to the
    full budget."""
    raw = os.environ.get('PSTPU_IMG_THREADS')
    if raw is not None:
        try:
            return max(1, int(raw))
        except ValueError:
            return 1
    import multiprocessing
    if multiprocessing.parent_process() is not None:
        return 1
    return max(1, os.cpu_count() or 1)


_budget_lock = threading.Lock()
_threads_in_use = 0


@contextlib.contextmanager
def _thread_grant(requested):
    """Cooperative intra-call fan-out: ``requested=None`` (the default) takes
    whatever share of the process-wide budget is currently free (floor 1, so
    callers always proceed) and returns it afterwards — a lone worker decoding
    a column fans out across all idle cores, while a full worker pool's
    concurrent calls naturally degrade to ~1 thread each instead of
    oversubscribing cores by pool_width x budget (the failure mode the old
    'leave PSTPU_IMG_THREADS=1 inside pools' guidance worked around). The
    floor means N concurrent callers can transiently hold budget + (N - 1)
    threads (first caller takes the free budget, later ones still get 1) —
    bounded by the pool width and accepted so callers never block on the
    grant. An explicit integer bypasses the accounting (the caller's exact
    contract)."""
    if requested is not None:
        yield max(1, int(requested))
        return
    global _threads_in_use
    budget = _default_threads()
    with _budget_lock:
        grant = max(1, budget - _threads_in_use)
        _threads_in_use += grant
    try:
        yield grant
    finally:
        with _budget_lock:
            _threads_in_use -= grant


def decode_images(buffers, threads=None, min_size=None):
    """Decode a list of encoded PNG/JPEG cells (bytes/memoryview) in one native
    call. Returns a list of numpy arrays — ``(H, W)`` for grayscale, ``(H, W, 3)``
    RGB otherwise; dtype uint8, or uint16 for 16-bit PNG.

    ``min_size=(min_h, min_w)`` enables scaled JPEG decode: each JPEG comes out
    at the smallest libjpeg m/8 DCT scale whose dims still cover the minimum
    (full size if the image is already smaller) — most pixels of a large photo
    are never computed, which is the cheapest possible "resize". PNGs ignore
    the hint (the format has no scaled decode).

    Raises :class:`NativeDecodeError` when any cell is an unsupported flavor
    (palette/alpha PNG, CMYK JPEG, corrupt data, non-image bytes) — the caller
    falls back to its per-image path.
    """
    lib = _load_library()
    if lib is None:
        raise NativeDecodeError('native image codec not available')
    n = len(buffers)
    if n == 0:
        return []
    min_h, min_w = (int(min_size[0]), int(min_size[1])) if min_size else (0, 0)
    # numpy views give stable base addresses for arbitrary (read-only) buffers
    views = [np.frombuffer(b, dtype=np.uint8) for b in buffers]
    ptrs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
    lens = (ctypes.c_uint64 * n)(*[v.size for v in views])
    infos = np.empty((n, 4), dtype=np.int32)
    infos_p = infos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    rc = lib.pstpu_img_probe_batch2(n, ptrs, lens, infos_p, min_w, min_h)
    if rc != -1:
        raise NativeDecodeError('unsupported or corrupt image at index {}'.format(rc), index=rc)

    outs = []
    out_ptrs = (ctypes.c_void_p * n)()
    for i in range(n):
        w, h, c, depth = (int(x) for x in infos[i])
        dtype = np.uint16 if depth == 16 else np.uint8
        shape = (h, w) if c == 1 else (h, w, c)
        arr = np.empty(shape, dtype=dtype)
        outs.append(arr)
        out_ptrs[i] = arr.ctypes.data

    with _thread_grant(threads) as fanout:
        rc = lib.pstpu_img_decode_batch2(n, ptrs, lens, out_ptrs, infos_p, fanout,
                                         min_w, min_h)
    if rc != -1:
        raise NativeDecodeError('image decode failed at index {}: {}'.format(
            rc, lib.pstpu_img_last_error().decode(errors='replace')), index=rc)
    return outs


def decode_images_auto(buffers, threads=None, min_size=None):
    """Decode a column of image cells with ONE header probe, into the best
    output layout the column admits:

      * every cell probes to the same dims/depth (the normal case for a
        prepared training store) -> ONE ``[N, H, W(, C)]`` array; the
        per-image out pointers simply walk the rows of a single allocation,
        so the per-image allocations and the column-stack copy that would
        follow them disappear;
      * mixed dims -> a list of per-image arrays (same outputs as
        :func:`decode_images`) WITHOUT re-probing the headers.

    Raises :class:`NativeDecodeError` like :func:`decode_images` for
    unsupported cells."""
    lib = _load_library()
    if lib is None:
        raise NativeDecodeError('native image codec not available')
    n = len(buffers)
    if n == 0:
        return []
    min_h, min_w = (int(min_size[0]), int(min_size[1])) if min_size else (0, 0)
    views = [np.frombuffer(b, dtype=np.uint8) for b in buffers]
    ptrs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
    lens = (ctypes.c_uint64 * n)(*[v.size for v in views])
    infos = np.empty((n, 4), dtype=np.int32)
    infos_p = infos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    rc = lib.pstpu_img_probe_batch2(n, ptrs, lens, infos_p, min_w, min_h)
    if rc != -1:
        raise NativeDecodeError('unsupported or corrupt image at index {}'.format(rc), index=rc)

    uniform = n == 1 or not (infos != infos[0]).any()
    if uniform:
        w, h, c, depth = (int(x) for x in infos[0])
        dtype = np.uint16 if depth == 16 else np.uint8
        shape = (n, h, w) if c == 1 else (n, h, w, c)
        result = np.empty(shape, dtype=dtype)
        stride = result.strides[0]
        base = result.ctypes.data
        out_ptrs = (ctypes.c_void_p * n)(*[base + i * stride for i in range(n)])
    else:
        result = []
        out_ptrs = (ctypes.c_void_p * n)()
        for i in range(n):
            w, h, c, depth = (int(x) for x in infos[i])
            dtype = np.uint16 if depth == 16 else np.uint8
            arr = np.empty((h, w) if c == 1 else (h, w, c), dtype=dtype)
            result.append(arr)
            out_ptrs[i] = arr.ctypes.data
    with _thread_grant(threads) as fanout:
        rc = lib.pstpu_img_decode_batch2(n, ptrs, lens, out_ptrs, infos_p, fanout,
                                         min_w, min_h)
    if rc != -1:
        raise NativeDecodeError('image decode failed at index {}: {}'.format(
            rc, lib.pstpu_img_last_error().decode(errors='replace')), index=rc)
    return result


def decode_images_block(buffers, threads=None, min_size=None):
    """:func:`decode_images_auto` restricted to the single-block layout:
    returns the ``[N, H, W(, C)]`` array, or ``None`` when dims differ."""
    result = decode_images_auto(buffers, threads=threads, min_size=min_size)
    return result if isinstance(result, np.ndarray) else None


def _resize_native(img, size, symbol_name):
    lib = _load_library()
    if lib is None:
        raise NativeDecodeError('native image codec not available')
    if img.dtype != np.uint8:
        raise ValueError('native resize supports uint8, got {}'.format(img.dtype))
    out_h, out_w = int(size[0]), int(size[1])
    c = img.shape[2] if img.ndim == 3 else 1
    src = np.ascontiguousarray(img)
    out = np.empty((out_h, out_w) + ((c,) if img.ndim == 3 else ()), np.uint8)
    rc = getattr(lib, symbol_name)(src.ctypes.data, img.shape[1], img.shape[0], c,
                                   out.ctypes.data, out_w, out_h)
    if rc != 0:
        raise NativeDecodeError('native resize failed: {}'.format(
            lib.pstpu_img_last_error().decode(errors='replace')))
    return out


def resize_area_image(img, size):
    """Area-resample one decoded uint8 image to ``size=(out_h, out_w)`` with
    the native resampler — the cv2 ``INTER_AREA`` stand-in for OpenCV-less
    deployments (within 1 LSB of cv2 when both axes downscale or both
    upscale; cv2's mixed down+up INTER_AREA is a non-separable special case
    this separable implementation does not chase). Returns a new array;
    raises :class:`NativeDecodeError` when the native library is
    unavailable."""
    return _resize_native(img, size, 'pstpu_img_resize_area')


def resize_bilinear_image(img, size):
    """Bilinear-resample one decoded uint8 image (half-pixel centers, cv2
    ``INTER_LINEAR`` semantics) — the mild-ratio half of the shared resize
    policy (see ``codecs._resize_image``)."""
    return _resize_native(img, size, 'pstpu_img_resize_bilinear')


def decode_images_resized(buffers, size, threads=None, min_size=None):
    """Fused decode + resize of a whole column into ONE
    ``[N, out_h, out_w(, C)]`` allocation. ``size`` is ``(out_h, out_w)``.
    Each image decodes at its probed dims (JPEG: at the smallest m/8 DCT scale
    covering the target, so most pixels of a large photo never exist) and is
    then resampled per the shared policy — bilinear below 2x decimation, area
    at >= 2x (see ``codecs._resize_image``) — into its output row: one
    GIL-released native call replaces a per-row Python resize transform.

    ``min_size=(min_h, min_w)`` overrides the DCT-scale floor (an explicit
    ``image_decode_hints`` entry wins over the resize target — e.g. decode at
    >= 2x the target for a supersampled downscale); default is the target
    itself.

    Returns ``None`` when the column mixes channel counts or carries 16-bit
    images (callers fall back to their per-image path); raises
    :class:`NativeDecodeError` for unsupported/corrupt cells."""
    lib = _load_library()
    if lib is None:
        raise NativeDecodeError('native image codec not available')
    n = len(buffers)
    if n == 0:
        return None
    out_h, out_w = int(size[0]), int(size[1])
    if out_h < 1 or out_w < 1:
        raise ValueError('resize target must be positive, got {}'.format(size))
    min_h, min_w = (int(min_size[0]), int(min_size[1])) if min_size else (out_h, out_w)
    views = [np.frombuffer(b, dtype=np.uint8) for b in buffers]
    ptrs = (ctypes.c_void_p * n)(*[v.ctypes.data for v in views])
    lens = (ctypes.c_uint64 * n)(*[v.size for v in views])
    infos = np.empty((n, 4), dtype=np.int32)
    infos_p = infos.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

    rc = lib.pstpu_img_probe_batch2(n, ptrs, lens, infos_p, min_w, min_h)
    if rc != -1:
        raise NativeDecodeError('unsupported or corrupt image at index {}'.format(rc), index=rc)
    if (infos[:, 3] != 8).any() or (infos[:, 2] != infos[0, 2]).any():
        return None  # 16-bit or mixed gray/RGB column: per-image path
    c = int(infos[0, 2])
    shape = (n, out_h, out_w) if c == 1 else (n, out_h, out_w, c)
    out = np.empty(shape, dtype=np.uint8)
    stride = out.strides[0]
    base = out.ctypes.data
    out_ptrs = (ctypes.c_void_p * n)(*[base + i * stride for i in range(n)])
    with _thread_grant(threads) as fanout:
        rc = lib.pstpu_img_decode_resize_batch(n, ptrs, lens, out_ptrs, infos_p,
                                               fanout, min_w, min_h, out_w, out_h)
    if rc != -1:
        raise NativeDecodeError('image decode+resize failed at index {}: {}'.format(
            rc, lib.pstpu_img_last_error().decode(errors='replace')), index=rc)
    return out
