"""ctypes bindings for the C++ shared-memory SPSC ring (shm_ring.cpp).

One ring per worker process, worker -> main. Non-blocking C primitives;
blocking (with stop-aware sleep-poll) lives here in Python. Availability is
probed like the row-group kernel: any build/load failure makes
``is_available()`` False and the process pool falls back to zmq transport.
"""

from __future__ import annotations

import ctypes
import logging
import threading
import time

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

DEFAULT_RING_BYTES = 64 << 20


class RingHeaderStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct RingHeader`` (shm_ring.cpp) — the
    shared-memory segment layout both sides of the ring map. Python never
    touches the header directly (all access goes through the C API), but the
    mirror is the executable documentation of the cross-process layout and
    lint rule PT900 proves it identical to the C struct, so a C-side edit
    that would desynchronize producer and consumer mappings fails the linter
    instead of corrupting rings at runtime."""

    _fields_ = [
        ('head', ctypes.c_uint64),
        ('tail', ctypes.c_uint64),
        ('capacity', ctypes.c_uint64),
        ('magic', ctypes.c_uint64),
        ('pad', ctypes.c_char * 32),
    ]


#: byte offset of the ring's data area inside the shm segment
RING_HEADER_BYTES = ctypes.sizeof(RingHeaderStruct)

#: broadcast-ring consumer slots per segment (must match kBcastSlots in
#: shm_ring.cpp — PT900 proves the whole header layout, which pins this too)
BCAST_MAX_CONSUMERS = 8


class BcastHeaderStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct BcastHeader`` (shm_ring.cpp) — the
    multi-consumer broadcast segment both the serve daemon and its attached
    consumers map. As with :class:`RingHeaderStruct`, Python never touches the
    header directly; the mirror is executable documentation of the
    cross-process layout, and lint rule PT900 proves it identical to the C
    struct so a C-side edit that would desynchronize producer and consumer
    mappings fails the linter instead of corrupting rings at runtime."""

    _fields_ = [
        ('tail', ctypes.c_uint64),
        ('capacity', ctypes.c_uint64),
        ('magic', ctypes.c_uint64),
        ('max_consumers', ctypes.c_uint64),
        ('epoch', ctypes.c_uint64),
        ('pad0', ctypes.c_char * 24),
        ('heads', ctypes.c_uint64 * 8),
        ('states', ctypes.c_uint64 * 8),
        ('gens', ctypes.c_uint64 * 8),
    ]


#: byte offset of the broadcast ring's data area inside the shm segment
BCAST_HEADER_BYTES = ctypes.sizeof(BcastHeaderStruct)


class IdleWait(object):
    """Escalating wait for ring poll loops: spin → ``sched_yield`` → sleep.

    The consumer/producer wait loops used to be flat sleep-poll backoffs; on a
    host running many attached serve consumers the aggregate idle polling
    burns cores while the producer is quiet. This helper keeps the first
    misses latency-free (pure spins), yields the core for the next tier, and
    escalates to exponentially longer sleeps only when the peer is genuinely
    idle. Spins are accounted to the ``ring_idle_spins`` counter (flushed in
    batches so the hot loop never touches the metrics lock per iteration).

    Call :meth:`wait` per empty poll and :meth:`reset` on progress.
    """

    __slots__ = ('_spins', '_yields', '_sleep_s', '_max_sleep_s', '_misses',
                 '_cur_sleep', '_pending_spins')

    def __init__(self, spins=64, yields=64, sleep_s=0.0002, max_sleep_s=0.002):
        self._spins = spins
        self._yields = yields
        self._sleep_s = sleep_s
        self._max_sleep_s = max_sleep_s
        self._misses = 0
        self._cur_sleep = sleep_s
        self._pending_spins = 0

    def _flush(self):
        if self._pending_spins:
            from petastorm_tpu import observability as obs
            obs.count('ring_idle_spins', self._pending_spins)
            self._pending_spins = 0

    def wait(self):
        """One empty-poll step: spin, yield, or sleep per the escalation."""
        self._misses += 1
        if self._misses <= self._spins:
            self._pending_spins += 1
            return
        if self._misses <= self._spins + self._yields:
            import os
            os.sched_yield()
            return
        if self._misses == self._spins + self._yields + 1:
            self._flush()  # entering the sleep tier: the peer is idle
        time.sleep(self._cur_sleep)
        self._cur_sleep = min(self._cur_sleep * 2, self._max_sleep_s)

    def reset(self):
        """Progress was made: restart the escalation at the spin tier."""
        if self._misses:
            self._flush()
            self._misses = 0
            self._cur_sleep = self._sleep_s


def _load_library():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            from petastorm_tpu.native.build import build_shm
            lib = ctypes.CDLL(build_shm(quiet=True))
        except Exception as e:  # noqa: BLE001 - fall back to zmq transport
            logger.info('shm ring unavailable (%s); process pool will use zmq', e)
            _load_failed = True
            return None
        lib.pstpu_ring_create.restype = ctypes.c_void_p
        lib.pstpu_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_attach.restype = ctypes.c_void_p
        lib.pstpu_ring_attach.argtypes = [ctypes.c_char_p]
        lib.pstpu_ring_last_error.restype = ctypes.c_char_p
        lib.pstpu_ring_capacity.restype = ctypes.c_uint64
        lib.pstpu_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_free_space.restype = ctypes.c_uint64
        lib.pstpu_ring_free_space.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_write.restype = ctypes.c_int
        lib.pstpu_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_write2.restype = ctypes.c_int
        lib.pstpu_ring_write2.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_writev.restype = ctypes.c_int
        lib.pstpu_ring_writev.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_void_p),
                                          ctypes.POINTER(ctypes.c_uint64),
                                          ctypes.c_int32]
        lib.pstpu_ring_reserve.restype = ctypes.c_void_p
        lib.pstpu_ring_reserve.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                           ctypes.POINTER(ctypes.c_int32)]
        lib.pstpu_ring_commit.restype = ctypes.c_int
        lib.pstpu_ring_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.pstpu_ring_abort.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_next_len.restype = ctypes.c_int64
        lib.pstpu_ring_next_len.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_read.restype = ctypes.c_int64
        lib.pstpu_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        # zero-copy consumer views + slot-lifetime guard (docs/native.md)
        lib.pstpu_ring_peek.restype = ctypes.c_longlong
        lib.pstpu_ring_peek.argtypes = [ctypes.c_void_p,
                                        ctypes.POINTER(ctypes.c_ulonglong),
                                        ctypes.c_ulonglong]
        lib.pstpu_ring_peek_copy.restype = ctypes.c_longlong
        lib.pstpu_ring_peek_copy.argtypes = [ctypes.c_void_p, ctypes.c_void_p,
                                             ctypes.c_ulonglong,
                                             ctypes.POINTER(ctypes.c_ulonglong)]
        lib.pstpu_ring_has_unread.restype = ctypes.c_int
        lib.pstpu_ring_has_unread.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_release.restype = ctypes.c_int
        lib.pstpu_ring_release.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong]
        lib.pstpu_guard_protect.restype = ctypes.c_longlong
        lib.pstpu_guard_protect.argtypes = [ctypes.c_void_p, ctypes.c_ulonglong,
                                            ctypes.c_int]
        lib.pstpu_ring_close.argtypes = [ctypes.c_void_p]
        # broadcast (single-producer, multi-consumer) ring — the serve
        # daemon's fan-out transport
        lib.pstpu_bcast_create.restype = ctypes.c_void_p
        lib.pstpu_bcast_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_bcast_attach.restype = ctypes.c_void_p
        lib.pstpu_bcast_attach.argtypes = [ctypes.c_char_p]
        lib.pstpu_bcast_capacity.restype = ctypes.c_uint64
        lib.pstpu_bcast_capacity.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_join.restype = ctypes.c_int64
        lib.pstpu_bcast_join.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_leave.restype = ctypes.c_int64
        lib.pstpu_bcast_leave.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pstpu_bcast_evict.restype = ctypes.c_int64
        lib.pstpu_bcast_evict.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pstpu_bcast_state.restype = ctypes.c_int64
        lib.pstpu_bcast_state.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pstpu_bcast_lag.restype = ctypes.c_int64
        lib.pstpu_bcast_lag.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pstpu_bcast_consumer_count.restype = ctypes.c_int64
        lib.pstpu_bcast_consumer_count.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_free_space.restype = ctypes.c_uint64
        lib.pstpu_bcast_free_space.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_tail.restype = ctypes.c_uint64
        lib.pstpu_bcast_tail.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_min_head.restype = ctypes.c_uint64
        lib.pstpu_bcast_min_head.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_write.restype = ctypes.c_int
        lib.pstpu_bcast_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                          ctypes.c_uint64]
        lib.pstpu_bcast_writev.restype = ctypes.c_int
        lib.pstpu_bcast_writev.argtypes = [ctypes.c_void_p,
                                           ctypes.POINTER(ctypes.c_void_p),
                                           ctypes.POINTER(ctypes.c_uint64),
                                           ctypes.c_int32]
        lib.pstpu_bcast_reserve.restype = ctypes.c_void_p
        lib.pstpu_bcast_reserve.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                            ctypes.POINTER(ctypes.c_int32)]
        lib.pstpu_bcast_commit.restype = ctypes.c_int
        lib.pstpu_bcast_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.pstpu_bcast_abort.argtypes = [ctypes.c_void_p]
        lib.pstpu_bcast_next_len.restype = ctypes.c_int64
        lib.pstpu_bcast_next_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.pstpu_bcast_read.restype = ctypes.c_int64
        lib.pstpu_bcast_read.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_void_p, ctypes.c_uint64]
        lib.pstpu_bcast_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_available():
    return _load_library() is not None


class ShmRing(object):
    """One SPSC byte ring in POSIX shared memory."""

    def __init__(self, handle, lib):
        self._handle = handle
        self._lib = lib

    @classmethod
    def create(cls, name, capacity=DEFAULT_RING_BYTES):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_ring_create(name.encode(), capacity)
        if not handle:
            raise OSError('ring create failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @classmethod
    def attach(cls, name):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_ring_attach(name.encode())
        if not handle:
            raise OSError('ring attach failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @property
    def capacity(self):
        return self._lib.pstpu_ring_capacity(self._handle)

    def try_write(self, data):
        """True = written; False = ring currently full. Raises when the
        message can never fit (grow ``ring_bytes``)."""
        rc = self._lib.pstpu_ring_write(self._handle, data, len(data))
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 len(data), self.capacity))
        return rc == 1

    def write(self, data, stop_check=None, poll_s=0.0002):
        """Blocking write with optional ``stop_check()`` abort callback."""
        while not self.try_write(data):
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)
        return True

    def try_write2(self, header, payload):
        """Gather write of header+payload as one message — no concat copy."""
        rc = self._lib.pstpu_ring_write2(self._handle, header, len(header),
                                         payload, len(payload))
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 len(header) + len(payload), self.capacity))
        return rc == 1

    def write2(self, header, payload, stop_check=None, poll_s=0.0002):
        while not self.try_write2(header, payload):
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)
        return True

    @staticmethod
    def _gather(parts):
        """(ptr_array, len_array, total, keepalive) for a list of bytes-likes /
        contiguous numpy arrays. Pointers are raw addresses — the keepalive
        list MUST outlive the write call (it does: writev holds it)."""
        import numpy as np
        n = len(parts)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        total = 0
        for i, p in enumerate(parts):
            if not isinstance(p, np.ndarray):
                # read-only buffers (bytes) export fine through frombuffer
                p = np.frombuffer(p, np.uint8) if len(p) else np.empty(0, np.uint8)
            keepalive.append(p)
            ptrs[i] = p.ctypes.data if p.size else None
            lens[i] = p.nbytes
            total += p.nbytes
        return ptrs, lens, total, keepalive

    def try_writev(self, parts):
        """Gather write of N bytes-like/ndarray segments as one message — the
        zero-join publish channel for whole column blocks."""
        ptrs, lens, total, keepalive = self._gather(parts)
        rc = self._lib.pstpu_ring_writev(self._handle, ptrs, lens, len(parts))
        del keepalive
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 total, self.capacity))
        return rc == 1

    def writev(self, parts, stop_check=None, poll_s=0.0002):
        ptrs, lens, total, keepalive = self._gather(parts)
        if total + 8 > self.capacity:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 total, self.capacity))
        while True:
            rc = self._lib.pstpu_ring_writev(self._handle, ptrs, lens, len(parts))
            if rc == 1:
                return True
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)

    def try_reserve(self, max_len):
        """Reserve a CONTIGUOUS writable in-ring region of up to ``max_len``
        payload bytes — the in-place publish channel: a fused batch decode
        assembles its rows directly in the slot the consumer maps, and
        :meth:`commit` makes it visible with a header write instead of a copy.
        Returns a writable memoryview of exactly ``max_len`` bytes, or None
        when the ring currently lacks space (retry); raises ValueError when a
        message of that size can never fit (callers use the copy channel).
        Exactly one reservation may be pending; :meth:`commit` or
        :meth:`abort` resolves it before any other write."""
        status = ctypes.c_int32(0)
        ptr = self._lib.pstpu_ring_reserve(self._handle, max_len,
                                           ctypes.byref(status))
        if status.value < 0:
            raise ValueError(
                'reservation of {} bytes cannot fit ring capacity {} — increase the '
                'process pool ring_bytes (or shrink row groups)'.format(
                    max_len, self.capacity))
        if not ptr:
            return None
        # the view aliases ring shared memory; the ring handle (held by the
        # worker for the pool's lifetime) anchors the mapping
        return memoryview((ctypes.c_char * max_len).from_address(ptr)).cast('B')  # noqa: PT500 - producer-side slot, ring outlives it

    def reserve(self, max_len, stop_check=None, poll_s=0.0002):
        """Blocking :meth:`try_reserve` with a stop-aware poll loop (the same
        contract as :meth:`write`); returns None when stopped."""
        while True:
            mv = self.try_reserve(max_len)
            if mv is not None:
                return mv
            if stop_check is not None and stop_check():
                return None
            time.sleep(poll_s)

    def commit(self, actual_len):
        """Publish the pending reservation with its actual message length."""
        if self._lib.pstpu_ring_commit(self._handle, actual_len) != 0:
            raise ValueError('ring commit failed: {}'.format(
                self._lib.pstpu_ring_last_error().decode()))

    def abort(self):
        """Drop the pending reservation (nothing became visible)."""
        self._lib.pstpu_ring_abort(self._handle)

    def has_message(self):
        """True when an UNREAD committed message is waiting. NON-consuming
        probe — the supervisor uses it to tell when a dead worker's ring has
        drained without stealing the message from the consumer loop. Probes
        past the zero-copy peek cursor, so messages already delivered as
        borrowed views (but not yet released) do not count as pending;
        without peeks it is identical to probing from the shared head. A
        closed ring reports empty (callers may hold a reference past
        close)."""
        if not self._handle:
            return False
        return self._lib.pstpu_ring_has_unread(self._handle) == 1

    def try_read(self):
        """One message as bytes, or None when the ring is empty."""
        mv = self.try_read_view()
        return None if mv is None else bytes(mv)

    def try_read_view(self):
        """One message as a memoryview (zero further copies: consumers may
        slice a header off and hand the rest straight to a deserializer), or
        None when the ring is empty."""
        n = self._lib.pstpu_ring_next_len(self._handle)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.pstpu_ring_read(self._handle, buf, n)
        if got < 0:
            return None  # raced/buffer mismatch: treat as empty, caller re-polls
        # per-message ctypes buffer: always writable, owned by the view chain
        return memoryview(buf)[:got]  # noqa: PT500 - fresh writable buffer per message

    def try_read_zero_copy(self):
        """One message as ``(view, span_bytes, borrowed)`` without retiring
        its ring bytes, or None when the ring is empty.

        :borrows: ``borrowed=True`` views point STRAIGHT into the ring's
            mapped data area — the producer may not reuse those bytes until
            the caller retires ``span_bytes`` through :meth:`release` (in
            take order; ``native/lifetime.RingBorrowLedger`` does the
            bookkeeping). Physically wrapped messages (plain writes wrap
            byte-wise; only reserve-committed messages are contiguous) come
            back as an owned copy with ``borrowed=False`` — the span still
            must be released, but the view's lifetime is the caller's.
        """
        out = (ctypes.c_ulonglong * 3)()
        status = self._lib.pstpu_ring_peek(self._handle, out, 3)
        if status <= 0:
            return None  # empty (or corrupt header: surfaced by has_message)
        if status == 1:
            n = int(out[1])
            view = memoryview(  # noqa: PT500 - borrow by design; ledger-released
                (ctypes.c_char * n).from_address(int(out[0]))).cast('B')
            return view, int(out[2]), True
        # wrapped message: copy it out of the ring (span still ledgered)
        buf = ctypes.create_string_buffer(int(out[1]))
        span = ctypes.c_ulonglong(0)
        got = self._lib.pstpu_ring_peek_copy(self._handle, buf, int(out[1]),
                                             ctypes.byref(span))
        if got < 0:
            return None
        return memoryview(buf)[:got], int(span.value), False  # noqa: PT500 - fresh buffer

    def release(self, span_bytes):
        """Retire ``span_bytes`` of zero-copy-taken messages back to the
        producer (FIFO order only — see :meth:`try_read_zero_copy`)."""
        if not self._handle:
            return
        if self._lib.pstpu_ring_release(self._handle, span_bytes) != 0:
            raise ValueError('ring release failed: {}'.format(
                self._lib.pstpu_ring_last_error().decode()))

    def close(self):
        if self._handle:
            self._lib.pstpu_ring_close(self._handle)
            self._handle = None


#: broadcast consumer-slot states (mirror kSlot* in shm_ring.cpp)
BCAST_ATTACHED = 1
BCAST_EVICTED = 2


class BcastConsumerGone(Exception):
    """Raised by consumer-side reads whose slot was evicted or freed. ``evicted``
    distinguishes a producer-side eviction (too slow; docs/serve.md) from a
    token invalidated by a detach."""

    def __init__(self, message, evicted):
        super().__init__(message)
        self.evicted = evicted


class BcastRing(object):
    """One single-producer / multi-consumer broadcast ring in POSIX shared
    memory (the serve daemon's fan-out transport, ``docs/serve.md``).

    A published message is logically reference-counted across the attached
    consumers: each consumer's read cursor advance IS its release, and the
    bytes are reclaimed when the slowest attached cursor passes them. Consumer
    slots are granted by the PRODUCER (:meth:`join` runs daemon-side between
    writes — the control-plane round trip is what keeps a join from racing a
    write); consumers attach the mapping with :meth:`attach` and read with the
    granted token. The producer may :meth:`evict` a lagging consumer, whose
    next read raises :class:`BcastConsumerGone` instead of stalling the fleet.
    """

    def __init__(self, handle, lib):
        self._handle = handle
        self._lib = lib

    @classmethod
    def create(cls, name, capacity=DEFAULT_RING_BYTES):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_bcast_create(name.encode(), capacity)
        if not handle:
            raise OSError('bcast ring create failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @classmethod
    def attach(cls, name):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_bcast_attach(name.encode())
        if not handle:
            raise OSError('bcast ring attach failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @property
    def capacity(self):
        return self._lib.pstpu_bcast_capacity(self._handle)

    # -- producer side -------------------------------------------------------

    def join(self):
        """Grant a consumer slot (PRODUCER side, between writes). Returns the
        consumer token, or raises OSError when every slot is taken."""
        token = self._lib.pstpu_bcast_join(self._handle)
        if token < 0:
            raise OSError('bcast ring has no free consumer slots '
                          '({} max)'.format(BCAST_MAX_CONSUMERS))
        return token

    def leave(self, token):
        """Release a consumer slot (either side; idempotent for stale tokens).
        True when the token was still valid."""
        return self._lib.pstpu_bcast_leave(self._handle, token) == 0

    def evict(self, token):
        """PRODUCER side: mark a lagging consumer evicted — its cursor stops
        bounding the producer; its next read raises BcastConsumerGone."""
        return self._lib.pstpu_bcast_evict(self._handle, token) == 0

    def state(self, token):
        """1 attached, 2 evicted, 0 freed, -1 stale token."""
        return self._lib.pstpu_bcast_state(self._handle, token)

    def lag(self, token):
        """Unconsumed bytes behind the producer for one consumer (-1 stale)."""
        return self._lib.pstpu_bcast_lag(self._handle, token)

    def consumer_count(self):
        """Attached consumers; 0 for a closed ring (teardown paths probe this
        before writing, so close-vs-publish races resolve to a dropped frame,
        never a call on a dead handle)."""
        if not self._handle:
            return 0
        return self._lib.pstpu_bcast_consumer_count(self._handle)

    def free_space(self):
        return self._lib.pstpu_bcast_free_space(self._handle)

    def tail(self):
        """Monotonic producer position (bytes published incl. framing)."""
        return self._lib.pstpu_bcast_tail(self._handle)

    def min_head(self):
        """Slowest attached cursor (== tail with nobody attached): the fleet
        has consumed everything below this position. The serve daemon's blob
        GC keys on it. 0 for a closed ring."""
        if not self._handle:
            return 0
        return self._lib.pstpu_bcast_min_head(self._handle)

    def try_write(self, data):
        """True = broadcast to every attached consumer; False = some consumer
        is too far behind (caller retries / evicts). Raises when the message
        can never fit."""
        rc = self._lib.pstpu_bcast_write(self._handle, data, len(data))
        if rc < 0:
            raise ValueError('message of {} bytes exceeds bcast ring capacity {} — '
                             'increase serve ring_bytes'.format(len(data), self.capacity))
        return rc == 1

    def try_writev(self, parts):
        """Gather write of N bytes-like/ndarray segments as one broadcast
        message (zero-join publish; same contract as ShmRing.try_writev)."""
        ptrs, lens, total, keepalive = ShmRing._gather(parts)
        rc = self._lib.pstpu_bcast_writev(self._handle, ptrs, lens, len(parts))
        del keepalive
        if rc < 0:
            raise ValueError('message of {} bytes exceeds bcast ring capacity {} — '
                             'increase serve ring_bytes'.format(total, self.capacity))
        return rc == 1

    def try_reserve(self, max_len):
        """In-place publish channel (PR 6 contract, preserved on the fan-out
        transport): a contiguous writable slot of ``max_len`` payload bytes,
        or None when a consumer is too far behind; raises ValueError when it
        can never fit."""
        status = ctypes.c_int32(0)
        ptr = self._lib.pstpu_bcast_reserve(self._handle, max_len,
                                            ctypes.byref(status))
        if status.value < 0:
            raise ValueError('reservation of {} bytes cannot fit bcast ring capacity '
                             '{} — increase serve ring_bytes'.format(max_len, self.capacity))
        if not ptr:
            return None
        return memoryview((ctypes.c_char * max_len).from_address(ptr)).cast('B')  # noqa: PT500 - producer-side slot, ring outlives it

    def commit(self, actual_len):
        if self._lib.pstpu_bcast_commit(self._handle, actual_len) != 0:
            raise ValueError('bcast commit failed: {}'.format(
                self._lib.pstpu_ring_last_error().decode()))

    def abort(self):
        self._lib.pstpu_bcast_abort(self._handle)

    # -- consumer side -------------------------------------------------------

    def next_len(self, token):
        """Length of this consumer's next message; -1 empty. Raises
        BcastConsumerGone on eviction / stale token."""
        n = self._lib.pstpu_bcast_next_len(self._handle, token)
        if n == -3:
            raise BcastConsumerGone('consumer evicted from bcast ring (lagged '
                                    'beyond the producer bound)', evicted=True)
        if n == -4:
            raise BcastConsumerGone('bcast consumer token is stale (slot freed '
                                    'or re-granted)', evicted=False)
        return n

    def try_read_view(self, token):
        """One message for this consumer as a fresh writable memoryview, or
        None when nothing is waiting. Raises BcastConsumerGone on eviction /
        stale token; torn reads from a concurrent eviction are discarded by
        the native seqlock validation, never delivered."""
        n = self.next_len(token)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.pstpu_bcast_read(self._handle, token, buf, n)
        if got == -3:
            raise BcastConsumerGone('consumer evicted from bcast ring (lagged '
                                    'beyond the producer bound)', evicted=True)
        if got == -4:
            raise BcastConsumerGone('bcast consumer token is stale (slot freed '
                                    'or re-granted)', evicted=False)
        if got < 0:
            return None  # raced (message grew past our probe): re-poll
        return memoryview(buf)[:got]  # noqa: PT500 - fresh writable buffer per message

    def read_view(self, token, stop_check=None, timeout_s=None):
        """Blocking :meth:`try_read_view` with spin→yield→sleep escalation
        (:class:`IdleWait`). Returns None on stop/timeout."""
        idle = IdleWait()
        deadline = (time.monotonic() + timeout_s) if timeout_s is not None else None
        while True:
            view = self.try_read_view(token)
            if view is not None:
                idle.reset()
                return view
            if stop_check is not None and stop_check():
                idle.reset()
                return None
            if deadline is not None and time.monotonic() >= deadline:
                idle.reset()
                return None
            idle.wait()

    def close(self):
        if self._handle:
            self._lib.pstpu_bcast_close(self._handle)
            self._handle = None
