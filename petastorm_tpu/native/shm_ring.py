"""ctypes bindings for the C++ shared-memory SPSC ring (shm_ring.cpp).

One ring per worker process, worker -> main. Non-blocking C primitives;
blocking (with stop-aware sleep-poll) lives here in Python. Availability is
probed like the row-group kernel: any build/load failure makes
``is_available()`` False and the process pool falls back to zmq transport.
"""

from __future__ import annotations

import ctypes
import logging
import threading
import time

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()
_load_failed = False

DEFAULT_RING_BYTES = 64 << 20


class RingHeaderStruct(ctypes.Structure):
    """Field-for-field mirror of ``struct RingHeader`` (shm_ring.cpp) — the
    shared-memory segment layout both sides of the ring map. Python never
    touches the header directly (all access goes through the C API), but the
    mirror is the executable documentation of the cross-process layout and
    lint rule PT900 proves it identical to the C struct, so a C-side edit
    that would desynchronize producer and consumer mappings fails the linter
    instead of corrupting rings at runtime."""

    _fields_ = [
        ('head', ctypes.c_uint64),
        ('tail', ctypes.c_uint64),
        ('capacity', ctypes.c_uint64),
        ('magic', ctypes.c_uint64),
        ('pad', ctypes.c_char * 32),
    ]


#: byte offset of the ring's data area inside the shm segment
RING_HEADER_BYTES = ctypes.sizeof(RingHeaderStruct)


def _load_library():
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            from petastorm_tpu.native.build import build_shm
            lib = ctypes.CDLL(build_shm(quiet=True))
        except Exception as e:  # noqa: BLE001 - fall back to zmq transport
            logger.info('shm ring unavailable (%s); process pool will use zmq', e)
            _load_failed = True
            return None
        lib.pstpu_ring_create.restype = ctypes.c_void_p
        lib.pstpu_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_attach.restype = ctypes.c_void_p
        lib.pstpu_ring_attach.argtypes = [ctypes.c_char_p]
        lib.pstpu_ring_last_error.restype = ctypes.c_char_p
        lib.pstpu_ring_capacity.restype = ctypes.c_uint64
        lib.pstpu_ring_capacity.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_free_space.restype = ctypes.c_uint64
        lib.pstpu_ring_free_space.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_write.restype = ctypes.c_int
        lib.pstpu_ring_write.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_write2.restype = ctypes.c_int
        lib.pstpu_ring_write2.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64,
                                          ctypes.c_char_p, ctypes.c_uint64]
        lib.pstpu_ring_writev.restype = ctypes.c_int
        lib.pstpu_ring_writev.argtypes = [ctypes.c_void_p,
                                          ctypes.POINTER(ctypes.c_void_p),
                                          ctypes.POINTER(ctypes.c_uint64),
                                          ctypes.c_int32]
        lib.pstpu_ring_reserve.restype = ctypes.c_void_p
        lib.pstpu_ring_reserve.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                           ctypes.POINTER(ctypes.c_int32)]
        lib.pstpu_ring_commit.restype = ctypes.c_int
        lib.pstpu_ring_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.pstpu_ring_abort.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_next_len.restype = ctypes.c_int64
        lib.pstpu_ring_next_len.argtypes = [ctypes.c_void_p]
        lib.pstpu_ring_read.restype = ctypes.c_int64
        lib.pstpu_ring_read.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64]
        lib.pstpu_ring_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib


def is_available():
    return _load_library() is not None


class ShmRing(object):
    """One SPSC byte ring in POSIX shared memory."""

    def __init__(self, handle, lib):
        self._handle = handle
        self._lib = lib

    @classmethod
    def create(cls, name, capacity=DEFAULT_RING_BYTES):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_ring_create(name.encode(), capacity)
        if not handle:
            raise OSError('ring create failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @classmethod
    def attach(cls, name):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('shm ring library not available')
        handle = lib.pstpu_ring_attach(name.encode())
        if not handle:
            raise OSError('ring attach failed: {}'.format(
                lib.pstpu_ring_last_error().decode()))
        return cls(handle, lib)

    @property
    def capacity(self):
        return self._lib.pstpu_ring_capacity(self._handle)

    def try_write(self, data):
        """True = written; False = ring currently full. Raises when the
        message can never fit (grow ``ring_bytes``)."""
        rc = self._lib.pstpu_ring_write(self._handle, data, len(data))
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 len(data), self.capacity))
        return rc == 1

    def write(self, data, stop_check=None, poll_s=0.0002):
        """Blocking write with optional ``stop_check()`` abort callback."""
        while not self.try_write(data):
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)
        return True

    def try_write2(self, header, payload):
        """Gather write of header+payload as one message — no concat copy."""
        rc = self._lib.pstpu_ring_write2(self._handle, header, len(header),
                                         payload, len(payload))
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 len(header) + len(payload), self.capacity))
        return rc == 1

    def write2(self, header, payload, stop_check=None, poll_s=0.0002):
        while not self.try_write2(header, payload):
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)
        return True

    @staticmethod
    def _gather(parts):
        """(ptr_array, len_array, total, keepalive) for a list of bytes-likes /
        contiguous numpy arrays. Pointers are raw addresses — the keepalive
        list MUST outlive the write call (it does: writev holds it)."""
        import numpy as np
        n = len(parts)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_uint64 * n)()
        keepalive = []
        total = 0
        for i, p in enumerate(parts):
            if not isinstance(p, np.ndarray):
                # read-only buffers (bytes) export fine through frombuffer
                p = np.frombuffer(p, np.uint8) if len(p) else np.empty(0, np.uint8)
            keepalive.append(p)
            ptrs[i] = p.ctypes.data if p.size else None
            lens[i] = p.nbytes
            total += p.nbytes
        return ptrs, lens, total, keepalive

    def try_writev(self, parts):
        """Gather write of N bytes-like/ndarray segments as one message — the
        zero-join publish channel for whole column blocks."""
        ptrs, lens, total, keepalive = self._gather(parts)
        rc = self._lib.pstpu_ring_writev(self._handle, ptrs, lens, len(parts))
        del keepalive
        if rc < 0:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 total, self.capacity))
        return rc == 1

    def writev(self, parts, stop_check=None, poll_s=0.0002):
        ptrs, lens, total, keepalive = self._gather(parts)
        if total + 8 > self.capacity:
            raise ValueError('message of {} bytes exceeds ring capacity {} — increase the '
                             'process pool ring_bytes (or shrink row groups)'.format(
                                 total, self.capacity))
        while True:
            rc = self._lib.pstpu_ring_writev(self._handle, ptrs, lens, len(parts))
            if rc == 1:
                return True
            if stop_check is not None and stop_check():
                return False
            time.sleep(poll_s)

    def try_reserve(self, max_len):
        """Reserve a CONTIGUOUS writable in-ring region of up to ``max_len``
        payload bytes — the in-place publish channel: a fused batch decode
        assembles its rows directly in the slot the consumer maps, and
        :meth:`commit` makes it visible with a header write instead of a copy.
        Returns a writable memoryview of exactly ``max_len`` bytes, or None
        when the ring currently lacks space (retry); raises ValueError when a
        message of that size can never fit (callers use the copy channel).
        Exactly one reservation may be pending; :meth:`commit` or
        :meth:`abort` resolves it before any other write."""
        status = ctypes.c_int32(0)
        ptr = self._lib.pstpu_ring_reserve(self._handle, max_len,
                                           ctypes.byref(status))
        if status.value < 0:
            raise ValueError(
                'reservation of {} bytes cannot fit ring capacity {} — increase the '
                'process pool ring_bytes (or shrink row groups)'.format(
                    max_len, self.capacity))
        if not ptr:
            return None
        # the view aliases ring shared memory; the ring handle (held by the
        # worker for the pool's lifetime) anchors the mapping
        return memoryview((ctypes.c_char * max_len).from_address(ptr)).cast('B')  # noqa: PT500 - producer-side slot, ring outlives it

    def reserve(self, max_len, stop_check=None, poll_s=0.0002):
        """Blocking :meth:`try_reserve` with a stop-aware poll loop (the same
        contract as :meth:`write`); returns None when stopped."""
        while True:
            mv = self.try_reserve(max_len)
            if mv is not None:
                return mv
            if stop_check is not None and stop_check():
                return None
            time.sleep(poll_s)

    def commit(self, actual_len):
        """Publish the pending reservation with its actual message length."""
        if self._lib.pstpu_ring_commit(self._handle, actual_len) != 0:
            raise ValueError('ring commit failed: {}'.format(
                self._lib.pstpu_ring_last_error().decode()))

    def abort(self):
        """Drop the pending reservation (nothing became visible)."""
        self._lib.pstpu_ring_abort(self._handle)

    def has_message(self):
        """True when a committed message is waiting. NON-consuming probe
        (``pstpu_ring_next_len`` only reports the next message's length) —
        the supervisor uses it to tell when a dead worker's ring has drained
        without stealing the message from the consumer loop. A closed ring
        reports empty (callers may hold a reference past close)."""
        if not self._handle:
            return False
        return self._lib.pstpu_ring_next_len(self._handle) >= 0

    def try_read(self):
        """One message as bytes, or None when the ring is empty."""
        mv = self.try_read_view()
        return None if mv is None else bytes(mv)

    def try_read_view(self):
        """One message as a memoryview (zero further copies: consumers may
        slice a header off and hand the rest straight to a deserializer), or
        None when the ring is empty."""
        n = self._lib.pstpu_ring_next_len(self._handle)
        if n < 0:
            return None
        buf = ctypes.create_string_buffer(int(n))
        got = self._lib.pstpu_ring_read(self._handle, buf, n)
        if got < 0:
            return None  # raced/buffer mismatch: treat as empty, caller re-polls
        # per-message ctypes buffer: always writable, owned by the view chain
        return memoryview(buf)[:got]  # noqa: PT500 - fresh writable buffer per message

    def close(self):
        if self._handle:
            self._lib.pstpu_ring_close(self._handle)
            self._handle = None
