"""Native (C++) Parquet row-group reader with transparent pyarrow fallback.

The hot loop of every worker is "read selected columns of one row group"
(reference py_dict_reader_worker.py:254-258). Here that loop runs in first-party
C++ (``rowgroup_reader.cpp``): Arrow C++ decodes the columns off the GIL and the
result crosses into Python zero-copy via the Arrow C Data Interface.

``open_parquet(path, filesystem)`` picks the native kernel for local files when
the compiled library is available, else a ``pyarrow.parquet.ParquetFile``-backed
shim with an identical surface:

* ``read_row_group(i, columns=None)`` -> ``pyarrow.Table``
* ``metadata.row_group(i).num_rows``
* ``close()``

Set ``PETASTORM_TPU_DISABLE_NATIVE=1`` to force the pyarrow path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import threading

from petastorm_tpu import observability as obs

logger = logging.getLogger(__name__)

_lib = None
_lib_lock = threading.Lock()
_load_failed = False


def _load_library():
    """Load (building if needed) the native kernel; None when unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lib_lock:
        if _lib is not None or _load_failed:
            return _lib
        if os.environ.get('PETASTORM_TPU_DISABLE_NATIVE'):
            _load_failed = True
            return None
        try:
            from petastorm_tpu.native.build import build
            so_path = build(quiet=True)
            lib = ctypes.CDLL(so_path)
        except Exception as e:  # noqa: BLE001 - any failure => pyarrow fallback
            logger.info('native kernel unavailable (%s); using pyarrow fallback', e)
            _load_failed = True
            return None
        lib.pstpu_open.restype = ctypes.c_void_p
        lib.pstpu_open.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_longlong]
        lib.pstpu_close.argtypes = [ctypes.c_void_p]
        lib.pstpu_last_error.restype = ctypes.c_char_p
        lib.pstpu_num_row_groups.argtypes = [ctypes.c_void_p]
        lib.pstpu_num_rows.argtypes = [ctypes.c_void_p]
        lib.pstpu_num_rows.restype = ctypes.c_longlong
        lib.pstpu_row_group_num_rows.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.pstpu_row_group_num_rows.restype = ctypes.c_longlong
        lib.pstpu_num_columns.argtypes = [ctypes.c_void_p]
        lib.pstpu_column_name.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                          ctypes.c_char_p, ctypes.c_int]
        lib.pstpu_read_row_group.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                             ctypes.POINTER(ctypes.c_int),
                                             ctypes.c_int, ctypes.c_void_p]
        lib.pstpu_scan_plain_pages.restype = ctypes.c_longlong
        lib.pstpu_scan_plain_pages.argtypes = [
            ctypes.c_void_p, ctypes.c_ulonglong,
            ctypes.POINTER(ctypes.c_ulonglong), ctypes.POINTER(ctypes.c_longlong),
            ctypes.POINTER(ctypes.c_ulonglong),  # per-page values-region lengths
            ctypes.c_int, ctypes.c_int]
        from petastorm_tpu.native import fused as _fused
        try:
            abi = lib.pstpu_abi_version()
        except AttributeError:
            abi = None  # pre-versioned .so: definitionally not EXPECTED_ABI
        if abi != _fused.EXPECTED_ABI:
            # a kernel whose struct/function ABI we cannot trust must not be
            # called through mirrors describing a different layout — that is
            # silent memory corruption, not a fallback. Refuse it loudly.
            logger.warning(
                'native kernel reports ABI version %s but this build of '
                'petastorm_tpu expects %d (stale libpstpu.so build cache?); '
                'using the pyarrow fallback — rebuild with '
                'python -m petastorm_tpu.native.build --force',
                abi, _fused.EXPECTED_ABI)
            _load_failed = True
            return None
        _fused.register_abi(lib)
        _lib = lib
        return _lib


def is_available():
    return _load_library() is not None


def _last_error(lib):
    return lib.pstpu_last_error().decode('utf-8', 'replace')


class _RowGroupMeta(object):
    def __init__(self, num_rows):
        self.num_rows = num_rows


class _MetadataShim(object):
    """Duck-type of the ``pq.ParquetFile.metadata`` subset workers use."""

    def __init__(self, native_file):
        self._file = native_file
        self.num_row_groups = native_file.num_row_groups
        self.num_rows = native_file.num_rows

    def row_group(self, i):
        return _RowGroupMeta(self._file.row_group_num_rows(i))


class NativeParquetFile(object):
    """C++-backed Parquet file. One instance per worker thread (concurrent
    reads of a shared instance are serialized by the kernel's handle mutex)."""

    def __init__(self, path, use_threads=True, buffer_size=0):
        lib = _load_library()
        if lib is None:
            raise RuntimeError('native kernel not available')
        self._lib = lib
        self._handle = lib.pstpu_open(path.encode(), 1 if use_threads else 0,
                                      buffer_size)
        if not self._handle:
            raise IOError('pstpu_open({}): {}'.format(path, _last_error(lib)))
        self.path = path
        self.num_row_groups = lib.pstpu_num_row_groups(self._handle)
        self.num_rows = lib.pstpu_num_rows(self._handle)
        # map a requested column name (top-level field, or a full dotted leaf
        # path) to the parquet *leaf* indices it covers: nested fields (lists,
        # structs) span multiple leaves like "col.list.element"
        self._leaf_indices = {}
        buf = ctypes.create_string_buffer(4096)
        for i in range(lib.pstpu_num_columns(self._handle)):
            if lib.pstpu_column_name(self._handle, i, buf, len(buf)) >= 0:
                dotted = buf.value.decode()
                top = dotted.split('.', 1)[0]
                self._leaf_indices.setdefault(top, []).append(i)
                if dotted != top:
                    self._leaf_indices.setdefault(dotted, []).append(i)
        self.metadata = _MetadataShim(self)
        # zero-copy page-scan state (lazy: first read_row_group with columns)
        from petastorm_tpu.native.pagescan import _MmapPool
        self._pq_meta = None        # pyarrow FileMetaData | False (unusable)
        self._flat_index = {}
        self._mmaps = _MmapPool()
        self._fused_plans = {}      # (rg, columns, hints sig) -> FusedPlan | None

    def row_group_num_rows(self, i):
        n = self._lib.pstpu_row_group_num_rows(self._handle, i)
        if n < 0:
            raise IndexError(_last_error(self._lib))
        return n

    def _ensure_pq_meta(self):
        """Parse the footer with pyarrow ONCE per file (the chunk metadata the
        page-scan/fused qualification checks need); False when unusable."""
        if self._pq_meta is None:
            import pyarrow.parquet as pq
            try:
                self._pq_meta = pq.read_metadata(self.path)
            except Exception:  # noqa: BLE001 - odd footer: Arrow path serves it all
                self._pq_meta = False
            else:
                # flat REQUIRED-eligible columns: leaf path == top-level name
                self._flat_index = {
                    self._pq_meta.schema.column(idx).path: idx
                    for idx in range(self._pq_meta.num_columns)
                    if '.' not in self._pq_meta.schema.column(idx).path}
        return self._pq_meta

    def _zerocopy_columns(self, i, columns):
        """``{name: ChunkedArray}`` for the columns servable as views over the
        mmapped file (first-party page scan — see native/pagescan.py).

        :borrows: the arrays alias the pool's long-lived file mapping; each
            holds it alive through ``pa.py_buffer``'s base."""
        if os.environ.get('PSTPU_DISABLE_PAGESCAN'):
            return {}
        if self._ensure_pq_meta() is False:
            return {}
        from petastorm_tpu.native import pagescan
        return pagescan.read_columns_zerocopy(
            self.path, self._pq_meta, i, columns, self._flat_index,
            self._mmaps, self._lib)

    # -- fused batch decode (native/fused.py; docs/native.md) ---------------

    def fused_plan(self, i, columns, schema_fields=None, decode_hints=None,
                   resize_hints=None, include_pagescan=False):
        """:class:`~petastorm_tpu.native.fused.FusedPlan` for one row group's
        column selection (memoized per file), or None when fused decode is
        disabled/unusable for this file."""
        if os.environ.get('PSTPU_DISABLE_FUSED') or self._ensure_pq_meta() is False:
            return None
        key = (i, tuple(columns), bool(include_pagescan),
               frozenset(n for n in (decode_hints or {}) if decode_hints[n]),
               frozenset(n for n in (resize_hints or {}) if resize_hints[n]))
        if key not in self._fused_plans:
            from petastorm_tpu.native import fused
            self._fused_plans[key] = fused.plan_row_group(
                self._pq_meta, self._flat_index, i, columns, schema_fields,
                decode_hints, resize_hints, include_pagescan=include_pagescan)
        return self._fused_plans[key]

    def _fused_chunks(self, cols):
        """Per-column chunk byte views over the mmapped file (bounds-checked
        against the file size; a stale footer fails the read, not the
        process)."""
        mm = self._mmaps.get(self.path)
        chunks = []
        for p in cols:
            if p.chunk_off < 0 or p.chunk_off + p.chunk_len > mm.size:
                chunks.append(None)
            else:
                chunks.append(mm[p.chunk_off:p.chunk_off + p.chunk_len])
        return chunks

    def read_fused(self, i, columns, schema_fields=None, decode_hints=None,
                   resize_hints=None):
        """Fused read→decode→collate of one row group: every qualifying column
        lands as a numpy array backed by ONE fresh contiguous batch buffer,
        decoded in a single GIL-released native call. Returns ``(block,
        rest)`` — ``rest`` preserves the requested order of the columns that
        must ride the Arrow path (with their fallback reasons accounted)."""
        from petastorm_tpu.native import fused
        plan = self.fused_plan(i, columns, schema_fields, decode_hints, resize_hints)
        if plan is None:
            return {}, list(columns)
        if not plan.columns:
            fused.count_fallbacks(plan.reasons)
            return {}, list(columns)
        block, _reasons = fused.read_block(self._lib,
                                           self._fused_chunks(plan.columns),
                                           plan, stage_args={'row_group': i})
        rest = [c for c in columns if c not in block]
        return block, rest

    def read_fused_predicate(self, i, columns, pred_fields, clauses,
                             schema_fields=None, decode_hints=None,
                             resize_hints=None):
        """Filtered fused read of one row group: predicate evaluation (with
        min/max page-stat skipping), row selection and the decode of ONLY the
        surviving rows run in a single GIL-released call. ``clauses`` is the
        ``PredicateBase.native_clauses()`` protocol list. Returns ``(block,
        rest, sel_mask, n_selected, pages_skipped)`` — ``rest`` columns must
        be Arrow-read and filtered with ``sel_mask`` by the caller — or None
        when the predicate shape / columns are not natively evaluable (reason
        ``predicate`` accounted per predicate column) or the kernel declined."""
        from petastorm_tpu.native import fused
        plan = self.fused_plan(i, columns, schema_fields, decode_hints,
                               resize_hints, include_pagescan=True)
        if plan is None or not plan.columns:
            return None
        got = fused.plan_predicate_columns(self._pq_meta, self._flat_index, i,
                                           pred_fields, schema_fields)
        if got is None:
            fused.count_fallbacks({f: 'predicate' for f in pred_fields})
            return None
        pred_plans, pred_index = got
        compiled = fused.compile_predicate(clauses, pred_index)
        if isinstance(compiled, str):
            fused.count_fallbacks({f: compiled for f in pred_fields})
            return None
        preds, keepalive = compiled
        res = fused.read_block_pred(
            self._lib, self._fused_chunks(plan.columns), plan,
            self._fused_chunks(pred_plans), pred_plans, preds, keepalive,
            stage_args={'row_group': i})
        if res is None:
            return None
        block, _reasons, sel_mask, n_selected, pages_skipped = res
        rest = [c for c in columns if c not in block]
        return block, rest, sel_mask, n_selected, pages_skipped

    def fused_read_into(self, plan, out_buf, offsets):
        """Run a prepared fused plan writing directly into ``out_buf`` (the
        shm-ring in-place mode: the buffer is the ring slot the consumer
        maps). Returns the per-column native results."""
        from petastorm_tpu.native import fused
        with obs.stage('fused_decode', cat='native', rows=plan.expected_rows):
            return fused.read_into(self._lib, self._fused_chunks(plan.columns),
                                   plan.columns, plan.expected_rows, out_buf,
                                   offsets)

    def read_row_group(self, i, columns=None):
        """Read one row group as a ``pyarrow.Table``. Columns that qualify for
        the first-party zero-copy page scan (UNCOMPRESSED PLAIN REQUIRED
        fixed-width — RawTensorCodec training stores) become views over the
        mmapped file; the rest decode on Arrow C++ threads and import
        zero-copy through the Arrow C Data Interface. Mixed tables split per
        column, preserving the requested column order."""
        import pyarrow as pa

        if columns:
            with obs.stage('pagescan', cat='native'):
                fast = self._zerocopy_columns(i, columns)
        else:
            fast = {}
        rest = [c for c in columns if c not in fast] if columns is not None else None

        # which decode path served each column is the telemetry answer to
        # "why is this store slow": page-scan columns are zero-copy views,
        # arrow-fallback columns pay a full decode
        if fast:
            obs.count('pagescan_columns_total', len(fast))
        if rest:
            obs.count('arrow_fallback_columns_total', len(rest))

        # columns=[] must keep the 0-column N-row semantics of the Arrow path
        # (partition-key-only reads take row counts from it), so the fast-only
        # return requires a NON-empty request fully served
        if columns and not rest:
            return pa.table({c: fast[c] for c in columns})
        if rest is not None:
            indices = []
            for c in rest:
                try:
                    indices.extend(self._leaf_indices[c])
                except KeyError:
                    raise KeyError('column {!r} not in file {} (has: {})'.format(
                        c, self.path, sorted(self._leaf_indices)))
            arr = (ctypes.c_int * len(indices))(*indices)
            n = len(indices)
        else:
            arr, n = None, -1

        # ArrowArrayStream is 4 pointers + private fields; 256 bytes is ample
        with obs.stage('arrow_decode', cat='native'):
            stream_buf = ctypes.create_string_buffer(256)
            rc = self._lib.pstpu_read_row_group(self._handle, i, arr, n,
                                                ctypes.byref(stream_buf))
            if rc != 0:
                raise IOError('pstpu_read_row_group({}, rg={}): {}'.format(
                    self.path, i, _last_error(self._lib)))
            reader = pa.RecordBatchReader._import_from_c(
                ctypes.addressof(stream_buf))
            table = reader.read_all()
        if not fast:
            return table
        return pa.table({c: (fast[c] if c in fast else table.column(c))
                         for c in columns})

    def close(self):
        if self._handle:
            self._lib.pstpu_close(self._handle)
            self._handle = None
        # drops the pool's references only: arrays built over a mapping keep
        # it alive through their buffers
        self._mmaps.close()

    def __del__(self):
        try:
            self.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.close()


def open_parquet(path, filesystem=None, use_threads=True, buffer_size=0,
                 chunk_cache=None):
    """Open ``path`` with the native kernel when possible (local file, kernel
    built), else fall back to ``pq.ParquetFile`` over the given filesystem.

    ``use_threads=True`` (Arrow-internal decode threads) measures faster under
    the worker pool even on constrained hosts: the decode offload overlaps
    Arrow C++ work with the workers' GIL-bound Python (codec loop, row
    assembly), which a single-threaded read serializes.

    ``chunk_cache`` (a ``ChunkCacheConfig``) routes REMOTE files through the
    chunk store: qualifying column chunks are mirrored locally once and served
    zero-copy by the page scanner — the path local files already ride. Ignored
    for local filesystems and when the native kernel is unavailable (the scan
    is what the mirror exists to feed)."""
    import pyarrow.fs as pafs
    import pyarrow.parquet as pq

    local = filesystem is None or isinstance(filesystem, pafs.LocalFileSystem)
    if local and is_available():
        try:
            return NativeParquetFile(path, use_threads=use_threads,
                                     buffer_size=buffer_size)
        except IOError as e:
            logger.warning('native open failed for %s (%s); pyarrow fallback', path, e)
    if not local and chunk_cache is not None and is_available():
        from petastorm_tpu.chunkstore.reader import ChunkCachedParquetFile
        try:
            return ChunkCachedParquetFile(path, filesystem, chunk_cache)
        except Exception as e:  # noqa: BLE001 - cache dir/remote stat trouble: plain remote path
            logger.warning('chunk-cached open failed for %s (%s); plain remote read',
                           path, e)
    if filesystem is None:
        return pq.ParquetFile(path)
    # remote stores (s3/gs/hdfs, incl. the retry-wrapped PyFileSystems) get
    # pre_buffer: a row group's column-chunk ranges coalesce into few large
    # reads issued ahead of decode — the milliseconds-per-round-trip regime
    # where per-chunk sequential reads dominate wall time
    return pq.ParquetFile(filesystem.open_input_file(path), pre_buffer=not local)
