"""Build the native row-group reader kernel.

Compiles ``rowgroup_reader.cpp`` against the Arrow/Parquet C++ libraries
bundled inside the installed pyarrow wheel — no system Arrow needed. Invoked
explicitly (``python -m petastorm_tpu.native.build``) or automatically on first
import of :mod:`petastorm_tpu.native` (with a graceful pure-pyarrow fallback
when no toolchain is available).

**Sanitizer lane** (``docs/native.md``): ``PSTPU_SANITIZE=address,undefined``
switches every target to an ASan/UBSan-instrumented build. Sanitized builds
land in separate ``*.san.so`` files with their own flag-keyed stamps, so the
sanitized and release kernels coexist in the source dir and flipping the env
var back costs no rebuild. The instrumented ``.so`` only loads into a process
with the sanitizer runtimes preloaded (``LD_PRELOAD=libasan.so libubsan.so``
for gcc) — ``tests/test_sanitized_native.py`` drives the whole lane through a
subprocess that replays the fused-decode fuzz corpus and the corrupt-chunk
regressions through the instrumented kernels.
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_HERE, 'rowgroup_reader.cpp')
OUTPUT = os.path.join(_HERE, 'libpstpu.so')
SHM_SOURCE = os.path.join(_HERE, 'shm_ring.cpp')
SHM_OUTPUT = os.path.join(_HERE, 'libpstpu_shm.so')
IMG_SOURCE = os.path.join(_HERE, 'image_codec.cpp')
IMG_OUTPUT = os.path.join(_HERE, 'libpstpu_img.so')

#: sanitizers PSTPU_SANITIZE accepts (comma-separated; gcc/clang spellings)
_SANITIZERS = ('address', 'undefined', 'leak', 'thread')


def sanitize_tokens():
    """Validated tuple of sanitizers from ``PSTPU_SANITIZE`` (empty = release
    build). An unknown token is a hard error — a typo must not silently
    produce an uninstrumented kernel the caller believes is sanitized."""
    raw = os.environ.get('PSTPU_SANITIZE', '').strip()
    if not raw:
        return ()
    tokens = tuple(t.strip() for t in raw.split(',') if t.strip())
    unknown = [t for t in tokens if t not in _SANITIZERS]
    if unknown:
        raise RuntimeError('PSTPU_SANITIZE: unknown sanitizer(s) {} '
                           '(supported: {})'.format(unknown, ', '.join(_SANITIZERS)))
    return tokens


def _sanitized_output(base):
    """Sanitized builds live in their own ``.san.so`` next to the release
    ``.so`` (own stamp, own lock): both coexist and flipping PSTPU_SANITIZE
    back and forth never invalidates the other flavor."""
    if not sanitize_tokens():
        return base
    return base[:-len('.so')] + '.san.so'


def _sanitize_flags():
    tokens = sanitize_tokens()
    if not tokens:
        return []
    # -O1: keep the checks honest without optimizing the faulting code away;
    # frame pointers + debug info make the sanitizer reports readable
    return ['-fsanitize={}'.format(','.join(tokens)),
            '-fno-omit-frame-pointer', '-g', '-O1']


def _sanitized_stamp(stamp_fn):
    """Key the cache stamp by the sanitize flags so a .san.so compiled for a
    different sanitizer set rebuilds instead of masquerading."""
    def stamped():
        tokens = sanitize_tokens()
        base = stamp_fn()
        return 'san[{}]:{}'.format(','.join(tokens), base) if tokens else base
    return stamped


def _arrow_paths():
    import pyarrow
    include = pyarrow.get_include()
    libdirs = pyarrow.get_library_dirs()
    # wheel ships versioned sonames only (libarrow.so.2500); link by exact name
    arrow_lib = parquet_lib = None
    for d in libdirs:
        for so in glob.glob(os.path.join(d, 'libarrow.so*')):
            arrow_lib = os.path.basename(so)
        for so in glob.glob(os.path.join(d, 'libparquet.so*')):
            parquet_lib = os.path.basename(so)
    if not arrow_lib or not parquet_lib:
        raise RuntimeError('pyarrow wheel does not bundle libarrow/libparquet '
                           '(searched {})'.format(libdirs))
    return include, libdirs, arrow_lib, parquet_lib


def _source_hash(path):
    import hashlib
    with open(path, 'rb') as f:
        return hashlib.sha256(f.read()).hexdigest()


def _stamp():
    # the .so links versioned Arrow sonames with an rpath into the wheel dir:
    # a pyarrow upgrade invalidates it even though the source didn't change.
    # The source hash (not mtime — checkout mtimes are arbitrary) invalidates
    # it on edits.
    import pyarrow
    return '{}:{}:{}'.format(pyarrow.__version__, sys.version_info[:2],
                             _source_hash(SOURCE))


def _shm_stamp():
    # 'rt1' is the build-recipe tag: bumping it invalidates .so files compiled
    # with an older command line (e.g. before -lrt, which glibc < 2.34 needs
    # for shm_open — without it the .so loads fail with an undefined symbol)
    return 'rt1:' + _source_hash(SHM_SOURCE)


def _cpu_fingerprint():
    """Identity of the CPU the .so was compiled FOR: with ``-march=native`` a
    baked image or shared filesystem can carry the binary onto a different
    machine, where stale-but-source-fresh code would SIGILL instead of
    rebuilding. Model name + ISA flags of cpu0 pin it."""
    import hashlib
    import platform
    ident = [platform.machine()]
    try:
        with open('/proc/cpuinfo') as f:
            for line in f:
                if line.startswith(('model name', 'flags')):
                    ident.append(line.strip())
                if line == '\n' and len(ident) > 1:
                    break  # cpu0 only
    except OSError:
        pass
    return hashlib.sha256('\n'.join(ident).encode()).hexdigest()[:16]


def _img_stamp():
    # source + target CPU: either changing forces a rebuild
    return '{}:{}'.format(_source_hash(IMG_SOURCE), _cpu_fingerprint())


def _target_is_fresh(output, stamp_fn):
    if not os.path.exists(output):
        return False
    try:
        with open(output + '.stamp') as f:
            return f.read() == stamp_fn()
    except OSError:
        return False


def _build_target(output, stamp_fn, make_cmd, label, force, quiet):
    """Shared concurrency-safe build scheme for every native target.

    Safe under concurrency (spawned worker processes may all trigger the first
    build): compilation goes to a per-pid temp file that is atomically renamed
    into place — a process that already dlopen'ed the old .so keeps its mapped
    inode — and an flock serializes the g++ runs so only one compiles.
    ``make_cmd`` is called under the lock (it may probe the environment, e.g.
    pyarrow paths) and returns the full compiler argv ending in the temp path.
    """
    if not force and _target_is_fresh(output, stamp_fn):
        return output
    import fcntl
    lock_path = output + '.lock'
    with open(lock_path, 'w') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if not force and _target_is_fresh(output, stamp_fn):  # built while we waited
                return output
            tmp_out = '{}.tmp.{}'.format(output, os.getpid())
            cmd = make_cmd(tmp_out)
            if not quiet:
                print('building {}:'.format(label), ' '.join(cmd))
            result = subprocess.run(cmd, capture_output=True, text=True)
            if result.returncode != 0:
                if os.path.exists(tmp_out):
                    os.unlink(tmp_out)
                raise RuntimeError('{} build failed:\n{}'.format(label, result.stderr))
            os.replace(tmp_out, output)
            with open(output + '.stamp', 'w') as f:
                f.write(stamp_fn())
            return output
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def build(force=False, quiet=False):
    """Compile the row-group reader kernel against the pyarrow wheel's Arrow
    C++ libraries. Returns the .so path (a ``.san.so`` under PSTPU_SANITIZE)."""
    def make_cmd(tmp_out):
        include, libdirs, arrow_lib, parquet_lib = _arrow_paths()
        cmd = ['g++', '-O2', '-std=c++20', '-shared', '-fPIC'] \
            + _sanitize_flags() + [SOURCE, '-I{}'.format(include)]
        for d in libdirs:
            cmd += ['-L{}'.format(d), '-Wl,-rpath,{}'.format(d)]
        return cmd + ['-l:{}'.format(arrow_lib), '-l:{}'.format(parquet_lib),
                      '-o', tmp_out]

    return _build_target(_sanitized_output(OUTPUT), _sanitized_stamp(_stamp),
                         make_cmd, 'native kernel', force, quiet)


def build_shm(force=False, quiet=False):
    """Compile the shared-memory ring transport (no external deps)."""
    def make_cmd(tmp_out):
        # -lrt: shm_open/shm_unlink live in librt until glibc 2.34 (a no-op
        # stub library after); without it the .so carries an undefined symbol
        return ['g++', '-O2', '-std=c++17', '-shared', '-fPIC'] \
            + _sanitize_flags() + [SHM_SOURCE, '-lrt', '-o', tmp_out]

    return _build_target(_sanitized_output(SHM_OUTPUT),
                         _sanitized_stamp(_shm_stamp), make_cmd, 'shm ring',
                         force, quiet)


def build_img(force=False, quiet=False):
    """Compile the batched image decoder against the system libjpeg/libpng/libdeflate.

    ``-march=native`` is safe and right here: the kernel is ALWAYS compiled on
    the machine that runs it (build-on-first-use; wheels ship source), so the
    vector ISA the local CPU actually has (SSE4/AVX2) is available to the
    resample/unfilter loops. The .so never travels."""
    def make_cmd(tmp_out):
        return ['g++', '-O3', '-march=native', '-std=c++17', '-shared', '-fPIC'] \
            + _sanitize_flags() + [IMG_SOURCE,
                                   '-ljpeg', '-lpng16', '-ldeflate', '-o', tmp_out]

    return _build_target(_sanitized_output(IMG_OUTPUT),
                         _sanitized_stamp(_img_stamp), make_cmd, 'image codec',
                         force, quiet)


if __name__ == '__main__':
    build(force='--force' in sys.argv)
    print('built', OUTPUT)
    build_shm(force='--force' in sys.argv)
    print('built', SHM_OUTPUT)
    try:
        # optional at runtime (codecs fall back to OpenCV), so a host without
        # the png/jpeg/deflate dev libraries must not fail the prebuild step
        build_img(force='--force' in sys.argv)
        print('built', IMG_OUTPUT)
    except RuntimeError as e:
        print('image codec skipped (optional): {}'.format(e))
