"""Build the native row-group reader kernel.

Compiles ``rowgroup_reader.cpp`` against the Arrow/Parquet C++ libraries
bundled inside the installed pyarrow wheel — no system Arrow needed. Invoked
explicitly (``python -m petastorm_tpu.native.build``) or automatically on first
import of :mod:`petastorm_tpu.native` (with a graceful pure-pyarrow fallback
when no toolchain is available).
"""

from __future__ import annotations

import glob
import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SOURCE = os.path.join(_HERE, 'rowgroup_reader.cpp')
OUTPUT = os.path.join(_HERE, 'libpstpu.so')
SHM_SOURCE = os.path.join(_HERE, 'shm_ring.cpp')
SHM_OUTPUT = os.path.join(_HERE, 'libpstpu_shm.so')


def _arrow_paths():
    import pyarrow
    include = pyarrow.get_include()
    libdirs = pyarrow.get_library_dirs()
    # wheel ships versioned sonames only (libarrow.so.2500); link by exact name
    arrow_lib = parquet_lib = None
    for d in libdirs:
        for so in glob.glob(os.path.join(d, 'libarrow.so*')):
            arrow_lib = os.path.basename(so)
        for so in glob.glob(os.path.join(d, 'libparquet.so*')):
            parquet_lib = os.path.basename(so)
    if not arrow_lib or not parquet_lib:
        raise RuntimeError('pyarrow wheel does not bundle libarrow/libparquet '
                           '(searched {})'.format(libdirs))
    return include, libdirs, arrow_lib, parquet_lib


def _source_hash(path):
    import hashlib
    with open(path, 'rb') as f:
        return hashlib.sha256(f.read()).hexdigest()


def _stamp():
    # the .so links versioned Arrow sonames with an rpath into the wheel dir:
    # a pyarrow upgrade invalidates it even though the source didn't change.
    # The source hash (not mtime — checkout mtimes are arbitrary) invalidates
    # it on edits.
    import pyarrow
    return '{}:{}:{}'.format(pyarrow.__version__, sys.version_info[:2],
                             _source_hash(SOURCE))


def _shm_stamp():
    return _source_hash(SHM_SOURCE)


def _is_fresh():
    if not os.path.exists(OUTPUT):
        return False
    try:
        with open(OUTPUT + '.stamp') as f:
            return f.read() == _stamp()
    except OSError:
        return False


def _shm_is_fresh():
    if not os.path.exists(SHM_OUTPUT):
        return False
    try:
        with open(SHM_OUTPUT + '.stamp') as f:
            return f.read() == _shm_stamp()
    except OSError:
        return False


def build(force=False, quiet=False):
    """Compile the kernel if missing or stale. Returns the .so path.

    Safe under concurrency (spawned worker processes may all trigger the first
    build): compilation goes to a per-pid temp file that is atomically renamed
    into place — a process that already dlopen'ed the old .so keeps its mapped
    inode — and an flock serializes the g++ runs so only one compiles."""
    if not force and _is_fresh():
        return OUTPUT
    import fcntl
    lock_path = OUTPUT + '.lock'
    with open(lock_path, 'w') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if not force and _is_fresh():  # another process built while we waited
                return OUTPUT
            include, libdirs, arrow_lib, parquet_lib = _arrow_paths()
            tmp_out = '{}.tmp.{}'.format(OUTPUT, os.getpid())
            cmd = ['g++', '-O2', '-std=c++20', '-shared', '-fPIC', SOURCE,
                   '-I{}'.format(include)]
            for d in libdirs:
                cmd += ['-L{}'.format(d), '-Wl,-rpath,{}'.format(d)]
            cmd += ['-l:{}'.format(arrow_lib), '-l:{}'.format(parquet_lib),
                    '-o', tmp_out]
            if not quiet:
                print('building native kernel:', ' '.join(cmd))
            result = subprocess.run(cmd, capture_output=True, text=True)
            if result.returncode != 0:
                if os.path.exists(tmp_out):
                    os.unlink(tmp_out)
                raise RuntimeError('native kernel build failed:\n' + result.stderr)
            os.replace(tmp_out, OUTPUT)
            with open(OUTPUT + '.stamp', 'w') as f:
                f.write(_stamp())
            return OUTPUT
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


def build_shm(force=False, quiet=False):
    """Compile the shared-memory ring transport (no external deps). Same
    concurrency-safe temp-file + flock scheme as :func:`build`."""
    if not force and _shm_is_fresh():
        return SHM_OUTPUT
    import fcntl
    lock_path = SHM_OUTPUT + '.lock'
    with open(lock_path, 'w') as lock_file:
        fcntl.flock(lock_file, fcntl.LOCK_EX)
        try:
            if not force and _shm_is_fresh():
                return SHM_OUTPUT
            tmp_out = '{}.tmp.{}'.format(SHM_OUTPUT, os.getpid())
            cmd = ['g++', '-O2', '-std=c++17', '-shared', '-fPIC', SHM_SOURCE,
                   '-o', tmp_out]
            if not quiet:
                print('building shm ring:', ' '.join(cmd))
            result = subprocess.run(cmd, capture_output=True, text=True)
            if result.returncode != 0:
                if os.path.exists(tmp_out):
                    os.unlink(tmp_out)
                raise RuntimeError('shm ring build failed:\n' + result.stderr)
            os.replace(tmp_out, SHM_OUTPUT)
            with open(SHM_OUTPUT + '.stamp', 'w') as f:
                f.write(_shm_stamp())
            return SHM_OUTPUT
        finally:
            fcntl.flock(lock_file, fcntl.LOCK_UN)


if __name__ == '__main__':
    build(force='--force' in sys.argv)
    print('built', OUTPUT)
    build_shm(force='--force' in sys.argv)
    print('built', SHM_OUTPUT)
