"""Payload serializers for the worker-process -> main-process results channel.

Parity: /root/reference/petastorm/reader_impl/{pickle_serializer,
pyarrow_serializer, arrow_table_serializer}.py — the reference routes batch
readers through its Arrow record-batch-stream serializer (reference
reader.py:269) and everything else through pickle.

TPU-first: our workers publish *column blocks* (dicts of numpy arrays), so the
default transport is :class:`NumpyBlockSerializer` — a raw-buffer framing whose
deserialize is near-zero-cost (numpy views over the received message, no parse,
no per-array copy). Pickle remains the universal fallback and is embedded for
non-block payloads; ``ArrowTableSerializer`` covers ``pyarrow.Table`` payloads
for users who plug Arrow-producing workers in.
"""

from __future__ import annotations

import pickle
import struct

import numpy as np
import pyarrow as pa


class PickleSerializer(object):
    def serialize(self, obj):
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        return pickle.loads(data)


class NumpyBlockSerializer(object):
    """Column blocks (dict of numpy arrays) as a pickled header + concatenated
    raw array buffers.

    Serialize is one memcpy per array (vs. pickle's pickler machinery — ~3x
    faster on image-sized blocks); deserialize builds numpy VIEWS over the
    received message (~zero cost), which is safe for both transports: the shm
    ring copies each message into a fresh per-message buffer
    (native/shm_ring.py:try_read_view) and zmq hands out an owning bytes — the
    views keep either alive. Object-dtype columns and non-block payloads
    (NGram window lists, exceptions, sentinels) ride an embedded pickle.
    """

    _BLOCK = b'N'
    _PICKLE = b'P'

    @staticmethod
    def _split_block(obj):
        """THE block-eligibility classification + header framing, shared by
        :meth:`serialize` and :meth:`serialize_into` (the two channels must
        stay byte-identical for :meth:`deserialize`): returns
        ``(raw_arrays, header_bytes)`` or ``None`` when the payload must ride
        plain pickle."""
        if not isinstance(obj, dict) or not obj:
            return None
        raw = {}
        others = {}
        for k, v in obj.items():
            if (isinstance(v, np.ndarray) and v.dtype != object and not v.dtype.hasobject
                    and v.dtype.names is None):  # structured dtypes lose field
                raw[k] = np.ascontiguousarray(v)  # names through dtype.str: pickle them
            else:
                others[k] = v
        try:
            header = pickle.dumps(
                ([(k, v.dtype.str, v.shape) for k, v in raw.items()], others),
                protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 - unpicklable extras: plain pickle
            return None
        return raw, header

    @staticmethod
    def _array_bytes(v):
        # datetime/timedelta arrays refuse buffer export (PEP 3118); tobytes
        return v.tobytes() if v.dtype.kind in 'Mm' else memoryview(v).cast('B')

    def serialize(self, obj):
        split = self._split_block(obj)
        if split is None:
            return self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        raw, header = split
        parts = [self._BLOCK, struct.pack('<I', len(header)), header]
        parts.extend(self._array_bytes(v) for v in raw.values())
        return b''.join(parts)

    def deserialize(self, data):
        mv = memoryview(data)
        marker = bytes(mv[:1])
        if marker == self._PICKLE:
            return pickle.loads(mv[1:])
        (hlen,) = struct.unpack('<I', mv[1:5])
        meta, out = pickle.loads(mv[5:5 + hlen])
        off = 5 + hlen
        for name, dtype_str, shape in meta:
            dt = np.dtype(dtype_str)
            n = dt.itemsize
            for dim in shape:
                n *= dim
            out[name] = np.frombuffer(mv[off:off + n], dtype=dt).reshape(shape)
            off += n
        return out

    def serialize_routed(self, obj, alloc, min_size=0):
        """One-pass channel routing for the process-pool publish path: the
        block classification/framing runs ONCE, then large raw blocks are
        written via ``alloc`` (single copy) and everything else is framed
        in-band. Returns ``('blob', buffer)`` or ``('bytes', message)``."""
        split = self._split_block(obj)
        if split is None:
            return 'bytes', self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        raw, header = split
        total = 5 + len(header) + sum(v.nbytes for v in raw.values())
        if raw and total >= min_size:
            return 'blob', self._write_frame_into(raw, header, alloc(total))
        parts = [self._BLOCK, struct.pack('<I', len(header)), header]
        parts.extend(self._array_bytes(v) for v in raw.values())
        return 'bytes', b''.join(parts)

    @classmethod
    def _write_frame_into(cls, raw, header, target):
        buf = memoryview(target)
        buf[0:1] = cls._BLOCK
        struct.pack_into('<I', buf, 1, len(header))
        buf[5:5 + len(header)] = header
        off = 5 + len(header)
        for v in raw.values():
            n = v.nbytes
            buf[off:off + n] = cls._array_bytes(v)
            off += n
        return buf

    def serialize_into(self, obj, alloc, min_size=0):
        """Single-copy serialize: compute the exact framed-message size, obtain
        a writable buffer from ``alloc(size)`` (e.g. an mmapped /dev/shm file),
        and write the message straight into it — no intermediate ``b''.join``
        allocation. Returns the buffer, or ``None`` when ``obj`` does not
        qualify (non-block payload, object columns only, or total < ``min_size``
        — callers then use the regular :meth:`serialize` channel). The written
        bytes :meth:`deserialize` identically to :meth:`serialize` output."""
        split = self._split_block(obj)
        if split is None:
            return None
        raw, header = split
        if not raw:
            return None
        total = 5 + len(header) + sum(v.nbytes for v in raw.values())
        if total < min_size:
            return None
        return self._write_frame_into(raw, header, alloc(total))


class ArrowTableSerializer(object):
    """Serializes ``pyarrow.Table`` payloads as IPC streams
    (reference arrow_table_serializer.py:23-33). Non-table payloads (e.g.
    exceptions) fall back to pickle with a marker byte."""

    _TABLE = b'T'
    _PICKLE = b'P'

    def serialize(self, obj):
        if isinstance(obj, pa.Table):
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, obj.schema) as writer:
                writer.write_table(obj)
            return self._TABLE + sink.getvalue().to_pybytes()
        return self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        # The shm transport delivers memoryviews; bytes(...) normalizes the
        # marker so it compares equal to the bytes constants.
        marker, body = bytes(data[:1]), data[1:]
        if marker == self._TABLE:
            with pa.ipc.open_stream(pa.BufferReader(body)) as reader:
                return reader.read_all()
        return pickle.loads(body)
