"""Payload serializers for the worker-process -> main-process results channel.

Parity: /root/reference/petastorm/reader_impl/{pickle_serializer,
pyarrow_serializer, arrow_table_serializer}.py — the reference routes batch
readers through its Arrow record-batch-stream serializer (reference
reader.py:269) and everything else through pickle.

TPU-first: our workers publish *column blocks* (dicts of numpy arrays), so the
default transport is :class:`NumpyBlockSerializer` — a raw-buffer framing whose
deserialize is near-zero-cost (numpy views over the received message, no parse,
no per-array copy). Pickle remains the universal fallback and is embedded for
non-block payloads; ``ArrowTableSerializer`` covers ``pyarrow.Table`` payloads
for users who plug Arrow-producing workers in.
"""

from __future__ import annotations

import pickle
import struct

import numpy as np
import pyarrow as pa


class PickleSerializer(object):
    def serialize(self, obj):
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        return pickle.loads(data)


class NumpyBlockSerializer(object):
    """Column blocks (dict of numpy arrays) as a pickled header + concatenated
    raw array buffers.

    Serialize is one memcpy per array (vs. pickle's pickler machinery — ~3x
    faster on image-sized blocks); deserialize builds numpy VIEWS over the
    received message (~zero cost), which is safe for both transports: the shm
    ring copies each message into a fresh per-message buffer
    (native/shm_ring.py:try_read_view) and zmq hands out an owning bytes — the
    views keep either alive. RAGGED object columns whose cells are
    uniform-dtype ndarrays (variable-size decoded images, the PNG/JPEG
    columnar block shape) ride the same raw-buffer channel — one buffer per
    cell, shapes in the header — instead of a full pickle copy of the pixels;
    other object columns and non-block payloads (NGram window lists,
    exceptions, sentinels) ride an embedded pickle.
    """

    _BLOCK = b'N'
    _PICKLE = b'P'

    @staticmethod
    def _ragged_buffers(v):
        """``(cell_arrays, dtype_str, shapes)`` when every non-None cell of the
        1-D object column ``v`` is an ndarray of ONE simple dtype (None cells
        allowed: nullable fields); else None. ``shapes`` has a None per None
        cell; ``cell_arrays`` holds only the present cells, contiguous."""
        if v.ndim != 1 or v.size == 0:
            return None
        dtype = None
        cells, shapes = [], []
        for el in v:
            if el is None:
                shapes.append(None)
                continue
            if not isinstance(el, np.ndarray) or el.dtype.hasobject or \
                    el.dtype.names is not None:
                return None
            if dtype is None:
                dtype = el.dtype
            elif el.dtype != dtype:
                return None
            el = np.ascontiguousarray(el)
            cells.append(el)
            shapes.append(el.shape)
        if dtype is None:  # all-None column: nothing raw to frame
            return None
        return cells, dtype.str, shapes

    @classmethod
    def _split_block(cls, obj):
        """THE block-eligibility classification + header framing, shared by
        every channel (join, parts, blob — all must stay byte-identical for
        :meth:`deserialize`): returns ``(buffers, header_bytes)`` — buffers is
        the ordered flat list of contiguous arrays whose raw bytes follow the
        header — or ``None`` when the payload must ride plain pickle. Header
        meta entries are ``(name, dtype_str, shape, ragged_shapes)`` with
        exactly one of shape/ragged_shapes set."""
        if not isinstance(obj, dict) or not obj:
            return None
        meta = []
        buffers = []
        others = {}
        for k, v in obj.items():
            if not isinstance(v, np.ndarray):
                others[k] = v
            elif v.dtype != object and not v.dtype.hasobject and v.dtype.names is None:
                v = np.ascontiguousarray(v)  # structured dtypes lose field
                meta.append((k, v.dtype.str, v.shape, None))  # names via str: pickled
                buffers.append(v)
            else:
                ragged = cls._ragged_buffers(v) if v.dtype == object else None
                if ragged is None:
                    others[k] = v
                else:
                    cells, dtype_str, shapes = ragged
                    meta.append((k, dtype_str, None, shapes))
                    buffers.extend(cells)
        try:
            header = pickle.dumps((meta, others), protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 - unpicklable extras: plain pickle
            return None
        return buffers, header

    @staticmethod
    def _array_bytes(v):
        # datetime/timedelta arrays refuse buffer export (PEP 3118), and
        # memoryview.cast('B') rejects views with zeros in shape/strides
        # (empty blocks — e.g. a predicate filtering a row group to nothing);
        # tobytes() for both, b'' is free anyway
        if v.dtype.kind in 'Mm' or v.size == 0:
            return v.tobytes()
        return memoryview(v).cast('B')  # noqa: PT500 - serialize-side source view, read only

    def serialize(self, obj):
        parts = self.serialize_parts(obj)
        if parts is None:
            return self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
        return self.join_parts(parts)

    def serialize_parts(self, obj):
        """The zero-join channel: the framed message as a LIST of segments
        (one leading bytes prefix, then the raw column/cell arrays) for a
        gather-writing transport (``ShmRing.writev``) — the concatenation of
        the segments is byte-identical to :meth:`serialize` output. Returns
        None when the payload must ride plain pickle (callers then use
        :meth:`serialize`)."""
        split = self._split_block(obj)
        if split is None:
            return None
        buffers, header = split
        return [b''.join((self._BLOCK, struct.pack('<I', len(header)), header))] + buffers

    @classmethod
    def frame_for_layout(cls, meta):
        """Framing prefix (marker + header) for a block whose column layout is
        known AHEAD of decode — the in-place ring channel writes this before
        the payload bytes exist, then the fused native decode lands the rows
        directly after it. ``meta`` entries are the ``(name, dtype_str, shape,
        ragged_shapes)`` tuples of :meth:`_split_block`; the resulting message
        bytes are identical to :meth:`serialize` output for the same block, so
        :meth:`deserialize` cannot tell the channels apart. Returns None for
        layouts the raw-buffer framing cannot carry."""
        try:
            header = pickle.dumps((list(meta), {}), protocol=pickle.HIGHEST_PROTOCOL)
        except Exception:  # noqa: BLE001 - unpicklable layout: copy path
            return None
        return b''.join((cls._BLOCK, struct.pack('<I', len(header)), header))

    @classmethod
    def parts_size(cls, parts):
        return sum(p.nbytes if isinstance(p, np.ndarray) else len(p) for p in parts)

    @classmethod
    def join_parts(cls, parts):
        """In-band fallback for an already-split payload (byte-identical to
        :meth:`serialize` output) — the split never runs twice."""
        return b''.join(cls._array_bytes(p) if isinstance(p, np.ndarray) else p
                        for p in parts)

    @classmethod
    def write_parts_into(cls, parts, target):
        """Write a :meth:`serialize_parts` result into ``target`` (e.g. an
        mmapped /dev/shm blob) — the single-copy channel for payloads already
        split once; bytes are identical to :meth:`serialize` output."""
        buf = memoryview(target)  # noqa: PT500 - target is a caller-provided writable buffer
        off = 0
        for p in parts:
            if isinstance(p, np.ndarray):
                n = p.nbytes
                buf[off:off + n] = cls._array_bytes(p)
            else:
                n = len(p)
                buf[off:off + n] = p
            off += n
        return buf

    def deserialize(self, data):
        mv = memoryview(data)
        marker = bytes(mv[:1])
        if marker == self._PICKLE:
            return pickle.loads(mv[1:])
        (hlen,) = struct.unpack('<I', mv[1:5])
        meta, out = pickle.loads(mv[5:5 + hlen])
        off = 5 + hlen
        for name, dtype_str, shape, ragged in meta:
            dt = np.dtype(dtype_str)
            if ragged is None:
                n = dt.itemsize
                for dim in shape:
                    n *= dim
                out[name] = np.frombuffer(mv[off:off + n], dtype=dt).reshape(shape)
                off += n
            else:
                col = np.empty(len(ragged), dtype=object)
                for i, shp in enumerate(ragged):
                    if shp is None:
                        continue
                    n = dt.itemsize
                    for dim in shp:
                        n *= dim
                    cell = np.frombuffer(mv[off:off + n], dtype=dt).reshape(shp)
                    # ragged cells must arrive WRITABLE regardless of transport:
                    # over zmq the message is immutable bytes and the view is
                    # read-only (in-place image ops / torch.from_numpy would
                    # fail); the ring/blob channels hand out writable buffers,
                    # where the view stays zero-copy
                    col[i] = cell if cell.flags.writeable else cell.copy()
                    off += n
                out[name] = col
        return out

    def serialize_into(self, obj, alloc, min_size=0):
        """Single-copy serialize: compute the exact framed-message size, obtain
        a writable buffer from ``alloc(size)`` (e.g. an mmapped /dev/shm file),
        and write the message straight into it — no intermediate ``b''.join``
        allocation. Returns the buffer, or ``None`` when ``obj`` does not
        qualify (non-block payload, object columns only, or total < ``min_size``
        — callers then use the regular :meth:`serialize` channel). The written
        bytes :meth:`deserialize` identically to :meth:`serialize` output."""
        parts = self.serialize_parts(obj)
        if parts is None or len(parts) == 1:  # non-block, or no raw buffers
            return None
        total = self.parts_size(parts)
        if total < min_size:
            return None
        return self.write_parts_into(parts, alloc(total))


class ArrowTableSerializer(object):
    """Serializes ``pyarrow.Table`` payloads as IPC streams
    (reference arrow_table_serializer.py:23-33). Non-table payloads (e.g.
    exceptions) fall back to pickle with a marker byte."""

    _TABLE = b'T'
    _PICKLE = b'P'

    def serialize(self, obj):
        if isinstance(obj, pa.Table):
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, obj.schema) as writer:
                writer.write_table(obj)
            return self._TABLE + sink.getvalue().to_pybytes()
        return self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        # The shm transport delivers memoryviews; bytes(...) normalizes the
        # marker so it compares equal to the bytes constants.
        marker, body = bytes(data[:1]), data[1:]
        if marker == self._TABLE:
            with pa.ipc.open_stream(pa.BufferReader(body)) as reader:
                return reader.read_all()
        return pickle.loads(body)
