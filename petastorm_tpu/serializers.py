"""Payload serializers for the worker-process -> main-process results channel.

Parity: /root/reference/petastorm/reader_impl/{pickle_serializer,
pyarrow_serializer, arrow_table_serializer}.py. Pickle is the default;
``ArrowTableSerializer`` moves columnar batches as Arrow IPC record-batch
streams, which is zero-copy on the receive side.
"""

from __future__ import annotations

import pickle

import pyarrow as pa


class PickleSerializer(object):
    def serialize(self, obj):
        return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        return pickle.loads(data)


class ArrowTableSerializer(object):
    """Serializes ``pyarrow.Table`` payloads as IPC streams
    (reference arrow_table_serializer.py:23-33). Non-table payloads (e.g.
    exceptions) fall back to pickle with a marker byte."""

    _TABLE = b'T'
    _PICKLE = b'P'

    def serialize(self, obj):
        if isinstance(obj, pa.Table):
            sink = pa.BufferOutputStream()
            with pa.ipc.new_stream(sink, obj.schema) as writer:
                writer.write_table(obj)
            return self._TABLE + sink.getvalue().to_pybytes()
        return self._PICKLE + pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)

    def deserialize(self, data):
        # The shm transport delivers memoryviews; bytes(...) normalizes the
        # marker so it compares equal to the bytes constants.
        marker, body = bytes(data[:1]), data[1:]
        if marker == self._TABLE:
            with pa.ipc.open_stream(pa.BufferReader(body)) as reader:
                return reader.read_all()
        return pickle.loads(body)
