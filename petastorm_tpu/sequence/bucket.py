"""Bucket-by-length batching buffer for the loader's row path.

Padding waste is quadratic in length dispersion: one 900-token row in a batch
of 12-token rows pads everything to 900. The fix is classic bucketing — rows
are routed to length buckets, and the buffer only releases rows in
SAME-BUCKET runs of ``batch_size``, so each padded batch mixes only
near-equal lengths. The loader composes this with
:class:`~petastorm_tpu.sequence.collate.CollateSpec` bucket boundaries, so the
padded length of each batch is its bucket boundary.

The class implements the exact client-side buffer interface
:class:`~petastorm_tpu.jax.loader.JaxDataLoader` already speaks
(``add_many``/``can_retrieve``/``retrieve``/``finish``/``clear``/``size``)
plus the checkpoint surface (``_items`` row snapshot, ``rng_state``), so
loader ``state_dict()``/resume works through bucketed batching unchanged:
checkpointed rows are re-injected with ``add_many`` and re-bucket
deterministically.

Determinism: bucket assignment is a pure function of row length; release
order is FIFO per bucket; the only randomness is the optional seeded
WITHIN-bucket shuffle at release time (rule PT1400 rejects unseeded global
RNG here — the stream must be reproducible under a fixed seed).
"""

from __future__ import annotations

from bisect import bisect_left
from collections import deque

import numpy as np


class BucketBatchBuffer(object):
    """
    :param boundaries: sorted length boundaries; a row of length L lands in
        the first bucket whose boundary >= L (longer rows share one overflow
        bucket).
    :param batch_size: run length released per full bucket — align with the
        loader's ``batch_size`` so every emitted batch is single-bucket.
    :param length_of: field name (or callable row -> int) giving a row's
        sequence length.
    :param seed: seeds the within-bucket shuffle applied as each full run is
        released; ``None`` keeps strict FIFO order (still deterministic).
    """

    def __init__(self, boundaries, batch_size, length_of, seed=None):
        self._boundaries = tuple(sorted(int(b) for b in boundaries))
        if not self._boundaries:
            raise ValueError('boundaries must be non-empty')
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1')
        self._batch_size = batch_size
        if callable(length_of):
            self._length_of = length_of
        else:
            name = length_of

            def _field_length(row, _name=name):
                value = row[_name] if isinstance(row, dict) else getattr(row, _name)
                return len(value)
            self._length_of = _field_length
        # one overflow bucket past the last boundary keeps long rows batched
        # together instead of erroring (their collate pads beyond the ladder)
        self._buckets = [deque() for _ in range(len(self._boundaries) + 1)]
        self._ready = deque()
        self._size = 0
        self._finished = False
        self._rng = np.random.default_rng(seed) if seed is not None else None

    # -- buffer interface (JaxDataLoader row path) --------------------------

    @property
    def size(self):
        return self._size

    def add_many(self, rows):
        for row in rows:
            idx = bisect_left(self._boundaries, self._length_of(row))
            bucket = self._buckets[idx]
            bucket.append(row)
            self._size += 1
            if len(bucket) >= self._batch_size:
                self._release(bucket, self._batch_size)

    def can_add(self):
        return not self._finished

    def can_retrieve(self):
        if self._ready:
            return True
        if self._finished:
            # leftovers flush in boundary order; batches formed across a
            # bucket seam pad to the larger bucket — correct, just less tight
            for bucket in self._buckets:
                if bucket:
                    self._release(bucket, len(bucket))
                    return True
        return False

    def retrieve(self):
        if not self.can_retrieve():
            raise RuntimeError('Bucket buffer has no retrievable rows')
        self._size -= 1
        return self._ready.popleft()

    def finish(self):
        self._finished = True

    def clear(self):
        for bucket in self._buckets:
            bucket.clear()
        self._ready.clear()
        self._size = 0
        self._finished = False

    def _release(self, bucket, count):
        run = [bucket.popleft() for _ in range(count)]
        if self._rng is not None and count > 1:
            order = self._rng.permutation(count)
            run = [run[i] for i in order]
        self._ready.extend(run)

    # -- checkpoint surface -------------------------------------------------

    @property
    def _items(self):
        """Every buffered row (released runs first, then buckets in boundary
        order) — the loader's ``state_dict()`` snapshots this, and resume
        re-buckets the rows via ``add_many``."""
        rows = list(self._ready)
        for bucket in self._buckets:
            rows.extend(bucket)
        return rows

    @property
    def rng_state(self):
        return self._rng.bit_generator.state if self._rng is not None else None

    @rng_state.setter
    def rng_state(self, state):
        if state is not None:
            if self._rng is None:
                self._rng = np.random.default_rng(0)
            self._rng.bit_generator.state = state

    def __repr__(self):
        return 'BucketBatchBuffer(boundaries={}, size={}, ready={})'.format(
            self._boundaries, self._size, len(self._ready))
