"""Greedy sequence packing: many variable-length rows -> few dense token slots.

Padding pays for the LONGEST row in every batch; packing instead concatenates
whole sequences into fixed ``tokens_per_batch`` slots (first-fit-decreasing —
the classic bin-packing heuristic, within 22% of optimal in the worst case and
far closer on zipf-ish length mixes), emitting per-token ``segment_ids`` and
``positions`` arrays so block-diagonal attention masks and per-segment
position embeddings can be reconstructed downstream. A slot's pad tail is
``segment_ids == 0``.

Efficiency is accounted per batch and cumulatively
(``packing_efficiency`` = real tokens / slot capacity — docs/observability.md);
the token bench (``bench.py --workload tokens``) holds the padded-vs-packed
comparison.

Determinism (rule PT1400): packing decisions are pure functions of the pooled
rows' lengths — no RNG, no wall clock — so a fixed seed upstream reproduces
bit-identical packed batches.
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu import observability as obs
from petastorm_tpu.errors import PetastormTpuError


def first_fit_decreasing(lengths, capacity):
    """Pack item lengths into bins of ``capacity`` with first-fit-decreasing.

    Returns a list of bins, each a list of item INDICES into ``lengths``
    (bins in creation order; indices in decreasing-length order within a bin,
    ties broken by original index so the result is deterministic).
    Items longer than ``capacity`` raise — truncation is the caller's
    explicit decision (``PadSpec.max_length`` upstream).
    """
    order = sorted(range(len(lengths)), key=lambda i: (-int(lengths[i]), i))
    bins, remaining = [], []
    for i in order:
        n = int(lengths[i])
        if n > capacity:
            raise PetastormTpuError(
                'Sequence of length {} exceeds tokens_per_batch={}; truncate upstream '
                '(PadSpec(max_length=...)) or raise the slot capacity'.format(n, capacity))
        for b, free in enumerate(remaining):
            if n <= free:
                bins[b].append(i)
                remaining[b] -= n
                break
        else:
            bins.append([i])
            remaining.append(capacity - n)
    return bins


def pack_rows(rows, tokens_per_batch, sequence_fields, length_of=None, pad_value=0):
    """Pack row dicts/namedtuples into dense slots.

    :param rows: rows whose ``sequence_fields`` are 1-D (or [L, ...]) arrays
        sharing one length per row
    :param sequence_fields: field names packed along the token axis
    :param length_of: field defining each row's token length (default: first
        of ``sequence_fields``)
    :returns: ``(batch, stats)`` — ``batch`` maps each sequence field to a
        ``[num_slots, tokens_per_batch, ...]`` array plus ``segment_ids`` /
        ``positions`` (int32, same shape, 0-padded; segment ids are 1-based
        per slot) and ``num_segments`` ``[num_slots]``; ``stats`` carries
        ``real_tokens`` / ``slot_tokens`` / ``packing_efficiency``.
    """
    if not rows:
        raise PetastormTpuError('Cannot pack an empty row list')
    rows = [r._asdict() if hasattr(r, '_asdict') else r for r in rows]
    fields = list(sequence_fields)
    length_field = length_of or fields[0]
    lengths = [len(np.asarray(r[length_field])) for r in rows]
    bins = first_fit_decreasing(lengths, tokens_per_batch)

    batch = {}
    for name in fields:
        cells = [np.asarray(r[name]) for r in rows]
        trailing = cells[0].shape[1:]
        out = np.full((len(bins), tokens_per_batch) + trailing, pad_value,
                      dtype=cells[0].dtype)
        for b, members in enumerate(bins):
            cursor = 0
            for i in members:
                n = lengths[i]
                out[b, cursor:cursor + n] = cells[i][:n]
                cursor += n
        batch[name] = out

    segment_ids = np.zeros((len(bins), tokens_per_batch), dtype=np.int32)
    positions = np.zeros((len(bins), tokens_per_batch), dtype=np.int32)
    num_segments = np.zeros(len(bins), dtype=np.int32)
    for b, members in enumerate(bins):
        cursor = 0
        for seg, i in enumerate(members, start=1):
            n = lengths[i]
            segment_ids[b, cursor:cursor + n] = seg
            positions[b, cursor:cursor + n] = np.arange(n, dtype=np.int32)
            cursor += n
        num_segments[b] = len(members)
    batch['segment_ids'] = segment_ids
    batch['positions'] = positions
    batch['num_segments'] = num_segments

    real = int(sum(lengths))
    slot_tokens = len(bins) * tokens_per_batch
    stats = {'real_tokens': real, 'slot_tokens': slot_tokens,
             'packing_efficiency': round(real / slot_tokens, 4) if slot_tokens else 0.0}
    return batch, stats


class PackedSequenceLoader(object):
    """Iterate a reader as PACKED token batches.

    Pulls rows (row-oriented readers directly; batched readers are transposed
    a block at a time), pools ``pool_rows`` of them, first-fit-decreasing
    packs the pool into ``tokens_per_batch`` slots, and yields batches of
    ``slots_per_batch`` slots. Slots the pool could not fill to a full batch
    return to the pool and re-pack with later arrivals, so mid-stream batches
    stay dense; on reader exhaustion the tail is flushed (or dropped with
    ``drop_last``).

    Non-sequence fields are dropped from the output (a packed slot has no
    single value for them) — project them upstream if needed.

    Checkpointing: :meth:`state_dict` embeds the underlying reader state plus
    the pooled rows, mirroring the
    :class:`~petastorm_tpu.jax.loader.JaxDataLoader` contract.

    :param reader: a :class:`petastorm_tpu.reader.Reader` (row or columnar)
    :param tokens_per_batch: slot capacity in tokens
    :param sequence_fields: fields packed along the token axis
    :param slots_per_batch: slots per yielded batch (the device batch dim)
    :param pool_rows: rows pooled before each packing pass — larger pools
        pack tighter at the cost of latency and checkpoint size
    """

    def __init__(self, reader, tokens_per_batch, sequence_fields,
                 slots_per_batch=8, pool_rows=256, length_of=None, pad_value=0,
                 drop_last=False, resume_state=None):
        if tokens_per_batch < 1 or slots_per_batch < 1 or pool_rows < 1:
            raise ValueError('tokens_per_batch, slots_per_batch and pool_rows must be >= 1')
        self.reader = reader
        self._tokens = tokens_per_batch
        self._fields = list(sequence_fields)
        self._slots = slots_per_batch
        self._pool_rows = pool_rows
        self._length_of = length_of or self._fields[0]
        self._pad_value = pad_value
        self._drop_last = drop_last
        self._pool = []
        self._real_tokens = 0
        self._slot_tokens = 0
        self._batches_out = 0
        if resume_state is not None:
            if not isinstance(resume_state, dict) or resume_state.get('version') != 1:
                raise ValueError('Unrecognized resume_state (expected a dict produced by '
                                 'PackedSequenceLoader.state_dict())')
            self._pool = list(resume_state['rows'])

    def __iter__(self):
        from petastorm_tpu.jax.loader import _rows_from_columnar_batch, _to_plain_row
        for item in self.reader:
            if self.reader.batched_output:
                self._pool.extend(_rows_from_columnar_batch(item))
            else:
                self._pool.append(_to_plain_row(item))
            while len(self._pool) >= self._pool_rows:
                batch = self._pack_once(flush=False)
                if batch is None:
                    break  # pool packs to < slots_per_batch full slots: need more rows
                yield batch
        while self._pool:
            batch = self._pack_once(flush=True)
            if batch is None:
                return
            yield batch

    def _pack_once(self, flush):
        lengths = [len(np.asarray(r[self._length_of])) for r in self._pool]
        bins = first_fit_decreasing(lengths, self._tokens)
        if not flush:
            if len(bins) < self._slots + 1:
                # keep one spill bin pooled: the last-opened bin is the least
                # full, so emitting it mid-stream would dilute efficiency
                return None
            emit_bins, spill = bins[:self._slots], bins[self._slots:]
        else:
            emit_bins, spill = bins[:self._slots], bins[self._slots:]
            if self._drop_last and len(emit_bins) < self._slots:
                self._pool = []
                return None
        emitted_rows = [self._pool[i] for b in emit_bins for i in b]
        self._pool = [self._pool[i] for b in spill for i in b]
        batch, stats = pack_rows(emitted_rows, self._tokens, self._fields,
                                 length_of=self._length_of, pad_value=self._pad_value)
        self._real_tokens += stats['real_tokens']
        self._slot_tokens += len(emit_bins) * self._tokens
        self._batches_out += 1
        obs.count('seq_packed_batches_total')
        obs.count('seq_packed_real_tokens_total', stats['real_tokens'])
        obs.gauge_set('packing_efficiency', self.packing_efficiency)
        return batch

    @property
    def packing_efficiency(self):
        """Cumulative real-token fill of all emitted slots (0.0 before the
        first batch; the acceptance bar on the zipf bench is >= 0.85)."""
        if not self._slot_tokens:
            return 0.0
        return round(self._real_tokens / self._slot_tokens, 4)

    @property
    def diagnostics(self):
        out = dict(self.reader.diagnostics)
        out.update({
            'packing_efficiency': self.packing_efficiency,
            'packed_batches': self._batches_out,
            'packed_real_tokens': self._real_tokens,
            'packed_slot_tokens': self._slot_tokens,
        })
        return out

    def state_dict(self):
        from petastorm_tpu.jax.loader import _to_plain_row
        return {'version': 1,
                'reader': self.reader.state_dict(),
                'rows': [_to_plain_row(r) for r in self._pool]}

    def stop(self):
        self.reader.stop()

    def join(self):
        self.reader.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
