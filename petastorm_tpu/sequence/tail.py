"""Tail-following reads: iterate a dataset that is still being written.

The contract (docs/sequence.md) is snapshot-based, mirroring the elastic
package's marker idiom (``elastic/coordinator.py``): a writer opened with
``append=True`` calls :meth:`~petastorm_tpu.etl.dataset_metadata.DatasetWriter.publish`
whenever it wants written-so-far data visible. ``publish`` closes the open
part files (a Parquet footer only exists on a closed file), rewrites
``_common_metadata`` with the merged row-group inventory, and stamps an
immutable marker ``_snapshots/snap-NNNNNN.json`` holding the CUMULATIVE piece
inventory ``[[relpath, row_group, num_rows], ...]`` (hard-link publish with an
``O_EXCL`` fallback on local filesystems — readers skip a torn marker and pick
it up complete on the next poll).

:class:`TailFollowingReader` turns that into a row stream with exactly-once
delivery: each *delta epoch* is one inner
:func:`~petastorm_tpu.reader.make_reader` scoped (via ``piece_filter``) to the
row groups a new snapshot added beyond the already-delivered set. Because
every delta epoch is its own Reader, everything downstream — ventilator plan,
chunk-store prefetch walking ``upcoming_items``, per-epoch shuffling — is
automatically snapshot-scoped; a piece is either wholly inside one delta or
not visible at all, never split. Growth is observable as the
``dataset_grew`` counter (docs/observability.md); polling between snapshots
is bounded by ``poll_interval``/``idle_timeout``.

This module legitimately reads the wall clock (poll cadence) — it is
deliberately OUTSIDE rule PT1400's scope, which covers sampling/packing
decisions, not IO pacing.
"""

from __future__ import annotations

import errno
import json
import os
import posixpath
import time

from pyarrow import fs as pafs

from petastorm_tpu import observability as obs
from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.fs import FilesystemResolver

SNAPSHOT_DIR = '_snapshots'
_SNAPSHOT_FMT = 'snap-{:06d}.json'


def _snapshot_id(basename):
    """``snap-000012.json`` -> 12, or None for foreign/tmp files."""
    if not (basename.startswith('snap-') and basename.endswith('.json')):
        return None
    stem = basename[len('snap-'):-len('.json')]
    return int(stem) if stem.isdigit() else None


def list_snapshots(dataset_url):
    """All published snapshots as ``[(snapshot_id, info_dict)]``, ascending.

    Torn or foreign files under ``_snapshots/`` are skipped — a marker is
    only returned once it parses as a complete snapshot (the same
    skip-and-repoll contract the elastic generation log uses).
    """
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    snap_dir = posixpath.join(root, SNAPSHOT_DIR)
    infos = fs.get_file_info(pafs.FileSelector(snap_dir, allow_not_found=True))
    out = []
    for info in infos:
        if info.type != pafs.FileType.File:
            continue
        snap_id = _snapshot_id(posixpath.basename(info.path))
        if snap_id is None:
            continue
        try:
            with fs.open_input_stream(info.path) as f:
                payload = json.loads(f.read().decode('utf-8'))
            if payload.get('snapshot') != snap_id or 'pieces' not in payload:
                continue
        except (ValueError, OSError):
            continue  # torn marker mid-write: complete on a later poll
        out.append((snap_id, payload))
    out.sort(key=lambda pair: pair[0])
    return out


def latest_snapshot(dataset_url):
    """The newest complete snapshot's info dict, or None if none published."""
    snaps = list_snapshots(dataset_url)
    return snaps[-1][1] if snaps else None


def publish_snapshot(dataset_url, final=False):
    """Stamp a snapshot marker naming every row group the CURRENT
    ``_common_metadata`` inventory describes.

    Normally called through
    :meth:`~petastorm_tpu.etl.dataset_metadata.DatasetWriter.publish`, which
    first closes open part files and rewrites the inventory — calling this
    directly only makes sense on a dataset whose metadata is already current
    (e.g. stamping snapshot 0 on a finished dataset so tail followers can
    start from it).

    :param final: marks the snapshot terminal — tail followers drain it and
        stop instead of polling for more
    :returns: the published snapshot id (int)
    """
    from petastorm_tpu.etl.dataset_metadata import (ROW_GROUPS_PER_FILE_KEY,
                                                    _read_common_metadata)
    resolver = FilesystemResolver(dataset_url)
    fs, root = resolver.filesystem(), resolver.get_dataset_path()
    arrow_meta = _read_common_metadata(fs, root)
    meta = (arrow_meta.metadata or {}) if arrow_meta is not None else {}
    if ROW_GROUPS_PER_FILE_KEY not in meta:
        raise PetastormTpuError(
            'Cannot publish a snapshot of {}: no row-group inventory in '
            '_common_metadata (write through materialize_dataset / '
            'DatasetWriter.publish first)'.format(dataset_url))
    counts = json.loads(meta[ROW_GROUPS_PER_FILE_KEY].decode('utf-8'))
    pieces = []
    for relpath in sorted(counts):
        entry = counts[relpath]
        row_counts = entry if isinstance(entry, list) else [None] * entry
        for rg, num_rows in enumerate(row_counts):
            pieces.append([relpath, rg, num_rows])

    snap_dir = posixpath.join(root, SNAPSHOT_DIR)
    fs.create_dir(snap_dir, recursive=True)
    existing = [sid for sid, _ in list_snapshots(dataset_url)]
    snap_id = (existing[-1] + 1) if existing else 0
    while True:
        payload = json.dumps({'snapshot': snap_id, 'final': bool(final),
                              'pieces': pieces})
        path = posixpath.join(snap_dir, _SNAPSHOT_FMT.format(snap_id))
        if _write_marker(fs, path, payload):
            return snap_id
        snap_id += 1  # lost an O_EXCL race: the next id is ours


def _write_marker(fs, path, payload):
    """Write ``payload`` at ``path``, never replacing an existing marker.

    Local filesystems get the elastic coordinator's atomic idiom — write a
    tmp file, hard-link it into place (O_EXCL fallback where links are
    unsupported). Non-local stores write a plain stream: snapshots are
    single-writer by contract, and readers skip torn markers anyway.
    Returns False when ``path`` already exists (caller picks the next id).
    """
    if not os.path.isdir(os.path.dirname(path)):
        with fs.open_output_stream(path) as sink:
            sink.write(payload.encode('utf-8'))
        return True
    tmp = '{}.tmp.{}'.format(path, os.getpid())
    try:
        with open(tmp, 'w') as f:
            f.write(payload)
        try:
            os.link(tmp, path)
            return True
        except OSError as e:
            if getattr(e, 'errno', None) not in (errno.EPERM, errno.ENOSYS,
                                                 errno.EOPNOTSUPP):
                return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
    except OSError:
        return False
    try:
        os.write(fd, payload.encode('utf-8'))
    finally:
        os.close(fd)
    return True


class TailFollowingReader(object):
    """Follow a growing dataset, delivering every published row exactly once.

    Each published snapshot's NEW row groups become one inner reader epoch
    (``piece_filter``-scoped); a row group enters the delivered set only when
    its delta epoch drains cleanly, and mid-epoch positions checkpoint through
    the inner reader's own v2 resume cursor — so ``state_dict()`` / resume
    never re-delivers or skips a row.

    :param dataset_url: dataset being appended by a concurrent
        ``DatasetWriter(append=True)`` + ``publish()`` writer
    :param poll_interval: seconds between snapshot-directory scans while idle
    :param idle_timeout: raise :class:`PetastormTpuError` after this many
        seconds without a new snapshot (``None`` = poll forever); a snapshot
        published with ``final=True`` always ends the stream cleanly instead
    :param resume_state: dict from :meth:`state_dict`
    :param reader_kwargs: forwarded to :func:`~petastorm_tpu.reader.make_reader`
        for every delta epoch (``num_epochs``/``piece_filter``/``resume_state``
        are owned by this class and rejected)
    """

    def __init__(self, dataset_url, poll_interval=0.5, idle_timeout=60.0,
                 resume_state=None, **reader_kwargs):
        for owned in ('num_epochs', 'piece_filter', 'resume_state'):
            if owned in reader_kwargs:
                raise PetastormTpuError(
                    '{} is owned by TailFollowingReader (one inner epoch per '
                    'snapshot delta)'.format(owned))
        if poll_interval <= 0:
            raise PetastormTpuError('poll_interval must be > 0')
        self._dataset_url = dataset_url
        self._poll_interval = poll_interval
        self._idle_timeout = idle_timeout
        self._reader_kwargs = dict(reader_kwargs)
        self._delivered = set()     # {(relpath, row_group)} from DRAINED epochs
        self._consumed_snapshot = -1
        self._grew = 0
        self._rows_out = 0
        self._final_seen = False
        self._stopped = False
        self._inner = None
        self._current_delta = None  # sorted [(relpath, rg)] of the open epoch
        if resume_state is not None:
            self._load_state(resume_state)

    # -- iteration ----------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self._inner is not None:
                try:
                    item = next(self._inner)
                except StopIteration:
                    self._retire_inner()
                    continue
                self._rows_out += self._rows_in(item)
                return item
            if self._stopped:
                raise StopIteration
            # once a final marker is seen, drain remaining snapshots without
            # waiting for more
            if not self._open_next_delta(poll=not self._final_seen):
                raise StopIteration  # final snapshot fully delivered

    next = __next__

    def _rows_in(self, item):
        if getattr(self._inner, 'batched_output', False):
            d = item._asdict() if hasattr(item, '_asdict') else item
            first = next(iter(d.values()))
            try:
                return len(first)
            except TypeError:
                return 1  # ngram window blocks: nested dicts, count as one
        return 1

    def _retire_inner(self):
        """A delta epoch drained cleanly: its pieces are now delivered."""
        self._delivered.update(self._current_delta)
        self._current_delta = None
        inner, self._inner = self._inner, None
        inner.stop()
        inner.join()

    def _open_next_delta(self, poll):
        """Scope a reader to the next snapshot's new pieces. Returns True when
        an epoch opened; False when a final snapshot is fully delivered.
        Raises on idle timeout (writer gone without a final marker)."""
        deadline = (time.monotonic() + self._idle_timeout
                    if self._idle_timeout is not None else None)
        while True:
            for snap_id, info in list_snapshots(self._dataset_url):
                if snap_id <= self._consumed_snapshot:
                    continue
                delta = sorted((relpath, rg) for relpath, rg, _ in info['pieces']
                               if (relpath, rg) not in self._delivered)
                self._consumed_snapshot = snap_id
                self._final_seen = self._final_seen or bool(info.get('final'))
                if delta:
                    self._grew += 1
                    obs.count('dataset_grew')
                    self._start_inner(delta)
                    return True
            if self._final_seen:
                return False
            if not poll:
                return False
            if deadline is not None and time.monotonic() >= deadline:
                raise PetastormTpuError(
                    'No new snapshot of {} within idle_timeout={}s — the '
                    'appending writer is gone without publishing final=True '
                    '(raise idle_timeout, or None to poll forever)'.format(
                        self._dataset_url, self._idle_timeout))
            time.sleep(self._poll_interval)

    def _start_inner(self, delta, inner_resume=None):
        from petastorm_tpu.reader import make_reader
        self._current_delta = delta
        delta_set = set(delta)
        root = FilesystemResolver(self._dataset_url).get_dataset_path()

        def _in_delta(piece, _root=root, _set=delta_set):
            rel = posixpath.relpath(piece.path, _root)
            return (rel, piece.row_group) in _set

        self._inner = make_reader(self._dataset_url, num_epochs=1,
                                  piece_filter=_in_delta,
                                  resume_state=inner_resume,
                                  **self._reader_kwargs)

    # -- checkpoint ---------------------------------------------------------

    def state_dict(self):
        """Resumable position: the delivered set, the snapshot cursor, and —
        when a delta epoch is mid-flight — its piece list plus the inner
        reader's own resume cursor."""
        return {
            'version': 1,
            'delivered': sorted(self._delivered),
            'consumed_snapshot': self._consumed_snapshot,
            'final_seen': self._final_seen,
            'current_delta': (list(self._current_delta)
                              if self._current_delta is not None else None),
            'inner': self._inner.state_dict() if self._inner is not None else None,
        }

    def _load_state(self, state):
        if not isinstance(state, dict) or state.get('version') != 1:
            raise PetastormTpuError('Unrecognized resume_state (expected a dict '
                                    'from TailFollowingReader.state_dict())')
        self._delivered = {(relpath, rg) for relpath, rg in state['delivered']}
        self._consumed_snapshot = state['consumed_snapshot']
        self._final_seen = state['final_seen']
        if state['current_delta'] is not None:
            delta = sorted((relpath, rg) for relpath, rg in state['current_delta'])
            self._start_inner(delta, inner_resume=state['inner'])

    # -- reader surface -----------------------------------------------------

    @property
    def batched_output(self):
        if self._inner is not None:
            return self._inner.batched_output
        return self._reader_kwargs.get('output', 'rows') == 'columnar'

    @property
    def diagnostics(self):
        """Tail keys are ALWAYS present (key-set stability contract); the open
        delta epoch's inner reader diagnostics merge in underneath."""
        out = dict(self._inner.diagnostics) if self._inner is not None else {}
        out['dataset_grew'] = self._grew
        out['tail_snapshot'] = self._consumed_snapshot
        out['tail_pieces_delivered'] = len(self._delivered)
        out['tail_rows_delivered'] = self._rows_out
        return out

    def stop(self):
        self._stopped = True
        if self._inner is not None:
            self._inner.stop()

    def join(self):
        if self._inner is not None:
            self._inner.join()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc_value, tb):
        self.stop()
        self.join()
