"""Hot-swappable dataset mixtures with per-source telemetry.

:class:`MixtureReader` grows
:class:`~petastorm_tpu.weighted_sampling_reader.WeightedSamplingReader` into
the mixture surface LLM curricula need:

* **live re-weighting** — ``set_weights([...])`` retargets the sampling
  distribution between two ``next()`` calls (annealing code-vs-prose mid-run
  without rebuilding readers);
* **epoch schedules** — a :class:`MixtureSchedule` maps epoch index ->
  weights, applied at each :meth:`MixtureReader.reset` boundary;
* **per-source accounting** — rows, tokens (when ``token_field`` names the
  sequence column) and exhaustion flags per source, surfaced as
  ``mixture_source_*`` keys in :attr:`MixtureReader.diagnostics` and rendered
  by the stall report (docs/observability.md).

Determinism (rule PT1400): every sampling decision consumes the seeded
constructor stream — never a wall clock, never the process-global RNG — so a
fixed seed reproduces the interleaving exactly, including across
``set_weights`` calls (a weight swap changes the distribution, not the
stream).
"""

from __future__ import annotations

import numpy as np

from petastorm_tpu.errors import PetastormTpuError
from petastorm_tpu.weighted_sampling_reader import WeightedSamplingReader


class MixtureSchedule(object):
    """Epoch-indexed weight schedule: ``{epoch: weights}`` (or a list of
    ``(epoch, weights)``). Epoch E uses the entry with the LARGEST key <= E,
    so ``{0: [9, 1], 3: [5, 5]}`` anneals at epoch 3 and holds after."""

    def __init__(self, entries):
        items = sorted(dict(entries).items())
        if not items:
            raise PetastormTpuError('MixtureSchedule needs at least one entry')
        if items[0][0] != 0:
            raise PetastormTpuError('MixtureSchedule must define epoch 0 '
                                    '(got first epoch {})'.format(items[0][0]))
        self._entries = [(int(e), tuple(float(w) for w in ws)) for e, ws in items]

    def weights_for(self, epoch):
        chosen = self._entries[0][1]
        for e, ws in self._entries:
            if e > epoch:
                break
            chosen = ws
        return chosen

    def __repr__(self):
        return 'MixtureSchedule({})'.format(dict(self._entries))


class MixtureReader(WeightedSamplingReader):
    """
    :param readers: sources to mix (same schema/batched-ness/NGram contract as
        :class:`WeightedSamplingReader`)
    :param weights: initial relative weights; ``None`` requires ``schedule``
    :param seed: seeds the sampling stream
    :param on_exhausted: ``'renormalize'`` (default) | ``'stop'``
    :param schedule: optional :class:`MixtureSchedule` (or its ctor argument)
        applied at construction (epoch 0) and at every :meth:`reset`
    :param token_field: field whose per-row length counts as tokens in the
        per-source accounting (``None`` counts rows only)
    """

    def __init__(self, readers, weights=None, seed=None, on_exhausted='renormalize',
                 schedule=None, token_field=None):
        if schedule is not None and not isinstance(schedule, MixtureSchedule):
            schedule = MixtureSchedule(schedule)
        if weights is None:
            if schedule is None:
                raise PetastormTpuError('MixtureReader needs weights or a schedule')
            weights = schedule.weights_for(0)
        super(MixtureReader, self).__init__(readers, weights, seed=seed,
                                            on_exhausted=on_exhausted)
        self._schedule = schedule
        self._token_field = token_field
        self._epoch = 0
        self._weight_updates = 0
        self._source_rows = [0] * len(self._readers)
        self._source_tokens = [0] * len(self._readers)

    # -- live weight control ------------------------------------------------

    def set_weights(self, weights):
        """Swap the sampling weights between two ``next()`` calls. Exhausted
        sources stay exhausted (their new mass renormalizes over the live
        set); the RNG stream is untouched, so a seeded run stays reproducible
        across the swap."""
        if len(weights) != len(self._readers):
            raise PetastormTpuError('Expected {} weights, got {}'.format(
                len(self._readers), len(weights)))
        w = np.asarray(weights, dtype=np.float64)
        if (w < 0).any() or float(w.sum()) <= 0:
            raise PetastormTpuError('weights must be non-negative and sum to a '
                                    'positive value')
        self._weights = w / float(w.sum())
        self._rebuild_cum()
        self._weight_updates += 1

    @property
    def weights(self):
        """The current normalized weight vector (including exhausted sources'
        nominal mass — live renormalization happens at draw time)."""
        return tuple(float(x) for x in self._weights)

    @property
    def epoch(self):
        return self._epoch

    def reset(self):
        """Epoch boundary: reset every finished source for another pass, revive
        exhausted ones, and apply the schedule's weights for the new epoch.
        Infinite sources (``num_epochs=None``) just keep streaming across the
        boundary — for them an epoch is only a weight-schedule step."""
        for r in self._readers:
            if getattr(r, 'last_row_consumed', False):
                r.reset()
        self._live = [True] * len(self._readers)
        self._epoch += 1
        if self._schedule is not None:
            self.set_weights(self._schedule.weights_for(self._epoch))
            self._weight_updates -= 1  # schedule steps are not user swaps
        else:
            self._rebuild_cum()
        self.last_row_consumed = False

    # -- telemetry hooks ----------------------------------------------------

    def _on_row(self, choice, row):
        if self.batched_output:
            d = row._asdict() if hasattr(row, '_asdict') else row
            first = next(iter(d.values()))
            n = len(first)
            self._source_rows[choice] += n
            if self._token_field is not None:
                col = d[self._token_field]
                self._source_tokens[choice] += int(sum(len(c) for c in col))
        else:
            self._source_rows[choice] += 1
            if self._token_field is not None:
                cell = (row[self._token_field] if isinstance(row, dict)
                        else getattr(row, self._token_field))
                self._source_tokens[choice] += len(cell)

    @property
    def diagnostics(self):
        """Union of every source's diagnostics (sources listed later win key
        collisions) plus the ``mixture_source_*`` family the stall report
        renders: per-source rows/tokens/exhausted, the live weight vector,
        and the count of live weight swaps."""
        out = {}
        for r in self._readers:
            out.update(getattr(r, 'diagnostics', {}) or {})
        for i in range(len(self._readers)):
            out['mixture_source_{}_rows'.format(i)] = self._source_rows[i]
            out['mixture_source_{}_tokens'.format(i)] = self._source_tokens[i]
            out['mixture_source_{}_exhausted'.format(i)] = int(not self._live[i])
        out['mixture_weights'] = list(self.weights)
        out['mixture_weight_updates'] = self._weight_updates
        out['mixture_epoch'] = self._epoch
        return out
