"""Sequence data plane: variable-length (token) workloads as first-class
citizens.

The rest of the framework moves fixed-shape tensors; this package owns
everything whose shape is data-dependent (docs/sequence.md):

* :mod:`collate` — ragged/padded collation: per-field ``pad_to`` multiples /
  bucket boundaries, per-batch length vectors, padding-waste telemetry
  (``padding_waste_fraction``). Wired into
  :class:`~petastorm_tpu.jax.loader.JaxDataLoader` via ``collate_spec=``.
* :mod:`bucket` — bucket-by-length batching: a drop-in client-side loader
  buffer that releases rows in same-bucket runs of ``batch_size``, so padded
  batches waste almost nothing. Deterministic, seedable, and
  checkpoint-compatible with the loader's ``state_dict()``.
* :mod:`packing` — greedy first-fit-decreasing sequence packing into fixed
  ``tokens_per_batch`` slots, emitting ``segment_ids``/``positions`` arrays
  so attention masks can be reconstructed downstream
  (``packing_efficiency`` telemetry).
* :mod:`mixture` — :class:`MixtureReader`: hot-swappable per-source weights
  (``set_weights()`` live, :class:`MixtureSchedule` at epoch boundaries),
  per-source rows/tokens/exhaustion counters merged into ``diagnostics``
  and the stall report.
* :mod:`tail` — tail-following streaming ingest: iterate a dataset a
  concurrent :func:`~petastorm_tpu.etl.dataset_metadata.materialize_dataset`
  writer is still appending to. Epoch = one published snapshot
  (``_snapshots/`` ``O_EXCL`` markers, the elastic generation log as the
  template), exactly-once row delivery across snapshots, bounded poll
  cadence, ``dataset_grew`` counter.

Determinism contract: mixture/packing/bucket sampling decisions must never
consume wall clocks or unseeded global RNG streams — rule PT1400
(``petastorm_tpu/analysis/sequence_lints.py``) enforces it statically.
"""

from __future__ import annotations

from petastorm_tpu.sequence.bucket import BucketBatchBuffer
from petastorm_tpu.sequence.collate import (CollateSpec, PadSpec, collate_ragged_rows,
                                            padded_length)
from petastorm_tpu.sequence.mixture import MixtureReader, MixtureSchedule
from petastorm_tpu.sequence.packing import (PackedSequenceLoader, first_fit_decreasing,
                                            pack_rows)
from petastorm_tpu.sequence.tail import (TailFollowingReader, latest_snapshot,
                                         list_snapshots, publish_snapshot)

__all__ = [
    'BucketBatchBuffer', 'CollateSpec', 'MixtureReader', 'MixtureSchedule',
    'PackedSequenceLoader', 'PadSpec', 'TailFollowingReader',
    'collate_ragged_rows', 'first_fit_decreasing', 'latest_snapshot',
    'list_snapshots', 'pack_rows', 'padded_length', 'publish_snapshot',
]
