"""Ragged/padded collation: variable-length rows -> dense padded batches.

The fixed collate path (:func:`petastorm_tpu.jax.loader.collate_rows`) refuses
non-uniform shapes, because silently padding would change what the model sees.
This module is the explicit opt-in: a :class:`CollateSpec` names which fields
are ragged and HOW to pad them (a ``pad_to`` multiple, ``buckets`` boundaries,
an optional hard ``max_length`` truncation), the collate emits dense
``[B, L, ...]`` arrays plus an int32 ``<field>_lengths`` vector per ragged
field, and every batch's padding waste is accounted
(``padding_waste_fraction`` — docs/observability.md).

Everything here is deterministic: padded lengths are pure functions of the
batch's real lengths and the spec, never of wall clocks or RNG draws
(rule PT1400 scopes the sampling-decision modules; this one has no decisions
to make).
"""

from __future__ import annotations

from bisect import bisect_left

import numpy as np

from petastorm_tpu.errors import PetastormTpuError


class PadSpec(object):
    """Padding policy for ONE ragged field (leading axis is the ragged one).

    :param pad_to: pad the batch length up to the next multiple of this
        (e.g. 128 keeps XLA shape buckets coarse). ``None`` = exact max.
    :param buckets: sorted length boundaries; the batch pads to the smallest
        boundary >= its longest row (lengths beyond the last boundary fall
        back to ``pad_to`` rounding). Pair with
        :class:`~petastorm_tpu.sequence.bucket.BucketBatchBuffer` so rows of
        one batch share a bucket and the padding waste stays small.
    :param max_length: hard cap — longer rows are TRUNCATED to this many
        elements (an explicit data-changing decision, so never a default).
    :param pad_value: fill value for the padded tail (default 0).
    :param emit_lengths: also emit ``<field>_lengths`` (int32 real lengths,
        pre-truncation capped at ``max_length``) into the batch.
    """

    __slots__ = ('pad_to', 'buckets', 'max_length', 'pad_value', 'emit_lengths')

    def __init__(self, pad_to=None, buckets=None, max_length=None, pad_value=0,
                 emit_lengths=True):
        if pad_to is not None and pad_to < 1:
            raise ValueError('pad_to must be >= 1')
        if buckets is not None:
            buckets = tuple(sorted(int(b) for b in buckets))
            if not buckets or buckets[0] < 1:
                raise ValueError('buckets must be a non-empty sequence of lengths >= 1')
        if max_length is not None and max_length < 1:
            raise ValueError('max_length must be >= 1')
        self.pad_to = pad_to
        self.buckets = buckets
        self.max_length = max_length
        self.pad_value = pad_value
        self.emit_lengths = emit_lengths

    def __repr__(self):
        return 'PadSpec(pad_to={}, buckets={}, max_length={})'.format(
            self.pad_to, self.buckets, self.max_length)


def padded_length(length, spec):
    """The dense length a batch whose longest row is ``length`` pads to —
    a pure function of (length, spec): bucket boundary first, then ``pad_to``
    rounding, after the ``max_length`` cap."""
    n = int(length)
    if spec.max_length is not None:
        n = min(n, spec.max_length)
    if spec.buckets is not None:
        i = bisect_left(spec.buckets, n)
        if i < len(spec.buckets):
            return spec.buckets[i]
    if spec.pad_to is not None:
        n = ((n + spec.pad_to - 1) // spec.pad_to) * spec.pad_to
    return max(n, 1)


class CollateSpec(object):
    """Batch-level ragged collation policy: which fields pad, and which field's
    length drives bucketing/packing decisions.

    :param pads: mapping field name -> :class:`PadSpec` (a bare ``PadSpec``
        is accepted for single-field shorthand via ``{'field': PadSpec()}``)
    :param length_of: the field whose per-row length is THE sequence length
        (bucket assignment, token accounting). Defaults to the first ``pads``
        key.
    """

    __slots__ = ('pads', 'length_of')

    def __init__(self, pads, length_of=None):
        if not isinstance(pads, dict) or not pads:
            raise ValueError('pads must be a non-empty {field: PadSpec} dict')
        for name, spec in pads.items():
            if not isinstance(spec, PadSpec):
                raise ValueError('pads[{!r}] must be a PadSpec, got {!r}'.format(name, spec))
        self.pads = dict(pads)
        self.length_of = length_of if length_of is not None else next(iter(pads))
        if self.length_of not in self.pads:
            raise ValueError('length_of {!r} is not a padded field ({})'.format(
                self.length_of, sorted(self.pads)))

    def row_length(self, row):
        """Real (untruncated) sequence length of one row dict/namedtuple."""
        value = row[self.length_of] if isinstance(row, dict) else getattr(row, self.length_of)
        return len(value)


def _cell(row, name):
    return row[name] if isinstance(row, dict) else getattr(row, name)


def _pad_field(values, spec, name):
    """Stack ragged cells into one dense [B, L, ...] array + lengths."""
    cells = [np.asarray(v) for v in values]
    lengths = np.array([c.shape[0] if c.ndim else 0 for c in cells], dtype=np.int32)
    if spec.max_length is not None:
        lengths = np.minimum(lengths, spec.max_length)
    trailing = {c.shape[1:] for c in cells}
    if len(trailing) > 1:
        raise PetastormTpuError(
            'Field {!r} mixes trailing shapes {} within a batch; ragged collation pads '
            'only the leading axis'.format(name, sorted(trailing)))
    target = padded_length(int(lengths.max()) if len(lengths) else 1, spec)
    dtype = cells[0].dtype
    if dtype == object:
        raise PetastormTpuError(
            'Field {!r} decoded to object cells; ragged collation needs numeric '
            'arrays (check the codec / TransformSpec output)'.format(name))
    out = np.full((len(cells), target) + cells[0].shape[1:], spec.pad_value, dtype=dtype)
    for i, c in enumerate(cells):
        n = int(lengths[i])
        out[i, :n] = c[:n]
    return out, lengths


def collate_ragged_rows(rows, spec, stats=None):
    """Collate row dicts/namedtuples into a padded batch.

    Fields named in ``spec.pads`` are padded per their :class:`PadSpec` (with
    an ``<name>_lengths`` int32 vector when ``emit_lengths``); every other
    field goes through the fixed :func:`~petastorm_tpu.jax.loader.collate_rows`
    path unchanged.

    :param stats: optional mutable dict accumulating ``real_tokens`` /
        ``padded_tokens`` across calls (the loader's padding-waste telemetry
        reads these; tokens are counted on ``spec.length_of`` only, so the
        waste fraction describes the model's sequence axis, not every
        padded field).
    """
    from petastorm_tpu.jax.loader import collate_rows

    if not rows:
        raise PetastormTpuError('Cannot collate an empty batch')
    rows = [r._asdict() if hasattr(r, '_asdict') else r for r in rows]
    batch = {}
    for name, pad in spec.pads.items():
        if name not in rows[0]:
            raise PetastormTpuError('CollateSpec pads unknown field {!r} (batch has {})'.format(
                name, sorted(rows[0])))
        padded, lengths = _pad_field([_cell(r, name) for r in rows], pad, name)
        batch[name] = padded
        if pad.emit_lengths:
            batch[name + '_lengths'] = lengths
        if stats is not None and name == spec.length_of:
            stats['real_tokens'] = stats.get('real_tokens', 0) + int(lengths.sum())
            stats['padded_tokens'] = (stats.get('padded_tokens', 0) +
                                      padded.shape[0] * padded.shape[1])
    fixed = [n for n in rows[0] if n not in spec.pads]
    if fixed:
        batch.update(collate_rows(rows, field_names=fixed))
    return batch


def padding_waste_fraction(stats):
    """``1 - real/padded`` over an accumulated stats dict (0.0 before any
    batch — the key-always-present diagnostics contract)."""
    padded = stats.get('padded_tokens', 0)
    if not padded:
        return 0.0
    return round(1.0 - stats.get('real_tokens', 0) / padded, 4)
