"""Fixed-size rebatching of columnar batches.

Parity+: the reference built a fixed-size Arrow-table rebatcher
(/root/reference/petastorm/pyarrow_helpers/batching_table_queue.py:20-79) but
never wired it into the Reader (no imports outside its tests — SURVEY.md §2.6).
Here the equivalent operates on column blocks (the container our workers
publish) and IS wired in: ``make_batch_reader(batch_size=N)`` and
``make_reader(output='columnar', batch_size=N)`` yield constant-shape batches,
which matters on TPU — XLA recompiles on every new batch shape, so
row-group-sized (variable) batches defeat compilation caching.

The block container itself lives in ``petastorm_tpu.columnar``
(:class:`BatchingColumnQueue`, re-exported here); this module owns the
results-queue reader that pumps the worker pool through it.
"""

from __future__ import annotations

from petastorm_tpu.columnar import BatchingColumnQueue  # noqa: F401  (re-export)


class RebatchingResultsQueueReader(object):
    """Consumer-side results reader emitting fixed-``batch_size`` namedtuples of
    column arrays. Wraps the worker pool's row-group-sized output through a
    :class:`BatchingColumnQueue`; the final short batch is emitted unless
    ``drop_last``."""

    def __init__(self, schema, batch_size, drop_last=False):
        from petastorm_tpu.workers.worker_base import EmptyResultError
        self._empty_result_error = EmptyResultError
        self._schema = schema
        self._queue = BatchingColumnQueue(batch_size)
        self._drop_last = drop_last
        self._exhausted = False
        self._open_seqs = set()  # items with rows still buffered in the queue
        self.delivered_callback = None

    @property
    def batched_output(self):
        return True

    def on_item_done(self, seq):
        """An item whose rows are still buffered is delivered only when they
        drain into a yielded batch; an item never seen (published no rows) is
        delivered now."""
        if seq not in self._open_seqs and self.delivered_callback is not None:
            self.delivered_callback(seq)

    def _mark_drained(self):
        for seq in self._queue.pop_drained_tags():
            self._open_seqs.discard(seq)
            if self.delivered_callback is not None:
                self.delivered_callback(seq)

    def read_next(self, pool):
        while self._queue.empty():
            if self._exhausted:
                # pool already signalled end-of-epoch: flush or finish
                remainder = self._queue.drain()
                if self._drop_last:
                    remainder = None  # discard, so reset() starts a clean pass
                    # dropped rows are NOT delivered: a checkpoint taken now
                    # re-reads their row groups on resume instead of losing them
                    for tag in self._queue.pop_drained_tags():
                        self._open_seqs.discard(tag)
                else:
                    self._mark_drained()
                self._exhausted = False  # re-arm for reset()/next epoch
                if remainder is None:
                    raise self._empty_result_error()
                return self._schema.make_namedtuple(**remainder)
            try:
                batch = pool.get_results()
                seq = getattr(pool, 'last_result_seq', None)
                if seq is not None:
                    self._open_seqs.add(seq)
                self._queue.put(batch, tag=seq)
            except self._empty_result_error:
                self._exhausted = True
        out = self._queue.get()
        self._mark_drained()
        return self._schema.make_namedtuple(**out)
