"""Fixed-size rebatching of columnar batches.

Parity+: the reference built a fixed-size Arrow-table rebatcher
(/root/reference/petastorm/pyarrow_helpers/batching_table_queue.py:20-79) but
never wired it into the Reader (no imports outside its tests — SURVEY.md §2.6).
Here the equivalent operates on dicts of numpy column arrays (the container our
batch workers publish) and IS wired in: ``make_batch_reader(batch_size=N)``
yields constant-shape batches, which matters on TPU — XLA recompiles on every
new batch shape, so row-group-sized (variable) batches defeat compilation
caching.

Rows are never copied at ``put`` time: input columns are buffered as views and
only concatenated when a batch boundary crosses a buffer segment.
"""

from __future__ import annotations

from collections import deque

import numpy as np


class BatchingColumnQueue(object):
    """FIFO queue of columnar batches re-chunked to a fixed row count.

    ``put`` accepts a dict of equal-length column arrays; ``get`` returns a dict
    with exactly ``batch_size`` rows, preserving input row order (reference
    batching_table_queue.py:20-79 semantics, columnar instead of Arrow tables).
    """

    def __init__(self, batch_size):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1, got {}'.format(batch_size))
        self._batch_size = batch_size
        self._segments = deque()  # (dict of column arrays, tag)
        self._head = 0  # rows of the head segment already consumed
        self._buffered = 0
        self._drained_tags = []  # tags of segments fully consumed by _take

    def __len__(self):
        return self._buffered

    def put(self, batch, tag=None):
        """``tag``: opaque id returned via :meth:`pop_drained_tags` once every
        row of this batch has left the queue (checkpoint bookkeeping)."""
        lengths = {len(v) for v in batch.values()}
        if len(lengths) != 1:
            raise ValueError('ragged batch: column lengths {}'.format(sorted(lengths)))
        n = lengths.pop()
        if n == 0:
            if tag is not None:
                self._drained_tags.append(tag)
            return
        self._segments.append((batch, tag))
        self._buffered += n

    def pop_drained_tags(self):
        """Tags of segments whose rows have all been taken since the last call."""
        tags, self._drained_tags = self._drained_tags, []
        return tags

    def empty(self):
        """True when a full ``batch_size`` batch cannot be produced yet."""
        return self._buffered < self._batch_size

    def get(self):
        assert not self.empty()
        return self._take(self._batch_size)

    def drain(self):
        """Return all remaining rows as one final (possibly short) batch, or
        None if nothing is buffered."""
        if self._buffered == 0:
            return None
        return self._take(self._buffered)

    def _take(self, count):
        parts = []  # list of dict-of-views
        taken = 0
        while taken < count:
            head, tag = self._segments[0]
            head_len = len(next(iter(head.values())))
            take = min(count - taken, head_len - self._head)
            parts.append({k: v[self._head:self._head + take] for k, v in head.items()})
            self._head += take
            taken += take
            if self._head == head_len:
                self._segments.popleft()
                self._head = 0
                if tag is not None:
                    self._drained_tags.append(tag)
        self._buffered -= count
        if len(parts) == 1:
            return parts[0]
        return {k: _concat_column([p[k] for p in parts]) for k in parts[0]}


def _concat_column(parts):
    """Concatenate per-segment column arrays. List-typed Parquet columns decode
    to a 2-D array when a row group's lists are uniform-length but a 1-D object
    array otherwise (batch_worker._column_to_numpy) — mixed segments of one
    logical column must degrade to object rows instead of crashing concat."""
    # same-rank, same-trailing-shape parts concatenate directly (including 1-D
    # object arrays of bytes/decimals/ragged rows); only genuinely mixed
    # layouts — 2-D uniform next to 1-D ragged, or differing widths — degrade
    uniform = (len({p.ndim for p in parts}) == 1 and
               len({p.shape[1:] for p in parts}) == 1)
    if uniform:
        return np.concatenate(parts)
    rows = []
    for p in parts:
        rows.extend(p[i] for i in range(len(p)))
    out = np.empty(len(rows), dtype=object)
    for i, r in enumerate(rows):
        out[i] = r
    return out


class RebatchingResultsQueueReader(object):
    """Consumer-side results reader emitting fixed-``batch_size`` namedtuples of
    column arrays. Wraps the worker pool's row-group-sized output through a
    :class:`BatchingColumnQueue`; the final short batch is emitted unless
    ``drop_last``."""

    def __init__(self, schema, batch_size, drop_last=False):
        from petastorm_tpu.workers.worker_base import EmptyResultError
        self._empty_result_error = EmptyResultError
        self._schema = schema
        self._queue = BatchingColumnQueue(batch_size)
        self._drop_last = drop_last
        self._exhausted = False
        self._open_seqs = set()  # items with rows still buffered in the queue
        self.delivered_callback = None

    @property
    def batched_output(self):
        return True

    def on_item_done(self, seq):
        """An item whose rows are still buffered is delivered only when they
        drain into a yielded batch; an item never seen (published no rows) is
        delivered now."""
        if seq not in self._open_seqs and self.delivered_callback is not None:
            self.delivered_callback(seq)

    def _mark_drained(self):
        for seq in self._queue.pop_drained_tags():
            self._open_seqs.discard(seq)
            if self.delivered_callback is not None:
                self.delivered_callback(seq)

    def read_next(self, pool):
        while self._queue.empty():
            if self._exhausted:
                # pool already signalled end-of-epoch: flush or finish
                remainder = self._queue.drain()
                if self._drop_last:
                    remainder = None  # discard, so reset() starts a clean pass
                    # dropped rows are NOT delivered: a checkpoint taken now
                    # re-reads their row groups on resume instead of losing them
                    for tag in self._queue.pop_drained_tags():
                        self._open_seqs.discard(tag)
                else:
                    self._mark_drained()
                self._exhausted = False  # re-arm for reset()/next epoch
                if remainder is None:
                    raise self._empty_result_error()
                return self._schema.make_namedtuple(**remainder)
            try:
                batch = pool.get_results()
                seq = getattr(pool, 'last_result_seq', None)
                if seq is not None:
                    self._open_seqs.add(seq)
                self._queue.put(batch, tag=seq)
            except self._empty_result_error:
                self._exhausted = True
        out = self._queue.get()
        self._mark_drained()
        return self._schema.make_namedtuple(**out)
