"""Fixed-size rebatching of columnar batches.

Parity+: the reference built a fixed-size Arrow-table rebatcher
(/root/reference/petastorm/pyarrow_helpers/batching_table_queue.py:20-79) but
never wired it into the Reader (no imports outside its tests — SURVEY.md §2.6).
Here the equivalent operates on dicts of numpy column arrays (the container our
batch workers publish) and IS wired in: ``make_batch_reader(batch_size=N)``
yields constant-shape batches, which matters on TPU — XLA recompiles on every
new batch shape, so row-group-sized (variable) batches defeat compilation
caching.

Rows are never copied at ``put`` time: input columns are buffered as views and
only concatenated when a batch boundary crosses a buffer segment.
"""

from __future__ import annotations

from collections import deque

import numpy as np


class BatchingColumnQueue(object):
    """FIFO queue of columnar batches re-chunked to a fixed row count.

    ``put`` accepts a dict of equal-length column arrays; ``get`` returns a dict
    with exactly ``batch_size`` rows, preserving input row order (reference
    batching_table_queue.py:20-79 semantics, columnar instead of Arrow tables).
    """

    def __init__(self, batch_size):
        if batch_size < 1:
            raise ValueError('batch_size must be >= 1, got {}'.format(batch_size))
        self._batch_size = batch_size
        self._segments = deque()  # dicts of column arrays
        self._head = 0  # rows of the head segment already consumed
        self._buffered = 0

    def __len__(self):
        return self._buffered

    def put(self, batch):
        lengths = {len(v) for v in batch.values()}
        if len(lengths) != 1:
            raise ValueError('ragged batch: column lengths {}'.format(sorted(lengths)))
        n = lengths.pop()
        if n == 0:
            return
        self._segments.append(batch)
        self._buffered += n

    def empty(self):
        """True when a full ``batch_size`` batch cannot be produced yet."""
        return self._buffered < self._batch_size

    def get(self):
        assert not self.empty()
        return self._take(self._batch_size)

    def drain(self):
        """Return all remaining rows as one final (possibly short) batch, or
        None if nothing is buffered."""
        if self._buffered == 0:
            return None
        return self._take(self._buffered)

    def _take(self, count):
        parts = []  # list of dict-of-views
        taken = 0
        while taken < count:
            head = self._segments[0]
            head_len = len(next(iter(head.values())))
            take = min(count - taken, head_len - self._head)
            parts.append({k: v[self._head:self._head + take] for k, v in head.items()})
            self._head += take
            taken += take
            if self._head == head_len:
                self._segments.popleft()
                self._head = 0
        self._buffered -= count
        if len(parts) == 1:
            return parts[0]
        return {k: _concat_column([p[k] for p in parts]) for k in parts[0]}


def _concat_column(parts):
    """Concatenate per-segment column arrays. List-typed Parquet columns decode
    to a 2-D array when a row group's lists are uniform-length but a 1-D object
    array otherwise (batch_worker._column_to_numpy) — mixed segments of one
    logical column must degrade to object rows instead of crashing concat."""
    # same-rank, same-trailing-shape parts concatenate directly (including 1-D
    # object arrays of bytes/decimals/ragged rows); only genuinely mixed
    # layouts — 2-D uniform next to 1-D ragged, or differing widths — degrade
    uniform = (len({p.ndim for p in parts}) == 1 and
               len({p.shape[1:] for p in parts}) == 1)
    if uniform:
        return np.concatenate(parts)
    rows = []
    for p in parts:
        rows.extend(p[i] for i in range(len(p)))
    out = np.empty(len(rows), dtype=object)
    for i, r in enumerate(rows):
        out[i] = r
    return out


class RebatchingResultsQueueReader(object):
    """Consumer-side results reader emitting fixed-``batch_size`` namedtuples of
    column arrays. Wraps the worker pool's row-group-sized output through a
    :class:`BatchingColumnQueue`; the final short batch is emitted unless
    ``drop_last``."""

    def __init__(self, schema, batch_size, drop_last=False):
        from petastorm_tpu.workers.worker_base import EmptyResultError
        self._empty_result_error = EmptyResultError
        self._schema = schema
        self._queue = BatchingColumnQueue(batch_size)
        self._drop_last = drop_last
        self._exhausted = False

    @property
    def batched_output(self):
        return True

    def read_next(self, pool):
        while self._queue.empty():
            if self._exhausted:
                # pool already signalled end-of-epoch: flush or finish
                remainder = self._queue.drain()
                if self._drop_last:
                    remainder = None  # discard, so reset() starts a clean pass
                self._exhausted = False  # re-arm for reset()/next epoch
                if remainder is None:
                    raise self._empty_result_error()
                return self._schema.make_namedtuple(**remainder)
            try:
                self._queue.put(pool.get_results())
            except self._empty_result_error:
                self._exhausted = True
        return self._schema.make_namedtuple(**self._queue.get())
