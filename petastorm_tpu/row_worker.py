"""Row-oriented decode worker: loads ONE row group per task, decodes per-row.

Parity: /root/reference/petastorm/py_dict_reader_worker.py — in-worker predicate
pushdown (read+decode predicate columns first, early-exit empty masks, then read
the rest, :188-252), read-through cache keyed on dataset/piece (:160-163), NGram
assembly (:165-166), shuffle_row_drop_partition row subsetting (:254-274, with
NGram-aware spillover :266-271), and a consumer-side results-queue reader that
converts row dicts to schema namedtuples (:64-97).

TPU-first: decode happens here on the CPU host, overlapped with device compute;
rows are selected BEFORE decode so predicates/row-drop never pay image-decode
cost for dropped rows.
"""

from __future__ import annotations

import hashlib
from collections import deque

import numpy as np
import pyarrow as pa

from petastorm_tpu.native import open_parquet
from petastorm_tpu.workers.worker_base import EmptyResultError, WorkerBase


def _column_values(column):
    """ChunkedArray -> list of python values. Binary columns skip ``to_pylist``
    (which copies every cell into a bytes object) and hand out zero-copy
    memoryview slices of the Arrow data buffer instead — the codecs
    (np.frombuffer, cv2.imdecode) consume memoryviews directly, so the only
    copy left in the decode path is the decode itself."""
    t = column.type
    if pa.types.is_binary(t) or pa.types.is_large_binary(t):
        out = []
        for chunk in column.chunks:
            n = len(chunk)
            if n == 0:
                continue
            if chunk.null_count:
                out.extend(chunk.to_pylist())
                continue
            off_dtype = np.int64 if pa.types.is_large_binary(t) else np.int32
            _, offsets_buf, data_buf = chunk.buffers()
            offs = np.frombuffer(offsets_buf, dtype=off_dtype, count=n + 1,
                                 offset=chunk.offset * np.dtype(off_dtype).itemsize).tolist()
            mv = memoryview(data_buf)
            out.extend(mv[offs[i]:offs[i + 1]] for i in range(n))
        return out
    return column.to_pylist()


def _cache_key(dataset_path, piece, column_names):
    cols = hashlib.md5(','.join(sorted(column_names)).encode()).hexdigest()[:8]
    return '{}:{}:rg{}:{}'.format(
        hashlib.md5(dataset_path.encode()).hexdigest(), piece.path, piece.row_group, cols)


def select_row_drop_indices(num_rows, partition_spec, ngram=None):
    """Row indices kept for one shuffle-row-drop partition.

    ``partition_spec`` is ``(partition_index, num_partitions)``. With an NGram,
    each partition spills over by ``length - 1`` rows so windows spanning the
    partition boundary are not lost (reference py_dict_reader_worker.py:266-271).
    """
    if partition_spec is None:
        return np.arange(num_rows)
    part, n_parts = partition_spec
    chunks = np.array_split(np.arange(num_rows), n_parts)
    chunk = chunks[part]
    if ngram is not None and len(chunk) and chunk[-1] < num_rows - 1:
        spill = np.arange(chunk[-1] + 1, min(chunk[-1] + ngram.length, num_rows))
        chunk = np.concatenate([chunk, spill])
    return chunk


class RowGroupDecoderWorker(WorkerBase):
    """``args`` (picklable, shared by all workers):
      dataset_path, filesystem_factory, pieces, schema (full stored schema),
      output_schema (post column-selection, pre-transform), transform_spec,
      transformed_schema, ngram, cache
    """

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._fs = None
        self._open_files = {}

    def _parquet_file(self, path):
        if self._fs is None:
            self._fs = self.args['filesystem_factory']()
        if path not in self._open_files:
            if len(self._open_files) > 8:  # bound per-worker open handles
                _, old = self._open_files.popitem()
                old.close()
            self._open_files[path] = open_parquet(path, self._fs)
        return self._open_files[path]

    def shutdown(self):
        for f in self._open_files.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
        self._open_files = {}

    # -- main task ----------------------------------------------------------

    def process(self, piece_index, worker_predicate=None, shuffle_row_drop_partition=None):
        args = self.args
        piece = args['pieces'][piece_index]
        out_schema = args['output_schema']
        ngram = args['ngram']

        if ngram is not None:
            needed = [n for n in ngram.get_field_names_at_all_timesteps() if n in out_schema.fields]
        else:
            needed = list(out_schema.fields)

        cache = args['cache']
        if worker_predicate is None and shuffle_row_drop_partition is None:
            key = _cache_key(args['dataset_path'], piece, needed)
            rows = cache.get(key, lambda: self._load_rows(piece, needed))
        elif worker_predicate is not None:
            rows = self._load_rows_with_predicate(piece, needed, worker_predicate,
                                                  shuffle_row_drop_partition)
        else:
            rows = self._load_rows(piece, needed, shuffle_row_drop_partition)

        transform = args['transform_spec']
        if transform is not None and transform.func is not None:
            rows = [transform.func(r) for r in rows]
        if transform is not None:
            final_fields = set(args['transformed_schema'].fields)
            rows = [{k: v for k, v in r.items() if k in final_fields} for r in rows]

        if ngram is not None:
            rows = ngram.form_ngram(rows, args['transformed_schema'] or out_schema)

        if rows:
            self.publish(rows)

    # -- loading ------------------------------------------------------------

    def _read_columns(self, piece, column_names, row_indices=None):
        """Read the named logical columns of the piece; returns (dict of
        per-column python value lists, num_rows). Partition-key columns are
        materialized from the piece's path."""
        schema = self.args['schema']
        physical = [c for c in column_names if c not in piece.partition_keys
                    and c in schema.fields]
        pf = self._parquet_file(piece.path)
        table = pf.read_row_group(piece.row_group, columns=physical)
        num_rows = table.num_rows
        if row_indices is not None:
            table = table.take(row_indices)
        columns = {name: _column_values(table.column(name)) for name in physical}
        n = table.num_rows
        for key, value in piece.partition_keys.items():
            if key in column_names:
                columns[key] = [value] * n
        return columns, num_rows

    def _decode_rows(self, columns, column_names, n):
        schema = self.args['schema']
        decoded_cols = {}
        for name in column_names:
            field = schema.fields[name]
            col = columns[name]
            codec = field.codec
            if hasattr(codec, 'decode_batch'):
                # whole-column native decode (one GIL-released call per column)
                decoded_cols[name] = codec.decode_batch(field, col)
            else:
                decoded_cols[name] = [None if v is None else codec.decode(field, v) for v in col]
        return [{name: decoded_cols[name][i] for name in column_names} for i in range(n)]

    def _load_rows(self, piece, column_names, shuffle_row_drop_partition=None):
        indices = None
        if shuffle_row_drop_partition is not None:
            pf = self._parquet_file(piece.path)
            num_rows = piece.num_rows or pf.metadata.row_group(piece.row_group).num_rows
            indices = select_row_drop_indices(num_rows, shuffle_row_drop_partition,
                                              self.args['ngram'])
        columns, _ = self._read_columns(piece, column_names, indices)
        n = len(next(iter(columns.values()))) if columns else 0
        return self._decode_rows(columns, column_names, n)

    def _load_rows_with_predicate(self, piece, column_names, predicate,
                                  shuffle_row_drop_partition):
        """Predicate pushdown: decode predicate columns first, mask, early-exit,
        then read+decode remaining columns only for surviving rows."""
        predicate_fields = sorted(predicate.get_fields())
        schema = self.args['schema']
        unknown = [f for f in predicate_fields
                   if f not in schema.fields and f not in piece.partition_keys]
        if unknown:
            raise ValueError('Predicate fields {} are not in the dataset schema'.format(unknown))

        pf = self._parquet_file(piece.path)
        num_rows = pf.metadata.row_group(piece.row_group).num_rows
        drop_indices = select_row_drop_indices(num_rows, shuffle_row_drop_partition,
                                               self.args['ngram'])
        pred_columns, _ = self._read_columns(piece, predicate_fields, drop_indices
                                             if shuffle_row_drop_partition else None)
        n = len(next(iter(pred_columns.values()))) if pred_columns else 0
        pred_rows = self._decode_rows(pred_columns, predicate_fields, n)
        mask = [predicate.do_include(r) for r in pred_rows]
        if not any(mask):
            return []
        kept_local = np.flatnonzero(mask)
        base = drop_indices if shuffle_row_drop_partition else np.arange(num_rows)
        kept_global = base[kept_local]

        remaining = [c for c in column_names if c not in predicate_fields]
        rem_columns, _ = self._read_columns(piece, remaining, kept_global)
        rem_rows = self._decode_rows(rem_columns, remaining, len(kept_global))
        result = []
        for i, local_idx in enumerate(kept_local):
            row = dict(pred_rows[local_idx])
            row.update(rem_rows[i])
            result.append({k: row[k] for k in column_names if k in row})
        return result


class RowResultsQueueReader(object):
    """Consumer-side: converts published row-dict chunks into schema namedtuples,
    one row per ``read_next`` call (reference py_dict_reader_worker.py:64-97).

    Checkpoint support: each buffered chunk remembers the seq of the item it
    came from; when the chunk's last row is yielded, ``delivered_callback(seq)``
    fires (→ ``ventilator.mark_delivered``), so a :meth:`Reader.state_dict`
    snapshot never counts partially-yielded row groups as consumed."""

    def __init__(self, schema, ngram=None):
        self._schema = schema
        self._ngram = ngram
        self._buffer = deque()
        self._spans = deque()  # [seq, rows_remaining] per buffered chunk
        self.delivered_callback = None

    @property
    def batched_output(self):
        return False

    def on_item_done(self, seq):
        """Pool completion sentinel consumed for ``seq``. Sentinels are only
        consumed when the buffer is empty (all prior rows yielded), so this can
        only fire for items already drained — or items that produced no rows —
        and marking delivered is safe in both cases."""
        if self.delivered_callback is not None:
            self.delivered_callback(seq)

    def read_next(self, pool):
        while not self._buffer:
            rows = pool.get_results()  # raises EmptyResultError at end of epoch
            self._buffer.extend(rows)
            self._spans.append([getattr(pool, 'last_result_seq', None), len(rows)])
        row = self._buffer.popleft()
        span = self._spans[0]
        span[1] -= 1
        if span[1] == 0:
            self._spans.popleft()
            if span[0] is not None and self.delivered_callback is not None:
                self.delivered_callback(span[0])
        if self._ngram is not None:
            return self._ngram.make_namedtuple(self._schema, row)
        return self._schema.make_namedtuple_from_dict(row)
