"""Row-group decode worker: loads ONE row group per task, decodes by column.

Parity: /root/reference/petastorm/py_dict_reader_worker.py — in-worker predicate
pushdown (read+decode predicate columns first, early-exit empty masks, then read
the rest, :188-252), read-through cache keyed on dataset/piece (:160-163), NGram
assembly (:165-166), shuffle_row_drop_partition row subsetting (:254-274, with
NGram-aware spillover :266-271), and a consumer-side results-queue reader that
yields one schema namedtuple per ``read_next`` (:64-97).

TPU-first departure from the reference: the worker's output is a *column block*
(dict of ``field -> [N, ...]`` numpy array / object column — see
``petastorm_tpu.columnar``), not a list of per-row Python dicts. Decode runs
column-at-a-time (``codec.decode_column`` / ``decode_batch``), so the per-row
Python work the reference pays (dict per row, namedtuple per row, per-cell
decode call) disappears; consumers slice rows or batches out of blocks with
numpy. Per-row dicts are materialized only where the API demands them: user
row transforms and NGram window assembly.
"""

from __future__ import annotations

import hashlib
import logging
from collections import deque

import numpy as np

from petastorm_tpu import observability as obs
from petastorm_tpu.cache import NullCache
from petastorm_tpu.columnar import (BlockResultsReaderBase, block_num_rows, block_to_rows,
                                    column_cells, rows_to_block, stack_cells, take_block)
from petastorm_tpu.native import open_parquet
from petastorm_tpu.predicates import evaluate_predicate_mask
from petastorm_tpu.workers.worker_base import WorkerBase

logger = logging.getLogger(__name__)


def _cache_key(dataset_path, piece, column_names, decode_hints=None, resize_hints=None):
    cols = ','.join(sorted(column_names))
    if decode_hints:
        # scaled-decode output differs per hint: readers with different hints
        # must not share cached decoded blocks
        cols += '|' + repr(sorted(decode_hints.items()))
    if resize_hints:
        # decode-time resize bakes the target size into the cached block —
        # a reader with a different (or no) resize must not read it back
        cols += '|rsz' + repr(sorted(resize_hints.items()))
    cols = hashlib.md5(cols.encode()).hexdigest()[:8]
    # 'b1': cache payloads are column blocks (round 3) — never mix with the
    # row-list payloads an older on-disk cache may hold
    return '{}:{}:rg{}:b1:{}'.format(
        hashlib.md5(dataset_path.encode()).hexdigest(), piece.path, piece.row_group, cols)


def select_row_drop_indices(num_rows, partition_spec, ngram=None):
    """Row indices kept for one shuffle-row-drop partition.

    ``partition_spec`` is ``(partition_index, num_partitions)``. With an NGram,
    each partition spills over by ``length - 1`` rows so windows spanning the
    partition boundary are not lost (reference py_dict_reader_worker.py:266-271).
    """
    if partition_spec is None:
        return np.arange(num_rows)
    part, n_parts = partition_spec
    chunks = np.array_split(np.arange(num_rows), n_parts)
    chunk = chunks[part]
    if ngram is not None and len(chunk) and chunk[-1] < num_rows - 1:
        spill = np.arange(chunk[-1] + 1, min(chunk[-1] + ngram.length, num_rows))
        chunk = np.concatenate([chunk, spill])
    return chunk


class RowGroupDecoderWorker(WorkerBase):
    """``args`` (picklable, shared by all workers):
      dataset_path, filesystem_factory, pieces, schema (full stored schema),
      output_schema (post column-selection, pre-transform), transform_spec,
      transformed_schema, ngram, cache
    """

    def __init__(self, worker_id, publish_func, args):
        super().__init__(worker_id, publish_func, args)
        self._fs = None
        self._open_files = {}

    def _parquet_file(self, path):
        if self._fs is None:
            self._fs = self.args['filesystem_factory']()
        if path not in self._open_files:
            if len(self._open_files) > 8:  # bound per-worker open handles
                _, old = self._open_files.popitem()
                old.close()
            self._open_files[path] = open_parquet(
                path, self._fs, chunk_cache=self.args.get('chunk_cache'))
        return self._open_files[path]

    def shutdown(self):
        for f in self._open_files.values():
            try:
                f.close()
            except Exception:  # noqa: BLE001
                pass
        self._open_files = {}

    # -- main task ----------------------------------------------------------

    def process(self, piece_index, worker_predicate=None, shuffle_row_drop_partition=None):
        args = self.args
        piece = args['pieces'][piece_index]
        out_schema = args['output_schema']
        ngram = args['ngram']

        if ngram is not None:
            needed = [n for n in ngram.get_field_names_at_all_timesteps() if n in out_schema.fields]
        else:
            needed = list(out_schema.fields)

        cache = args['cache']
        if worker_predicate is None and shuffle_row_drop_partition is None:
            if (args['transform_spec'] is None and ngram is None
                    and isinstance(cache, NullCache)
                    and (self._publish_fused_blob(piece, needed)
                         or self._publish_fused_inplace(piece, needed))):
                # the whole batch was decoded straight into shared memory
                # (serve fan-out blob, or the shm-ring slot the consumer
                # maps); the publish was a layout descriptor / header write
                return
            key = _cache_key(args['dataset_path'], piece, needed,
                             getattr(args['transform_spec'], 'image_decode_hints', None),
                             getattr(args['transform_spec'], 'image_resize', None))
            block = cache.get(key, lambda: self._load_block(piece, needed))
        elif worker_predicate is not None:
            block = self._load_block_with_predicate(piece, needed, worker_predicate,
                                                    shuffle_row_drop_partition)
        else:
            block = self._load_block(piece, needed, shuffle_row_drop_partition)

        if block is None or block_num_rows(block) == 0:
            return

        transform = args['transform_spec']
        if transform is not None:
            block = self._apply_transform(block, transform)
            if block is None or block_num_rows(block) == 0:
                return

        if ngram is not None:
            if args.get('columnar_ngram'):
                windows = ngram.form_ngram_columnar(block)
                if windows is not None:
                    self.publish(windows)
                return
            rows = block_to_rows(block)
            windows = ngram.form_ngram(rows, args['transformed_schema'] or out_schema)
            if windows:
                self.publish(windows)
            return

        obs.count('worker_rows_decoded_total', block_num_rows(block))
        self.publish(block)

    def _apply_transform(self, block, transform):
        """Row transforms get per-row dicts (reference parity,
        py_dict_reader_worker.py:38-52); ``TransformSpec(batched=True)`` funcs
        get the column block itself — zero row materialization."""
        final_fields = set(self.args['transformed_schema'].fields)
        if transform.func is None:
            return {k: v for k, v in block.items() if k in final_fields}
        with obs.stage('transform', cat='worker'):
            if getattr(transform, 'batched', False):
                out = transform.func(dict(block))
                return {k: v for k, v in out.items() if k in final_fields}
            rows = block_to_rows(block)
            rows = [transform.func(r) for r in rows]
            rows = [{k: v for k, v in r.items() if k in final_fields} for r in rows]
        if not rows:
            return None
        return rows_to_block(rows)

    # -- loading ------------------------------------------------------------

    def _read_table(self, piece, column_names, row_indices=None):
        """Read the named physical columns of the piece; returns
        ``(arrow table, total rows in the row group)``."""
        schema = self.args['schema']
        physical = [c for c in column_names if c not in piece.partition_keys
                    and c in schema.fields]
        pf = self._parquet_file(piece.path)
        with obs.stage('read', cat='worker', piece=piece.path,
                       row_group=piece.row_group):
            table = pf.read_row_group(piece.row_group, columns=physical)
            num_rows = table.num_rows
            if row_indices is not None:
                table = table.take(row_indices)
        return table, num_rows

    def _decode_table(self, table, column_names, piece, pre=None):
        """Arrow table -> column block. Columns already decoded by the fused
        native pass (``pre``) are adopted as-is; the rest go through the
        codec's whole-column fast path when it has one, else per-cell decode +
        stack. Partition-key columns are materialized from the piece's path.
        ``table`` may be None when ``pre`` covers every physical column."""
        schema = self.args['schema']
        transform = self.args.get('transform_spec')
        decode_hints = getattr(transform, 'image_decode_hints', None) or {}
        resize_hints = getattr(transform, 'image_resize', None) or {}
        pre = pre or {}
        n = table.num_rows if table is not None else block_num_rows(pre)
        block = {}
        with obs.stage('decode', cat='worker', rows=n):
            self._decode_columns(table, column_names, piece, block,
                                 schema, decode_hints, resize_hints, transform, n,
                                 pre)
        return block

    def _partition_column(self, field, value, n):
        """One partition-key column materialized from the piece's path value.
        np.full types the column from the decoded scalar (int64/str/bool...)
        so partition labels stage to device like any other column
        (batch_worker.py does the same for plain stores)."""
        if field is not None and field.codec is not None:
            value = field.codec.decode(field, value)
        try:
            return np.full(n, value)
        except (ValueError, TypeError):
            col = np.empty(n, dtype=object)
            col[:] = value
            return col

    def _decode_columns(self, table, column_names, piece, block, schema,
                        decode_hints, resize_hints, transform, n, pre=None):
        pre = pre or {}
        for name in column_names:
            if name in pre:
                # fused-decoded columns are fresh writable batch-buffer views:
                # the decode()'s writable-array contract holds with no copy
                block[name] = pre[name]
                continue
            if name in piece.partition_keys:
                block[name] = self._partition_column(
                    schema.fields.get(name), piece.partition_keys[name], n)
                continue
            field = schema.fields[name]
            codec = field.codec
            column = table.column(name)
            decoded = None
            if hasattr(codec, 'decode_column'):
                if getattr(codec, 'decode_column_accepts_hints', False):
                    decoded = codec.decode_column(field, column,
                                                  min_size=decode_hints.get(name),
                                                  resize=resize_hints.get(name))
                else:
                    decoded = codec.decode_column(field, column)
            if decoded is None:
                cells = column_cells(column)
                if hasattr(codec, 'decode_batch'):
                    hint = decode_hints.get(name)
                    resize = resize_hints.get(name)
                    values = (codec.decode_batch(field, cells, min_size=hint, resize=resize)
                              if (hint or resize) else codec.decode_batch(field, cells))
                else:
                    values = [None if v is None else codec.decode(field, v) for v in cells]
                decoded = stack_cells(values)
            elif (transform is not None and transform.func is not None
                  and isinstance(decoded, np.ndarray) and not decoded.flags.writeable):
                # zero-copy columnar decodes (RawTensorCodec) may be read-only
                # views of the Arrow buffer; user transform funcs are entitled
                # to mutate in place (decode()'s writable-array contract), so
                # give them their own copy
                decoded = decoded.copy()
            block[name] = decoded
        return block

    def _fused_columns(self, piece, column_names):
        """``{name: decoded numpy column}`` for the subset served by the fused
        native read→decode→collate pass (one GIL-released call for the whole
        subset — ``docs/native.md``); ``{}`` when nothing qualifies. Columns
        the zero-copy view path already serves stay with it."""
        pf = self._parquet_file(piece.path)
        if not hasattr(pf, 'read_fused'):
            return {}
        schema = self.args['schema']
        transform = self.args.get('transform_spec')
        physical = [c for c in column_names if c not in piece.partition_keys
                    and c in schema.fields]
        if not physical:
            return {}
        try:
            block, _rest = pf.read_fused(
                piece.row_group, physical, schema.fields,
                getattr(transform, 'image_decode_hints', None),
                getattr(transform, 'image_resize', None))
        except Exception as e:  # noqa: BLE001 - any surprise: Arrow path serves it all
            logger.debug('fused read of %s rg=%s failed (%s); Arrow path',
                         piece.path, piece.row_group, e)
            return {}
        return block

    def _publish_fused_blob(self, piece, column_names):
        """Serve fan-out zero-copy mode (docs/serve.md): when the publish
        channel offers ``reserve_fused`` (the daemon's blob plane), run the
        fused decode WRITING DIRECTLY INTO a shared blob mapping and publish
        only the column-layout descriptor — consumers view the mapping in
        place, so the batch is written once (by the decode itself) and never
        copied again, no matter how many consumers attach. Unlike the ring
        in-place mode this does not need sizes known ahead (a blob is random
        access), so np.save raggedless cells (NdarrayCodec) qualify too.
        Returns False (no observable effect) when any precondition fails."""
        reserve = getattr(self.publish_func, 'reserve_fused', None)
        pf = self._parquet_file(piece.path) if reserve is not None else None
        if pf is None or not hasattr(pf, 'fused_plan'):
            return False
        schema = self.args['schema']
        transform = self.args.get('transform_spec')
        if any(c in piece.partition_keys for c in column_names):
            return False  # partition columns would need a post-decode append
        physical = [c for c in column_names if c in schema.fields]
        if not physical or len(physical) != len(column_names):
            return False
        plan = pf.fused_plan(piece.row_group, physical, schema.fields,
                             getattr(transform, 'image_decode_hints', None),
                             getattr(transform, 'image_resize', None),
                             include_pagescan=True)
        if plan is None or plan.rest or not plan.columns:
            return False
        n = plan.expected_rows
        if n <= 0:
            return False
        offsets, total = [], 0
        for p in plan.columns:
            offsets.append(total)
            total += p.out_bound
        reserved = reserve(total, n)
        if reserved is None:
            return False
        view, finish, abort = reserved
        try:
            results = pf.fused_read_into(plan, view, offsets)
        except Exception as e:  # noqa: BLE001 - kernel refusal: copy path serves it
            logger.debug('fused blob read failed (%s); copy path', e)
            abort()
            return False
        from petastorm_tpu.native import fused
        cols = []
        for p, res, off in zip(plan.columns, results, offsets):
            region = fused.column_region(p, res, n)
            if region is None:
                abort()
                fused.count_fallbacks(
                    {p.name: fused.REASON_BY_STATUS.get(res[0], 'post-validate')})
                return False
            dtype_str, shape, nbytes = region
            cols.append((p.name, dtype_str, shape, off, nbytes))
        finish(cols)
        obs.count('fused_columns_total', len(plan.columns))
        obs.count('fused_batches_total')
        obs.count('serve_fused_blob_batches_total')
        obs.count('worker_rows_decoded_total', n)
        fused.count_fallbacks(plan.reasons)
        return True

    def _publish_fused_inplace(self, piece, column_names):
        """shm-ring in-place mode: reserve the ring slot the consumer will
        map, frame the serializer header first (every fused column's size is
        known ahead), run the fused decode WRITING DIRECTLY INTO THE SLOT,
        and publish with a header write — no batch copy anywhere between the
        Parquet pages and the consumer's numpy views. Returns False (leaving
        no observable effect) whenever any precondition fails; the caller
        then takes the ordinary load-and-publish path."""
        reserve = getattr(self.publish_func, 'reserve_block', None)
        pf = self._parquet_file(piece.path) if reserve is not None else None
        if pf is None or not hasattr(pf, 'fused_plan'):
            return False
        schema = self.args['schema']
        transform = self.args.get('transform_spec')
        physical = [c for c in column_names if c not in piece.partition_keys
                    and c in schema.fields]
        if not physical:
            return False
        plan = pf.fused_plan(piece.row_group, physical, schema.fields,
                             getattr(transform, 'image_decode_hints', None),
                             getattr(transform, 'image_resize', None),
                             include_pagescan=True)
        if plan is None or plan.rest or not plan.columns or not plan.inplace_ok:
            return False
        if any(p.field_dtype is not None and p.field_dtype != p.out_dtype
               for p in plan.columns):
            return False  # a post-decode astype would need a second buffer
        n = plan.expected_rows
        if n <= 0:
            return False
        part_cols = []
        for name in column_names:
            if name not in piece.partition_keys:
                continue
            col = self._partition_column(schema.fields.get(name),
                                         piece.partition_keys[name], n)
            if col.dtype == object or col.dtype.hasobject:
                return False  # object columns cannot frame as raw buffers
            part_cols.append((name, np.ascontiguousarray(col)))
        meta, offsets, total = [], [], 0
        for p in plan.columns:
            meta.append((p.name, p.out_dtype.str, p.out_shape, None))
            offsets.append(total)
            total += p.out_bound
        for name, col in part_cols:
            meta.append((name, col.dtype.str, col.shape, None))
        payload = total + sum(col.nbytes for _, col in part_cols)
        reserved = reserve(meta, payload)
        if reserved is None:
            return False
        view, commit, abort = reserved
        try:
            results = pf.fused_read_into(plan, view, offsets)
        except Exception as e:  # noqa: BLE001 - kernel refusal: copy path serves it
            logger.debug('in-place fused read failed (%s); copy path', e)
            abort()
            return False
        from petastorm_tpu.native import fused
        failed = {plan.columns[i].name: fused.REASON_BY_STATUS.get(r[0], 'internal')
                  for i, r in enumerate(results)
                  if r[0] != 0 or r[1] != plan.columns[i].out_bound}
        if failed:
            abort()
            fused.count_fallbacks(failed)
            return False
        out = np.frombuffer(view, dtype=np.uint8)  # noqa: PT500 - writable ring slot owned by this reservation
        off = total
        for _name, col in part_cols:
            out[off:off + col.nbytes] = np.frombuffer(
                col.tobytes() if col.dtype.kind in 'Mm' else memoryview(col).cast('B'),
                dtype=np.uint8)
            off += col.nbytes
        commit(payload)
        obs.count('fused_columns_total', len(plan.columns))
        obs.count('fused_batches_total')
        obs.count('fused_inplace_batches_total')
        obs.count('worker_rows_decoded_total', n)
        fused.count_fallbacks(plan.reasons)
        return True

    def _load_block(self, piece, column_names, shuffle_row_drop_partition=None):
        indices = None
        if shuffle_row_drop_partition is not None:
            pf = self._parquet_file(piece.path)
            num_rows = piece.num_rows or pf.metadata.row_group(piece.row_group).num_rows
            indices = select_row_drop_indices(num_rows, shuffle_row_drop_partition,
                                              self.args['ngram'])
        # row subsets (shuffle-row-drop) need Arrow's take; the full-group read
        # serves fused columns first and Arrow only for the remainder
        pre = self._fused_columns(piece, column_names) if indices is None else {}
        rest = [c for c in column_names if c not in pre]
        schema = self.args['schema']
        if pre and not any(c not in piece.partition_keys and c in schema.fields
                           for c in rest):
            table = None  # every physical column came out of the fused pass
        else:
            table, _ = self._read_table(piece, rest, indices)
        return self._decode_table(table, column_names, piece, pre=pre)

    def _fused_predicate_block(self, pf, piece, column_names, predicate_fields,
                               predicate, drop_indices):
        """Native predicate pushdown (docs/native.md): clause evaluation,
        min/max page-stat skipping, row selection and the decode of ONLY the
        surviving rows all run inside one GIL-released fused call; Arrow is
        consulted just for the columns the kernel cannot serve (their rows
        filtered with the same selection). Returns the decoded block (possibly
        zero rows), or None when the predicate shape / columns are not
        natively evaluable — the caller then runs the Python pushdown path."""
        if not hasattr(pf, 'read_fused_predicate'):
            return None
        clauses = getattr(predicate, 'native_clauses', lambda: None)()
        if clauses is None:
            return None
        schema = self.args['schema']
        if any(f in piece.partition_keys or f not in schema.fields
               for f in predicate_fields):
            return None  # partition-key predicates: piece-level path decides
        transform = self.args.get('transform_spec')
        physical = [c for c in column_names if c not in piece.partition_keys
                    and c in schema.fields]
        if not physical:
            return None
        try:
            res = pf.read_fused_predicate(
                piece.row_group, physical, predicate_fields, clauses,
                schema.fields,
                getattr(transform, 'image_decode_hints', None),
                getattr(transform, 'image_resize', None))
        except Exception as e:  # noqa: BLE001 - any surprise: Python pushdown serves it
            logger.debug('fused predicate read of %s rg=%s failed (%s); Python path',
                         piece.path, piece.row_group, e)
            return None
        if res is None:
            return None
        block, _rest, sel_mask, n_selected, _pages_skipped = res
        kept_global = np.flatnonzero(sel_mask)
        if drop_indices is not None:
            # the kernel selected over the FULL row group; narrow both the
            # fused block and the surviving-row indices to this partition
            keep = np.isin(kept_global, drop_indices)
            block = take_block(block, np.flatnonzero(keep))
            kept_global = kept_global[keep]
        if not len(kept_global):
            return {}
        remaining = [c for c in column_names if c not in block]
        rem_block = {}
        if remaining:
            if any(c not in piece.partition_keys and c in schema.fields
                   for c in remaining):
                rem_table, _ = self._read_table(piece, remaining, kept_global)
                rem_block = self._decode_table(rem_table, remaining, piece)
            else:
                rem_block = self._decode_columns(None, remaining, piece, {},
                                                 schema, {}, {}, transform,
                                                 len(kept_global))
        return {name: (block[name] if name in block else rem_block[name])
                for name in column_names if name in block or name in rem_block}

    def _load_block_with_predicate(self, piece, column_names, predicate,
                                   shuffle_row_drop_partition):
        """Predicate pushdown: decode predicate columns first, mask, early-exit,
        then read+decode remaining columns only for surviving rows."""
        predicate_fields = sorted(predicate.get_fields())
        schema = self.args['schema']
        unknown = [f for f in predicate_fields
                   if f not in schema.fields and f not in piece.partition_keys]
        if unknown:
            raise ValueError('Predicate fields {} are not in the dataset schema'.format(unknown))

        pf = self._parquet_file(piece.path)
        num_rows = pf.metadata.row_group(piece.row_group).num_rows
        drop_indices = select_row_drop_indices(num_rows, shuffle_row_drop_partition,
                                               self.args['ngram'])
        fast = self._fused_predicate_block(
            pf, piece, column_names, predicate_fields, predicate,
            drop_indices if shuffle_row_drop_partition else None)
        if fast is not None:
            return fast or None
        pred_table, _ = self._read_table(piece, predicate_fields, drop_indices
                                         if shuffle_row_drop_partition else None)
        pred_block = self._decode_table(pred_table, predicate_fields, piece)
        mask = evaluate_predicate_mask(predicate, dict(pred_block),
                                       block_num_rows(pred_block))
        if mask is None:  # vectorized path declined: per-row semantics
            pred_rows = block_to_rows(pred_block, predicate_fields)
            mask = [predicate.do_include(r) for r in pred_rows]
        if not np.any(mask):
            return None
        kept_local = np.flatnonzero(mask)
        base = drop_indices if shuffle_row_drop_partition else np.arange(num_rows)
        kept_global = base[kept_local]

        remaining = [c for c in column_names if c not in predicate_fields]
        rem_table, _ = self._read_table(piece, remaining, kept_global)
        rem_block = self._decode_table(rem_table, remaining, piece)
        kept_pred = take_block(pred_block, kept_local)
        return {name: (kept_pred[name] if name in kept_pred else rem_block[name])
                for name in column_names if name in kept_pred or name in rem_block}


class NgramBlockResultsQueueReader(BlockResultsReaderBase):
    """Consumer-side reader for ``make_reader(output='columnar', ngram=...)``:
    yields one nested window block per published item — a plain dict
    ``offset -> {field: [W, ...]}`` (namedtuples cannot key on integer offsets,
    so no conversion). ``batched_output=True``: W varies per row group like
    any columnar batch."""

    def __init__(self, schema, ngram):
        super().__init__(schema)
        self._ngram = ngram


class RowResultsQueueReader(object):
    """Consumer-side: slices schema namedtuples out of published column blocks,
    one row per ``read_next`` call (reference py_dict_reader_worker.py:64-97 —
    minus its per-row dict intermediate). NGram readers receive lists of
    window dicts instead of blocks and buffer them row-wise.

    Checkpoint support: each buffered chunk/block remembers the seq of the item
    it came from; when its last row is yielded, ``delivered_callback(seq)``
    fires (-> ``ventilator.mark_delivered``), so a :meth:`Reader.state_dict`
    snapshot never counts partially-yielded row groups as consumed."""

    def __init__(self, schema, ngram=None):
        self._schema = schema
        self._ngram = ngram
        self._namedtuple = schema.namedtuple if ngram is None else None
        self._field_order = list(schema.fields)
        # ngram path: buffered window rows; block path: (columns, n, seq) queue
        self._buffer = deque()
        self._spans = deque()  # [seq, rows_remaining] per buffered chunk
        self._block_cols = None
        self._block_n = 0
        self._block_i = 0
        self._block_seq = None
        self.delivered_callback = None

    @property
    def batched_output(self):
        return False

    def on_item_done(self, seq):
        """Pool completion sentinel consumed for ``seq``. Sentinels are only
        consumed when the buffer is empty (all prior rows yielded), so this can
        only fire for items already drained — or items that produced no rows —
        and marking delivered is safe in both cases."""
        if self.delivered_callback is not None:
            self.delivered_callback(seq)

    def read_next(self, pool):
        if self._ngram is not None:
            return self._read_next_ngram(pool)
        while self._block_cols is None:
            block = pool.get_results()  # raises EmptyResultError at end of epoch
            n = block_num_rows(block)
            if n == 0:
                continue
            self._block_cols = [block[name] for name in self._field_order]
            self._block_n = n
            self._block_i = 0
            self._block_seq = getattr(pool, 'last_result_seq', None)
        i = self._block_i
        row = self._namedtuple(*[col[i] for col in self._block_cols])
        self._block_i = i + 1
        if self._block_i == self._block_n:
            seq = self._block_seq
            self._block_cols = None
            if seq is not None and self.delivered_callback is not None:
                self.delivered_callback(seq)
        return row

    def _read_next_ngram(self, pool):
        while not self._buffer:
            rows = pool.get_results()
            self._buffer.extend(rows)
            self._spans.append([getattr(pool, 'last_result_seq', None), len(rows)])
        row = self._buffer.popleft()
        span = self._spans[0]
        span[1] -= 1
        if span[1] == 0:
            self._spans.popleft()
            if span[0] is not None and self.delivered_callback is not None:
                self.delivered_callback(span[0])
        return self._ngram.make_namedtuple(self._schema, row)
