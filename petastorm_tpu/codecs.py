"""Field codecs: how a logical tensor/scalar field is stored inside a Parquet column.

A codec translates between the in-memory numpy representation of a field and the
on-disk Parquet cell value (a scalar or a ``bytes`` blob).

Parity with the reference (/root/reference/petastorm/codecs.py:36-254):
``ScalarCodec``, ``NdarrayCodec``, ``CompressedNdarrayCodec``, ``CompressedImageCodec``.

TPU-first differences:
  * Codecs carry a stable string ``codec_id`` and JSON-serializable params so the
    schema can be stored as JSON in Parquet metadata instead of pickle (the
    reference's pickle coupling is its own documented regret, see
    /root/reference/petastorm/codecs.py:20-21).
  * ``ScalarCodec`` is parameterized by numpy dtype; Arrow types are derived,
    no Spark involvement.
  * Decoded outputs are C-contiguous little-endian arrays, ready for zero-copy
    staging into device host buffers.
"""

from __future__ import annotations

import io
import re
import struct
from decimal import Decimal

import numpy as np
import pyarrow as pa

from petastorm_tpu.errors import SchemaError


def _import_cv2():
    import cv2

    # Parallelism comes from the reader's worker pool — one image per worker
    # thread. OpenCV's internal thread pool on top of that oversubscribes the
    # cores and triples per-image decode latency under contention.
    if getattr(cv2, '_pstpu_threads_pinned', False) is False:
        try:
            cv2.setNumThreads(0)
        except AttributeError:
            pass
        cv2._pstpu_threads_pinned = True
    return cv2

_CODEC_REGISTRY = {}


def register_codec(cls):
    """Class decorator registering a codec under its ``codec_id`` for JSON round-trip."""
    _CODEC_REGISTRY[cls.codec_id] = cls
    return cls


def codec_from_json(spec):
    """Reconstruct a codec from its JSON dict ``{"codec_id": ..., **params}``."""
    spec = dict(spec)
    codec_id = spec.pop('codec_id')
    if codec_id not in _CODEC_REGISTRY:
        raise SchemaError('Unknown codec id: {}'.format(codec_id))
    return _CODEC_REGISTRY[codec_id].from_json(spec)


class DataFieldCodec(object):
    """Abstract codec protocol (reference: DataframeColumnCodec, codecs.py:36-50)."""

    #: stable identifier used in JSON-serialized schemas
    codec_id = None

    #: Parquet column compression this codec's payloads want: ``None`` defers to
    #: the dataset default; ``'none'`` opts out (codecs whose cells are already
    #: compressed — png/jpeg/zlib bytes — gain nothing from snappy and pay its
    #: decode on every read, which is pure input-pipeline stall)
    preferred_column_compression = None

    def encode(self, field, value):
        """Encode an in-memory value to the Parquet cell representation."""
        raise NotImplementedError

    def decode(self, field, encoded):
        """Decode a Parquet cell value back to the numpy in-memory representation."""
        raise NotImplementedError

    def arrow_type(self, field):
        """The ``pyarrow.DataType`` of the physical column this codec writes."""
        raise NotImplementedError

    def to_json(self):
        return {'codec_id': self.codec_id}

    @classmethod
    def from_json(cls, params):
        return cls(**params)

    def __eq__(self, other):
        return type(self) is type(other) and self.to_json() == other.to_json()

    def __ne__(self, other):
        return not self == other

    def __hash__(self):
        return hash(self.codec_id)

    def __repr__(self):
        return '{}()'.format(type(self).__name__)


_NUMPY_TO_ARROW = {
    np.int8: pa.int8(),
    np.uint8: pa.uint8(),
    np.int16: pa.int16(),
    np.uint16: pa.uint16(),
    np.int32: pa.int32(),
    np.uint32: pa.uint32(),
    np.int64: pa.int64(),
    np.uint64: pa.uint64(),
    np.float16: pa.float16(),
    np.float32: pa.float32(),
    np.float64: pa.float64(),
    np.bool_: pa.bool_(),
    np.str_: pa.string(),
    np.bytes_: pa.binary(),
    np.datetime64: pa.timestamp('ns'),
    Decimal: pa.string(),
}


def arrow_type_for_numpy(numpy_dtype):
    """Map a field's numpy dtype (a type object) to the Arrow storage type."""
    if numpy_dtype in _NUMPY_TO_ARROW:
        return _NUMPY_TO_ARROW[numpy_dtype]
    dt = np.dtype(numpy_dtype)
    if dt.type in _NUMPY_TO_ARROW:
        return _NUMPY_TO_ARROW[dt.type]
    raise SchemaError('No Arrow mapping for numpy dtype {}'.format(numpy_dtype))


@register_codec
class ScalarCodec(DataFieldCodec):
    """Stores a scalar in a typed Parquet column (reference codecs.py:189-231).

    ``dtype`` optionally overrides the field's numpy dtype for storage (e.g. store
    an int64 field as int32 on disk).
    """

    codec_id = 'scalar'

    def __init__(self, dtype=None):
        self._dtype = np.dtype(dtype).type if dtype is not None else None

    def _storage_dtype(self, field):
        return self._dtype or field.numpy_dtype

    def encode(self, field, value):
        if field.shape:
            raise SchemaError(
                'ScalarCodec can only encode scalars; field {} has shape {}'.format(field.name, field.shape))
        dtype = self._storage_dtype(field)
        if dtype is Decimal:
            # the physical column is a string column (see _NUMPY_TO_ARROW)
            return str(value)
        if dtype in (np.str_, np.bytes_):
            return value if not isinstance(value, np.generic) else value.item()
        if isinstance(value, np.ndarray):
            if value.shape != ():
                raise SchemaError('Field {} expects a scalar, got array of shape {}'.format(field.name, value.shape))
            value = value[()]
        if dtype is np.datetime64:
            # normalize to ns precision: the physical column is timestamp('ns')
            return np.datetime64(value, 'ns')
        return dtype(value).item()

    def decode(self, field, encoded):
        dtype = field.numpy_dtype
        if dtype is Decimal:
            return Decimal(encoded)
        return dtype(encoded)

    def decode_column(self, field, column):
        """Whole-column decode of a numeric/bool Arrow column to one numpy array
        (the columnar hot path) — ``None`` for flavors that need the per-cell
        path (nulls, strings, Decimals, datetimes)."""
        dtype = field.numpy_dtype
        if dtype is Decimal or dtype in (np.str_, np.bytes_, np.datetime64):
            return None
        if column.null_count:
            return None
        arr = column.to_numpy(zero_copy_only=False)
        if isinstance(arr, np.ndarray) and arr.dtype.kind in 'biuf':
            return arr.astype(np.dtype(dtype), copy=False)
        return None

    def arrow_type(self, field):
        return arrow_type_for_numpy(self._storage_dtype(field))

    def to_json(self):
        spec = {'codec_id': self.codec_id}
        if self._dtype is not None:
            spec['dtype'] = np.dtype(self._dtype).str
        return spec

    def __repr__(self):
        return 'ScalarCodec(dtype={})'.format(np.dtype(self._dtype).str if self._dtype else None)


def _require_ndarray(field, value):
    if not isinstance(value, np.ndarray):
        raise SchemaError('Field {} expects a numpy array, got {}'.format(field.name, type(value)))
    if value.dtype.type is not np.dtype(field.numpy_dtype).type:
        raise SchemaError('Field {} expects dtype {}, got {}'.format(
            field.name, np.dtype(field.numpy_dtype), value.dtype))
    _validate_shape(field, value.shape)


def _validate_shape(field, shape):
    """Shape compliance with ``None`` wildcards (reference codecs.py:234-254)."""
    expected = field.shape
    if expected is None:
        return
    if len(shape) != len(expected):
        raise SchemaError('Field {} expects rank {} (shape {}), got shape {}'.format(
            field.name, len(expected), expected, shape))
    for actual_dim, expected_dim in zip(shape, expected):
        if expected_dim is not None and actual_dim != expected_dim:
            raise SchemaError('Field {} expects shape {}, got {}'.format(field.name, expected, shape))


@register_codec
class NdarrayCodec(DataFieldCodec):
    """Raw ``np.save`` bytes in a binary column (reference codecs.py:121-152)."""

    codec_id = 'ndarray'

    def encode(self, field, value):
        _require_ndarray(field, value)
        buf = io.BytesIO()
        np.save(buf, np.ascontiguousarray(value))
        return buf.getvalue()

    def decode(self, field, encoded):
        arr = _fast_npy_decode(encoded)
        if arr is None:  # unusual header (e.g. structured dtype): general path
            arr = np.load(io.BytesIO(encoded), allow_pickle=False)
        return arr

    def decode_column(self, field, column):
        """Whole-column decode: all cells of a row group almost always carry an
        IDENTICAL ``np.save`` header (same shape/dtype), so parse it once, then
        each remaining cell is a bytes-compare plus one memcpy into a
        preallocated ``[N, ...]`` output — no per-cell header parse, no per-cell
        intermediate array + copy. ``None`` (-> generic per-cell path) for
        nulls, ragged shapes, or non-standard headers."""
        from petastorm_tpu.columnar import column_cells

        if column.null_count:
            return None
        cells = column_cells(column)
        if not cells:
            return None
        first = memoryview(cells[0])
        parsed = _parse_npy_header(first)
        if parsed is None:
            return None
        dtype, fortran, shape, data_off = parsed
        if fortran:
            return None
        count = 1
        for dim in shape:
            count *= dim
        cell_len = data_off + count * dtype.itemsize
        header = bytes(first[:data_off])
        out = np.empty((len(cells),) + shape, dtype=dtype)
        flat_out = out.reshape(len(cells), -1) if count else out.reshape(len(cells), 0)
        for i, cell in enumerate(cells):
            buf = memoryview(cell)
            if len(buf) != cell_len or bytes(buf[:data_off]) != header:
                return None  # mixed shapes/dtypes in this row group: generic path
            flat_out[i] = np.frombuffer(buf, dtype=dtype, count=count, offset=data_off)
        return out

    def arrow_type(self, field):
        return pa.binary()


# np.save v1/v2 headers are a repr'd dict padded with spaces; parsing it with
# a regex instead of np.load's tokenizer+ast.literal_eval removes the single
# biggest non-image cost in the row decode hot loop (~40us -> ~4us per cell)
_NPY_MAGIC = b'\x93NUMPY'
_NPY_HEADER_RE = re.compile(
    rb"\{'descr': '([^']+)', 'fortran_order': (False|True), "
    rb"'shape': \(([0-9, ]*),?\), \}\s*")


def _parse_npy_header(buf):
    """``(dtype, fortran_order, shape, data_offset)`` of standard ``np.save``
    bytes; None if the header is non-standard."""
    if len(buf) < 12 or bytes(buf[:6]) != _NPY_MAGIC:
        return None
    major = buf[6]
    if major == 1:
        (hlen,) = struct.unpack('<H', buf[8:10])
        data_off = 10 + hlen
        header = bytes(buf[10:data_off])
    else:
        (hlen,) = struct.unpack('<I', buf[8:12])
        data_off = 12 + hlen
        header = bytes(buf[12:data_off])
    m = _NPY_HEADER_RE.match(header)
    if m is None:
        return None
    dtype = np.dtype(m.group(1).decode())
    fortran = m.group(2) == b'True'
    shape = tuple(int(x) for x in m.group(3).split(b',') if x.strip())
    return dtype, fortran, shape, data_off


def _fast_npy_decode(encoded):
    """Decode standard ``np.save`` bytes; None if the header is non-standard."""
    buf = memoryview(encoded)
    parsed = _parse_npy_header(buf)
    if parsed is None:
        return None
    dtype, fortran, shape, data_off = parsed
    count = 1
    for dim in shape:
        count *= dim
    if data_off + count * dtype.itemsize > len(buf):
        return None
    flat = np.frombuffer(buf, dtype=dtype, count=count, offset=data_off)
    # copy: frombuffer over bytes is read-only, but decode() must hand user
    # transforms a writable array (np.load parity)
    return flat.reshape(shape, order='F' if fortran else 'C').copy()


@register_codec
class RawTensorCodec(DataFieldCodec):
    """Fixed-shape tensors stored as raw little-endian C-order bytes — the
    zero-copy storage format for throughput-critical tensor columns.

    TPU-first design with no reference counterpart (closest behavior:
    NdarrayCodec, reference codecs.py:121-152). The Unischema already pins the
    field's dtype and shape, so the per-cell ``np.save`` header NdarrayCodec
    writes is redundant when the shape is fully specified. Dropping it makes
    every cell the same length, which means the Arrow binary column's values
    buffer is exactly the contiguous ``[N, *shape]`` payload — whole-column
    decode is ONE reshape view of that buffer: no per-cell header parse and no
    per-cell memcpy (NdarrayCodec's columnar decode pays one memcpy per cell).

    Constraints (enforced at encode):
      * the field shape must be fully specified — no ``None`` wildcard dims
        (without per-cell headers a ragged cell would be unrecoverable; use
        NdarrayCodec for ragged fields);
      * dtype must be a fixed-width numeric/bool type.

    Columnar decode returns a view into the Arrow column (read-only when the
    underlying buffer is); mutate-in-place transforms should copy first.
    """

    codec_id = 'raw_tensor'

    @staticmethod
    def _cell_spec(field):
        dtype = np.dtype(field.numpy_dtype)
        if dtype.kind not in 'biuf':
            raise SchemaError('RawTensorCodec supports fixed-width numeric/bool dtypes; '
                              'field {} has dtype {}'.format(field.name, dtype))
        if dtype.byteorder == '>':
            raise SchemaError('RawTensorCodec stores little-endian; field {} has '
                              'big-endian dtype {}'.format(field.name, dtype))
        if field.shape is None or any(dim is None for dim in field.shape):
            raise SchemaError(
                'RawTensorCodec requires a fully-specified shape (no None dims); field {} '
                'has shape {} — use NdarrayCodec for ragged fields'.format(field.name, field.shape))
        count = 1
        for dim in field.shape:
            count *= dim
        return dtype, tuple(field.shape), count

    def encode(self, field, value):
        _require_ndarray(field, value)
        dtype, shape, _ = self._cell_spec(field)
        return np.ascontiguousarray(value, dtype=dtype).tobytes()

    def decode(self, field, encoded):
        dtype, shape, count = self._cell_spec(field)
        if len(encoded) != count * dtype.itemsize:
            raise SchemaError('Field {}: raw cell is {} bytes, expected {} for shape {} '
                              'dtype {}'.format(field.name, len(encoded),
                                                count * dtype.itemsize, shape, dtype))
        # copy: decode() must hand user transforms a writable array
        return np.frombuffer(encoded, dtype=dtype, count=count).reshape(shape).copy()

    def decode_column(self, field, column):
        """Whole-column zero-copy decode: one reshape view over the Arrow
        values buffer — fixed-size-binary storage (current writer) and plain
        binary (stores written before round 5) both serve it. ``None``
        (-> per-cell path) for nulls, other storage, or cells whose length
        disagrees with the schema."""
        if column.null_count:
            return None
        dtype, shape, count = self._cell_spec(field)
        cell_len = count * dtype.itemsize
        if column.num_chunks > 1 and pa.types.is_fixed_size_binary(column.type):
            # page-scanned columns arrive one chunk per page; a per-chunk view
            # + one stack beats falling to the per-cell path
            views = [self.decode_column(field, pa.chunked_array([c]))
                     for c in column.chunks]
            if any(v is None for v in views):
                return None
            return np.concatenate(views, axis=0)
        # combine_chunks copies even for a single chunk — take the chunk
        # directly in the (overwhelmingly common) one-chunk-per-row-group case
        col = column.chunk(0) if column.num_chunks == 1 else column.combine_chunks()
        n = len(col)
        if not n:
            return None
        if pa.types.is_fixed_size_binary(col.type):
            if col.type.byte_width != cell_len:
                return None
            payload = np.frombuffer(col.buffers()[1], dtype=np.uint8)[
                col.offset * cell_len: (col.offset + n) * cell_len]
            return payload.view(dtype).reshape((n,) + shape)
        if col.type not in (pa.binary(), pa.large_binary()):
            return None
        bufs = col.buffers()
        off_dtype = np.int64 if col.type == pa.large_binary() else np.int32
        offsets = np.frombuffer(bufs[1], dtype=off_dtype)[col.offset: col.offset + n + 1]
        if int(offsets[-1]) - int(offsets[0]) != n * cell_len or \
                (np.diff(offsets) != cell_len).any():
            return None  # some cell has the wrong length: per-cell path will report it
        payload = np.frombuffer(bufs[2], dtype=np.uint8)[int(offsets[0]):int(offsets[-1])]
        return payload.view(dtype).reshape((n,) + shape)

    #: cells are raw pixels/weights — snappy buys ~nothing on typical tensor
    #: payloads and costs read-side decompression; 'none' additionally makes
    #: the column servable by the zero-copy page scanner (native/pagescan.py)
    preferred_column_compression = 'none'

    def arrow_type(self, field):
        # fixed-size binary: the parquet physical type becomes
        # FIXED_LEN_BYTE_ARRAY whose PLAIN pages carry NO per-value length
        # prefixes — the page's values region IS the Arrow data buffer, which
        # is what makes the zero-copy page scan possible
        dtype, _, count = self._cell_spec(field)
        return pa.binary(count * dtype.itemsize)


@register_codec
class CompressedNdarrayCodec(DataFieldCodec):
    """zlib-compressed ``np.savez_compressed`` bytes (reference codecs.py:155-186)."""

    codec_id = 'compressed_ndarray'
    preferred_column_compression = 'none'  # cells are already zlib streams

    def encode(self, field, value):
        _require_ndarray(field, value)
        buf = io.BytesIO()
        np.savez_compressed(buf, arr=np.ascontiguousarray(value))
        return buf.getvalue()

    def decode(self, field, encoded):
        with np.load(io.BytesIO(encoded), allow_pickle=False) as npz:
            return npz['arr']

    def arrow_type(self, field):
        return pa.binary()


@register_codec
class ScalarListCodec(DataFieldCodec):
    """1-D variable-length array stored as a native Parquet LIST column.

    Used for list columns of plain (non-petastorm) Parquet stores inferred via
    ``Unischema.from_arrow_schema`` (reference unischema.py:291-340 treats these
    as 1-D numpy arrays on read).
    """

    codec_id = 'scalar_list'

    def encode(self, field, value):
        arr = np.asarray(value)
        if arr.ndim != 1:
            raise SchemaError('Field {} expects a 1-D array, got shape {}'.format(field.name, arr.shape))
        return arr.astype(np.dtype(field.numpy_dtype), copy=False).tolist()

    def decode(self, field, encoded):
        return np.asarray(encoded, dtype=np.dtype(field.numpy_dtype))

    def decode_column(self, field, column):
        """Whole-column decode of a LIST column whose rows are uniform-length:
        one reshape over the flattened Arrow values buffer instead of N python
        lists. ``None`` (-> per-cell path) for ragged/null flavors."""
        if column.null_count:
            return None
        col = column.combine_chunks()
        offs = col.offsets.to_numpy()
        if len(offs) < 2:
            return None
        lens = np.diff(offs)
        if (lens != lens[0]).any() or col.values.null_count:
            return None
        vals = col.values.to_numpy(zero_copy_only=False)
        if not isinstance(vals, np.ndarray) or vals.dtype.kind not in 'biuf':
            return None
        out = vals[offs[0]:offs[-1]].reshape(len(lens), int(lens[0]))
        return out.astype(np.dtype(field.numpy_dtype), copy=False)

    def arrow_type(self, field):
        return pa.list_(arrow_type_for_numpy(field.numpy_dtype))


def _area_weights(in_len, out_len):
    """``[out_len, in_len]`` row-stochastic pixel-coverage matrix (the area
    resampling kernel as an explicit matmul — slow-path fallback only)."""
    scale = in_len / out_len
    w = np.zeros((out_len, in_len), np.float32)
    for o in range(out_len):
        lo, hi = o * scale, min((o + 1) * scale, in_len)
        s = min(in_len - 1, int(lo))
        e = min(in_len, max(s + 1, int(np.ceil(hi))))
        for p in range(s, e):
            w[o, p] = max(0.0, min(p + 1, hi) - max(p, lo))
        total = w[o].sum()
        if total:
            w[o] /= total
    return w


def _bilinear_weights(in_len, out_len):
    """``[out_len, in_len]`` row-stochastic bilinear matrix (half-pixel
    centers, cv2 ``INTER_LINEAR`` semantics) — slow-path fallback only."""
    scale = in_len / out_len
    w = np.zeros((out_len, in_len), np.float32)
    for o in range(out_len):
        f = (o + 0.5) * scale - 0.5
        i = int(np.floor(f))
        frac = f - i
        if i < 0:
            i, frac = 0, 0.0
        if i >= in_len - 1:
            i, frac = (in_len - 2, 1.0) if in_len >= 2 else (0, 0.0)
        w[o, i] = 1.0 - frac
        if in_len >= 2:
            w[o, i + 1] += frac
    return w


def _resample_numpy(img, out_h, out_w, weights_fn):
    """Pure-numpy separable resample for dtypes the native resampler declines
    (e.g. uint16) on OpenCV-less hosts. Rare path; clarity over speed."""
    wy = weights_fn(img.shape[0], out_h)
    wx = weights_fn(img.shape[1], out_w)
    arr = img.astype(np.float32)
    squeeze = arr.ndim == 2
    if squeeze:
        arr = arr[..., None]
    out = np.einsum('yh,hwc,xw->yxc', wy, arr, wx)
    if img.dtype.kind in 'iu':
        out = np.clip(np.rint(out), 0, np.iinfo(img.dtype).max)
    out = out.astype(img.dtype)
    return out[..., 0] if squeeze else out


def _area_resize_numpy(img, out_h, out_w):
    return _resample_numpy(img, out_h, out_w, _area_weights)


def _bilinear_resize_numpy(img, out_h, out_w):
    return _resample_numpy(img, out_h, out_w, _bilinear_weights)


def _mild_ratio(in_h, in_w, out_h, out_w):
    """True when bilinear is the right filter: any upscaled axis, or both-axis
    decimation under 2x — the regime where a box (area) filter spans <= 2
    source pixels per axis and degenerates to the same support as bilinear.
    Mixed down+up shapes go bilinear on EVERY backend: area's anti-aliasing
    premise needs decimation on both axes, and on such shapes the native area
    resampler legitimately diverges from cv2 INTER_AREA (~100 LSB on the
    upscaled axis) — the same store must decode identically with or without
    OpenCV installed. The scaled-JPEG decode path lands here by construction
    (the covering m/8 scale is < 2x the target)."""
    if out_h > in_h or out_w > in_w:
        return True
    return in_h < 2 * out_h and in_w < 2 * out_w


def _resize_image(img, out_h, out_w, dst=None):
    """THE resize policy, shared by every decode path so they cannot drift:
    ``INTER_AREA`` for real decimation (>= 2x on either axis, where the box
    filter's anti-aliasing matters and cv2's integer-factor fast path lives),
    bilinear for mild ratios (< 2x both axes, where area's support collapses
    to bilinear's but cv2's generic non-integer area path costs ~7x more —
    measured 395 vs 57 us for 220px->160px). cv2 (SIMD) when available, else
    the native resampler (uint8), else the numpy resampler (any dtype).
    ``dst`` writes the result into a preallocated row of a block."""
    if img.shape[:2] == (out_h, out_w):
        if dst is None:
            return img
        dst[...] = img
        return dst
    try:
        cv2 = _import_cv2()
    except ImportError:
        cv2 = None
    if cv2 is not None:
        interp = cv2.INTER_LINEAR if _mild_ratio(img.shape[0], img.shape[1], out_h, out_w) \
            else cv2.INTER_AREA
        if dst is not None:
            cv2.resize(img, (out_w, out_h), dst=dst, interpolation=interp)
            return dst
        return cv2.resize(img, (out_w, out_h), interpolation=interp)
    mild = _mild_ratio(img.shape[0], img.shape[1], out_h, out_w)
    if img.dtype == np.uint8:
        from petastorm_tpu.native import image_codec
        if image_codec.is_available():
            native = (image_codec.resize_bilinear_image if mild
                      else image_codec.resize_area_image)
            out = native(img, (out_h, out_w))
        else:
            out = (_bilinear_resize_numpy if mild else _area_resize_numpy)(img, out_h, out_w)
    else:
        out = (_bilinear_resize_numpy if mild else _area_resize_numpy)(img, out_h, out_w)
    if dst is None:
        return out
    dst[...] = out
    return dst


@register_codec
class CompressedImageCodec(DataFieldCodec):
    """png/jpeg image compression (reference codecs.py:53-118).

    Accepts uint8 (and uint16 for png) HxW or HxWx3 arrays in RGB channel order;
    handles the RGB<->BGR swap around OpenCV internally, as the reference does
    (codecs.py:92-101).
    """

    codec_id = 'compressed_image'
    preferred_column_compression = 'none'  # cells are already png/jpeg streams
    #: TransformSpec.image_resize only works on fields whose codec declares
    #: this (transform_schema validates it, so a typo'd/ineligible field fails
    #: loudly instead of silently skipping the resize)
    supports_image_resize = True

    def __init__(self, image_codec='png', quality=80):
        if image_codec not in ('png', 'jpeg', 'jpg'):
            raise SchemaError('Unsupported image codec: {}'.format(image_codec))
        self._format = 'jpeg' if image_codec == 'jpg' else image_codec
        self._quality = int(quality)

    @property
    def image_codec(self):
        return self._format

    @property
    def quality(self):
        return self._quality

    def encode(self, field, value):
        cv2 = _import_cv2()
        _require_ndarray(field, value)
        if value.dtype.type not in (np.uint8, np.uint16):
            raise SchemaError('Image codec supports uint8/uint16, got {}'.format(value.dtype))
        if self._format == 'jpeg' and value.dtype.type is np.uint16:
            raise SchemaError('jpeg does not support uint16 images')
        if value.ndim == 3 and value.shape[2] == 3:
            value = cv2.cvtColor(value, cv2.COLOR_RGB2BGR)
        elif value.ndim not in (2, 3):
            raise SchemaError('Image must be HxW or HxWxC, got shape {}'.format(value.shape))
        if self._format == 'png':
            ok, contents = cv2.imencode('.png', value)
        else:
            ok, contents = cv2.imencode('.jpeg', value, [int(cv2.IMWRITE_JPEG_QUALITY), self._quality])
        if not ok:
            raise SchemaError('Image encoding failed for field {}'.format(field.name))
        return contents.tobytes()

    def decode(self, field, encoded):
        cv2 = _import_cv2()
        image = cv2.imdecode(np.frombuffer(encoded, dtype=np.uint8), cv2.IMREAD_UNCHANGED)
        if image is None:
            raise SchemaError('Image decoding failed for field {}'.format(field.name))
        if image.ndim == 3 and image.shape[2] == 3:
            image = cv2.cvtColor(image, cv2.COLOR_BGR2RGB)
        return image.astype(np.dtype(field.numpy_dtype), copy=False)

    #: _decode_table passes ``min_size`` (from TransformSpec.image_decode_hints)
    #: to decode_column — the only codec whose columnar decode takes a hint
    decode_column_accepts_hints = True

    def decode_column(self, field, column, min_size=None, resize=None):
        """Whole-column decode with ONE native header probe: straight into one
        ``[N, H, W(, C)]`` block when every cell probes to the same dims (skips
        the per-image allocations AND the column-stack copy of the
        ``decode_batch`` + ``stack_cells`` path), else per-image arrays stacked
        to an object column — still a single probe. ``resize=(out_h, out_w)``
        (from ``TransformSpec.image_resize``) fuses an area resample into the
        same native call, so every image lands pre-resized in one uniform
        block. ``None`` defers to the generic path (nulls, unsupported flavors,
        native codec unavailable)."""
        from petastorm_tpu.columnar import column_cells, stack_cells
        from petastorm_tpu.native import image_codec

        if column.null_count or not image_codec.is_available():
            return None
        cells = column_cells(column)
        if not cells:
            return None
        dtype = np.dtype(field.numpy_dtype)
        try:
            if resize is not None:
                return self._decode_column_resized(cells, resize, dtype, min_size)
            decoded = image_codec.decode_images_auto(cells, min_size=min_size)
        except (image_codec.NativeDecodeError, MemoryError):
            return None
        if isinstance(decoded, np.ndarray):
            return decoded.astype(dtype, copy=False)
        return stack_cells([img.astype(dtype, copy=False) for img in decoded])

    @staticmethod
    def _decode_column_resized(cells, resize, dtype, min_size=None):
        """Native single-probe decode (JPEG at the DCT scale covering
        ``min_size`` — an explicit decode hint — or else the resize target),
        then cv2 ``INTER_AREA`` per image straight into the rows of one uniform
        ``[N, out_h, out_w(, C)]`` block — cv2's SIMD resize beats the native
        scalar resample several-fold, so the fully-native fused path
        (:func:`decode_images_resized`) is only used when OpenCV is absent."""
        from petastorm_tpu.native import image_codec

        out_h, out_w = int(resize[0]), int(resize[1])
        try:
            _import_cv2()
        except ImportError:
            # no SIMD resize: the fully-native fused decode+resize is faster
            # than decode + scalar resample in two steps
            block = image_codec.decode_images_resized(cells, resize, min_size=min_size)
            return None if block is None else block.astype(dtype, copy=False)
        decoded = image_codec.decode_images_auto(cells, min_size=min_size or resize)
        if isinstance(decoded, np.ndarray):
            if decoded.shape[1:3] == (out_h, out_w):
                return decoded.astype(dtype, copy=False)
            imgs = list(decoded)
        else:
            imgs = decoded
        if any(img.dtype != np.uint8 for img in imgs):
            return None  # 16-bit: per-image path handles dtype conversion
        channels = {img.shape[2] if img.ndim == 3 else 1 for img in imgs}
        if len(channels) != 1:
            return None  # mixed gray/RGB cannot share one block
        c = channels.pop()
        out = np.empty((len(imgs), out_h, out_w) + ((c,) if c > 1 else ()), np.uint8)
        for i, img in enumerate(imgs):
            _resize_image(img, out_h, out_w, dst=out[i])
        return out.astype(dtype, copy=False)

    def decode_batch(self, field, encoded_list, min_size=None, resize=None):
        """Decode a whole column of image cells in one native call (GIL
        released, pixels land in numpy memory in RGB order with no BGR swap
        pass) — the batched replacement for the reference's per-image loop
        (reference codecs.py:92-111). Unsupported flavors (palette/alpha PNG,
        CMYK JPEG) fall back to the per-image OpenCV path; ``None`` cells
        (nullable fields) pass through.

        ``min_size=(min_h, min_w)`` (from ``TransformSpec.image_decode_hints``)
        enables scaled JPEG decode: images come out at the smallest m/8 DCT
        scale covering the minimum instead of full resolution. The OpenCV
        fallback decodes full size — still >= the hint, so downstream
        resize-to-target transforms see a valid input either way.

        ``resize=(out_h, out_w)`` (from ``TransformSpec.image_resize``) makes
        every decoded image come out at exactly that size — cv2 ``INTER_AREA``
        here; the columnar fast path fuses the same resample natively — so the
        contract holds on whichever path decodes the column."""
        from petastorm_tpu.native import image_codec

        present = [(i, v) for i, v in enumerate(encoded_list) if v is not None]
        out = [None] * len(encoded_list)
        if not present:
            return out
        if resize is not None and min_size is None:
            min_size = resize
        if image_codec.is_available():
            try:
                decoded = image_codec.decode_images([v for _, v in present],
                                                    min_size=min_size)
            except (image_codec.NativeDecodeError, MemoryError):
                # MemoryError: a corrupt header can claim huge dims and blow
                # the output allocation; retry per-image like any other bad cell
                decoded = None
        else:
            decoded = None
        if decoded is None:
            decoded = [self.decode(field, v) for _, v in present]
        else:
            dtype = np.dtype(field.numpy_dtype)
            decoded = [img.astype(dtype, copy=False) for img in decoded]
        if resize is not None:
            out_h, out_w = int(resize[0]), int(resize[1])
            decoded = [_resize_image(img, out_h, out_w) for img in decoded]
        for (i, _), img in zip(present, decoded):
            out[i] = img
        return out

    def arrow_type(self, field):
        return pa.binary()

    def to_json(self):
        return {'codec_id': self.codec_id, 'image_codec': self._format, 'quality': self._quality}

    def __repr__(self):
        return 'CompressedImageCodec(image_codec={!r}, quality={})'.format(self._format, self._quality)
