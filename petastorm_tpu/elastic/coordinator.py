"""Elastic resharding coordinator + the ventilator that drives it.

The coordinator owns one host's view of the shared coordination directory:

* ``members/`` — heartbeat leases (:mod:`petastorm_tpu.elastic.membership`)
* ``generations/NNNNNNNN.json`` — the generation log. Each file pins one
  generation's sorted member list; files are created with ``O_EXCL`` so
  exactly one proposal wins each number and the sequence is monotonic by
  construction. The *current* generation is the highest-numbered file.
* ``epochs/NNNNNN/done/NNNNNNNN`` — the per-epoch scoreboard. A row group
  is **committed** when its marker file exists; markers are created with
  ``O_EXCL``, so exactly one host wins each commit no matter how racy the
  handoff was — the COMMIT is exactly-once by construction. Sample
  delivery is at-least-once in one narrow window: a host stalled past
  ``lease_s`` (GC pause, fs hiccup) but still running may have its
  in-flight groups adopted and both hosts then yield those rows; only one
  wins the marker, and ``lease_s`` bounds the duplicate exposure.
* ``epochs/NNNNNN/inflight/<host>.json`` — each host's claimed-but-not-yet
  -committed row groups. A *live* host's in-flight items are never claimed
  by anyone else; a dead host's (lease expired or lease file gone) become
  adoptable, which is counted as ``rowgroups_handed_off``.
* ``commits/<host>.jsonl`` — an append-only audit log of the commits this
  host won (epoch, item, global rank, generation). The union of all hosts'
  logs is the pod's committed stream; chaos tests assert it covers every
  row group exactly once and in the seeded global order.

The resharding protocol, per poll: scan leases; if the alive set differs
from the current generation's member set, propose generation N+1 with the
alive set (``O_EXCL``; losers adopt the winner's file). Unstarted row
groups re-partition under the new map instantly — ownership is the pure
function :func:`~petastorm_tpu.elastic.shardmap.owner_of`, so no state
migrates. In-flight row groups follow dispatch-id ownership: they stay
pinned to the claiming host while its lease lives, and are adopted by
their new owner only after the lease expires.
"""

from __future__ import annotations

import errno
import itertools
import json
import logging
import os
import threading
import time
from collections import OrderedDict

from petastorm_tpu import observability as obs
from petastorm_tpu.elastic.membership import MembershipRegistry
from petastorm_tpu.elastic.shardmap import ShardMap
from petastorm_tpu.workers.ventilator import VentilatorBase

logger = logging.getLogger(__name__)


def _atomic_write(path, payload, retry):
    tmp = '{}.tmp.{}'.format(path, os.getpid())

    def write_and_swap():
        with open(tmp, 'w') as f:
            f.write(payload)
        os.rename(tmp, path)

    retry.call(write_and_swap)


class ElasticCoordinator(object):
    """One host's protocol engine over the shared coordination directory.

    Not thread-safe by itself; the elastic ventilator serializes calls on
    its feeding thread, except :meth:`commit` which may run on the
    consumer's results thread — commit only touches ``O_EXCL`` markers,
    the append-only log, and lock-guarded caches.
    """

    def __init__(self, config, num_items, seed=None, shuffle=True):
        self.config = config
        self.num_items = int(num_items)
        self.seed = seed
        self.shuffle = bool(shuffle)
        self.host_id = config.host_id
        self.coord_dir = config.coord_dir
        self.poll_s = config.poll_s
        self.monitor = config.monitor
        self._retry = config.retry_policy()
        self.registry = MembershipRegistry(self.coord_dir, self.host_id,
                                           lease_s=config.lease_s,
                                           retry=self._retry)
        self._generations_dir = os.path.join(self.coord_dir, 'generations')
        self._epochs_dir = os.path.join(self.coord_dir, 'epochs')
        self._commit_log = os.path.join(self.coord_dir, 'commits',
                                        self.host_id + '.jsonl')
        self._lock = threading.Lock()
        self._generation = 0
        self._members = ()
        self._maps = {}             # (generation, epoch) -> ShardMap
        self._last_alive = ()
        self._counted_expired = set()
        self._last_scan = 0.0
        self._epoch_state = {}      # epoch -> dict(done=set, deferred=set,
                                    #   dead_inflight=set, ventilated=set,
                                    #   inflight=set, handed_off=set)
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._started:
            return
        self._retry.call(os.makedirs, self._generations_dir, exist_ok=True)
        self._retry.call(os.makedirs, self._epochs_dir, exist_ok=True)
        self._retry.call(os.makedirs, os.path.dirname(self._commit_log),
                         exist_ok=True)
        self.registry.join()
        if self.monitor is not None:
            self.monitor.on_join(self.host_id)
        self._started = True
        self.poll(epoch=None, force=True)

    def close(self):
        if self._started:
            self.registry.leave()
            self._started = False

    # -- generation log ----------------------------------------------------

    def _gen_path(self, generation):
        return os.path.join(self._generations_dir,
                            '{:08d}.json'.format(generation))

    def _read_current_generation(self):
        try:
            names = self._retry.call(os.listdir, self._generations_dir)
        except OSError as e:
            if getattr(e, 'errno', None) == errno.ENOENT:
                return 0, ()
            raise
        numbers = sorted(int(n.split('.')[0]) for n in names
                         if n.endswith('.json') and n.split('.')[0].isdigit())
        for generation in reversed(numbers):
            try:
                data = self._retry.call(self._read_json,
                                        self._gen_path(generation))
            except (OSError, ValueError):
                # a peer's publish not yet fully visible (eventual-consistency
                # shared fs) or an I/O hiccup past the retry budget: skip it
                # this poll — a later scan will see the complete file
                continue
            return generation, tuple(data.get('members') or ())
        return self._generation, self._members

    def _read_json(self, path):
        with open(path, 'r') as f:
            return json.loads(f.read())

    def _propose_generation(self, generation, members):
        """Atomic exclusive proposal: the payload is staged in a private tmp
        file and published with ``os.link`` — link is atomic AND exclusive
        (EEXIST when a peer won the number), so a concurrent reader sees
        either no file or a complete one, never a partial write."""
        payload = json.dumps({'generation': generation,
                              'members': list(members),
                              'proposed_by': self.host_id})
        path = self._gen_path(generation)
        tmp = '{}.tmp.{}'.format(path, os.getpid())
        try:
            with open(tmp, 'w') as f:
                f.write(payload)
            try:
                os.link(tmp, path)
                return True
            except OSError as e:
                if getattr(e, 'errno', None) not in (errno.EPERM, errno.ENOSYS,
                                                     errno.EOPNOTSUPP):
                    return False
        except OSError:
            return False
        finally:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        # hard links unsupported (some FUSE object-store mounts): fall back to
        # O_EXCL + write — not atomic, but readers skip a torn file and pick
        # it up complete on a later poll
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except OSError:
            return False
        try:
            os.write(fd, payload.encode('utf-8'))
        finally:
            os.close(fd)
        return True

    # -- membership / resharding poll -------------------------------------

    def poll(self, epoch=None, force=False):
        """Refresh the membership + scoreboard view (rate-limited to one
        filesystem scan per ``poll_s``); advance the generation when the
        alive set drifted from the current generation's member set."""
        now = time.time()
        if not force and (now - self._last_scan) < self.poll_s:
            return
        self._last_scan = now

        infos = self.registry.scan(now=now)
        alive = set(m.host for m in infos if m.alive)
        alive.add(self.host_id)     # our own lease is renewed by our thread
        alive = tuple(sorted(alive))
        expired = tuple(sorted(m.host for m in infos if m.expired))

        for host in expired:
            if host not in self._counted_expired:
                self._counted_expired.add(host)
                obs.count('elastic_lease_expirations')
                if self.monitor is not None:
                    self.monitor.on_lease_expire(host)
        for host in alive:
            if host in self._counted_expired:
                self._counted_expired.discard(host)   # rejoined
        if self.monitor is not None:
            for host in alive:
                if host not in self._last_alive and host != self.host_id:
                    self.monitor.on_join(host)
        self._last_alive = alive

        current, members = self._read_current_generation()
        if alive and members != alive:
            with obs.stage('reshard', cat='elastic'):
                self._propose_generation(current + 1, alive)
                current, members = self._read_current_generation()

        if current > self._generation and members:
            self._generation = current
            self._members = members
            obs.count('reshard_generations')
            obs.gauge_set('elastic_generation', current)
            obs.gauge_set('elastic_member_count', len(members))
            if self.monitor is not None:
                self.monitor.on_reshard(current, members)

        if epoch is not None:
            self._refresh_epoch(epoch, alive)

    def _refresh_epoch(self, epoch, alive):
        with self._lock:
            # consumer threads retire stale epochs (del) under the lock; an
            # unlocked get here races the dict resize. The state dict itself
            # stays valid once fetched — per-epoch state is only ever dropped,
            # never rebound.
            state = self._epoch_state.get(epoch)
        if state is None:
            return
        done = set()
        try:
            for name in self._retry.call(os.listdir, self._done_dir(epoch)):
                if name.isdigit():
                    done.add(int(name))
        except OSError:
            pass
        deferred, dead_inflight = set(), set()
        try:
            names = self._retry.call(os.listdir, self._inflight_dir(epoch))
        except OSError:
            names = []
        for name in sorted(names):
            if not name.endswith('.json'):
                continue
            host = name[:-len('.json')]
            if host == self.host_id:
                continue
            try:
                data = self._retry.call(
                    self._read_json, os.path.join(self._inflight_dir(epoch), name))
            except (OSError, ValueError):
                # unreadable peer inflight: assume it pins its items (the
                # conservative direction — never adopt on an I/O hiccup)
                continue
            items = set(int(i) for i in data.get('items') or ())
            if host in alive:
                deferred |= items
            else:
                dead_inflight |= items
        with self._lock:
            state['done'] |= done
            state['deferred'] = deferred - state['done']
            state['dead_inflight'] = dead_inflight - state['done']
            pending_commits = sorted(state['commit_retry'] - state['done'])
        for item in pending_commits:
            # markers that could not be created when the item was delivered
            # (persistent fs error): the item is still ours, keep trying —
            # commit() re-resolves won/exists/error each attempt
            self.commit(epoch, item)

    # -- per-epoch scoreboard ----------------------------------------------

    def _epoch_dir(self, epoch):
        return os.path.join(self._epochs_dir, '{:06d}'.format(epoch))

    def _done_dir(self, epoch):
        return os.path.join(self._epoch_dir(epoch), 'done')

    def _inflight_dir(self, epoch):
        return os.path.join(self._epoch_dir(epoch), 'inflight')

    def _inflight_path(self, epoch):
        return os.path.join(self._inflight_dir(epoch),
                            self.host_id + '.json')

    def begin_epoch(self, epoch):
        self._retry.call(os.makedirs, self._done_dir(epoch), exist_ok=True)
        self._retry.call(os.makedirs, self._inflight_dir(epoch), exist_ok=True)
        with self._lock:
            self._epoch_state.setdefault(epoch, {
                'done': set(), 'deferred': set(), 'dead_inflight': set(),
                'ventilated': set(), 'inflight': set(), 'handed_off': set(),
                'commit_retry': set()})
        # bounded memory: forget scoreboards of long-finished epochs
        with self._lock:
            stale = sorted(self._epoch_state)[:-4]
            for e in stale:
                del self._epoch_state[e]
        self.poll(epoch=epoch, force=True)

    def shard_map(self, epoch):
        key = (self._generation, epoch)
        cached = self._maps.get(key)
        if cached is None:
            cached = ShardMap(self._generation, self._members, self.num_items,
                              self.seed, epoch, shuffle=self.shuffle)
            self._maps = {key: cached}   # only the live generation matters
        return cached

    def claimable_items(self, epoch):
        """Row groups this host should ventilate next, in global emission
        order: owned under the current map, not committed, not pinned by a
        live peer's in-flight claim, not already ventilated locally."""
        if not self._members or self.host_id not in self._members:
            return []       # not (yet) part of the current generation
        smap = self.shard_map(epoch)
        with self._lock:
            state = self._epoch_state[epoch]
            blocked = state['done'] | state['deferred'] | state['ventilated']
        return [item for item in smap.owned_items(self.host_id)
                if item not in blocked]

    def note_ventilated(self, epoch, item):
        """Record a local claim just before dispatching ``item`` to the
        pool: the in-flight file is the claim other hosts honor."""
        with self._lock:
            state = self._epoch_state[epoch]
            state['ventilated'].add(item)
            state['inflight'].add(item)
            handed_off = (item in state['dead_inflight']
                          and item not in state['handed_off'])
            if handed_off:
                state['handed_off'].add(item)
            inflight = sorted(state['inflight'])
        if handed_off:
            obs.count('rowgroups_handed_off')
        if self.monitor is not None:
            self.monitor.on_claim(self.host_id, (epoch, item))
        self._write_inflight(epoch, inflight)

    def _write_inflight(self, epoch, items):
        payload = json.dumps({'host': self.host_id,
                              'generation': self._generation,
                              'items': items})
        try:
            _atomic_write(self._inflight_path(epoch), payload, self._retry)
        except OSError:
            pass    # a lost claim write only risks duplicate *reads*, never
                    # duplicate commits — the done marker stays exclusive

    def is_done(self, epoch, item):
        with self._lock:
            return item in self._epoch_state[epoch]['done']

    def _create_marker(self, epoch, item):
        """Try to create ``item``'s O_EXCL marker: ``'won'`` (this host's
        marker), ``'exists'`` (a peer's), or ``'error'`` (the marker is
        verifiably NOT on disk — the item must stay uncommitted)."""
        path = os.path.join(self._done_dir(epoch), '{:08d}'.format(item))

        def create_marker():
            try:
                fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
            except FileExistsError:
                return 'exists'
            os.close(fd)
            return 'won'

        try:
            return self._retry.call(create_marker)
        except OSError:
            return 'error'

    def commit(self, epoch, item):
        """Try to win ``item``'s commit marker. True when this host's
        delivery is THE delivery; False when a peer already committed it —
        or when the marker could not be created at all (then the item stays
        uncommitted locally and the marker is retried on later polls:
        counting it done with no marker on disk would let this host finish
        an epoch its peers can never see complete)."""
        outcome = self._create_marker(epoch, item)
        with self._lock:
            state = self._epoch_state.get(epoch)
            inflight = None
            if state is not None:
                if outcome == 'error':
                    state['commit_retry'].add(item)
                else:
                    state['done'].add(item)
                    state['inflight'].discard(item)
                    state['commit_retry'].discard(item)
                    inflight = sorted(state['inflight'])
        won = outcome == 'won'
        if won:
            obs.count('elastic_commits')
            if self.monitor is not None:
                self.monitor.on_deliver(self.host_id, (epoch, item))
            self._append_commit(epoch, item)
        if inflight is not None:
            self._write_inflight(epoch, inflight)
        return won

    def _append_commit(self, epoch, item):
        smap = self.shard_map(epoch)
        line = json.dumps({'epoch': epoch, 'item': item,
                           'rank': smap.rank(item),
                           'generation': self._generation,
                           'host': self.host_id}) + '\n'
        try:
            with open(self._commit_log, 'a') as f:
                f.write(line)
                f.flush()
        except OSError:
            pass    # the audit log is diagnostic; markers are the truth

    def epoch_complete(self, epoch):
        with self._lock:
            return len(self._epoch_state[epoch]['done']) >= self.num_items

    def undone_items(self, epoch):
        """Cluster-wide uncommitted row groups (the portable checkpoint
        cursor: any single host's snapshot covers the whole pod)."""
        with self._lock:
            state = self._epoch_state.get(epoch)
            done = set(state['done']) if state is not None else set()
        return [i for i in range(self.num_items) if i not in done]

    # -- introspection -----------------------------------------------------

    @property
    def generation(self):
        return self._generation

    @property
    def members(self):
        return self._members

    def status(self):
        return {'host': self.host_id, 'generation': self._generation,
                'members': list(self._members),
                'alive': list(self._last_alive)}


class ElasticVentilator(VentilatorBase):
    """Drop-in for :class:`~petastorm_tpu.workers.ventilator.
    ConcurrentVentilator` that ventilates only the row groups this host
    owns under the coordinator's live shard map.

    Same pool-facing contract: tagged ``_seq`` dispatch under a minted
    trace, ``processed_item`` releases the in-flight budget exactly once
    per item, ``mark_delivered`` fires on final delivery — here it also
    tries to win the item's global commit marker, which is what feeds the
    exactly-once commit scoreboard (the commit happens AFTER the rows were
    yielded, so a lost race after a false lease expiry means the rows went
    out twice pod-wide — see the module docstring; ``lease_s`` bounds
    that window). ``upcoming_items`` peeks the claimable head
    for the chunk prefetcher; ``set_max_queue_size`` retargets the budget
    for the autotuner.
    """

    def __init__(self, ventilate_fn, items_to_ventilate, coordinator,
                 iterations=1, max_ventilation_queue_size=None):
        if iterations is not None and (not isinstance(iterations, int)
                                       or iterations < 1):
            raise ValueError('iterations must be a positive integer or None, '
                             'got {!r}'.format(iterations))
        if coordinator.num_items != len(items_to_ventilate):
            raise ValueError('coordinator covers {} items but {} were given'
                             .format(coordinator.num_items,
                                     len(items_to_ventilate)))
        self._ventilate_fn = ventilate_fn
        self._items = list(items_to_ventilate)
        self._coord = coordinator
        self._iterations = iterations
        self._max_q = (max_ventilation_queue_size
                       if max_ventilation_queue_size is not None
                       else max(1, len(self._items)))
        self.trace_ns = os.urandom(4).hex()
        self._cv = threading.Condition()
        self._in_flight = 0
        self._seq = 0
        self._undelivered = OrderedDict()   # seq -> (epoch, item)
        self._pending_peek = []
        self._epoch_base = 0
        self._next_epoch = 0
        self._current_epoch = 0
        self._epochs_remaining = iterations
        self._stop_requested = False
        self._completed = len(self._items) == 0
        self._thread = None

    def start(self):
        if self._thread is not None:
            raise RuntimeError('Ventilator already started')
        if self._completed:
            return
        self._coord.start()
        self._thread = threading.Thread(target=self._ventilate_loop,
                                        daemon=True,
                                        name='pstpu-elastic-ventilator')
        self._thread.start()

    def processed_item(self, seq=None):
        with self._cv:
            self._in_flight -= 1
            self._cv.notify()

    def mark_delivered(self, seq):
        if seq is None:
            return
        with self._cv:
            info = self._undelivered.pop(seq, None)
        if info is not None:
            self._coord.commit(*info)

    def state_dict(self):
        """Portable snapshot: the CLUSTER-wide uncommitted row groups of
        the current epoch (any one host's checkpoint covers the pod) plus
        the remaining epoch count. ``rng_state`` is None — the elastic
        shuffle is a pure function of ``(seed, epoch)``, so there is no
        RNG stream to carry."""
        with self._cv:
            epoch = self._current_epoch
            remaining = self._epochs_remaining
        return {'replay_indices': sorted(self._coord.undone_items(epoch)),
                'iterations_remaining': remaining,
                'rng_state': None}

    def set_max_queue_size(self, n):
        with self._cv:
            self._max_q = max(1, int(n))
            self._cv.notify_all()

    def upcoming_items(self, max_items):
        with self._cv:
            indices = self._pending_peek[:max_items]
        return [self._items[i] for i in indices]

    def completed(self):
        return self._completed

    def reset(self):
        """Start a fresh run of the requested iterations. Epoch numbers
        keep advancing across resets (the scoreboard is per-epoch, so a
        reset must not collide with already-committed epochs)."""
        if not self._completed:
            raise RuntimeError('Cannot reset ventilator while ventilation '
                               'is still in progress')
        if self._thread is not None:
            self._thread.join()
        self._thread = None
        self._stop_requested = False
        self._completed = len(self._items) == 0
        with self._cv:
            self._epoch_base = self._next_epoch
            self._epochs_remaining = self._iterations
            self._in_flight = 0
            self._undelivered.clear()
            self._pending_peek = []
        self.start()

    def stop(self):
        self._stop_requested = True
        with self._cv:
            self._cv.notify_all()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join()
        self._completed = True
        self._coord.close()

    # -- the feeding loop --------------------------------------------------

    def _ventilate_loop(self):
        try:
            epochs = (itertools.count() if self._iterations is None
                      else range(self._iterations))
            for epoch_in_run in epochs:
                if self._stop_requested:
                    break
                epoch = self._epoch_base + epoch_in_run
                with self._cv:
                    self._current_epoch = epoch
                    self._next_epoch = epoch + 1
                    self._epochs_remaining = (
                        None if self._iterations is None
                        else self._iterations - epoch_in_run - 1)
                self._run_epoch(epoch)
        except Exception:   # noqa: BLE001 — a dead feed thread must not
            # leave consumers blocked forever on a queue that will never
            # fill: mark the ventilation complete so the reader drains and
            # stops, and leave the root cause in the log
            logger.exception('elastic ventilator feed thread died; '
                             'marking ventilation complete')
            obs.count('elastic_ventilator_errors')
        finally:
            self._completed = True

    def _run_epoch(self, epoch):
        coord = self._coord
        coord.begin_epoch(epoch)
        while not self._stop_requested:
            coord.poll(epoch=epoch)
            if coord.epoch_complete(epoch):
                return
            claimable = coord.claimable_items(epoch)
            with self._cv:
                self._pending_peek = list(claimable)
            if not claimable:
                # nothing to do locally: peers are finishing their share,
                # or in-flight groups are pinned by live leases
                self._stop_wait(coord.poll_s)
                continue
            item = claimable[0]
            with self._cv:
                while (self._in_flight >= self._max_q
                       and not self._stop_requested):
                    self._cv.wait(timeout=0.1)
                if self._stop_requested:
                    return
                self._in_flight += 1
                seq = self._seq
                self._seq += 1
                self._undelivered[seq] = (epoch, item)
            if coord.is_done(epoch, item):
                # a peer committed it while we waited on the budget
                with self._cv:
                    self._undelivered.pop(seq, None)
                    self._in_flight -= 1
                    self._cv.notify()
                continue
            coord.note_ventilated(epoch, item)
            with obs.mint_trace(self.trace_ns, seq):
                with obs.stage('ventilate', cat='ventilator'):
                    self._ventilate_fn(**dict(self._items[item], _seq=seq))

    def _stop_wait(self, seconds):
        deadline = time.time() + seconds
        while not self._stop_requested:
            remaining = deadline - time.time()
            if remaining <= 0:
                return
            with self._cv:
                self._cv.wait(timeout=min(remaining, 0.1))


__all__ = ['ElasticCoordinator', 'ElasticVentilator']
