"""Deterministic, churn-stable shard maps for elastic pod sharding.

Everything in this module is a *pure function* of ``(seed, epoch, member
set)`` — no wall-clock, no process-local RNG state, no set-iteration-order
dependence (lint PT1200 enforces this statically).  Two properties fall out:

* **Agreement without messages.** Every host computes the same map from the
  same inputs, so membership changes never need a leader election or a
  broadcast — hosts converge on the new assignment as soon as they observe
  the new generation's member list.
* **Churn stability.** Row-group ownership uses rendezvous (highest-random-
  weight) hashing: when a host leaves, only the row groups it owned move;
  when a host joins, it takes an even slice from everyone.  The *global
  emission order* is a seeded permutation of ``(seed, epoch)`` alone — it
  does not mention the member set at all, so the committed row-group
  sequence is bit-for-bit identical whether or not churn occurred.
"""

from __future__ import annotations

import hashlib

import numpy as np


def stable_hash(*parts):
    """A 64-bit hash of ``parts`` that is stable across processes and hosts.

    Built on blake2b over the ``repr`` of each part (null-separated), so it
    is immune to ``PYTHONHASHSEED`` — unlike builtin ``hash`` — and any mix
    of ints/strings/tuples hashes consistently everywhere.
    """
    digest = hashlib.blake2b(digest_size=8)
    for part in parts:
        digest.update(repr(part).encode('utf-8'))
        digest.update(b'\x00')
    return int.from_bytes(digest.digest(), 'big')


def owner_of(item_index, members, seed, epoch):
    """The host that owns row group ``item_index`` under this member set.

    Rendezvous hashing: each member scores ``stable_hash(seed, epoch,
    member, item)`` and the highest score wins.  Independent per item, so
    membership changes only move the items whose winner changed.
    """
    best = None
    best_score = -1
    for member in sorted(members):
        score = stable_hash('pstpu.elastic.owner', seed, epoch, member,
                            item_index)
        if score > best_score:
            best, best_score = member, score
    return best


def global_order(num_items, seed, epoch, shuffle=True):
    """The pod-wide emission order of row-group indices for this epoch.

    A function of ``(seed, epoch)`` only — deliberately independent of the
    member set, so the order survives any amount of churn.  With
    ``shuffle=False`` the order is the identity (row groups in file order).
    """
    if not shuffle:
        return list(range(num_items))
    rng = np.random.default_rng(stable_hash('pstpu.elastic.order', seed,
                                            epoch))
    return [int(i) for i in rng.permutation(num_items)]


class ShardMap(object):
    """One generation's assignment of ``num_items`` row groups to members.

    Immutable; constructed fresh each time the generation advances.  The
    map pins the member set it was derived from (``members``), so a host
    can tell "I own this under generation g" apart from "I would own this
    under the membership I can see right now".
    """

    __slots__ = ('generation', 'members', 'num_items', 'seed', 'epoch',
                 '_order', '_rank', '_owners')

    def __init__(self, generation, members, num_items, seed, epoch,
                 shuffle=True):
        if not members:
            raise ValueError('a shard map needs at least one member')
        self.generation = int(generation)
        self.members = tuple(sorted(members))
        self.num_items = int(num_items)
        self.seed = seed
        self.epoch = int(epoch)
        self._order = global_order(num_items, seed, epoch, shuffle=shuffle)
        self._rank = {item: rank for rank, item in enumerate(self._order)}
        self._owners = {item: owner_of(item, self.members, seed, epoch)
                        for item in range(num_items)}

    def owner(self, item_index):
        """The member that owns ``item_index`` under this generation."""
        return self._owners[item_index]

    def rank(self, item_index):
        """Position of ``item_index`` in the global emission order."""
        return self._rank[item_index]

    def order(self):
        """The full global emission order (list of item indices)."""
        return list(self._order)

    def owned_items(self, member):
        """Items owned by ``member``, in global emission order."""
        return [item for item in self._order if self._owners[item] == member]

    def describe(self):
        return ('generation={} members={} items={} epoch={}'
                .format(self.generation, ','.join(self.members),
                        self.num_items, self.epoch))


__all__ = ['ShardMap', 'global_order', 'owner_of', 'stable_hash']
