"""Shared-filesystem membership registry with heartbeat leases.

Each pod host owns one lease file under ``<coord_dir>/members/`` (JSON:
host id, pid, lease duration, renewal timestamp) that a background
heartbeat thread renews atomically (tmp + ``os.rename``).  Liveness is
judged the same way the serve daemon's handshake does:

* a **fresh lease** (renewed within ``lease_s``) is alive — unless its
  holder's pid is provably *dead* on this machine (``os.kill(pid, 0)``
  raising, or a zombie in ``/proc``), which shortcuts the wait and marks
  the host dead immediately;
* an **expired lease** is dead. Pid liveness only ever SHORTENS a lease,
  never extends it: a stale lease is dead even when its same-machine pid
  is still running — a host stalled past ``lease_s`` is treated as
  departed, per the documented false-expiry window
  (``docs/parallelism.md``);
* a **missing lease** means the host left gracefully (``leave()``
  unlinks it) or never joined.

All lease I/O goes through the repo's :class:`~petastorm_tpu.retry.
RetryPolicy` (transient-error classification, bounded decorrelated
backoff, the ``FAULT_POINT`` chaos hook), so a slow or flaky NFS/GCS
stat retries instead of false-positiving a host as dead.  When a read
of an *existing* lease file keeps failing past the retry budget, the
holder is presumed ALIVE — an unreadable lease must never look like a
departure.
"""

from __future__ import annotations

import errno
import json
import os
import threading
import time

from petastorm_tpu.retry import RetryPolicy, is_transient_io_error


def _machine_id():
    """A stable identity for this machine, for same-host pid shortcuts."""
    try:
        return os.uname().nodename
    except (AttributeError, OSError):
        return 'unknown'


def _pid_alive(pid):
    """Best-effort pid liveness (signal-0 probe + /proc zombie check).

    Mirrors the serve client's handshake: unknown/unsure answers lean
    ALIVE so a permission error never reaps a live host.
    """
    if not pid or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True
    try:
        with open('/proc/{}/stat'.format(pid), 'r') as f:
            return f.read().rsplit(')', 1)[-1].split()[0] != 'Z'
    except (OSError, IndexError):
        return True


#: retry budget for lease reads/writes — short backoffs: the heartbeat
#: period bounds how long a renewal may take end to end
DEFAULT_LEASE_RETRY = RetryPolicy(max_attempts=4, initial_backoff_s=0.02,
                                  multiplier=2.0, max_backoff_s=0.25,
                                  jitter=0.25, classify=is_transient_io_error)


class MemberInfo(object):
    """One member's decoded lease, plus the liveness verdict. ``notes`` is
    the holder's annotation dict (e.g. its fabric endpoint) — empty when the
    lease predates annotations or could not be read."""

    __slots__ = ('host', 'pid', 'lease_s', 'renewed', 'alive', 'expired',
                 'notes')

    def __init__(self, host, pid, lease_s, renewed, alive, expired, notes=None):
        self.host = host
        self.pid = pid
        self.lease_s = lease_s
        self.renewed = renewed
        self.alive = alive
        self.expired = expired
        self.notes = notes if notes is not None else {}

    def to_dict(self):
        return {'host': self.host, 'pid': self.pid, 'lease_s': self.lease_s,
                'renewed': self.renewed, 'alive': self.alive,
                'expired': self.expired, 'notes': self.notes}


class MembershipRegistry(object):
    """Lease-file membership for one host in one coordination directory.

    :param coord_dir: shared directory all pod hosts can reach
    :param host_id: this host's stable identity (e.g. ``host0`` or the
        value derived from ``jax.process_index()``)
    :param lease_s: lease duration; a lease not renewed for this long
        marks its holder dead
    :param retry: :class:`RetryPolicy` for lease I/O (default bounded
        short-backoff policy); tests inject flaky-fs faults through the
        policy's ``FAULT_POINT`` hook
    :param annotations: optional JSON-serializable dict carried inside every
        lease renewal (surfaced to peers as :attr:`MemberInfo.notes`) — how a
        host publishes per-host metadata such as its chunk-fabric endpoint
        WITHOUT a second discovery protocol: the annotation lives and dies
        with the lease itself
    """

    def __init__(self, coord_dir, host_id, lease_s=5.0, retry=None,
                 annotations=None):
        if lease_s <= 0:
            raise ValueError('lease_s must be positive, got {!r}'.format(lease_s))
        self.coord_dir = coord_dir
        self.host_id = str(host_id)
        self.lease_s = float(lease_s)
        self.annotations = dict(annotations) if annotations else {}
        self._retry = retry if retry is not None else DEFAULT_LEASE_RETRY
        self._members_dir = os.path.join(coord_dir, 'members')
        self._lease_path = os.path.join(self._members_dir,
                                        self.host_id + '.lease')
        self._heartbeat = None
        self._stop = threading.Event()
        self._joined = False

    # -- lifecycle ---------------------------------------------------------

    def join(self):
        """Write this host's lease and start the heartbeat renewal thread."""
        if self._joined:
            return
        self._retry.call(os.makedirs, self._members_dir, exist_ok=True)
        self._renew()
        self._stop.clear()
        self._heartbeat = threading.Thread(
            target=self._heartbeat_loop,
            name='pstpu-elastic-heartbeat-{}'.format(self.host_id),
            daemon=True)
        self._heartbeat.start()
        self._joined = True

    def leave(self):
        """Stop heartbeating and remove the lease (a graceful departure)."""
        self._stop.set()
        if self._heartbeat is not None:
            self._heartbeat.join(timeout=self.lease_s)
            self._heartbeat = None
        if self._joined:
            try:
                self._retry.call(os.unlink, self._lease_path)
            except OSError:
                pass
            self._joined = False

    def __enter__(self):
        self.join()
        return self

    def __exit__(self, *exc):
        self.leave()
        return False

    # -- lease renewal -----------------------------------------------------

    def _renew(self):
        record = {'host': self.host_id, 'pid': os.getpid(),
                  'machine': _machine_id(),
                  'lease_s': self.lease_s,
                  'renewed': time.time()}
        if self.annotations:
            record['notes'] = self.annotations
        payload = json.dumps(record)
        tmp = self._lease_path + '.tmp.{}'.format(os.getpid())

        def write_and_swap():
            with open(tmp, 'w') as f:
                f.write(payload)
            os.rename(tmp, self._lease_path)

        self._retry.call(write_and_swap)

    def _heartbeat_loop(self):
        period = max(self.lease_s / 3.0, 0.02)
        while not self._stop.wait(period):
            try:
                self._renew()
            except OSError:
                # Past the retry budget: keep trying next period. The lease
                # may expire meanwhile, which peers will treat as a death —
                # the conservative outcome for a host that cannot reach the
                # shared filesystem at all.
                continue

    # -- membership reads --------------------------------------------------

    def _read_lease(self, path):
        with open(path, 'r') as f:
            return json.loads(f.read())

    def scan(self, now=None):
        """Decode every lease file into a list of :class:`MemberInfo`.

        Liveness per lease: fresh + same-machine pid provably dead =>
        dead now (the crash shortcut); fresh otherwise => alive; stale =>
        dead (expired) regardless of pid liveness. A
        lease that cannot be read past the retry budget is reported alive
        and unexpired — I/O trouble must never masquerade as a departure.
        """
        now = time.time() if now is None else now
        try:
            names = self._retry.call(os.listdir, self._members_dir)
        except OSError as e:
            if getattr(e, 'errno', None) == errno.ENOENT:
                return []
            raise
        infos = []
        for name in sorted(names):
            if not name.endswith('.lease'):
                continue
            host = name[:-len('.lease')]
            path = os.path.join(self._members_dir, name)
            try:
                data = self._retry.call(self._read_lease, path)
            except (OSError, ValueError):
                if not os.path.exists(path):
                    continue    # unlinked mid-scan: a graceful leave
                infos.append(MemberInfo(host, None, None, None,
                                        alive=True, expired=False))
                continue
            pid = data.get('pid')
            lease_s = float(data.get('lease_s') or self.lease_s)
            renewed = float(data.get('renewed') or 0.0)
            notes = data.get('notes')
            if not isinstance(notes, dict):
                notes = {}
            fresh = (now - renewed) <= lease_s
            if fresh and pid is not None and os.getpid() != pid \
                    and data.get('machine') == _machine_id() \
                    and not _pid_alive(pid):
                # Same-machine shortcut: the holder is visibly dead (e.g.
                # SIGKILLed); no need to wait out the remaining lease time.
                fresh = False
            infos.append(MemberInfo(host, pid, lease_s, renewed,
                                    alive=fresh, expired=not fresh,
                                    notes=notes))
        return infos

    def alive_members(self, now=None):
        """Sorted tuple of host ids whose leases are currently live."""
        return tuple(sorted(m.host for m in self.scan(now=now) if m.alive))

    def expired_members(self, now=None):
        """Sorted tuple of host ids whose leases exist but have expired."""
        return tuple(sorted(m.host for m in self.scan(now=now) if m.expired))


__all__ = ['DEFAULT_LEASE_RETRY', 'MemberInfo', 'MembershipRegistry',
           '_pid_alive']
